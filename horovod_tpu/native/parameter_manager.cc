#include "parameter_manager.h"

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>

#include "bayesian_optimization.h"
#include "common.h"
#include "logging.h"

namespace hvdtpu {

static double NowMicros() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Continuous search bounds. The pipeline-chunk bounds depend on the
// workload profile: with wire compression active every element ships
// 2-4x fewer bytes, so the slice that keeps the socket busy is
// proportionally smaller.
static constexpr double kFusionLo = 0.0, kFusionHi = 64.0;
static constexpr double kCycleLo = 1.0, kCycleHi = 100.0;
static constexpr double kChunkLoKb = 64.0, kChunkHiKb = 4096.0;
static constexpr double kChunkLoKbCompressed = 16.0,
                        kChunkHiKbCompressed = 1024.0;

// Wire word layout: (rearm_epoch << 8) | profile bits.
static constexpr uint64_t kProfileCompression = 1;
static constexpr uint64_t kProfileReduceScatter = 2;
static constexpr uint64_t kProfileGroups = 4;
static constexpr uint64_t kProfileShm = 8;

ParameterManager::ParameterManager() = default;
ParameterManager::~ParameterManager() = default;

void ParameterManager::Initialize(int32_t rank,
                                  const std::string& autotune_log_file) {
  std::lock_guard<std::mutex> lk(mu_);
  rank_ = rank;
  seed_salt_ = static_cast<uint64_t>(EnvInt64("HVD_TPU_GENERATION", 0));
  // Sampling pace / drift knobs (env-overridable so tests and bench can
  // converge in seconds instead of minutes; docs/AUTOTUNE.md).
  cycles_per_sample_ = std::max(
      1, static_cast<int>(EnvInt64("HVD_TPU_AUTOTUNE_CYCLES_PER_SAMPLE", 10)));
  max_samples_ = std::max(
      1, static_cast<int>(EnvInt64("HVD_TPU_AUTOTUNE_MAX_SAMPLES", 40)));
  warmup_samples_ = std::max(
      0, static_cast<int>(EnvInt64("HVD_TPU_AUTOTUNE_WARMUP", 3)));
  drift_threshold_ =
      std::max(1.01, EnvDouble("HVD_TPU_AUTOTUNE_DRIFT", 2.0));
  drift_window_cycles_ =
      std::max(4, static_cast<int>(EnvInt64("HVD_TPU_AUTOTUNE_DRIFT_WINDOW", 40)));
  // Generation (re)start: every rank — survivor or fresh — resets the
  // re-arm epoch to 0 so the wire bootstrap only signals genuine
  // intra-generation re-arms (a survivor carrying an old epoch into a
  // new generation would make fresh workers re-arm out of lockstep).
  // rearms_total_ deliberately survives: it is a monotonic counter.
  rearm_epoch_ = 0;
  rearm_pending_ = false;
  armed_once_ = false;  // re-opened by the generation's SetAutoTuning
  profile_compression_ = false;
  profile_reduce_scatter_ = false;
  profile_groups_ = false;
  profile_shm_ = false;
  if (rank == 0 && !autotune_log_file.empty()) {
    log_.open(autotune_log_file, std::ios::out | std::ios::trunc);
    if (log_.is_open()) {
      log_ << "fusion_mb,cycle_time_ms,pipeline_chunk_kb,cache_enabled,"
              "hierarchical_allreduce,hierarchical_allgather,"
              "hierarchical_reduce_scatter,shm_transport,"
              "score_bytes_per_us,event\n";
    }
  }
  BuildSearchSpace();
}

// lockorder: requires(mu_)
void ParameterManager::BuildSearchSpace() {
  // Categorical combos to sweep: (cache, hier_allreduce, hier_allgather,
  // hier_reduce_scatter). Fixed knobs collapse their dimension, and the
  // reduce-scatter knob only opens when the job actually executes
  // reduce-scatters (sharded-update-aware: tuning it on an allreduce-only
  // job would score identical configurations).
  categorical_combos_.clear();
  std::vector<bool> cache_opts =
      cache_fixed_ ? std::vector<bool>{cache_enabled_}
                   : std::vector<bool>{true, false};
  std::vector<bool> har_opts =
      hier_ar_fixed_ ? std::vector<bool>{hierarchical_allreduce_}
                     : std::vector<bool>{false, true};
  std::vector<bool> hag_opts =
      hier_ag_fixed_ ? std::vector<bool>{hierarchical_allgather_}
                     : std::vector<bool>{false, true};
  std::vector<bool> hrs_opts =
      (hier_rs_fixed_ || !profile_reduce_scatter_)
          ? std::vector<bool>{hierarchical_reduce_scatter_}
          : std::vector<bool>{false, true};
  // The shm dimension only opens on an shm-capable topology (profile
  // bit): on a flat single-rank-per-host job every sample would score
  // an identical configuration.
  std::vector<bool> shm_opts =
      (shm_fixed_ || !profile_shm_) ? std::vector<bool>{shm_transport_}
                                    : std::vector<bool>{true, false};
  for (bool c : cache_opts) {
    for (bool ar : har_opts) {
      for (bool ag : hag_opts) {
        for (bool rs : hrs_opts) {
          for (bool sm : shm_opts) {
            categorical_combos_.push_back({c, ar, ag, rs, sm});
          }
        }
      }
    }
  }
  // Budget-aware combo depth: every combo gets at least two samples,
  // and the sample budget grows to cover the whole sweep when the
  // categorical space is large (16 combos on a hierarchical sharded
  // job) — a silently unvisited tail would make those configurations
  // unadoptable.
  int combos = static_cast<int>(categorical_combos_.size());
  samples_per_combo_ = std::max(2, max_samples_ / combos);
  max_samples_ = std::max(max_samples_, combos * samples_per_combo_);
  double chunk_lo = profile_compression_ ? kChunkLoKbCompressed : kChunkLoKb;
  double chunk_hi = profile_compression_ ? kChunkHiKbCompressed : kChunkHiKb;
  optimizers_.clear();
  for (std::size_t i = 0; i < categorical_combos_.size(); ++i) {
    // Seeds are salted by (elastic generation, re-arm epoch): every
    // tuning pass explores FRESH sample points for its regime instead
    // of re-walking the previous pass's trajectory — while staying
    // deterministic across ranks (both salts are synchronized state),
    // so the bootstrap's first sample is identical everywhere.
    optimizers_.push_back(std::make_unique<BayesianOptimizer>(
        std::vector<std::pair<double, double>>{{kFusionLo, kFusionHi},
                                               {kCycleLo, kCycleHi},
                                               {chunk_lo, chunk_hi}},
        /*seed=*/1234 + i + 1000003ull * seed_salt_ +
            7919ull * rearm_epoch_));
  }
}

// lockorder: requires(mu_)
void ParameterManager::Arm() {
  armed_once_ = true;
  active_ = true;
  warmup_remaining_ = warmup_samples_;
  cycles_in_sample_ = 0;
  bytes_in_sample_ = 0;
  sample_count_ = 0;
  combo_index_ = 0;
  samples_in_combo_ = 0;
  best_score_ = 0.0;
  baseline_pending_ = false;
  drift_bytes_acc_ = 0;
  drift_tensors_acc_ = 0;
  drift_cycles_acc_ = 0;
  BuildSearchSpace();
  ReadyTune();
}

void ParameterManager::SetAutoTuning(bool active) {
  std::lock_guard<std::mutex> lk(mu_);
  if (active) {
    Arm();
  } else {
    active_ = false;
  }
}

bool ParameterManager::IsAutoTuning() const {
  std::lock_guard<std::mutex> lk(mu_);
  return active_;
}

int64_t ParameterManager::TensorFusionThresholdBytes() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int64_t>(fusion_mb_ * 1024.0 * 1024.0);
}

void ParameterManager::SetTensorFusionThresholdBytes(int64_t threshold,
                                                     bool fixed) {
  std::lock_guard<std::mutex> lk(mu_);
  fusion_mb_ = static_cast<double>(threshold) / (1024.0 * 1024.0);
  fusion_fixed_ = fusion_fixed_ || fixed;
}

double ParameterManager::CycleTimeMs() const {
  std::lock_guard<std::mutex> lk(mu_);
  return cycle_time_ms_;
}

void ParameterManager::SetCycleTimeMs(double cycle_time_ms, bool fixed) {
  std::lock_guard<std::mutex> lk(mu_);
  cycle_time_ms_ = cycle_time_ms;
  cycle_fixed_ = cycle_fixed_ || fixed;
}

bool ParameterManager::CacheEnabled() const {
  std::lock_guard<std::mutex> lk(mu_);
  return cache_enabled_;
}

void ParameterManager::SetCacheEnabled(bool enabled, bool fixed) {
  std::lock_guard<std::mutex> lk(mu_);
  cache_enabled_ = enabled;
  cache_fixed_ = cache_fixed_ || fixed;
}

bool ParameterManager::HierarchicalAllreduce() const {
  std::lock_guard<std::mutex> lk(mu_);
  return hierarchical_allreduce_;
}

void ParameterManager::SetHierarchicalAllreduce(bool enabled, bool fixed) {
  std::lock_guard<std::mutex> lk(mu_);
  hierarchical_allreduce_ = enabled;
  hier_ar_fixed_ = hier_ar_fixed_ || fixed;
}

bool ParameterManager::HierarchicalAllgather() const {
  std::lock_guard<std::mutex> lk(mu_);
  return hierarchical_allgather_;
}

void ParameterManager::SetHierarchicalAllgather(bool enabled, bool fixed) {
  std::lock_guard<std::mutex> lk(mu_);
  hierarchical_allgather_ = enabled;
  hier_ag_fixed_ = hier_ag_fixed_ || fixed;
}

bool ParameterManager::HierarchicalReduceScatter() const {
  std::lock_guard<std::mutex> lk(mu_);
  return hierarchical_reduce_scatter_;
}

void ParameterManager::SetHierarchicalReduceScatter(bool enabled, bool fixed) {
  std::lock_guard<std::mutex> lk(mu_);
  hierarchical_reduce_scatter_ = enabled;
  hier_rs_fixed_ = hier_rs_fixed_ || fixed;
}

bool ParameterManager::ShmTransport() const {
  std::lock_guard<std::mutex> lk(mu_);
  return shm_transport_;
}

void ParameterManager::SetShmTransport(bool enabled, bool fixed) {
  std::lock_guard<std::mutex> lk(mu_);
  shm_transport_ = enabled;
  shm_fixed_ = shm_fixed_ || fixed;
}

int64_t ParameterManager::PipelineChunkBytes() const {
  std::lock_guard<std::mutex> lk(mu_);
  if (pipeline_chunk_kb_ <= 0.0) return 0;
  return static_cast<int64_t>(pipeline_chunk_kb_ * 1024.0);
}

void ParameterManager::SetPipelineChunkBytes(int64_t bytes, bool fixed) {
  std::lock_guard<std::mutex> lk(mu_);
  pipeline_chunk_kb_ = static_cast<double>(bytes) / 1024.0;
  pipeline_fixed_ = pipeline_fixed_ || fixed;
}

void ParameterManager::ObserveWorkload(bool compression_active,
                                       bool reduce_scatter_active,
                                       bool groups_active,
                                       bool shm_capable) {
  std::lock_guard<std::mutex> lk(mu_);
  // Sticky: once a capability is seen the search space stays shaped for
  // it (a job that did one sharded step will do more; a job that did
  // one subgroup collective is running a mesh).
  bool comp_changed = compression_active && !profile_compression_;
  bool rs_changed = reduce_scatter_active && !profile_reduce_scatter_;
  bool grp_changed = groups_active && !profile_groups_;
  bool shm_changed = shm_capable && !profile_shm_;
  if (!comp_changed && !rs_changed && !grp_changed && !shm_changed) return;
  profile_compression_ = profile_compression_ || compression_active;
  profile_reduce_scatter_ = profile_reduce_scatter_ || reduce_scatter_active;
  profile_groups_ = profile_groups_ || groups_active;
  profile_shm_ = profile_shm_ || shm_capable;
  TriggerRearm(rs_changed ? "profile-reduce-scatter"
                          : (comp_changed ? "profile-compression"
                                          : (grp_changed ? "profile-groups"
                                                         : "profile-shm")));
}

// lockorder: requires(mu_)
bool ParameterManager::TriggerRearm(const char* reason) {
  // Caller holds mu_. Re-arm subsumes any in-flight tuning pass: the
  // measurement regime just changed, so its samples are stale. Before
  // the first Arm() (the env-seeding window at init) there is nothing
  // to re-enter — the seed shapes the initial search space instead.
  if (rearm_pending_ || !armed_once_) return false;
  rearm_pending_ = true;
  last_rearm_reason_ = reason;
  LOG(INFO) << "autotune re-arm pending (" << reason << ")";
  return true;
}

bool ParameterManager::RearmPending() const {
  std::lock_guard<std::mutex> lk(mu_);
  return rearm_pending_;
}

uint64_t ParameterManager::WireEpochForBroadcast() {
  std::lock_guard<std::mutex> lk(mu_);
  if (rearm_pending_) {
    rearm_pending_ = false;
    ++rearm_epoch_;
    ++rearms_total_;
    LOG(INFO) << "autotune re-armed (epoch " << rearm_epoch_ << ", "
              << last_rearm_reason_ << ")";
    LogSample(0.0, last_rearm_reason_.empty() ? "rearm"
                                              : last_rearm_reason_.c_str());
    Arm();
  }
  uint64_t profile = (profile_compression_ ? kProfileCompression : 0) |
                     (profile_reduce_scatter_ ? kProfileReduceScatter : 0) |
                     (profile_groups_ ? kProfileGroups : 0) |
                     (profile_shm_ ? kProfileShm : 0);
  return (static_cast<uint64_t>(rearm_epoch_) << 8) | profile;
}

void ParameterManager::NoteWireEpoch(uint64_t wire) {
  std::lock_guard<std::mutex> lk(mu_);
  uint32_t epoch = static_cast<uint32_t>(wire >> 8);
  if (epoch == rearm_epoch_) return;
  rearm_epoch_ = epoch;
  ++rearms_total_;
  profile_compression_ = (wire & kProfileCompression) != 0;
  profile_reduce_scatter_ = (wire & kProfileReduceScatter) != 0;
  profile_groups_ = (wire & kProfileGroups) != 0;
  profile_shm_ = (wire & kProfileShm) != 0;
  // Deterministic mirror of the coordinator's Arm(): fresh optimizers
  // with fixed seeds propose the same first sample, so every rank holds
  // identical knob values from this cycle on.
  Arm();
}

uint32_t ParameterManager::rearm_epoch() const {
  std::lock_guard<std::mutex> lk(mu_);
  return rearm_epoch_;
}

uint64_t ParameterManager::rearms_total() const {
  std::lock_guard<std::mutex> lk(mu_);
  return rearms_total_;
}

// lockorder: requires(mu_)
void ParameterManager::ReadyTune() {
  // Apply the next sample point of the current categorical combo.
  if (combo_index_ >= categorical_combos_.size()) return;
  const auto& combo = categorical_combos_[combo_index_];
  if (!cache_fixed_) cache_enabled_ = combo[0];
  if (!hier_ar_fixed_) hierarchical_allreduce_ = combo[1];
  if (!hier_ag_fixed_) hierarchical_allgather_ = combo[2];
  if (!hier_rs_fixed_ && profile_reduce_scatter_) {
    hierarchical_reduce_scatter_ = combo[3];
  }
  if (!shm_fixed_ && profile_shm_) shm_transport_ = combo[4];
  auto next = optimizers_[combo_index_]->NextSample();
  if (!fusion_fixed_) fusion_mb_ = next[0];
  if (!cycle_fixed_) cycle_time_ms_ = next[1];
  if (!pipeline_fixed_) pipeline_chunk_kb_ = next[2];
}

// lockorder: requires(mu_)
void ParameterManager::LogSample(double score, const char* event) {
  if (!log_.is_open()) return;
  log_ << fusion_mb_ << "," << cycle_time_ms_ << "," << pipeline_chunk_kb_
       << "," << cache_enabled_ << "," << hierarchical_allreduce_ << ","
       << hierarchical_allgather_ << "," << hierarchical_reduce_scatter_
       << "," << shm_transport_ << "," << score << "," << event << "\n";
  log_.flush();
}

bool ParameterManager::Update(int64_t tensors, int64_t bytes) {
  std::lock_guard<std::mutex> lk(mu_);
  if (!active_) {
    // Closed-loop drift watch. Idle heartbeat cycles carry no workload
    // signal and are excluded. The FIRST window after convergence only
    // CAPTURES the baseline: per-cycle bytes depend on the knobs in
    // force (a 100ms probe cycle batches far more than a 1ms one for a
    // free-running producer), so a baseline averaged over the tuning
    // pass's heterogeneous samples would misread a steady workload as
    // drifted and re-arm forever. Measuring it under the ADOPTED knobs
    // makes the comparison knobs-consistent.
    if (tensors <= 0 && bytes <= 0) return false;
    drift_bytes_acc_ += bytes;
    drift_tensors_acc_ += tensors;
    if (++drift_cycles_acc_ < drift_window_cycles_) return false;
    double mean_bytes =
        static_cast<double>(drift_bytes_acc_) / drift_cycles_acc_;
    double mean_tensors =
        static_cast<double>(drift_tensors_acc_) / drift_cycles_acc_;
    drift_bytes_acc_ = 0;
    drift_tensors_acc_ = 0;
    drift_cycles_acc_ = 0;
    if (baseline_pending_) {
      baseline_bytes_per_cycle_ = mean_bytes;
      baseline_tensors_per_cycle_ = mean_tensors;
      baseline_pending_ = false;
      return false;
    }
    auto drifted = [&](double cur, double base) {
      if (base <= 0.0) return cur > 0.0;
      double ratio = cur / base;
      return ratio > drift_threshold_ || ratio < 1.0 / drift_threshold_;
    };
    if (drifted(mean_bytes, baseline_bytes_per_cycle_) ||
        drifted(mean_tensors, baseline_tensors_per_cycle_)) {
      TriggerRearm("workload-shift");
    }
    return false;
  }
  // Sampling only advances on work cycles: an always-on tuner paced by
  // idle heartbeats would churn knobs under a job that has not even
  // started training yet.
  if (tensors <= 0 && bytes <= 0) return false;
  if (cycles_in_sample_ == 0 && bytes_in_sample_ == 0) {
    sample_start_us_ = NowMicros();
  }
  bytes_in_sample_ += bytes;
  ++cycles_in_sample_;
  if (cycles_in_sample_ < cycles_per_sample_) return false;

  double elapsed_us = NowMicros() - sample_start_us_;
  double score = elapsed_us > 0
                     ? static_cast<double>(bytes_in_sample_) / elapsed_us
                     : 0.0;
  cycles_in_sample_ = 0;
  bytes_in_sample_ = 0;

  if (warmup_remaining_ > 0) {
    --warmup_remaining_;
    return false;
  }
  return Tune(score);
}

// lockorder: requires(mu_)
bool ParameterManager::Tune(double score) {
  LogSample(score, "sample");
  if (score > best_score_) {
    best_score_ = score;
    best_fusion_mb_ = fusion_mb_;
    best_cycle_ms_ = cycle_time_ms_;
    best_pipeline_kb_ = pipeline_chunk_kb_;
    best_cache_ = cache_enabled_;
    best_hier_ar_ = hierarchical_allreduce_;
    best_hier_ag_ = hierarchical_allgather_;
    best_hier_rs_ = hierarchical_reduce_scatter_;
    best_shm_ = shm_transport_;
  }
  optimizers_[combo_index_]->AddSample(
      {fusion_mb_, cycle_time_ms_, pipeline_chunk_kb_}, score);
  ++sample_count_;
  ++samples_in_combo_;
  if (samples_in_combo_ >= samples_per_combo_) {
    samples_in_combo_ = 0;
    ++combo_index_;
  }
  if (sample_count_ >= max_samples_ ||
      combo_index_ >= categorical_combos_.size()) {
    // Converged: adopt the best configuration, capture the workload
    // baseline the drift watch compares against, and stop tuning (the
    // drift watch / profile observer re-arms when the job changes).
    if (!fusion_fixed_) fusion_mb_ = best_fusion_mb_;
    if (!cycle_fixed_) cycle_time_ms_ = best_cycle_ms_;
    if (!pipeline_fixed_) pipeline_chunk_kb_ = best_pipeline_kb_;
    if (!cache_fixed_) cache_enabled_ = best_cache_;
    if (!hier_ar_fixed_) hierarchical_allreduce_ = best_hier_ar_;
    if (!hier_ag_fixed_) hierarchical_allgather_ = best_hier_ag_;
    if (!hier_rs_fixed_ && profile_reduce_scatter_) {
      hierarchical_reduce_scatter_ = best_hier_rs_;
    }
    if (!shm_fixed_ && profile_shm_) shm_transport_ = best_shm_;
    // The drift baseline is captured by the FIRST converged window
    // (see Update), under the knobs just adopted.
    baseline_pending_ = true;
    baseline_bytes_per_cycle_ = 0.0;
    baseline_tensors_per_cycle_ = 0.0;
    drift_bytes_acc_ = 0;
    drift_tensors_acc_ = 0;
    drift_cycles_acc_ = 0;
    active_ = false;
    LogSample(best_score_, "converged");
    LOG(INFO) << "autotune converged: fusion_mb=" << fusion_mb_
              << " cycle_ms=" << cycle_time_ms_
              << " pipeline_kb=" << pipeline_chunk_kb_
              << " cache=" << cache_enabled_
              << " hier_rs=" << hierarchical_reduce_scatter_
              << " shm=" << shm_transport_
              << " score=" << best_score_ << " bytes/us";
    return true;
  }
  ReadyTune();
  return true;
}

// lockorder: requires(mu_)
ParameterManager::Params ParameterManager::GetParamsLocked() const {
  Params p;
  p.fusion_mb = fusion_mb_;
  p.cycle_time_ms = cycle_time_ms_;
  p.pipeline_chunk_kb = pipeline_chunk_kb_;
  p.cache_enabled = cache_enabled_ ? 1 : 0;
  p.hierarchical_allreduce = hierarchical_allreduce_ ? 1 : 0;
  p.hierarchical_allgather = hierarchical_allgather_ ? 1 : 0;
  p.hierarchical_reduce_scatter = hierarchical_reduce_scatter_ ? 1 : 0;
  p.shm_transport = shm_transport_ ? 1 : 0;
  p.active = active_ ? 1 : 0;
  return p;
}

ParameterManager::Params ParameterManager::GetParams() const {
  std::lock_guard<std::mutex> lk(mu_);
  return GetParamsLocked();
}

void ParameterManager::SetParams(const Params& p) {
  std::lock_guard<std::mutex> lk(mu_);
  fusion_mb_ = p.fusion_mb;
  cycle_time_ms_ = p.cycle_time_ms;
  pipeline_chunk_kb_ = p.pipeline_chunk_kb;
  cache_enabled_ = p.cache_enabled != 0;
  hierarchical_allreduce_ = p.hierarchical_allreduce != 0;
  hierarchical_allgather_ = p.hierarchical_allgather != 0;
  hierarchical_reduce_scatter_ = p.hierarchical_reduce_scatter != 0;
  // Workers honor their own env pin: a rank launched with HVD_TPU_SHM=0
  // never negotiated segments, and adopting "on" from the coordinator
  // must not make its PEERS (who did negotiate with other ranks) expect
  // a transport this rank can't speak — fixed knobs are pinned on every
  // rank identically when env is job-wide, which SetShmTransport's
  // fixed flag enforces here.
  if (!shm_fixed_) shm_transport_ = p.shm_transport != 0;
  active_ = p.active != 0;
}

std::string ParameterManager::Json() const {
  std::lock_guard<std::mutex> lk(mu_);
  char buf[1024];
  std::snprintf(
      buf, sizeof(buf),
      "{\"active\":%s,\"rearm_epoch\":%u,\"rearms_total\":%llu,"
      "\"samples\":%d,\"best_score_bytes_per_us\":%.6g,"
      "\"last_rearm_reason\":\"%s\","
      "\"params\":{\"fusion_mb\":%.17g,\"cycle_time_ms\":%.17g,"
      "\"pipeline_chunk_kb\":%.17g,\"cache_enabled\":%s,"
      "\"hierarchical_allreduce\":%s,\"hierarchical_allgather\":%s,"
      "\"hierarchical_reduce_scatter\":%s,\"shm_transport\":%s},"
      "\"fixed\":{\"fusion\":%s,\"cycle\":%s,\"pipeline_chunk\":%s,"
      "\"cache\":%s,\"hierarchical_allreduce\":%s,"
      "\"hierarchical_allgather\":%s,\"hierarchical_reduce_scatter\":%s,"
      "\"shm_transport\":%s},"
      "\"profile\":{\"compression\":%s,\"reduce_scatter\":%s,"
      "\"groups\":%s,\"shm\":%s},"
      "\"baseline\":{\"bytes_per_cycle\":%.6g,\"tensors_per_cycle\":%.6g}}",
      active_ ? "true" : "false", rearm_epoch_,
      static_cast<unsigned long long>(rearms_total_), sample_count_,
      best_score_, last_rearm_reason_.c_str(), fusion_mb_, cycle_time_ms_,
      pipeline_chunk_kb_, cache_enabled_ ? "true" : "false",
      hierarchical_allreduce_ ? "true" : "false",
      hierarchical_allgather_ ? "true" : "false",
      hierarchical_reduce_scatter_ ? "true" : "false",
      shm_transport_ ? "true" : "false",
      fusion_fixed_ ? "true" : "false", cycle_fixed_ ? "true" : "false",
      pipeline_fixed_ ? "true" : "false", cache_fixed_ ? "true" : "false",
      hier_ar_fixed_ ? "true" : "false", hier_ag_fixed_ ? "true" : "false",
      hier_rs_fixed_ ? "true" : "false", shm_fixed_ ? "true" : "false",
      profile_compression_ ? "true" : "false",
      profile_reduce_scatter_ ? "true" : "false",
      profile_groups_ ? "true" : "false",
      profile_shm_ ? "true" : "false", baseline_bytes_per_cycle_,
      baseline_tensors_per_cycle_);
  return buf;
}

}  // namespace hvdtpu
