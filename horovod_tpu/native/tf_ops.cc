// TensorFlow custom-op kernels for horovod_tpu collectives.
//
// Makes allreduce/allgather/broadcast real graph nodes: they compose with
// tf.function, tf.gradients (gradients are registered on the Python side,
// horovod_tpu/tensorflow/mpi_ops.py) and SavedModel export, instead of
// tunnelling through tf.py_function. Capability parity with the reference
// async CPU kernels (/root/reference horovod/tensorflow/mpi_ops.cc:276-463);
// fresh implementation: kernels call the framework-agnostic handle-based
// C API of libhorovod_tpu.so (native/operations.cc), whose symbols are
// already in the process (loaded RTLD_GLOBAL by common/basics.py), and
// AsyncOpKernel completion rides a scheduled closure that blocks on the
// handle — no TF thread ever enters the core's background loop.
//
// Build: `make libhorovod_tpu_tf.so TF_CFLAGS=... TF_LDFLAGS=...` with the
// flags from tf.sysconfig (driven lazily by horovod_tpu/tensorflow).

#include <cstdint>
#include <string>
#include <vector>

#include "tensorflow/core/framework/common_shape_fns.h"
#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"

extern "C" {
int horovod_tpu_enqueue_allreduce(const char* name, const void* data,
                                  void* output, int ndim, const int64_t* shape,
                                  int dtype, double prescale, double postscale,
                                  int compression);
int horovod_tpu_default_compression();
int horovod_tpu_enqueue_allgather(const char* name, const void* data, int ndim,
                                  const int64_t* shape, int dtype);
int horovod_tpu_enqueue_broadcast(const char* name, const void* data,
                                  void* output, int ndim, const int64_t* shape,
                                  int dtype, int root_rank);
int horovod_tpu_wait(int handle);
const char* horovod_tpu_error_string(int handle);
int64_t horovod_tpu_allgather_bytes(int handle);
int64_t horovod_tpu_allgather_rank_dim(int handle, int rank);
int horovod_tpu_allgather_copy(int handle, void* out);
void horovod_tpu_release(int handle);
int horovod_tpu_size();
int horovod_tpu_initialized();
}

namespace {

using namespace tensorflow;  // NOLINT

// Values must match native/message.h DataType (same table as
// common/basics.py _NUMPY_TO_DTYPE).
int HvdDtype(DataType dt) {
  switch (dt) {
    case DT_UINT8: return 0;
    case DT_INT8: return 1;
    case DT_UINT16: return 2;
    case DT_INT16: return 3;
    case DT_INT32: return 4;
    case DT_INT64: return 5;
    case DT_HALF: return 6;
    case DT_FLOAT: return 7;
    case DT_DOUBLE: return 8;
    case DT_BOOL: return 9;
    case DT_BFLOAT16: return 10;
    default: return -1;
  }
}

std::vector<int64_t> ShapeVec(const Tensor& t) {
  std::vector<int64_t> dims(t.dims());
  for (int i = 0; i < t.dims(); ++i) dims[i] = t.dim_size(i);
  if (dims.empty()) dims.push_back(1);  // 0-d rides as shape (1,)
  return dims;
}

const void* DataPtr(const Tensor& t) {
  return static_cast<const void*>(t.tensor_data().data());
}

void* MutableDataPtr(Tensor* t) {
  return const_cast<char*>(t->tensor_data().data());
}

Status CheckReady(DataType dt, int* hvd_dtype) {
  if (!horovod_tpu_initialized()) {
    return errors::FailedPrecondition(
        "horovod_tpu is not initialized; call hvd.init() before running "
        "collectives");
  }
  *hvd_dtype = HvdDtype(dt);
  if (*hvd_dtype < 0) {
    return errors::InvalidArgument("unsupported dtype for horovod_tpu: ",
                                   DataTypeString(dt));
  }
  return Status();
}

// Completes `handle` off the TF executor thread, sets the op status and
// fires `done`. The captured tensors keep their buffers alive until the
// core's background thread has consumed them.
void FinishAsync(OpKernelContext* ctx, AsyncOpKernel::DoneCallback done,
                 int handle, Tensor input_ref) {
  Env::Default()->SchedClosure([ctx, done, handle, input_ref]() {
    if (horovod_tpu_wait(handle) != 0) {
      ctx->SetStatus(errors::Internal("horovod_tpu collective failed: ",
                                      horovod_tpu_error_string(handle)));
    }
    horovod_tpu_release(handle);
    done();
  });
}

class HorovodTpuAllreduceOp : public AsyncOpKernel {
 public:
  explicit HorovodTpuAllreduceOp(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("op_name", &op_name_));
    OP_REQUIRES_OK(c, c->GetAttr("average", &average_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale", &postscale_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    int hvd_dtype;
    OP_REQUIRES_OK_ASYNC(ctx, CheckReady(input.dtype(), &hvd_dtype), done);
    Tensor* output = nullptr;
    OP_REQUIRES_OK_ASYNC(
        ctx, ctx->allocate_output(0, input.shape(), &output), done);
    std::vector<int64_t> dims = ShapeVec(input);
    // `average` divides by the communicator size at run (not trace) time.
    double post = average_ ? postscale_ / horovod_tpu_size() : postscale_;
    // Wire compression rides the job-wide env default here (the TF
    // binding's Compression codecs stay tensor-level); negotiation
    // validates the mode cross-rank like any other param.
    int handle = horovod_tpu_enqueue_allreduce(
        op_name_.c_str(), DataPtr(input), MutableDataPtr(output),
        static_cast<int>(dims.size()), dims.data(), hvd_dtype, prescale_,
        post, horovod_tpu_default_compression());
    FinishAsync(ctx, done, handle, input);
  }

 private:
  std::string op_name_;
  bool average_;
  float prescale_, postscale_;
};

class HorovodTpuAllgatherOp : public AsyncOpKernel {
 public:
  explicit HorovodTpuAllgatherOp(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("op_name", &op_name_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor input = ctx->input(0);
    int hvd_dtype;
    OP_REQUIRES_OK_ASYNC(ctx, CheckReady(input.dtype(), &hvd_dtype), done);
    std::vector<int64_t> dims = ShapeVec(input);
    int handle = horovod_tpu_enqueue_allgather(
        op_name_.c_str(), DataPtr(input), static_cast<int>(dims.size()),
        dims.data(), hvd_dtype);
    // Output first-dim is only known at completion (ranks may gather
    // unequal slices): allocate inside the completion closure.
    Env::Default()->SchedClosure([ctx, done, handle, input]() {
      if (horovod_tpu_wait(handle) != 0) {
        ctx->SetStatus(errors::Internal("horovod_tpu allgather failed: ",
                                        horovod_tpu_error_string(handle)));
        horovod_tpu_release(handle);
        done();
        return;
      }
      int64_t first_dim = 0;
      for (int r = 0; r < horovod_tpu_size(); ++r) {
        int64_t d = horovod_tpu_allgather_rank_dim(handle, r);
        if (d < 0) {
          ctx->SetStatus(errors::Internal("allgather rank sizes missing"));
          horovod_tpu_release(handle);
          done();
          return;
        }
        first_dim += d;
      }
      TensorShape out_shape = input.shape();
      if (out_shape.dims() == 0) out_shape.AddDim(1);
      out_shape.set_dim(0, first_dim);
      Tensor* output = nullptr;
      Status s = ctx->allocate_output(0, out_shape, &output);
      if (s.ok()) {
        int64_t nbytes = horovod_tpu_allgather_bytes(handle);
        if (nbytes != static_cast<int64_t>(output->tensor_data().size())) {
          s = errors::Internal("allgather size mismatch");
        } else {
          horovod_tpu_allgather_copy(handle, MutableDataPtr(output));
        }
      }
      if (!s.ok()) ctx->SetStatus(s);
      horovod_tpu_release(handle);
      done();
    });
  }

 private:
  std::string op_name_;
};

class HorovodTpuBroadcastOp : public AsyncOpKernel {
 public:
  explicit HorovodTpuBroadcastOp(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("op_name", &op_name_));
    OP_REQUIRES_OK(c, c->GetAttr("root_rank", &root_rank_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    int hvd_dtype;
    OP_REQUIRES_OK_ASYNC(ctx, CheckReady(input.dtype(), &hvd_dtype), done);
    Tensor* output = nullptr;
    OP_REQUIRES_OK_ASYNC(
        ctx, ctx->allocate_output(0, input.shape(), &output), done);
    std::vector<int64_t> dims = ShapeVec(input);
    int handle = horovod_tpu_enqueue_broadcast(
        op_name_.c_str(), DataPtr(input), MutableDataPtr(output),
        static_cast<int>(dims.size()), dims.data(), hvd_dtype, root_rank_);
    FinishAsync(ctx, done, handle, input);
  }

 private:
  std::string op_name_;
  int root_rank_;
};

REGISTER_OP("HorovodTpuAllreduce")
    .Attr("T: {uint8, int8, uint16, int16, int32, int64, float16, float32, "
          "float64, bfloat16}")
    .Attr("op_name: string")
    .Attr("average: bool = false")
    .SetIsStateful()
    .Attr("prescale: float = 1.0")
    .Attr("postscale: float = 1.0")
    .Input("tensor: T")
    .Output("reduced: T")
    .SetShapeFn(shape_inference::UnchangedShape);

REGISTER_OP("HorovodTpuAllgather")
    .Attr("T: {uint8, int8, uint16, int16, int32, int64, float16, float32, "
          "float64, bool, bfloat16}")
    .Attr("op_name: string")
    .SetIsStateful()
    .Input("tensor: T")
    .Output("gathered: T")
    .SetShapeFn([](shape_inference::InferenceContext* c) {
      shape_inference::ShapeHandle in = c->input(0);
      if (!c->RankKnown(in)) {
        c->set_output(0, c->UnknownShape());
        return Status();
      }
      shape_inference::ShapeHandle out;
      // First dim becomes the (unknown until run time) gathered length.
      TF_RETURN_IF_ERROR(c->ReplaceDim(in, 0, c->UnknownDim(), &out));
      c->set_output(0, out);
      return Status();
    });

REGISTER_OP("HorovodTpuBroadcast")
    .Attr("T: {uint8, int8, uint16, int16, int32, int64, float16, float32, "
          "float64, bool, bfloat16}")
    .Attr("op_name: string")
    .Attr("root_rank: int")
    .SetIsStateful()
    .Input("tensor: T")
    .Output("broadcast: T")
    .SetShapeFn(shape_inference::UnchangedShape);

REGISTER_KERNEL_BUILDER(Name("HorovodTpuAllreduce").Device(DEVICE_CPU),
                        HorovodTpuAllreduceOp);
REGISTER_KERNEL_BUILDER(Name("HorovodTpuAllgather").Device(DEVICE_CPU),
                        HorovodTpuAllgatherOp);
REGISTER_KERNEL_BUILDER(Name("HorovodTpuBroadcast").Device(DEVICE_CPU),
                        HorovodTpuBroadcastOp);

}  // namespace
