"""Source-level lock-order audit for the native core (`make
check-lockorder`).

TSAN (`make check-tsan`) proves the absence of *data races it happens
to observe*; a lock-order inversion deadlocks without racing, so TSAN's
happened-before engine only reports it if both orders actually execute
in one run. This pass proves the stronger static property over
``native/*.cc`` / ``*.h`` directly:

* **mutex-acquisition graph**: every RAII acquisition
  (``std::lock_guard`` / ``std::unique_lock`` / ``std::scoped_lock``)
  and explicit ``mu_.lock()`` is scanned per function body with brace
  scoping; acquiring B while A is held adds edge A -> B (including
  one level through calls to functions whose *bare name uniquely*
  identifies a lock-acquiring function). A cycle in the graph is a
  potential deadlock, reported with every edge's acquisition site —
  the static analogue of the runtime's "both call sites" divergence
  report.
* **guard audit**: fields annotated ``// guarded_by(mu_)`` on their
  declaration must only be touched in method bodies while that mutex
  is held. Constructors/destructors are exempt (no concurrent access
  before/after the object's lifetime).
* **drift guard**: a file that declares a ``std::mutex`` (any flavor)
  but annotates ZERO guarded fields contributes nothing to the guard
  audit — new mutex-protected state silently escapes coverage. Such a
  file is itself a finding (``mutex-without-guarded-fields``) until
  its fields are annotated or the mutex is explicitly excused.
* **blocking-call-under-lock**: a socket send/recv/connect/accept, a
  ``FutexWait``, an fsync, or a sleep executed while a mutex is held
  turns every contender into a convoy and can deadlock against the
  very peer the call waits on. Condition-variable waits are exempt
  (they release the lock); the scan flags the raw calls only.
* **atomics-pairing**: the shm ring's wake protocol is only correct
  because the publisher's seq bump + waiters-flag load and the
  waiter's flag store + expected-seq load are ALL seq_cst (see
  shm_context.cc WriteSome/WaitReadable). A relaxed or release store
  feeding a *gated* ``FutexWake`` can commit after the gate's load in
  the SC order — the wake is skipped and the peer parks forever. The
  scan pairs every gated wake / ``FutexWait`` with its surrounding
  atomics and demands seq_cst on each side of the handshake.

Intentional exceptions are suppressed in-source with
``// lockorder: allow(rule-name[, rule-name])`` on the flagged line;
each suppression should carry a justification in the same comment.

The parser is a token scanner, not a C++ front end: it strips comments
and strings, tracks braces, and recognizes the repo's idioms (SURVEY
5.2 single-background-thread discipline keeps the native core's
locking shallow, which is exactly what makes this decidable here).
Findings are deliberately high-confidence — `make check-lockorder`
gates the sanitizer targets, so a false positive would block CI.
"""

import argparse
import collections
import os
import re
import sys

GUARD_RE = re.compile(
    r"\bstd::(?:lock_guard|unique_lock)\s*<[^>]*>\s*"
    r"(?P<var>\w+)\s*[({](?P<mu>[\w.\->:]+)")
SCOPED_RE = re.compile(
    r"\bstd::scoped_lock\s*(?:<[^>]*>)?\s*(?P<var>\w+)\s*"
    r"[({](?P<mus>[^;)]+)[)}]")
BARE_LOCK_RE = re.compile(r"\b(?P<mu>[\w.\->:]+?)\.lock\(\)")
BARE_UNLOCK_RE = re.compile(r"\b(?P<mu>[\w.\->:]+?)\.unlock\(\)")
FUNC_START_RE = re.compile(
    r"(?:(?P<cls>\w+(?:<[^<>]*>)?)::)?(?P<name>~?\w+)\s*\(")
_NOT_FUNCS = {"if", "for", "while", "switch", "return", "catch",
              "sizeof", "defined", "do", "else", "new", "delete",
              "assert", "static_assert", "alignof", "decltype",
              "constexpr", "throw"}
GUARDED_BY_RE = re.compile(r"guarded_by\((?P<mu>\w+)\)", re.I)
REQUIRES_RE = re.compile(r"lockorder:\s*requires\((?P<mu>\w+)\)")
FIELD_DECL_RE = re.compile(r"\b(?P<field>[a-zA-Z_]\w*_)\s*[;={(\[]")
CALL_RE = re.compile(r"\b(?P<name>[A-Z]\w+)\s*\(")
ALLOW_RE = re.compile(r"lockorder:\s*allow\(\s*(?P<rules>[\w\-, ]+?)\s*\)")
MUTEX_DECL_RE = re.compile(
    r"\bstd::(?:recursive_|shared_|timed_|recursive_timed_)?mutex\s+"
    r"(?P<name>\w+)\s*[;,={]")
# Calls that park/stall the calling thread: POSIX socket ops, futex
# parks, durability syscalls, sleeps. `cv_.wait()` is deliberately NOT
# here — a condition_variable wait releases the lock, which is the
# correct idiom; only raw blocking under a held mutex convoys.
BLOCKING_CALL_RE = re.compile(
    r"(?<![\w.>])(?P<call>send|recv|sendmsg|recvmsg|sendto|recvfrom|"
    r"connect|accept|accept4|poll|ppoll|select|pselect|fsync|"
    r"fdatasync|usleep|nanosleep|sleep_for|sleep_until|FutexWait)"
    r"\s*\(")
_ATOMIC_WORD = r"[\w.>\[\]-]+"
# `if (<waiters>.load(<order>) ...) { ... }` — the gated-wake shape.
WAKE_GATE_RE = re.compile(
    r"if\s*\(\s*(?P<waiters>%s)\.load\s*\((?P<order>[^()]*)\)"
    r"[^;{]*\)\s*\{(?P<body>[^{}]*)\}" % _ATOMIC_WORD, re.S)
FUTEX_WAKE_RE = re.compile(r"FutexWake\s*\(\s*&?(?P<word>%s)"
                           % _ATOMIC_WORD)
FUTEX_WAIT_RE = re.compile(r"FutexWait\s*\(\s*&?(?P<word>%s)"
                           % _ATOMIC_WORD)
WAITER_FLAG_STORE_RE = re.compile(
    r"\b(?P<flag>%s)\.store\s*\(\s*1\s*,\s*(?P<order>[^()]*)\)"
    % _ATOMIC_WORD)

Finding = collections.namedtuple(
    "Finding", ["rule", "path", "line", "message"])


def _harvest_allow(seg, line, allows):
    m = ALLOW_RE.search(seg)
    if m:
        allows.setdefault(line, set()).update(
            r.strip() for r in m.group("rules").split(",") if r.strip())


def _strip(source):
    """Removes comments and string/char literals (preserving line
    structure) but first harvests `guarded_by` annotations
    ({line_number: mutex_name}), `lockorder: requires(mu)` function
    preconditions ({line_number: mutex_name}), and
    `lockorder: allow(...)` suppressions ({line_number: set(rules)})."""
    annotations = {}
    requires = {}
    allows = {}

    def harvest(seg, line):
        m = GUARDED_BY_RE.search(seg)
        if m:
            annotations[line] = m.group("mu")
        m = REQUIRES_RE.search(seg)
        if m:
            requires[line] = m.group("mu")
        _harvest_allow(seg, line, allows)

    out = []
    i, n = 0, len(source)
    line = 1
    while i < n:
        ch = source[i]
        if ch == "\n":
            line += 1
            out.append(ch)
            i += 1
        elif source.startswith("//", i):
            j = source.find("\n", i)
            j = n if j < 0 else j
            harvest(source[i:j], line)
            i = j
        elif source.startswith("/*", i):
            j = source.find("*/", i)
            j = n if j < 0 else j + 2
            seg = source[i:j]
            harvest(seg, line)
            line += seg.count("\n")
            out.append("\n" * seg.count("\n"))
            i = j
        elif ch in "\"'":
            quote = ch
            j = i + 1
            while j < n:
                if source[j] == "\\":
                    j += 2
                    continue
                if source[j] == quote:
                    j += 1
                    break
                if source[j] == "\n":  # unterminated; bail on the line
                    break
                j += 1
            out.append(quote + quote)
            i = j
        else:
            out.append(ch)
            i += 1
    return "".join(out), annotations, requires, allows


class Acquisition(object):
    __slots__ = ("mutex", "depth", "var", "path", "line", "top_level")

    def __init__(self, mutex, depth, var, path, line, top_level):
        self.mutex = mutex
        self.depth = depth
        self.var = var
        self.path = path
        self.line = line
        self.top_level = top_level


class FunctionBody(object):
    __slots__ = ("qualname", "cls", "name", "path", "line", "text",
                 "start_line")

    def __init__(self, qualname, cls, name, path, line, text):
        self.qualname = qualname
        self.cls = cls
        self.name = name
        self.path = path
        self.line = line
        self.text = text
        self.start_line = line


def _norm_mutex(cls, token):
    """Canonical graph node for a mutex token: member mutexes qualify
    by class (the same field name in two classes is two locks); locals
    and globals keep their own name."""
    token = token.strip().lstrip("&*")
    token = token.replace("this->", "")
    if token.endswith("_") and cls:
        return "%s::%s" % (cls, token)
    return token


def _match_paren(text, open_idx, open_ch="(", close_ch=")"):
    depth = 0
    j = open_idx
    while j < len(text):
        if text[j] == open_ch:
            depth += 1
        elif text[j] == close_ch:
            depth -= 1
            if depth == 0:
                return j
        j += 1
    return -1


def _extract_functions(text, path):
    """Function bodies: identifier + matched parameter parens +
    optional const/noexcept/override/initializer-list + a brace block.
    Declarations (`;` before `{`) and calls are rejected by the scan;
    matches inside an already-captured body are skipped."""
    functions = []
    covered_end = -1
    for m in FUNC_START_RE.finditer(text):
        if m.start() < covered_end:
            continue  # inside the previous function's body
        name = m.group("name")
        if name in _NOT_FUNCS:
            continue
        close = _match_paren(text, m.end() - 1)
        if close < 0:
            continue
        # Walk from ')' to the body '{'; any ';' or '=' first means a
        # declaration, call, or initializer — not a definition.
        j = close + 1
        open_idx = -1
        while j < len(text):
            ch = text[j]
            if ch == "{":
                open_idx = j
                break
            if ch in ";=":
                break
            if ch == "(":  # initializer-list member init `: a_(1)`
                j = _match_paren(text, j)
                if j < 0:
                    break
                j += 1
                continue
            j += 1
        if open_idx < 0:
            continue
        end = _match_paren(text, open_idx, "{", "}")
        if end < 0:
            end = len(text) - 1
        body = text[open_idx:end + 1]
        line = text.count("\n", 0, open_idx) + 1
        cls = m.group("cls") or _enclosing_class(text, m.start())
        qual = "%s::%s" % (cls, name) if cls else name
        functions.append(FunctionBody(qual, cls, name, path, line, body))
        covered_end = end
    return functions


def _enclosing_class(text, pos):
    """Best-effort: the innermost `class X {` / `struct X {` whose brace
    block contains `pos` (inline methods in headers)."""
    best = None
    for m in re.finditer(r"\b(?:class|struct)\s+(\w+)[^;{]*\{", text):
        if m.end() > pos:
            break
        depth = 0
        j = m.end() - 1
        while j < len(text):
            if text[j] == "{":
                depth += 1
            elif text[j] == "}":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        if m.end() <= pos < j:
            best = m.group(1)
    return best


def _scan_function(fn, pre_held=()):
    """Walks one body; returns (edges, top_level_mutexes, accesses)
    where edges are (held, acquired, path, line), top_level_mutexes the
    locks taken while holding nothing (for one-level call edges), and
    accesses [(token_line, held_mutex_names_set)] for the guard audit —
    accesses is a callable mapping a regex to occurrences for
    efficiency. `pre_held` mutex tokens (a `lockorder: requires(mu)`
    annotation on the definition) are held on entry — the caller's
    contract — at depth 0 so no closing brace releases them."""
    text = fn.text
    edges = []
    top_level = []
    held = [Acquisition(_norm_mutex(fn.cls, tok), 0, "<requires>",
                        fn.path, fn.start_line, False)
            for tok in pre_held]
    depth = 0
    line = fn.start_line
    i = 0
    calls = []     # (name, line, held_snapshot)
    accesses = []  # (line, frozenset(held mutex names)) per source line
    line_held = {}
    lock_vars = {}  # guard-object var -> raw mutex token (for relock)

    def record_line():
        prev = line_held.get(line)
        cur = frozenset(a.mutex for a in held)
        line_held[line] = cur if prev is None else (prev | cur)

    while i < len(text):
        ch = text[i]
        if ch == "\n":
            record_line()
            line += 1
            i += 1
            continue
        if ch == "{":
            depth += 1
            i += 1
            continue
        if ch == "}":
            depth -= 1
            while held and held[-1].depth > depth:
                held.pop()
            i += 1
            continue
        # try the lock idioms at this position
        m = GUARD_RE.match(text, i)
        if m is None:
            m2 = SCOPED_RE.match(text, i)
            if m2 is not None:
                for tok in m2.group("mus").split(","):
                    _acquire(fn, tok, depth, m2.group("var"), line,
                             held, edges, top_level)
                i = m2.end()
                continue
            m3 = BARE_UNLOCK_RE.match(text, i)
            if m3 is not None:
                tok = _norm_mutex(fn.cls, m3.group("mu"))
                for k in range(len(held) - 1, -1, -1):
                    if held[k].mutex == tok or held[k].var == \
                            m3.group("mu").strip():
                        del held[k]
                        break
                i = m3.end()
                continue
            m4 = BARE_LOCK_RE.match(text, i)
            if m4 is not None:
                raw = m4.group("mu").strip()
                # `lk.lock()` re-locks the mutex its unique_lock was
                # constructed over (tracked in lock_vars); a direct
                # `mu_.lock()` names the mutex itself.
                _acquire(fn, lock_vars.get(raw, raw), depth, raw, line,
                         held, edges, top_level)
                i = m4.end()
                continue
            m5 = CALL_RE.match(text, i)
            if m5 is not None and held:
                calls.append((m5.group("name"), line,
                              tuple(a.mutex for a in held)))
                i = m5.end()
                continue
            record_line()
            i += 1
            continue
        _acquire(fn, m.group("mu"), depth, m.group("var"), line, held,
                 edges, top_level)
        lock_vars[m.group("var")] = m.group("mu")
        i = m.end()
    record_line()
    return edges, top_level, calls, line_held


def _acquire(fn, token, depth, var, line, held, edges, top_level):
    mutex = _norm_mutex(fn.cls, token)
    for prior in held:
        if prior.mutex != mutex:
            edges.append((prior.mutex, mutex, fn.path, line, fn.qualname))
    if not held:
        top_level.append(mutex)
    held.append(Acquisition(mutex, depth, var, fn.path, line,
                            not held))


def analyze_files(paths):
    """Returns (findings, stats)."""
    findings = []
    functions = []
    file_annotations = {}  # path -> {line: mutex}
    file_requires = {}     # path -> {line: mutex}
    file_allows = {}       # path -> {line: set(rule)}
    texts = {}
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8",
                      errors="replace") as fh:
                raw = fh.read()
        except OSError as e:
            findings.append(Finding(
                "io-error", path, 1, "cannot read: %s" % e))
            continue
        text, annotations, requires, allows = _strip(raw)
        texts[path] = text
        file_annotations[path] = annotations
        file_requires[path] = requires
        file_allows[path] = allows
        functions.extend(_extract_functions(text, path))

    # Pass 1: per-function scans.
    edges = []           # (A, B, path, line, func)
    acquires_by_name = collections.defaultdict(set)  # bare fn name
    top_by_name = collections.defaultdict(set)
    fn_results = []
    for fn in functions:
        # a `lockorder: requires(mu)` on the definition line (or the
        # line above it) means the caller holds `mu` on entry
        req = file_requires.get(fn.path, {})
        pre = [mu for mu in (req.get(fn.start_line),
                             req.get(fn.start_line - 1)) if mu]
        f_edges, top_level, calls, line_held = _scan_function(fn, pre)
        edges.extend(f_edges)
        fn_results.append((fn, calls, line_held))
        if top_level:
            acquires_by_name[fn.name].add(fn.qualname)
            top_by_name[fn.name].update(top_level)

    # Pass 2: one-level call edges — only through bare names that
    # UNIQUELY identify a lock-acquiring function (ambiguity would
    # fabricate edges and block CI on a false cycle).
    for fn, calls, _ in fn_results:
        for name, line, held_snapshot in calls:
            if name == fn.name or len(acquires_by_name.get(name,
                                                           ())) != 1:
                continue
            for target in top_by_name[name]:
                for held_mu in held_snapshot:
                    if held_mu != target:
                        edges.append((held_mu, target, fn.path, line,
                                      "%s (calls %s)" % (fn.qualname,
                                                         name)))

    # Pass 3: cycle detection over the acquisition graph.
    graph = collections.defaultdict(set)
    edge_sites = {}
    for a, b, path, line, func in edges:
        graph[a].add(b)
        edge_sites.setdefault((a, b), (path, line, func))
    for cycle in _find_cycles(graph):
        chain = []
        for i in range(len(cycle)):
            a, b = cycle[i], cycle[(i + 1) % len(cycle)]
            path, line, func = edge_sites[(a, b)]
            chain.append("%s -> %s at %s:%d in %s"
                         % (a, b, os.path.basename(path), line, func))
        path, line, _ = edge_sites[(cycle[0], cycle[1 % len(cycle)])]
        findings.append(Finding(
            "lock-order-cycle", path, line,
            "lock-order cycle %s: two threads taking these locks in "
            "the two different orders deadlock without racing (TSAN "
            "cannot prove this; the acquisition graph can). %s"
            % (" -> ".join(cycle + [cycle[0]]), "; ".join(chain))))

    # Pass 4: guarded-field audit (annotation-driven).
    guarded = _collect_guarded_fields(texts, file_annotations)
    for fn, _, line_held in fn_results:
        if fn.cls is None or fn.name == fn.cls or fn.name.startswith("~"):
            continue  # free function / constructor / destructor
        fields = guarded.get(fn.cls)
        if not fields:
            continue
        text = fn.text
        offset_line = fn.start_line
        for m in re.finditer(r"\b([a-zA-Z_]\w*_)\b", text):
            field = m.group(1)
            mu = fields.get(field)
            if mu is None:
                continue
            line = offset_line + text.count("\n", 0, m.start())
            held = line_held.get(line, frozenset())
            want = _norm_mutex(fn.cls, mu)
            if want in held:
                continue
            findings.append(Finding(
                "guarded-field-unlocked", fn.path, line,
                "field %s::%s is annotated guarded_by(%s) but %s "
                "touches it at %s:%d without holding %s — a data race "
                "the annotation promises cannot happen"
                % (fn.cls, field, mu, fn.qualname,
                   os.path.basename(fn.path), line, mu)))

    # Pass 5: drift guard — a file that declares a mutex but annotates
    # zero guarded fields gives the guard audit nothing to check; its
    # protected state is invisible to Pass 4 and stays that way as the
    # file grows. Annotating at least one field (or excusing the mutex
    # in-source) is the price of declaring one.
    mutex_files = 0
    for path in sorted(texts):
        text = texts[path]
        decl = MUTEX_DECL_RE.search(text)
        if decl is None:
            continue
        mutex_files += 1
        if _has_field_annotation(text, file_annotations[path]):
            continue
        line = text.count("\n", 0, decl.start()) + 1
        findings.append(Finding(
            "mutex-without-guarded-fields", path, line,
            "file declares mutex %s but annotates zero guarded_by "
            "fields — the guard audit covers none of this file's "
            "shared state, and new fields silently escape it; "
            "annotate the fields this mutex protects, or excuse it "
            "with `// lockorder: allow(mutex-without-guarded-fields)` "
            "plus a justification" % decl.group("name")))

    # Pass 6: blocking calls under a held mutex. A send/recv/futex/
    # fsync/sleep inside a critical section stalls every contender for
    # the lock's full syscall latency — and when the blocked-on peer
    # needs that same lock to make progress, it is a deadlock no
    # acquisition-order analysis can see.
    for fn, _, line_held in fn_results:
        for m in BLOCKING_CALL_RE.finditer(fn.text):
            line = fn.start_line + fn.text.count("\n", 0, m.start())
            held = line_held.get(line, frozenset())
            if not held:
                continue
            findings.append(Finding(
                "blocking-call-under-lock", fn.path, line,
                "%s calls %s() while holding %s — the lock is pinned "
                "across a call that can block indefinitely, convoying "
                "every contender (and deadlocking if the peer this "
                "call waits on needs the same lock); move the call "
                "outside the critical section"
                % (fn.qualname, m.group("call"),
                   ", ".join(sorted(held)))))

    # Pass 7: atomics pairing around the futex wake protocol.
    for fn, _, _ in fn_results:
        _audit_atomics(fn, findings)

    # Suppressions: `lockorder: allow(rule)` on the flagged line, or on
    # the line directly above it (trailing comments don't fit next to a
    # long C++ statement; comment-above is the NOLINTNEXTLINE idiom).
    suppressed = 0
    kept = []
    for f in findings:
        allows = file_allows.get(f.path, {})
        if (f.rule in allows.get(f.line, ())
                or f.rule in allows.get(f.line - 1, ())):
            suppressed += 1
            continue
        kept.append(f)

    stats = {"files": len(texts), "functions": len(functions),
             "edges": len(set((a, b) for a, b, _, _, _ in edges)),
             "guarded_fields": sum(len(v) for v in guarded.values()),
             "mutex_files": mutex_files,
             "suppressed": suppressed}
    return kept, stats


def _has_field_annotation(text, annotations):
    """True if at least one guarded_by annotation sits on a field
    declaration line (an annotation on a non-field line is harvested
    but resolves to nothing in Pass 4 — it must not satisfy the drift
    guard)."""
    lines = text.split("\n")
    for line_no in annotations:
        if (0 < line_no <= len(lines)
                and FIELD_DECL_RE.search(lines[line_no - 1])):
            return True
    return False


def _audit_atomics(fn, findings):
    """The shm ring's missed-wake-free handshake (shm_context.cc
    WriteSome :296-305 / WaitReadable :364-376 and their write-side
    mirrors) needs seq_cst at all four corners:

      publisher:  seq.fetch_add(seq_cst);  if (waiters.load(seq_cst))
                  FutexWake(&seq);
      waiter:     waiters.store(1, seq_cst);  exp = seq.load(seq_cst);
                  recheck; FutexWait(&seq, exp);

    Weaken ANY one of them and there is an SC execution where the
    publisher misses the waiter flag AND the waiter misses the bump —
    the wake is skipped and the waiter parks for its full timeout
    (hvd-model's shm_ring[missed_wake] seeded bug is exactly this).
    An *unconditional* FutexWake (the Close() hangup path) has no such
    dependency and release ordering suffices — only gated wakes and
    waits are audited."""
    text = fn.text

    def lineof(pos):
        return fn.start_line + text.count("\n", 0, pos)

    for m in WAKE_GATE_RE.finditer(text):
        wake = FUTEX_WAKE_RE.search(m.group("body"))
        if wake is None:
            continue
        if "seq_cst" not in m.group("order"):
            findings.append(Finding(
                "atomics-pairing", fn.path, lineof(m.start()),
                "%s gates FutexWake(&%s) on %s.load(%s) — the gate "
                "load must be seq_cst to pair with the waiter's "
                "seq_cst flag store, or the publisher can miss a "
                "parked waiter"
                % (fn.qualname, wake.group("word"), m.group("waiters"),
                   m.group("order").strip() or "<relaxed>")))
        word = wake.group("word")
        pub = None
        for pm in re.finditer(
                re.escape(word) + r"\.(?:fetch_add|store)\s*"
                r"\(([^()]*)\)", text[:m.start()]):
            pub = pm
        if pub is not None and "seq_cst" not in pub.group(1):
            findings.append(Finding(
                "atomics-pairing", fn.path, lineof(pub.start()),
                "%s publishes %s with ordering (%s) but its wake is "
                "gated on a waiters flag — a store weaker than "
                "seq_cst can commit after the gate's load in the SC "
                "order, skipping the wake while the peer parks; the "
                "publish and the gate load must both be seq_cst"
                % (fn.qualname, word, pub.group(1).strip())))

    for m in FUTEX_WAIT_RE.finditer(text):
        word = m.group("word")
        before = text[:m.start()]
        flag = None
        for sm in WAITER_FLAG_STORE_RE.finditer(before):
            flag = sm
        if flag is not None and "seq_cst" not in flag.group("order"):
            findings.append(Finding(
                "atomics-pairing", fn.path, lineof(flag.start()),
                "%s announces its park via %s.store(1, %s) before "
                "FutexWait(&%s) — the flag store must be seq_cst so "
                "the publisher's gate load observes it; anything "
                "weaker allows a missed wake"
                % (fn.qualname, flag.group("flag"),
                   flag.group("order").strip(), word)))
        exp = None
        for lm in re.finditer(
                re.escape(word) + r"\.load\s*\(([^()]*)\)", before):
            exp = lm
        if exp is not None and "seq_cst" not in exp.group(1):
            findings.append(Finding(
                "atomics-pairing", fn.path, lineof(exp.start()),
                "%s loads the FutexWait expected value %s.load(%s) "
                "with an ordering weaker than seq_cst — the load can "
                "hoist above the waiter-flag store and miss the "
                "publisher's bump, so the kernel compare passes on a "
                "stale value and the wait parks through a wake"
                % (fn.qualname, word, exp.group(1).strip())))


def _collect_guarded_fields(texts, file_annotations):
    """{class: {field: mutex}} from `// guarded_by(mu)` annotations on
    field declaration lines."""
    guarded = collections.defaultdict(dict)
    for path, annotations in file_annotations.items():
        if not annotations:
            continue
        text = texts[path]
        lines = text.split("\n")
        for line_no, mu in annotations.items():
            if line_no - 1 >= len(lines):
                continue
            decl = lines[line_no - 1]
            fm = FIELD_DECL_RE.search(decl)
            if fm is None:
                continue
            # byte offset of the line for class resolution
            pos = sum(len(l) + 1 for l in lines[:line_no - 1])
            cls = _enclosing_class(text, pos)
            if cls is None:
                continue
            guarded[cls][fm.group("field")] = mu
    return guarded


def _find_cycles(graph):
    """Simple cycles via DFS, deduplicated by node set (a cycle is one
    finding, not one per rotation)."""
    cycles = []
    seen_sets = set()

    def dfs(start, node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(list(path))
            elif nxt not in on_path and nxt > start:
                # node ordering prunes rotations: only explore nodes
                # "greater" than the start so each cycle is found once
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()
    # self-deadlock: A -> A (re-acquiring a non-recursive mutex)
    for a in sorted(graph):
        if a in graph[a]:
            key = frozenset((a,))
            if key not in seen_sets:
                seen_sets.add(key)
                cycles.append([a])
    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def iter_sources(paths):
    exts = (".cc", ".h", ".cpp", ".hpp", ".cxx")
    for path in paths:
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                if name.endswith(exts):
                    yield os.path.join(path, name)
        else:
            yield path


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="lockorder",
        description="Static lock-order + guard audit over the native "
                    "core (docs/LINT.md; `make check-lockorder`).")
    parser.add_argument("paths", nargs="*",
                        help="files/directories (default: this "
                             "module's own native/ directory)")
    parser.add_argument("--stats", action="store_true",
                        help="print graph statistics to stderr")
    args = parser.parse_args(argv)

    paths = args.paths or [os.path.dirname(os.path.abspath(__file__))]
    files = list(iter_sources(paths))
    findings, stats = analyze_files(files)
    for f in findings:
        sys.stdout.write("%s:%d: [%s] %s\n"
                         % (f.path, f.line, f.rule, f.message))
    if args.stats or not findings:
        sys.stderr.write(
            "check-lockorder: %d file(s), %d function(s), %d "
            "acquisition edge(s), %d guarded field(s), %d "
            "mutex-declaring file(s), %d suppression(s): %s\n"
            % (stats["files"], stats["functions"], stats["edges"],
               stats["guarded_fields"], stats["mutex_files"],
               stats["suppressed"],
               "clean" if not findings else
               "%d finding(s)" % len(findings)))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
