#include "fusion_buffer_manager.h"

namespace hvdtpu {

Status FusionBufferManager::InitializeBuffer(int64_t threshold, int32_t key) {
  auto& buf = buffers_[key];
  if (buf == nullptr || static_cast<int64_t>(buf->size()) < threshold) {
    try {
      buf = std::make_shared<std::vector<char>>(
          static_cast<std::size_t>(threshold));
    } catch (const std::bad_alloc&) {
      return Status::UnknownError("failed to allocate fusion buffer");
    }
  }
  return Status::OK();
}

void* FusionBufferManager::GetBuffer(int32_t key) {
  auto it = buffers_.find(key);
  return it == buffers_.end() ? nullptr : it->second->data();
}

int64_t FusionBufferManager::GetSize(int32_t key) {
  auto it = buffers_.find(key);
  return it == buffers_.end() ? 0 : static_cast<int64_t>(it->second->size());
}

}  // namespace hvdtpu
