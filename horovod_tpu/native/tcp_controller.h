// Controller implementation over the host-network TcpContext.
// Role parity with /root/reference horovod/common/gloo/gloo_controller.{h,cc}
// and mpi/mpi_controller.{h,cc}: rank discovery + the four cross-rank
// negotiation primitives, with size==1 short-circuits.
#ifndef HVD_TPU_TCP_CONTROLLER_H
#define HVD_TPU_TCP_CONTROLLER_H

#include "controller.h"
#include "tcp_context.h"

namespace hvdtpu {

class TcpController : public Controller {
 public:
  TcpController(ResponseCache& response_cache, TensorQueue& tensor_queue,
                Timeline& timeline, ParameterManager& parameter_manager,
                TcpContext& tcp_context)
      : Controller(response_cache, tensor_queue, timeline, parameter_manager),
        tcp_context_(tcp_context) {}

  void Initialize() override;

  void GatherBlobs(const std::string& mine,
                   std::vector<std::string>* all) override;
  void BroadcastBlob(std::string* blob) override;
  void CrossRankBitwiseAnd(std::vector<uint64_t>& bits) override;
  void CrossRankBitwiseOr(std::vector<uint64_t>& bits) override;
  void Barrier() override;

 private:
  TcpContext& tcp_context_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_TCP_CONTROLLER_H
