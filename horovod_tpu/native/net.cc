#include "net.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdlib>
#include <cstring>
#include <thread>

#include "logging.h"

namespace hvdtpu {

static constexpr uint32_t kHandshakeMagic = 0x48564454;  // "HVDT"

Conn::~Conn() { Close(); }

Conn& Conn::operator=(Conn&& o) noexcept {
  if (this != &o) {
    Close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

void Conn::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Conn::SendAll(const void* buf, std::size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd_, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += n;
    len -= static_cast<std::size_t>(n);
  }
  return true;
}

bool Conn::RecvAll(void* buf, std::size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd_, p, len, 0);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += n;
    len -= static_cast<std::size_t>(n);
  }
  return true;
}

bool Conn::SendFrame(uint32_t tag, const void* payload, std::size_t len) {
  char hdr[12];
  uint64_t len64 = len;
  std::memcpy(hdr, &tag, 4);
  std::memcpy(hdr + 4, &len64, 8);
  if (!SendAll(hdr, 12)) return false;
  if (len > 0 && !SendAll(payload, len)) return false;
  return true;
}

bool Conn::RecvFrame(uint32_t* tag, std::string* payload) {
  char hdr[12];
  if (!RecvAll(hdr, 12)) return false;
  uint64_t len64;
  std::memcpy(tag, hdr, 4);
  std::memcpy(&len64, hdr + 4, 8);
  payload->resize(len64);
  if (len64 > 0 && !RecvAll(&(*payload)[0], len64)) return false;
  return true;
}

bool Conn::RecvFrameInto(uint32_t* tag, void* buf, std::size_t expected_len) {
  char hdr[12];
  if (!RecvAll(hdr, 12)) return false;
  uint64_t len64;
  std::memcpy(tag, hdr, 4);
  std::memcpy(&len64, hdr + 4, 8);
  if (len64 != expected_len) {
    LOG(ERROR) << "frame length mismatch: got " << len64 << " expected "
               << expected_len;
    return false;
  }
  return expected_len == 0 || RecvAll(buf, expected_len);
}

Listener::~Listener() { Close(); }

void Listener::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Listener::Start(int port) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) return false;
  int one = 1;
  ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  // Dynamic rendezvous holds the reserved ephemeral port open in a
  // bound (never listening) Python socket until init completes, so no
  // other process can be handed it; binding alongside that reservation
  // requires SO_REUSEPORT on both. Only set when the port is such a
  // reservation — fixed-port configs keep strict EADDRINUSE semantics.
  const char* held = std::getenv("HVD_TPU_LISTEN_REUSEPORT");
  if (held && held[0] == '1') {
    ::setsockopt(fd_, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  }
  sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    LOG(ERROR) << "bind failed on port " << port << ": " << strerror(errno);
    Close();
    return false;
  }
  if (::listen(fd_, 128) != 0) {
    Close();
    return false;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
  port_ = ntohs(addr.sin_port);
  return true;
}

int Listener::AcceptPeer(int* peer_rank, Channel* channel, int timeout_ms) {
  if (timeout_ms >= 0) {
    struct pollfd pfd = {fd_, POLLIN, 0};
    int r = ::poll(&pfd, 1, timeout_ms);
    if (r <= 0) return -1;
  }
  int cfd = ::accept(fd_, nullptr, nullptr);
  if (cfd < 0) return -1;
  int one = 1;
  ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  char hs[9];
  std::size_t got = 0;
  while (got < sizeof(hs)) {
    ssize_t n = ::recv(cfd, hs + got, sizeof(hs) - got, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      ::close(cfd);
      return -1;
    }
    got += static_cast<std::size_t>(n);
  }
  uint32_t magic;
  int32_t rank;
  std::memcpy(&magic, hs, 4);
  std::memcpy(&rank, hs + 4, 4);
  if (magic != kHandshakeMagic) {
    LOG(ERROR) << "bad handshake magic";
    ::close(cfd);
    return -1;
  }
  *peer_rank = rank;
  *channel = static_cast<Channel>(hs[8]);
  return cfd;
}

Conn ConnectPeer(const std::string& host, int port, int my_rank,
                 Channel channel, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    struct addrinfo hints;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    int fd = -1;
    if (::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) == 0) {
      for (auto* ai = res; ai != nullptr; ai = ai->ai_next) {
        fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
        ::close(fd);
        fd = -1;
      }
      ::freeaddrinfo(res);
    }
    if (fd >= 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      Conn c(fd);
      char hs[9];
      std::memcpy(hs, &kHandshakeMagic, 4);
      int32_t r32 = my_rank;
      std::memcpy(hs + 4, &r32, 4);
      hs[8] = static_cast<char>(channel);
      if (c.SendAll(hs, 9)) return c;
    }
    if (std::chrono::steady_clock::now() >= deadline) {
      LOG(ERROR) << "connect to " << host << ":" << port << " timed out";
      return Conn();
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

bool ParseHostPort(const std::string& s, std::string* host, int* port) {
  auto pos = s.rfind(':');
  if (pos == std::string::npos) return false;
  *host = s.substr(0, pos);
  *port = std::atoi(s.c_str() + pos + 1);
  return *port > 0;
}

std::vector<std::string> SplitString(const std::string& s, char sep) {
  std::vector<std::string> out;
  std::size_t start = 0;
  while (start <= s.size()) {
    auto pos = s.find(sep, start);
    if (pos == std::string::npos) {
      out.push_back(s.substr(start));
      break;
    }
    out.push_back(s.substr(start, pos - start));
    start = pos + 1;
  }
  if (!out.empty() && out.back().empty()) out.pop_back();
  return out;
}

}  // namespace hvdtpu
