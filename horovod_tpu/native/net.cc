#include "net.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <thread>

#include "checksum.h"
#include "fault.h"
#include "logging.h"
#include "metrics.h"
#include "shm_context.h"

namespace hvdtpu {

// v2 magic ("HVDU"): bumped from the pre-checksum "HVDT" so a
// mixed-version pairing fails loudly at handshake instead of as a
// baffling checksum mismatch on frame 0.
static constexpr uint32_t kHandshakeMagic = 0x48564455;

const char* NetErrorName(NetError e) {
  switch (e) {
    case NetError::NONE: return "ok";
    case NetError::CLOSED: return "connection closed by peer";
    case NetError::TIMEOUT: return "I/O deadline expired (hung peer?)";
    case NetError::CRC: return "frame checksum mismatch (corrupted frame)";
    case NetError::TOO_BIG: return "frame length exceeds HVD_TPU_MAX_FRAME_BYTES";
    case NetError::PROTOCOL: return "malformed frame";
  }
  return "?";
}

// ---------------- knobs (env, cached) ----------------

static long long EnvLL(const char* name, long long dflt) {
  const char* v = std::getenv(name);
  return v == nullptr ? dflt : std::strtoll(v, nullptr, 10);
}

std::size_t MaxFrameBytes() {
  static std::size_t v = [] {
    long long b = EnvLL("HVD_TPU_MAX_FRAME_BYTES", 1ll << 30);
    if (b < 4096) b = 4096;  // floor: control frames must still fit
    return static_cast<std::size_t>(b);
  }();
  return v;
}

int NetTimeoutSeconds() {
  static int v = [] {
    // Default rides the control poll window so the two deadline layers
    // agree (the oversubscribed 1024-rank sweep raises both via the
    // poll env; see tcp_context.cc ControlPollMs).
    long long s = EnvLL("HVD_TPU_NET_TIMEOUT_SECONDS",
                        EnvLL("HVD_TPU_CONTROL_POLL_TIMEOUT_SECONDS", 60));
    if (s <= 0) s = 60;
    if (s > 2147483) s = 2147483;
    return static_cast<int>(s);
  }();
  return v;
}

bool NetCrcEnabled() {
  static bool v = [] {
    const char* e = std::getenv("HVD_TPU_NET_CRC");
    return e == nullptr || e[0] != '0';
  }();
  return v;
}

static int KeepaliveSeconds() {
  static int v = [] {
    long long s = EnvLL("HVD_TPU_NET_KEEPALIVE_SECONDS", 10);
    if (s > 32767) s = 32767;
    return static_cast<int>(s);
  }();
  return v;
}

static void SetSocketTimeouts(int fd, int seconds) {
  struct timeval tv;
  tv.tv_sec = seconds;
  tv.tv_usec = 0;
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

void ConfigureSocket(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  SetSocketTimeouts(fd, NetTimeoutSeconds());
  // Keepalive: a powered-off host sends no RST — without probes its
  // connections stay ESTABLISHED until the first write times out.
  // idle/intvl/cnt tuned so a vanished peer is detected in roughly
  // idle + 3*intvl seconds rather than the kernel's two hours.
  int idle = KeepaliveSeconds();
  if (idle > 0) {
    ::setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
#ifdef TCP_KEEPIDLE
    ::setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
    int intvl = idle / 3 > 0 ? idle / 3 : 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
    int cnt = 3;
    ::setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
#endif
  }
}

// ---------------- frame header ----------------

void BuildFrameHeader(char* hdr, uint32_t tag, uint64_t len, uint32_t crc) {
  std::memcpy(hdr, &tag, 4);
  std::memcpy(hdr + 4, &len, 8);
  std::memcpy(hdr + 12, &crc, 4);
}

void ParseFrameHeader(const char* hdr, uint32_t* tag, uint64_t* len,
                      uint32_t* crc) {
  std::memcpy(tag, hdr, 4);
  std::memcpy(len, hdr + 4, 8);
  std::memcpy(crc, hdr + 12, 4);
}

uint32_t FrameHeaderCrc(uint32_t tag, uint64_t len) {
  char prefix[12];
  std::memcpy(prefix, &tag, 4);
  std::memcpy(prefix + 4, &len, 8);
  return Crc32c(prefix, sizeof(prefix));
}

uint32_t FrameCrc(uint32_t tag, uint64_t len, const void* payload,
                  std::size_t n) {
  if (!NetCrcEnabled()) return 0;
  uint32_t crc = FrameHeaderCrc(tag, len);
  if (n > 0) crc = Crc32c(payload, n, crc);
  return crc;
}

// ---------------- Conn ----------------

Conn::~Conn() { Close(); }

Conn& Conn::operator=(Conn&& o) noexcept {
  if (this != &o) {
    Close();
    fd_ = o.fd_;
    channel_ = o.channel_;
    shm_ = o.shm_;
    o.fd_ = -1;
    o.shm_ = nullptr;
  }
  return *this;
}

void Conn::AttachShm(ShmRing* ring) {
  if (shm_ != nullptr) delete shm_;
  shm_ = ring;
}

void Conn::Close() {
  if (shm_ != nullptr) {
    delete shm_;  // ShmRing::~ShmRing closes + wakes the peer
    shm_ = nullptr;
  }
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Conn::SetTimeouts(int seconds) {
  if (fd_ >= 0) SetSocketTimeouts(fd_, seconds);
}

void Conn::NoteIoError(ssize_t n, bool sending) {
  if (n == 0) {
    last_error_ = NetError::CLOSED;
    return;
  }
  if (errno == EAGAIN || errno == EWOULDBLOCK) {
    // Blocking socket + SO_RCVTIMEO/SO_SNDTIMEO: EAGAIN means the
    // deadline expired with the peer silent — the hung-peer signal.
    last_error_ = NetError::TIMEOUT;
    Metrics& m = GlobalMetrics();
    (sending ? m.net_send_timeouts_total : m.net_recv_timeouts_total)
        .fetch_add(1, std::memory_order_relaxed);
    return;
  }
  last_error_ = NetError::CLOSED;
}

bool Conn::SendAll(const void* buf, std::size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd_, p, len, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      NoteIoError(n, /*sending=*/true);
      return false;
    }
    p += n;
    len -= static_cast<std::size_t>(n);
  }
  return true;
}

bool Conn::RecvAll(void* buf, std::size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd_, p, len, 0);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      NoteIoError(n, /*sending=*/false);
      return false;
    }
    p += n;
    len -= static_cast<std::size_t>(n);
  }
  return true;
}

bool Conn::SendFrame(uint32_t tag, const void* payload, std::size_t len) {
  last_error_ = NetError::NONE;
  uint32_t crc = FrameCrc(tag, len, payload, len);
  FaultInjector& inj = GlobalFaultInjector();
  std::string corrupted;
  if (inj.active()) {
    FaultDecision d = inj.OnFrame(channel_, /*send=*/true);
    switch (d.action) {
      case FaultAction::DROP:
        return true;  // silently not sent: the peer's deadline must fire
      case FaultAction::DELAY:
      case FaultAction::STALL:
        std::this_thread::sleep_for(std::chrono::milliseconds(d.delay_ms));
        break;
      case FaultAction::CLOSE:
        Close();
        break;
      case FaultAction::CORRUPT:
        // Flip one payload byte AFTER the CRC was computed: the wire
        // carries corrupted data with an honest checksum, exactly what
        // a flaky NIC produces. Zero-length frames flip the crc itself.
        if (len > 0) {
          corrupted.assign(static_cast<const char*>(payload), len);
          corrupted[len / 2] ^= 0x20;
          payload = corrupted.data();
        } else {
          crc ^= 0x1;
        }
        break;
      case FaultAction::NONE:
        break;
    }
  }
  char hdr[kFrameHeaderBytes];
  BuildFrameHeader(hdr, tag, len, crc);
  if (!SendAll(hdr, sizeof(hdr))) return false;
  if (len > 0 && !SendAll(payload, len)) return false;
  return true;
}

bool Conn::RecvFrame(uint32_t* tag, std::string* payload) {
  last_error_ = NetError::NONE;
  FaultInjector& inj = GlobalFaultInjector();
  bool corrupt_in = false;
  if (inj.active()) {
    FaultDecision d = inj.OnFrame(channel_, /*send=*/false);
    switch (d.action) {
      case FaultAction::DELAY:
      case FaultAction::STALL:
        std::this_thread::sleep_for(std::chrono::milliseconds(d.delay_ms));
        break;
      case FaultAction::CLOSE:
        Close();
        break;
      case FaultAction::CORRUPT:
        corrupt_in = true;
        break;
      default:
        break;  // drop is send-side only
    }
  }
  char hdr[kFrameHeaderBytes];
  if (!RecvAll(hdr, sizeof(hdr))) return false;
  uint64_t len64;
  uint32_t crc;
  ParseFrameHeader(hdr, tag, &len64, &crc);
  if (len64 > MaxFrameBytes()) {
    // One corrupted length field must mean a detected error, not an
    // attempted multi-terabyte allocation.
    LOG(ERROR) << "frame length " << len64 << " exceeds max "
               << MaxFrameBytes() << " — rejecting (corrupt frame?)";
    last_error_ = NetError::TOO_BIG;
    GlobalMetrics().net_oversize_frames_total.fetch_add(
        1, std::memory_order_relaxed);
    return false;
  }
  payload->resize(len64);
  if (len64 > 0 && !RecvAll(&(*payload)[0], len64)) return false;
  if (corrupt_in && len64 > 0) (*payload)[len64 / 2] ^= 0x20;
  if (NetCrcEnabled() &&
      FrameCrc(*tag, len64, payload->data(), payload->size()) != crc) {
    LOG(ERROR) << "frame checksum mismatch (tag " << *tag << ", len "
               << len64 << ") — corrupted frame detected";
    last_error_ = NetError::CRC;
    GlobalMetrics().net_crc_errors_total.fetch_add(1,
                                                   std::memory_order_relaxed);
    return false;
  }
  return true;
}

bool Conn::RecvFrameInto(uint32_t* tag, void* buf, std::size_t expected_len) {
  last_error_ = NetError::NONE;
  FaultInjector& inj = GlobalFaultInjector();
  bool corrupt_in = false;
  if (inj.active()) {
    FaultDecision d = inj.OnFrame(channel_, /*send=*/false);
    switch (d.action) {
      case FaultAction::DELAY:
      case FaultAction::STALL:
        std::this_thread::sleep_for(std::chrono::milliseconds(d.delay_ms));
        break;
      case FaultAction::CLOSE:
        Close();
        break;
      case FaultAction::CORRUPT:
        corrupt_in = true;
        break;
      default:
        break;
    }
  }
  char hdr[kFrameHeaderBytes];
  if (!RecvAll(hdr, sizeof(hdr))) return false;
  uint64_t len64;
  uint32_t crc;
  ParseFrameHeader(hdr, tag, &len64, &crc);
  if (len64 != expected_len) {
    LOG(ERROR) << "frame length mismatch: got " << len64 << " expected "
               << expected_len;
    last_error_ = NetError::PROTOCOL;
    return false;
  }
  if (expected_len > 0 && !RecvAll(buf, expected_len)) return false;
  if (corrupt_in && expected_len > 0) {
    static_cast<char*>(buf)[expected_len / 2] ^= 0x20;
  }
  if (NetCrcEnabled() &&
      FrameCrc(*tag, len64, buf, expected_len) != crc) {
    LOG(ERROR) << "frame checksum mismatch (tag " << *tag << ", len "
               << len64 << ") — corrupted frame detected";
    last_error_ = NetError::CRC;
    GlobalMetrics().net_crc_errors_total.fetch_add(1,
                                                   std::memory_order_relaxed);
    return false;
  }
  return true;
}

// ---------------- Listener ----------------

Listener::~Listener() { Close(); }

void Listener::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Listener::Start(int port) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) return false;
  int one = 1;
  ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  // Dynamic rendezvous holds the reserved ephemeral port open in a
  // bound (never listening) Python socket until init completes, so no
  // other process can be handed it; binding alongside that reservation
  // requires SO_REUSEPORT on both. Only set when the port is such a
  // reservation — fixed-port configs keep strict EADDRINUSE semantics.
  const char* held = std::getenv("HVD_TPU_LISTEN_REUSEPORT");
  if (held && held[0] == '1') {
    ::setsockopt(fd_, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
  }
  sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    LOG(ERROR) << "bind failed on port " << port << ": " << strerror(errno);
    Close();
    return false;
  }
  // Backlog sized for the coordinator's connect storm: at init (and
  // after a mass control-reconnect) every worker dials rank 0's
  // listener at once, and a 128-entry queue drops SYNs past ~128 ranks
  // on a slow-to-accept (oversubscribed) host. The kernel clamps to
  // somaxconn.
  if (::listen(fd_, 1024) != 0) {
    Close();
    return false;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
  port_ = ntohs(addr.sin_port);
  return true;
}

// Reads exactly n handshake bytes from a fresh connection, bounded by
// deadline_ms from now (poll + nonblocking-style recv via MSG_DONTWAIT
// so a silent client cannot hold the accept loop hostage).
static bool RecvHandshakeBounded(int fd, void* buf, std::size_t n,
                                 int deadline_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(deadline_ms);
  char* p = static_cast<char*>(buf);
  std::size_t got = 0;
  while (got < n) {
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    if (left <= 0) return false;
    struct pollfd pfd = {fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(left));
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return false;
    ssize_t r = ::recv(fd, p + got, n - got, MSG_DONTWAIT);
    if (r == 0) return false;
    if (r < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return false;
    }
    got += static_cast<std::size_t>(r);
  }
  return true;
}

static void EncodeHandshake(char* hs, int32_t rank, Channel channel,
                            uint8_t flags, uint32_t generation,
                            uint64_t opseq) {
  std::memcpy(hs, &kHandshakeMagic, 4);
  std::memcpy(hs + 4, &rank, 4);
  hs[8] = static_cast<char>(channel);
  hs[9] = static_cast<char>(flags);
  std::memcpy(hs + 10, &generation, 4);
  std::memcpy(hs + 14, &opseq, 8);
}

int Listener::AcceptPeer(PeerHandshake* hs, int timeout_ms,
                         uint32_t expected_generation) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
  while (true) {
    int wait_ms = timeout_ms;
    if (timeout_ms >= 0) {
      auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                      deadline - std::chrono::steady_clock::now())
                      .count();
      if (left < 0) return -1;
      wait_ms = static_cast<int>(left);
    }
    struct pollfd pfd = {fd_, POLLIN, 0};
    int pr = ::poll(&pfd, 1, wait_ms);
    if (pr < 0 && errno == EINTR) continue;
    if (pr <= 0) return -1;
    int cfd = ::accept(fd_, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK ||
          errno == ECONNABORTED) {
        continue;
      }
      return -1;
    }
    ConfigureSocket(cfd);
    // Handshake read bounded independently of the overall accept
    // deadline: a silent client gets a short window, then the loop
    // returns to accepting real peers.
    int hs_ms = 5000;
    if (timeout_ms >= 0) {
      auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                      deadline - std::chrono::steady_clock::now())
                      .count();
      if (left < hs_ms) hs_ms = left > 0 ? static_cast<int>(left) : 1;
    }
    char buf[kHandshakeBytes];
    if (!RecvHandshakeBounded(cfd, buf, sizeof(buf), hs_ms)) {
      LOG(WARNING) << "dropping connection with no/short handshake "
                   << "(port scanner or stalled peer)";
      ::close(cfd);
      continue;
    }
    uint32_t magic;
    std::memcpy(&magic, buf, 4);
    if (magic != kHandshakeMagic) {
      LOG(ERROR) << "bad handshake magic — dropping connection";
      ::close(cfd);
      continue;
    }
    PeerHandshake parsed;
    std::memcpy(&parsed.rank, buf + 4, 4);
    parsed.channel = static_cast<Channel>(buf[8]);
    parsed.flags = static_cast<uint8_t>(buf[9]);
    std::memcpy(&parsed.generation, buf + 10, 4);
    std::memcpy(&parsed.opseq, buf + 14, 8);
    if (parsed.generation != expected_generation) {
      // A worker from an older elastic generation must never splice
      // into this ring; reject and keep accepting current-generation
      // peers. (A reconnect attempt gets an explicit verdict byte so
      // it fails fast instead of retrying the backoff budget out.)
      LOG(WARNING) << "rejecting rank " << parsed.rank
                   << " with stale generation " << parsed.generation
                   << " (current " << expected_generation << ")";
      if (parsed.flags & kHandshakeReconnect) {
        char verdict = 0;
        ::send(cfd, &verdict, 1, MSG_NOSIGNAL);
      }
      ::close(cfd);
      continue;
    }
    *hs = parsed;
    return cfd;
  }
}

// ---------------- ConnectPeer ----------------

// One non-blocking connect attempt bounded by attempt_ms.
static int ConnectOnce(const struct addrinfo* ai, int attempt_ms) {
  int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
  if (fd < 0) return -1;
  int flags = ::fcntl(fd, F_GETFL, 0);
  ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
  if (rc != 0) {
    if (errno != EINPROGRESS) {
      ::close(fd);
      return -1;
    }
    struct pollfd pfd = {fd, POLLOUT, 0};
    int pr = ::poll(&pfd, 1, attempt_ms);
    if (pr <= 0) {
      // Blackholed host: SYN answered by nothing. Give up on THIS
      // attempt; the caller's retry loop owns the overall deadline.
      ::close(fd);
      return -1;
    }
    int err = 0;
    socklen_t elen = sizeof(err);
    if (::getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) != 0 ||
        err != 0) {
      ::close(fd);
      return -1;
    }
  }
  ::fcntl(fd, F_SETFL, flags);  // back to blocking for the framed I/O
  return fd;
}

Conn ConnectPeer(const std::string& host, int port, int my_rank,
                 Channel channel, int timeout_ms, uint32_t generation,
                 uint64_t opseq, bool reconnect, bool group_ring,
                 bool shm_cap) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    auto left_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                       deadline - std::chrono::steady_clock::now())
                       .count();
    struct addrinfo hints;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    int fd = -1;
    // Per-attempt ceiling: 2 s (or what's left of the deadline), so
    // one blackholed address can't consume the whole budget.
    int attempt_ms = 2000;
    if (left_ms > 0 && left_ms < attempt_ms) {
      attempt_ms = static_cast<int>(left_ms);
    }
    if (attempt_ms < 50) attempt_ms = 50;
    if (::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) == 0) {
      for (auto* ai = res; ai != nullptr; ai = ai->ai_next) {
        fd = ConnectOnce(ai, attempt_ms);
        if (fd >= 0) break;
      }
      ::freeaddrinfo(res);
    }
    if (fd >= 0) {
      ConfigureSocket(fd);
      Conn c(fd, channel);
      char hs[kHandshakeBytes];
      EncodeHandshake(hs, my_rank, channel,
                      static_cast<uint8_t>(
                          (reconnect ? kHandshakeReconnect : 0) |
                          (group_ring ? kHandshakeGroupRing : 0) |
                          (shm_cap ? kHandshakeShmCap : 0)),
                      generation, opseq);
      if (c.SendAll(hs, sizeof(hs))) {
        if (!reconnect) return c;
        // Reconnects wait for the acceptor's verdict so a rejected
        // resume (desynced opseq / stale generation) fails fast. The
        // verdict read is bounded by the ATTEMPT budget, not the full
        // net deadline — a coordinator that accepted the TCP connection
        // but never services it must not eat the whole reconnect window.
        c.SetTimeouts(attempt_ms / 1000 + 1);
        char verdict = 0;
        if (c.RecvAll(&verdict, 1) && verdict == 1) {
          c.SetTimeouts(NetTimeoutSeconds());
          return c;
        }
        LOG(WARNING) << "reconnect to " << host << ":" << port
                     << (verdict == 0 && c.last_error() == NetError::NONE
                             ? " rejected by coordinator"
                             : " failed awaiting verdict");
        return Conn();
      }
    }
    if (std::chrono::steady_clock::now() >= deadline) {
      LOG(ERROR) << "connect to " << host << ":" << port << " timed out";
      return Conn();
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

bool ParseHostPort(const std::string& s, std::string* host, int* port) {
  auto pos = s.rfind(':');
  if (pos == std::string::npos) return false;
  *host = s.substr(0, pos);
  *port = std::atoi(s.c_str() + pos + 1);
  return *port > 0;
}

std::vector<std::string> SplitString(const std::string& s, char sep) {
  std::vector<std::string> out;
  std::size_t start = 0;
  while (start <= s.size()) {
    auto pos = s.find(sep, start);
    if (pos == std::string::npos) {
      out.push_back(s.substr(start));
      break;
    }
    out.push_back(s.substr(start, pos - start));
    start = pos + 1;
  }
  if (!out.empty() && out.back().empty()) out.pop_back();
  return out;
}

}  // namespace hvdtpu
