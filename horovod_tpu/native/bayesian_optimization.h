// Gaussian-process regression + expected-improvement acquisition for the
// autotuner. Capability parity with /root/reference
// horovod/common/optim/{bayesian_optimization,gaussian_process}.{h,cc};
// fresh implementation: hand-rolled Cholesky on the (tiny) sample matrix and
// random-search EI maximization instead of Eigen + L-BFGS — the search space
// is 2-dimensional, where random search is entirely adequate.
#ifndef HVD_TPU_BAYESIAN_OPTIMIZATION_H
#define HVD_TPU_BAYESIAN_OPTIMIZATION_H

#include <cstdint>
#include <utility>
#include <vector>

namespace hvdtpu {

class GaussianProcess {
 public:
  // RBF kernel with fixed hyperparameters on [0,1]-normalized inputs.
  GaussianProcess(double length_scale = 0.2, double signal_var = 1.0,
                  double noise_var = 1e-4)
      : length_scale_(length_scale),
        signal_var_(signal_var),
        noise_var_(noise_var) {}

  void Fit(const std::vector<std::vector<double>>& x,
           const std::vector<double>& y);
  void Predict(const std::vector<double>& x, double* mu, double* sigma) const;

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  double length_scale_, signal_var_, noise_var_;
  std::vector<std::vector<double>> x_;
  std::vector<std::vector<double>> chol_;  // lower-triangular L of K+noise I
  std::vector<double> alpha_;              // (K+noise I)^-1 (y - mean)
  double y_mean_ = 0.0;
  double y_scale_ = 1.0;
};

class BayesianOptimizer {
 public:
  explicit BayesianOptimizer(
      std::vector<std::pair<double, double>> bounds, uint64_t seed = 42);

  // Next point to evaluate: random for the first few samples, then argmax of
  // expected improvement over a random candidate sweep.
  std::vector<double> NextSample();
  void AddSample(const std::vector<double>& x, double y);
  std::vector<double> BestSample() const;
  double BestValue() const { return best_y_; }
  std::size_t NumSamples() const { return x_.size(); }

 private:
  std::vector<double> Normalize(const std::vector<double>& x) const;
  std::vector<double> Denormalize(const std::vector<double>& z) const;
  double NextRand();  // xorshift in [0,1)

  std::vector<std::pair<double, double>> bounds_;
  GaussianProcess gp_;
  std::vector<std::vector<double>> x_;  // normalized
  std::vector<double> y_;
  std::vector<double> best_x_;  // denormalized
  double best_y_;
  uint64_t rng_state_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_BAYESIAN_OPTIMIZATION_H
