// Host-network transport for the TPU build: framed blocking TCP sockets.
//
// Fills the role the reference fills with MPI communicators / Gloo TCP
// contexts (/root/reference horovod/common/mpi/mpi_context.cc,
// gloo/gloo_context.cc): a control star (every worker <-> rank 0) used by the
// coordinator protocol, and a data ring (rank i <-> rank i+1 mod N) used by
// the CPU collective ops. Rendezvous is launcher-injected env:
//   HVD_TPU_ADDRS = "host:port,host:port,..."  (index == rank)
// Each rank listens on its own port; connections carry a handshake with the
// peer's rank, channel, elastic generation, and control-op sequence.
//
// Chaos-hardened (docs/CHAOS.md): every frame carries a CRC32C; all
// sockets get send/recv deadlines (HVD_TPU_NET_TIMEOUT_SECONDS) and
// keepalive probes (HVD_TPU_NET_KEEPALIVE_SECONDS); frame lengths are
// bounded (HVD_TPU_MAX_FRAME_BYTES); connects are non-blocking with
// per-attempt timeouts; the fault injector (fault.h) hooks the frame
// layer under HVD_TPU_FAULT_SPEC.
#ifndef HVD_TPU_NET_H
#define HVD_TPU_NET_H

#include <sys/types.h>

#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

class ShmRing;

enum class Channel : uint8_t {
  CONTROL = 0,     // worker -> coordinator star
  RING = 1,        // prev -> next data ring (global)
  LOCAL_RING = 2,  // ring within one host's local group
  CROSS_RING = 3,  // ring across hosts at one local_rank
  // Not a handshake channel: the TRANSPORT tag the fault injector and
  // error messages use for data-plane legs riding a shared-memory ring
  // (docs/TRANSPORT.md). Fault rules with chan=ring/local/cross keep
  // matching those legs by their LOGICAL channel; chan=shm additionally
  // filters to shm-transported frames only.
  SHM = 4,
};

// Why the last frame-layer call on a Conn failed — the transport error
// taxonomy the recoverable-error messages are built from.
enum class NetError : uint8_t {
  NONE = 0,
  CLOSED,    // EOF / reset / refused — the peer (or a fault) closed it
  TIMEOUT,   // SO_RCVTIMEO / SO_SNDTIMEO deadline expired (hung peer)
  CRC,       // frame checksum mismatch (corrupted frame)
  TOO_BIG,   // frame length exceeded HVD_TPU_MAX_FRAME_BYTES
  PROTOCOL,  // malformed frame (bad tag / length mismatch)
};
const char* NetErrorName(NetError e);

// Frame wire format: [u32 tag][u64 len][u32 crc] + payload, where crc =
// CRC32C over the first 12 header bytes then the payload, so a corrupted
// tag, length, or payload all surface as a checksum mismatch.
constexpr std::size_t kFrameHeaderBytes = 16;

// Effective knob values (env, cached after first read).
std::size_t MaxFrameBytes();       // HVD_TPU_MAX_FRAME_BYTES, default 1 GiB
int NetTimeoutSeconds();           // HVD_TPU_NET_TIMEOUT_SECONDS
bool NetCrcEnabled();              // HVD_TPU_NET_CRC, default on

// Applies the transport socket discipline to fd: TCP_NODELAY, send/recv
// deadlines, and keepalive probes. Called on every accepted/connected
// socket.
void ConfigureSocket(int fd);

// Builds a frame header in place (writes kFrameHeaderBytes into hdr).
void BuildFrameHeader(char* hdr, uint32_t tag, uint64_t len,
                      uint32_t crc);
// Splits a frame header into its fields; length/crc validation is the
// caller's job.
void ParseFrameHeader(const char* hdr, uint32_t* tag, uint64_t* len,
                      uint32_t* crc);
// The frame checksum: CRC32C over the 12-byte tag+len prefix, then the
// payload. 0 when checksums are disabled (HVD_TPU_NET_CRC=0 — job-wide,
// both sides must agree). FrameHeaderCrc is the prefix-only seed for
// callers that stream the payload and extend with Crc32c incrementally.
uint32_t FrameCrc(uint32_t tag, uint64_t len, const void* payload,
                  std::size_t n);
uint32_t FrameHeaderCrc(uint32_t tag, uint64_t len);

// Framed duplex connection. Frame = [u32 tag][u64 len][u32 crc][payload].
class Conn {
 public:
  Conn() = default;
  explicit Conn(int fd) : fd_(fd) {}
  Conn(int fd, Channel channel) : fd_(fd), channel_(channel) {}
  ~Conn();
  Conn(const Conn&) = delete;
  Conn& operator=(const Conn&) = delete;
  Conn(Conn&& o) noexcept : fd_(o.fd_), channel_(o.channel_), shm_(o.shm_) {
    o.fd_ = -1;
    o.shm_ = nullptr;
  }
  Conn& operator=(Conn&& o) noexcept;

  bool valid() const { return fd_ >= 0; }
  void Close();

  // Shared-memory data plane (docs/TRANSPORT.md): a successfully
  // negotiated conn carries an SPSC ring — the sender writes it, the
  // receiver drains it — and the TCP socket stays open only as the
  // liveness signal (EOF/keepalive = peer death). Ownership transfers
  // to the Conn; Close() tears both down.
  void AttachShm(ShmRing* ring);
  ShmRing* shm() const { return shm_; }

  // Raw exact-length I/O; false on error/EOF/deadline (last_error set).
  bool SendAll(const void* buf, std::size_t len);
  bool RecvAll(void* buf, std::size_t len);

  bool SendFrame(uint32_t tag, const void* payload, std::size_t len);
  bool SendFrame(uint32_t tag, const std::string& payload) {
    return SendFrame(tag, payload.data(), payload.size());
  }
  bool RecvFrame(uint32_t* tag, std::string* payload);
  // Receives a frame directly into a caller buffer; fails if length differs.
  bool RecvFrameInto(uint32_t* tag, void* buf, std::size_t expected_len);

  int fd() const { return fd_; }
  Channel channel() const { return channel_; }
  void set_channel(Channel c) { channel_ = c; }
  NetError last_error() const { return last_error_; }

  // Overrides the socket deadlines for THIS connection (seconds; used by
  // the net selftests). ConfigureSocket applies the env default.
  void SetTimeouts(int seconds);

 private:
  // Classifies a failed send/recv return into last_error_.
  void NoteIoError(ssize_t n, bool sending);

  int fd_ = -1;
  Channel channel_ = Channel::CONTROL;
  NetError last_error_ = NetError::NONE;
  ShmRing* shm_ = nullptr;  // owned; see AttachShm
};

// v2 handshake: every connection opens with
//   [u32 magic][i32 rank][u8 channel][u8 flags][u32 generation][u64 opseq]
// Generation is the elastic generation the connector believes is
// current — a stale worker (older generation) is rejected at accept so
// it can never splice into a newer ring. opseq is the connector's
// completed control-frame count, used to validate that a RECONNECT
// (flags & kHandshakeReconnect) resumes at the exact frame the
// coordinator expects (see tcp_context.cc).
constexpr uint8_t kHandshakeReconnect = 0x1;
// Group-ring connect (docs/GROUPS.md): the connection joins a process
// group's data ring; opseq carries the GROUP ID instead of a resume
// cursor. Built lazily by the background thread at a group op's first
// execution (tcp_context.cc EnsureGroupRing).
constexpr uint8_t kHandshakeGroupRing = 0x2;
// Shared-memory capability (docs/TRANSPORT.md): the connector supports
// the intra-host shm data plane (HVD_TPU_SHM enabled). An acceptor that
// sees the bit on a data-plane connection expects ONE setup frame right
// after the handshake (segment name + host key, or an empty name when
// the connector decided against shm for this pair) and answers with an
// ack frame; either side lacking support or failing the attach lands
// the pair on plain TCP — transparently, by construction.
constexpr uint8_t kHandshakeShmCap = 0x4;
constexpr std::size_t kHandshakeBytes = 22;

struct PeerHandshake {
  int32_t rank = -1;
  Channel channel = Channel::CONTROL;
  uint8_t flags = 0;
  uint32_t generation = 0;
  uint64_t opseq = 0;
};

// Listening socket bound to a port; accepts handshaked peer connections.
class Listener {
 public:
  ~Listener();
  // Binds and listens; port==0 picks an ephemeral port. Returns false on error.
  bool Start(int port);
  int port() const { return port_; }
  bool valid() const { return fd_ >= 0; }
  int fd() const { return fd_; }
  void Close();
  // Accepts one connection and reads its handshake, bounding BOTH the
  // accept and the handshake read by timeout_ms (a client that connects
  // and sends nothing — port scanner, health probe — can no longer
  // wedge the accept loop). Connections with a bad magic, a short
  // handshake, or a stale generation are closed and skipped; the wait
  // continues until a valid peer arrives or the deadline passes.
  // Returns the fd, or -1 on timeout/error. timeout_ms < 0 blocks
  // indefinitely (handshake reads still bounded per-connection).
  int AcceptPeer(PeerHandshake* hs, int timeout_ms,
                 uint32_t expected_generation);

 private:
  int fd_ = -1;
  int port_ = 0;
};

// Connects to host:port with retry until timeout_ms, then handshakes.
// Individual connect attempts are non-blocking with a bounded wait, so a
// blackholed host (SYN dropped, no RST) honors the overall deadline
// instead of hanging in connect() for the kernel default (~2 min).
// When `reconnect` is set the connection additionally waits for the
// acceptor's 1-byte verdict (1 = resume; anything else = rejected).
// `group_ring` marks a group-ring connect (kHandshakeGroupRing; opseq
// then carries the group id). Returns an invalid Conn on failure.
// `shm_cap` advertises the shared-memory capability (kHandshakeShmCap)
// on data-plane connects.
Conn ConnectPeer(const std::string& host, int port, int my_rank,
                 Channel channel, int timeout_ms, uint32_t generation = 0,
                 uint64_t opseq = 0, bool reconnect = false,
                 bool group_ring = false, bool shm_cap = false);

// Splits "host:port" / "h1:p1,h2:p2,..." forms.
bool ParseHostPort(const std::string& s, std::string* host, int* port);
std::vector<std::string> SplitString(const std::string& s, char sep);

}  // namespace hvdtpu

#endif  // HVD_TPU_NET_H
