// Host-network transport for the TPU build: framed blocking TCP sockets.
//
// Fills the role the reference fills with MPI communicators / Gloo TCP
// contexts (/root/reference horovod/common/mpi/mpi_context.cc,
// gloo/gloo_context.cc): a control star (every worker <-> rank 0) used by the
// coordinator protocol, and a data ring (rank i <-> rank i+1 mod N) used by
// the CPU collective ops. Rendezvous is launcher-injected env:
//   HVD_TPU_ADDRS = "host:port,host:port,..."  (index == rank)
// Each rank listens on its own port; connections carry a one-byte channel tag.
#ifndef HVD_TPU_NET_H
#define HVD_TPU_NET_H

#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

enum class Channel : uint8_t {
  CONTROL = 0,     // worker -> coordinator star
  RING = 1,        // prev -> next data ring (global)
  LOCAL_RING = 2,  // ring within one host's local group
  CROSS_RING = 3,  // ring across hosts at one local_rank
};

// Framed duplex connection. Frame = [u32 tag][u64 len][payload].
class Conn {
 public:
  Conn() = default;
  explicit Conn(int fd) : fd_(fd) {}
  ~Conn();
  Conn(const Conn&) = delete;
  Conn& operator=(const Conn&) = delete;
  Conn(Conn&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Conn& operator=(Conn&& o) noexcept;

  bool valid() const { return fd_ >= 0; }
  void Close();

  // Raw exact-length I/O; false on error/EOF.
  bool SendAll(const void* buf, std::size_t len);
  bool RecvAll(void* buf, std::size_t len);

  bool SendFrame(uint32_t tag, const void* payload, std::size_t len);
  bool SendFrame(uint32_t tag, const std::string& payload) {
    return SendFrame(tag, payload.data(), payload.size());
  }
  bool RecvFrame(uint32_t* tag, std::string* payload);
  // Receives a frame directly into a caller buffer; fails if length differs.
  bool RecvFrameInto(uint32_t* tag, void* buf, std::size_t expected_len);

  int fd() const { return fd_; }

 private:
  int fd_ = -1;
};

// Listening socket bound to a port; accepts handshaked peer connections.
class Listener {
 public:
  ~Listener();
  // Binds and listens; port==0 picks an ephemeral port. Returns false on error.
  bool Start(int port);
  int port() const { return port_; }
  void Close();
  // Accepts one connection and reads its handshake. Returns fd or -1.
  // timeout_ms < 0 means block indefinitely.
  int AcceptPeer(int* peer_rank, Channel* channel, int timeout_ms);

 private:
  int fd_ = -1;
  int port_ = 0;
};

// Connects to host:port with retry until timeout, then handshakes
// (magic, my_rank, channel). Returns an invalid Conn on failure.
Conn ConnectPeer(const std::string& host, int port, int my_rank,
                 Channel channel, int timeout_ms);

// Splits "host:port" / "h1:p1,h2:p2,..." forms.
bool ParseHostPort(const std::string& s, std::string* host, int* port);
std::vector<std::string> SplitString(const std::string& s, char sep);

}  // namespace hvdtpu

#endif  // HVD_TPU_NET_H
