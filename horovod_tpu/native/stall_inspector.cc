#include "stall_inspector.h"

#include <sstream>

#include "logging.h"
#include "response_cache.h"

namespace hvdtpu {

void StallInspector::RecordUncachedTensorStart(const std::string& tensor_name,
                                               int rank, int global_size) {
  auto it = uncached_.find(tensor_name);
  if (it == uncached_.end()) {
    uncached_[tensor_name] = {Clock::now(), {rank}};
  } else {
    it->second.second.insert(rank);
  }
  (void)global_size;
}

void StallInspector::RemoveUncachedTensor(const std::string& tensor_name) {
  uncached_.erase(tensor_name);
}

void StallInspector::RecordCachedTensorStart(const std::string& tensor_name) {
  if (cached_.find(tensor_name) == cached_.end()) {
    cached_[tensor_name] = Clock::now();
  }
}

void StallInspector::RemoveCachedTensor(const std::string& tensor_name) {
  cached_.erase(tensor_name);
}

bool StallInspector::CheckForStalledTensors(int global_size) {
  bool should_shut_down = false;
  auto now = Clock::now();
  std::ostringstream warn;
  bool any = false;
  for (const auto& kv : uncached_) {
    auto age = std::chrono::duration_cast<std::chrono::seconds>(
                   now - kv.second.first)
                   .count();
    if (age < warning_seconds_) continue;
    any = true;
    std::ostringstream missing;
    bool first = true;
    for (int r = 0; r < global_size; ++r) {
      if (kv.second.second.count(r) == 0) {
        if (!first) missing << ", ";
        missing << r;
        first = false;
      }
    }
    warn << "\n" << kv.first << " [missing ranks: " << missing.str() << "]";
    if (shutdown_seconds_ > 0 && age >= shutdown_seconds_) {
      should_shut_down = true;
    }
  }
  if (any) {
    LOG(WARNING)
        << "One or more tensors were submitted to be reduced, gathered or "
           "broadcasted by subset of ranks and are waiting for remainder of "
           "ranks for more than " << warning_seconds_ << " seconds. This may "
           "indicate that different ranks are trying to submit different "
           "tensors or that only subset of ranks is submitting tensors, which "
           "will cause deadlock."
        << warn.str();
    if (should_shut_down) {
      LOG(ERROR) << "Stall threshold exceeded; initiating coordinated "
                    "shutdown.";
    }
  }
  return should_shut_down;
}

void StallInspector::InvalidateStalledCachedTensors(
    ResponseCache& cache, std::vector<uint32_t>& invalid_bits) {
  auto now = Clock::now();
  for (const auto& kv : cached_) {
    auto age =
        std::chrono::duration_cast<std::chrono::seconds>(now - kv.second)
            .count();
    if (age >= warning_seconds_) {
      invalid_bits.push_back(cache.peek_cache_bit(kv.first));
    }
  }
}

bool StallInspector::ShouldPerformCheck() {
  auto age = std::chrono::duration_cast<std::chrono::seconds>(Clock::now() -
                                                              last_check_)
                 .count();
  return warning_seconds_ > 0 && age >= warning_seconds_;
}

void StallInspector::UpdateCheckTime() { last_check_ = Clock::now(); }

}  // namespace hvdtpu
