#include "stall_inspector.h"

#include <algorithm>
#include <map>
#include <sstream>

#include "logging.h"
#include "metrics.h"
#include "response_cache.h"

namespace hvdtpu {

void StallInspector::RecordUncachedTensorStart(
    const std::string& tensor_name, int rank, int global_size,
    const std::vector<int>* members) {
  auto it = uncached_.find(tensor_name);
  if (it == uncached_.end()) {
    Uncached u;
    u.first = Clock::now();
    u.ready.insert(rank);
    if (members != nullptr) u.members = *members;
    uncached_.emplace(tensor_name, std::move(u));
  } else {
    it->second.ready.insert(rank);
    // Backfill the group scope: the FIRST announcement can precede this
    // process's new_group registration (the late-registration race),
    // arriving with no member list — a later member's announcement
    // carries it, and without the backfill a stalled group tensor would
    // list non-members as missing.
    if (it->second.members.empty() && members != nullptr) {
      it->second.members = *members;
    }
  }
  (void)global_size;
}

void StallInspector::RemoveUncachedTensor(const std::string& tensor_name) {
  uncached_.erase(tensor_name);
}

void StallInspector::RecordCachedTensorStart(const std::string& tensor_name) {
  if (cached_.find(tensor_name) == cached_.end()) {
    cached_[tensor_name] = Clock::now();
  }
}

void StallInspector::RemoveCachedTensor(const std::string& tensor_name) {
  cached_.erase(tensor_name);
}

bool StallInspector::CheckForStalledTensors(int global_size) {
  bool should_shut_down = false;
  auto now = Clock::now();
  // Group stalled tensors by their missing-rank set: the warning surface
  // is one line per SET per check (a 10k-tensor gradient bucket stalled
  // on one dead rank is one line, not 10k), and an unchanged set across
  // consecutive checks collapses to a short "still waiting" repeat line.
  // Every (tensor, check) stall event — printed or suppressed — counts
  // into the stall_warnings_total metric.
  struct Group {
    std::vector<std::string> names;
    long max_age = 0;
    int missing_count = 0;
  };
  std::map<std::string, Group> groups;  // key: "1, 3" missing-rank list
  for (const auto& kv : uncached_) {
    auto age = std::chrono::duration_cast<std::chrono::seconds>(
                   now - kv.second.first)
                   .count();
    if (age < warning_seconds_) continue;
    std::ostringstream missing;
    bool first = true;
    int missing_count = 0;
    // Group-scoped tensors only wait on their MEMBERS; non-members are
    // never "missing" (the tensor name itself carries the @g suffix).
    std::vector<int> expected = kv.second.members;
    if (expected.empty()) {
      expected.resize(static_cast<std::size_t>(global_size));
      for (int r = 0; r < global_size; ++r) expected[r] = r;
    }
    for (int r : expected) {
      if (kv.second.ready.count(r) == 0) {
        if (!first) missing << ", ";
        missing << r;
        first = false;
        ++missing_count;
      }
    }
    Group& g = groups[missing.str()];
    g.names.push_back(kv.first);
    g.max_age = std::max<long>(g.max_age, age);
    g.missing_count = missing_count;
    if (shutdown_seconds_ > 0 && age >= shutdown_seconds_) {
      should_shut_down = true;
    }
  }

  double since_last_check =
      std::chrono::duration<double>(now - last_check_).count();
  Metrics& metrics = GlobalMetrics();
  bool any_new = false;
  std::ostringstream warn;
  for (const auto& kv : groups) {
    const Group& g = kv.second;
    metrics.stall_warnings_total.fetch_add(g.names.size(),
                                           std::memory_order_relaxed);
    // Missing-rank seconds: each stalled tensor spent ~the check window
    // waiting on `missing_count` ranks since the last inspection.
    metrics.stall_missing_rank_micros_total.fetch_add(
        static_cast<uint64_t>(since_last_check * 1e6) * g.names.size() *
            g.missing_count,
        std::memory_order_relaxed);
    auto warned = warned_sets_.find(kv.first);
    if (warned != warned_sets_.end()) {
      // Same missing-rank set as a previous check: one compact repeat
      // line instead of re-listing every tensor.
      warned->second += 1;
      LOG(WARNING) << "Stall persists: " << g.names.size()
                   << " tensor(s) [missing ranks: " << kv.first
                   << "] still waiting after " << g.max_age
                   << "s (repeat #" << warned->second
                   << "; per-tensor details suppressed)";
      continue;
    }
    warned_sets_[kv.first] = 1;
    any_new = true;
    std::size_t shown = std::min<std::size_t>(g.names.size(), 5);
    warn << "\n" << g.names.size() << " tensor(s) [missing ranks: "
         << kv.first << "] waiting up to " << g.max_age << "s: ";
    for (std::size_t i = 0; i < shown; ++i) {
      if (i) warn << ", ";
      warn << g.names[i];
    }
    if (shown < g.names.size()) {
      warn << " (+" << g.names.size() - shown << " more)";
    }
  }
  // Sets that resolved (or changed membership) re-warn in full next time.
  for (auto it = warned_sets_.begin(); it != warned_sets_.end();) {
    if (groups.find(it->first) == groups.end()) {
      it = warned_sets_.erase(it);
    } else {
      ++it;
    }
  }

  if (any_new) {
    LOG(WARNING)
        << "One or more tensors were submitted to be reduced, gathered or "
           "broadcasted by subset of ranks and are waiting for remainder of "
           "ranks for more than " << warning_seconds_ << " seconds. This may "
           "indicate that different ranks are trying to submit different "
           "tensors or that only subset of ranks is submitting tensors, which "
           "will cause deadlock."
        << warn.str();
  }
  if (!groups.empty() && should_shut_down) {
    LOG(ERROR) << "Stall threshold exceeded; initiating coordinated "
                  "shutdown.";
  }
  return should_shut_down;
}

void StallInspector::InvalidateStalledCachedTensors(
    ResponseCache& cache, std::vector<uint32_t>& invalid_bits) {
  auto now = Clock::now();
  for (const auto& kv : cached_) {
    auto age =
        std::chrono::duration_cast<std::chrono::seconds>(now - kv.second)
            .count();
    if (age >= warning_seconds_) {
      invalid_bits.push_back(cache.peek_cache_bit(kv.first));
    }
  }
}

bool StallInspector::ShouldPerformCheck() {
  auto age = std::chrono::duration_cast<std::chrono::seconds>(Clock::now() -
                                                              last_check_)
                 .count();
  return warning_seconds_ > 0 && age >= warning_seconds_;
}

void StallInspector::UpdateCheckTime() { last_check_ = Clock::now(); }

}  // namespace hvdtpu
