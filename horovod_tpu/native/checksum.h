// CRC32C (Castagnoli, polynomial 0x1EDC6F41 reflected = 0x82F63B78):
// the frame-integrity checksum the transport stamps on every frame
// header (net.h). Chosen over CRC32 (IEEE) for its strictly better
// error-detection properties on short messages and because it is the
// checksum the storage/networking world standardized on (iSCSI, ext4,
// leveldb) — a corrupted gradient frame must surface as a detected
// transport error, never as silently wrong arithmetic.
//
// Software slicing-by-8 implementation (~1-2 GB/s): runs everywhere the
// core builds, no ISA dispatch. Incremental: feed chunks via the `crc`
// parameter to checksum streamed payloads without buffering them.
#ifndef HVD_TPU_CHECKSUM_H
#define HVD_TPU_CHECKSUM_H

#include <cstddef>
#include <cstdint>

namespace hvdtpu {

// One-shot or incremental CRC32C. Start with crc=0; to extend a running
// checksum, pass the previous return value.
uint32_t Crc32c(const void* data, std::size_t len, uint32_t crc = 0);

}  // namespace hvdtpu

#endif  // HVD_TPU_CHECKSUM_H
