// Host-CPU collective op implementations over the TCP data rings:
//   - CpuRingAllreduce: bandwidth-optimal ring (reduce-scatter + allgather)
//     over the fused buffer, dtype-aware reduction (16-bit floats accumulate
//     in fp32).
//   - CpuHierarchicalAllreduce: two-level composite — local-ring
//     reduce-scatter, cross-ring allreduce of the owned chunk, local-ring
//     allgather. The TCP analogue of the reference's NCCL ReduceScatter ->
//     cross-node MPI allreduce -> AllGather composite
//     (/root/reference horovod/common/ops/nccl_operations.cc:150-346).
//   - CpuRingAllgather: ring allgatherv with per-rank first-dim sizes.
//   - CpuHierarchicalAllgather: cross-ring circulation of each local_rank's
//     block column (inter-host links carry every byte exactly once), then
//     local-ring circulation of whole column-sets (role parity with the
//     reference's shared-memory hierarchical allgather,
//     ops/mpi_operations.cc:168-321).
//   - CpuBroadcast: chunk-streamed pipelined broadcast over the global ring.
//
// Role parity with /root/reference horovod/common/ops/mpi_operations.cc and
// gloo_operations.cc (the host data plane); the TPU in-jit data plane rides
// XLA collectives and never enters this code.
#ifndef HVD_TPU_CPU_OPERATIONS_H
#define HVD_TPU_CPU_OPERATIONS_H

#include <vector>

#include "collective_operations.h"
#include "compression.h"
#include "tcp_context.h"

namespace hvdtpu {

class CpuRingAllreduce : public AllreduceOp {
 public:
  CpuRingAllreduce(TcpContext& ctx, HorovodGlobalState* state)
      : AllreduceOp(state), ctx_(ctx) {}
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;

 protected:
  // In-place reduction of the fused buffer; overridden by the hierarchical
  // variant. Named activity is used for the timeline. `cmp` is the
  // negotiated wire-compression mode: the buffer stays f32; each ring
  // hop encodes only the bytes it puts on the wire (compression.h).
  // `group` != 0 runs the reduction over that process group's ring
  // (group positions replace world ranks; docs/GROUPS.md).
  virtual Status ReduceBuffer(void* buffer, int64_t count, DataType dtype,
                              CompressionMode cmp, uint32_t group);
  virtual const char* ActivityName() const { return "ALLREDUCE_RING"; }

  TcpContext& ctx_;
};

class CpuHierarchicalAllreduce : public CpuRingAllreduce {
 public:
  using CpuRingAllreduce::CpuRingAllreduce;
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;

 protected:
  Status ReduceBuffer(void* buffer, int64_t count, DataType dtype,
                      CompressionMode cmp, uint32_t group) override;
  const char* ActivityName() const override {
    return "ALLREDUCE_HIERARCHICAL";
  }
};

// Standalone reduce-scatter (docs/ZERO.md): the ring's reduce-scatter
// leg as a first-class negotiated op. Rank r's (shard-sized) output
// buffer receives logical chunk r of the flattened tensor's
// PartitionChunks partition, summed across ranks; wire compression
// applies per hop unchanged.
class CpuRingReduceScatter : public ReduceScatterOp {
 public:
  CpuRingReduceScatter(TcpContext& ctx, HorovodGlobalState* state)
      : ReduceScatterOp(state), ctx_(ctx) {}
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;

 protected:
  TcpContext& ctx_;
};

// Two-level reduce-scatter (intra-host grouped reduce -> inter-host ring
// -> shard distribution), gated on the topology being hierarchical AND
// the autotuned HierarchicalReduceScatter knob — sharded_update's data
// leg gets the same inter-host byte economy the hierarchical allreduce/
// allgather have (each byte crosses the host boundary once per HOST).
class CpuHierarchicalReduceScatter : public CpuRingReduceScatter {
 public:
  using CpuRingReduceScatter::CpuRingReduceScatter;
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;
};

class CpuRingAllgather : public AllgatherOp {
 public:
  CpuRingAllgather(TcpContext& ctx, HorovodGlobalState* state)
      : AllgatherOp(state), ctx_(ctx) {}
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;

 protected:
  TcpContext& ctx_;
};

class CpuHierarchicalAllgather : public CpuRingAllgather {
 public:
  using CpuRingAllgather::CpuRingAllgather;
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;
};

class CpuBroadcast : public BroadcastOp {
 public:
  CpuBroadcast(TcpContext& ctx, HorovodGlobalState* state)
      : BroadcastOp(state), ctx_(ctx) {}
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;

 private:
  TcpContext& ctx_;
};

// Elementwise `dst += src` with dtype dispatch (fp16/bf16 via fp32).
void ReduceSum(void* dst, const void* src, int64_t count, DataType dtype);
// Elementwise scale in place (used for prescale/postscale/average).
void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor);
// In-place ring allreduce of `count` elements on the chosen ring, with
// per-hop wire compression (cmp != NONE requires dtype == f32 — the
// negotiation's EffectiveCompression guarantees it). pipe_bytes > 0
// slices each hop into double-buffered pipeline segments of that many
// (uncompressed-equivalent) bytes so codec + transport + reduction
// overlap within the hop; 0 keeps the original unsliced exchange.
// group != 0 rides that process group's ring instead of the enum ring
// (the ring must already be built — TcpContext::EnsureGroupRing).
Status RingAllreduceOn(TcpContext& ctx, Ring ring, void* buffer, int64_t count,
                       DataType dtype,
                       CompressionMode cmp = CompressionMode::NONE,
                       int64_t pipe_bytes = 0, uint32_t group = 0);

}  // namespace hvdtpu

#endif  // HVD_TPU_CPU_OPERATIONS_H
