// Host-CPU collective op implementations over the TCP data ring:
//   - CpuRingAllreduce: bandwidth-optimal ring (reduce-scatter + allgather)
//     over the fused buffer, dtype-aware reduction (16-bit floats accumulate
//     in fp32).
//   - CpuRingAllgather: ring allgatherv with per-rank first-dim sizes.
//   - CpuBroadcast: root -> rank 0 relay -> star fan-out on the control
//     channel (safe: ops run lockstep on the single coordination thread).
//
// Role parity with /root/reference horovod/common/ops/mpi_operations.cc and
// gloo_operations.cc (the host data plane); the TPU in-jit data plane rides
// XLA collectives and never enters this code.
#ifndef HVD_TPU_CPU_OPERATIONS_H
#define HVD_TPU_CPU_OPERATIONS_H

#include <vector>

#include "collective_operations.h"
#include "tcp_context.h"

namespace hvdtpu {

class CpuRingAllreduce : public AllreduceOp {
 public:
  CpuRingAllreduce(TcpContext& ctx, HorovodGlobalState* state)
      : AllreduceOp(state), ctx_(ctx) {}
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;

 private:
  // In-place ring allreduce on `buffer` (count elements of dtype).
  Status RingAllreduce(void* buffer, int64_t count, DataType dtype);
  TcpContext& ctx_;
};

class CpuRingAllgather : public AllgatherOp {
 public:
  CpuRingAllgather(TcpContext& ctx, HorovodGlobalState* state)
      : AllgatherOp(state), ctx_(ctx) {}
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;

 private:
  TcpContext& ctx_;
};

class CpuBroadcast : public BroadcastOp {
 public:
  CpuBroadcast(TcpContext& ctx, HorovodGlobalState* state)
      : BroadcastOp(state), ctx_(ctx) {}
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override;
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override;

 private:
  TcpContext& ctx_;
};

// Elementwise `dst += src` with dtype dispatch (fp16/bf16 via fp32).
void ReduceSum(void* dst, const void* src, int64_t count, DataType dtype);
// Elementwise scale in place (used for prescale/postscale/average).
void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor);

}  // namespace hvdtpu

#endif  // HVD_TPU_CPU_OPERATIONS_H
