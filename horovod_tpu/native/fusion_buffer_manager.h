// Lazily-allocated persistent fusion buffers, one per (device, stream) key.
// Small tensors agreed in one fused Response are packed into this buffer so
// the collective runs once over one large payload.
//
// Capability parity with /root/reference
// horovod/common/fusion_buffer_manager.{h,cc}; the TPU-build core owns host
// memory directly (no framework AllocatePersistent indirection needed).
#ifndef HVD_TPU_FUSION_BUFFER_MANAGER_H
#define HVD_TPU_FUSION_BUFFER_MANAGER_H

#include <cstdint>
#include <map>
#include <memory>
#include <vector>

#include "common.h"

namespace hvdtpu {

class FusionBufferManager {
 public:
  // Ensures the buffer for `key` is at least `threshold` bytes.
  Status InitializeBuffer(int64_t threshold, int32_t key);
  void* GetBuffer(int32_t key);
  int64_t GetSize(int32_t key);

 private:
  std::map<int32_t, std::shared_ptr<std::vector<char>>> buffers_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_FUSION_BUFFER_MANAGER_H
