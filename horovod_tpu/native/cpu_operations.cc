#include "cpu_operations.h"

#include <algorithm>
#include <cstring>

#include "global_state.h"
#include "half.h"
#include "logging.h"

namespace hvdtpu {

template <typename T>
static void ReduceSumT(T* dst, const T* src, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
}

void ReduceSum(void* dst, const void* src, int64_t count, DataType dtype) {
  switch (dtype) {
    case DataType::HVD_UINT8:
      ReduceSumT(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
                 count);
      break;
    case DataType::HVD_INT8:
      ReduceSumT(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
                 count);
      break;
    case DataType::HVD_UINT16:
      ReduceSumT(static_cast<uint16_t*>(dst),
                 static_cast<const uint16_t*>(src), count);
      break;
    case DataType::HVD_INT16:
      ReduceSumT(static_cast<int16_t*>(dst), static_cast<const int16_t*>(src),
                 count);
      break;
    case DataType::HVD_INT32:
      ReduceSumT(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src),
                 count);
      break;
    case DataType::HVD_INT64:
      ReduceSumT(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT32:
      ReduceSumT(static_cast<float*>(dst), static_cast<const float*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT64:
      ReduceSumT(static_cast<double*>(dst), static_cast<const double*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT16: {
      auto* d = static_cast<uint16_t*>(dst);
      const auto* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToHalf(HalfToFloat(d[i]) + HalfToFloat(s[i]));
      }
      break;
    }
    case DataType::HVD_BFLOAT16: {
      auto* d = static_cast<uint16_t*>(dst);
      const auto* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToBFloat16(BFloat16ToFloat(d[i]) + BFloat16ToFloat(s[i]));
      }
      break;
    }
    case DataType::HVD_BOOL: {
      auto* d = static_cast<uint8_t*>(dst);
      const auto* s = static_cast<const uint8_t*>(src);
      for (int64_t i = 0; i < count; ++i) d[i] = d[i] || s[i];
      break;
    }
  }
}

template <typename T>
static void ScaleT(T* buf, int64_t n, double factor) {
  for (int64_t i = 0; i < n; ++i) {
    buf[i] = static_cast<T>(buf[i] * factor);
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::HVD_UINT8:
      ScaleT(static_cast<uint8_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT8:
      ScaleT(static_cast<int8_t*>(buf), count, factor);
      break;
    case DataType::HVD_UINT16:
      ScaleT(static_cast<uint16_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT16:
      ScaleT(static_cast<int16_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT32:
      ScaleT(static_cast<int32_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT64:
      ScaleT(static_cast<int64_t*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT32:
      ScaleT(static_cast<float*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT64:
      ScaleT(static_cast<double*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT16: {
      auto* b = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i) {
        b[i] = FloatToHalf(static_cast<float>(HalfToFloat(b[i]) * factor));
      }
      break;
    }
    case DataType::HVD_BFLOAT16: {
      auto* b = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i) {
        b[i] = FloatToBFloat16(
            static_cast<float>(BFloat16ToFloat(b[i]) * factor));
      }
      break;
    }
    case DataType::HVD_BOOL:
      break;  // scaling a bool is meaningless; ignore
  }
}

bool CpuRingAllreduce::Enabled(const std::vector<TensorTableEntry>& entries,
                               const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuRingAllreduce::RingAllreduce(void* buffer, int64_t count,
                                       DataType dtype) {
  int n = ctx_.size();
  if (n == 1 || count == 0) return Status::OK();
  int rank = ctx_.rank();
  std::size_t elem = DataTypeSize(dtype);

  // Partition elements into n near-equal chunks.
  std::vector<int64_t> counts(n), offsets(n);
  int64_t base = count / n, rem = count % n;
  int64_t off = 0;
  for (int i = 0; i < n; ++i) {
    counts[i] = base + (i < rem ? 1 : 0);
    offsets[i] = off;
    off += counts[i];
  }
  char* buf = static_cast<char*>(buffer);
  std::vector<char> tmp(static_cast<std::size_t>(counts[0]) * elem);

  // Reduce-scatter phase: after n-1 steps rank r owns chunk (r+1) % n.
  for (int step = 0; step < n - 1; ++step) {
    int send_chunk = (rank - step + n) % n;
    int recv_chunk = (rank - step - 1 + n) % n;
    if (!ctx_.RingExchange(buf + offsets[send_chunk] * elem,
                           counts[send_chunk] * elem, tmp.data(),
                           counts[recv_chunk] * elem)) {
      return Status::UnknownError("ring allreduce exchange failed");
    }
    ReduceSum(buf + offsets[recv_chunk] * elem, tmp.data(), counts[recv_chunk],
              dtype);
  }
  // Allgather phase: circulate fully-reduced chunks.
  for (int step = 0; step < n - 1; ++step) {
    int send_chunk = (rank + 1 - step + n) % n;
    int recv_chunk = (rank - step + n) % n;
    if (!ctx_.RingExchange(buf + offsets[send_chunk] * elem,
                           counts[send_chunk] * elem,
                           buf + offsets[recv_chunk] * elem,
                           counts[recv_chunk] * elem)) {
      return Status::UnknownError("ring allgather exchange failed");
    }
  }
  return Status::OK();
}

Status CpuRingAllreduce::Execute(std::vector<TensorTableEntry>& entries,
                                 const Response& response) {
  auto& timeline = global_state_->timeline;
  void* buffer = nullptr;
  std::size_t buffer_len = 0;
  int64_t total_elements = NumElements(entries);

  if (entries.size() > 1) {
    std::vector<std::string> names = response.tensor_names();
    timeline.ActivityStartAll(names, "MEMCPY_IN_FUSION_BUFFER");
    Status s = MemcpyInFusionBuffer(entries, &buffer, &buffer_len);
    timeline.ActivityEndAll(names);
    if (!s.ok()) return s;
  } else {
    auto& e = entries[0];
    if (e.output != e.data) {
      std::memcpy(e.output, e.data, e.SizeBytes());
    }
    buffer = e.output;
    buffer_len = e.SizeBytes();
  }

  // Per-entry prescale on its segment (factors may differ across fused
  // tensors; scaling commutes with the sum).
  {
    char* p = static_cast<char*>(buffer);
    for (auto& e : entries) {
      if (e.prescale_factor != 1.0) {
        ScaleBuffer(p, e.NumElements(), e.dtype, e.prescale_factor);
      }
      p += e.SizeBytes();
    }
  }

  timeline.ActivityStartAll(response.tensor_names(), "ALLREDUCE_RING");
  Status s = RingAllreduce(buffer, total_elements, entries[0].dtype);
  timeline.ActivityEndAll(response.tensor_names());
  if (!s.ok()) return s;

  {
    char* p = static_cast<char*>(buffer);
    for (auto& e : entries) {
      if (e.postscale_factor != 1.0) {
        ScaleBuffer(p, e.NumElements(), e.dtype, e.postscale_factor);
      }
      p += e.SizeBytes();
    }
  }

  if (entries.size() > 1) {
    timeline.ActivityStartAll(response.tensor_names(),
                              "MEMCPY_OUT_FUSION_BUFFER");
    MemcpyOutFusionBuffer(buffer, entries);
    timeline.ActivityEndAll(response.tensor_names());
  }
  return Status::OK();
}

bool CpuRingAllgather::Enabled(const std::vector<TensorTableEntry>& entries,
                               const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuRingAllgather::Execute(std::vector<TensorTableEntry>& entries,
                                 const Response& response) {
  int n = ctx_.size();
  int rank = ctx_.rank();
  auto& timeline = global_state_->timeline;
  timeline.ActivityStartAll(response.tensor_names(), "ALLGATHER_RING");
  for (auto& e : entries) {
    const auto& first_dims = response.tensor_sizes();
    if (static_cast<int>(first_dims.size()) != n) {
      return Status::UnknownError("allgather sizes missing");
    }
    int64_t slice_elems = 1;
    for (int d = 1; d < e.shape.ndims(); ++d) slice_elems *= e.shape.dim_size(d);
    std::size_t elem = DataTypeSize(e.dtype);

    std::vector<int64_t> block_bytes(n), block_offsets(n);
    int64_t total_bytes = 0;
    for (int r = 0; r < n; ++r) {
      block_bytes[r] = first_dims[r] * slice_elems * static_cast<int64_t>(elem);
      block_offsets[r] = total_bytes;
      total_bytes += block_bytes[r];
    }
    e.gathered = std::make_shared<std::vector<char>>(
        static_cast<std::size_t>(total_bytes));
    e.gathered_sizes =
        std::make_shared<std::vector<int64_t>>(first_dims);
    char* out = e.gathered->data();
    std::memcpy(out + block_offsets[rank], e.data,
                static_cast<std::size_t>(block_bytes[rank]));
    // Ring circulation: at step s, forward the block originally owned by
    // (rank - s) and receive the block owned by (rank - s - 1).
    for (int step = 0; step < n - 1; ++step) {
      int send_block = (rank - step + n) % n;
      int recv_block = (rank - step - 1 + n) % n;
      if (!ctx_.RingExchange(out + block_offsets[send_block],
                             static_cast<std::size_t>(block_bytes[send_block]),
                             out + block_offsets[recv_block],
                             static_cast<std::size_t>(block_bytes[recv_block]))) {
        timeline.ActivityEndAll(response.tensor_names());
        return Status::UnknownError("ring allgather exchange failed");
      }
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

bool CpuBroadcast::Enabled(const std::vector<TensorTableEntry>& entries,
                           const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuBroadcast::Execute(std::vector<TensorTableEntry>& entries,
                             const Response& response) {
  auto& timeline = global_state_->timeline;
  timeline.ActivityStartAll(response.tensor_names(), "BROADCAST_STAR");
  int rank = ctx_.rank();
  for (auto& e : entries) {
    std::size_t len = e.SizeBytes();
    // Relay to rank 0 if the root is elsewhere, then star fan-out from 0.
    // Ops run in lockstep on the coordination thread, so borrowing the
    // control star for bulk data is race-free.
    if (e.root_rank != 0) {
      if (rank == e.root_rank) {
        if (!ctx_.StarSend(0, e.data, len)) {
          timeline.ActivityEndAll(response.tensor_names());
          return Status::UnknownError("broadcast relay to rank 0 failed");
        }
      } else if (rank == 0) {
        if (!ctx_.StarRecv(e.root_rank, e.output, len)) {
          timeline.ActivityEndAll(response.tensor_names());
          return Status::UnknownError("broadcast recv at rank 0 failed");
        }
      }
    }
    if (rank == 0) {
      const void* src = (e.root_rank == 0) ? e.data : e.output;
      for (int r = 1; r < ctx_.size(); ++r) {
        if (r == e.root_rank) continue;
        if (!ctx_.StarSend(r, src, len)) {
          timeline.ActivityEndAll(response.tensor_names());
          return Status::UnknownError("broadcast fan-out failed");
        }
      }
      if (e.root_rank == 0 && e.output != e.data) {
        std::memcpy(e.output, e.data, len);
      }
    } else if (rank != e.root_rank) {
      if (!ctx_.StarRecv(0, e.output, len)) {
        timeline.ActivityEndAll(response.tensor_names());
        return Status::UnknownError("broadcast recv failed");
      }
    } else if (e.output != e.data) {
      std::memcpy(e.output, e.data, len);
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

}  // namespace hvdtpu
