#include "cpu_operations.h"

#include <algorithm>
#include <condition_variable>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>

#include "global_state.h"
#include "half.h"
#include "logging.h"

namespace hvdtpu {

// A failed ring exchange means the data-plane transport is desynced or a
// peer is gone — recoverable only by a generation restart. The status
// carries the CONNECTION_LOST marker (Python's elastic layer rolls back
// on it, and the background loop escalates it to a connection-lost
// shutdown — see PerformOperation) plus the transport-level cause from
// the context, so a chaos run's failure names what was injected
// (checksum mismatch, deadline expiry, peer close).
static Status RingLost(const TcpContext& ctx, const char* what) {
  std::string msg = CONNECTION_LOST_ERROR;
  msg += " [";
  msg += what;
  if (!ctx.last_error().empty()) {
    msg += ": ";
    msg += ctx.last_error();
  }
  msg += "]";
  return Status::UnknownError(msg);
}

template <typename T>
static void ReduceSumT(T* dst, const T* src, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
}

void ReduceSum(void* dst, const void* src, int64_t count, DataType dtype) {
  switch (dtype) {
    case DataType::HVD_UINT8:
      ReduceSumT(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
                 count);
      break;
    case DataType::HVD_INT8:
      ReduceSumT(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
                 count);
      break;
    case DataType::HVD_UINT16:
      ReduceSumT(static_cast<uint16_t*>(dst),
                 static_cast<const uint16_t*>(src), count);
      break;
    case DataType::HVD_INT16:
      ReduceSumT(static_cast<int16_t*>(dst), static_cast<const int16_t*>(src),
                 count);
      break;
    case DataType::HVD_INT32:
      ReduceSumT(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src),
                 count);
      break;
    case DataType::HVD_INT64:
      ReduceSumT(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT32:
      ReduceSumT(static_cast<float*>(dst), static_cast<const float*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT64:
      ReduceSumT(static_cast<double*>(dst), static_cast<const double*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT16: {
      auto* d = static_cast<uint16_t*>(dst);
      const auto* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToHalf(HalfToFloat(d[i]) + HalfToFloat(s[i]));
      }
      break;
    }
    case DataType::HVD_BFLOAT16: {
      auto* d = static_cast<uint16_t*>(dst);
      const auto* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToBFloat16(BFloat16ToFloat(d[i]) + BFloat16ToFloat(s[i]));
      }
      break;
    }
    case DataType::HVD_BOOL: {
      auto* d = static_cast<uint8_t*>(dst);
      const auto* s = static_cast<const uint8_t*>(src);
      for (int64_t i = 0; i < count; ++i) d[i] = d[i] || s[i];
      break;
    }
  }
}

template <typename T>
static void ScaleT(T* buf, int64_t n, double factor) {
  for (int64_t i = 0; i < n; ++i) {
    buf[i] = static_cast<T>(buf[i] * factor);
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::HVD_UINT8:
      ScaleT(static_cast<uint8_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT8:
      ScaleT(static_cast<int8_t*>(buf), count, factor);
      break;
    case DataType::HVD_UINT16:
      ScaleT(static_cast<uint16_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT16:
      ScaleT(static_cast<int16_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT32:
      ScaleT(static_cast<int32_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT64:
      ScaleT(static_cast<int64_t*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT32:
      ScaleT(static_cast<float*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT64:
      ScaleT(static_cast<double*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT16: {
      auto* b = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i) {
        b[i] = FloatToHalf(static_cast<float>(HalfToFloat(b[i]) * factor));
      }
      break;
    }
    case DataType::HVD_BFLOAT16: {
      auto* b = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i) {
        b[i] = FloatToBFloat16(
            static_cast<float>(BFloat16ToFloat(b[i]) * factor));
      }
      break;
    }
    case DataType::HVD_BOOL:
      break;  // scaling a bool is meaningless; ignore
  }
}

// Partitions `count` elements into n near-equal chunks. The same math
// lives in horovod_tpu/common/ops.py shard_partition — the Python side
// must size reduce-scatter shard buffers identically.
static void PartitionChunks(int64_t count, int n, std::vector<int64_t>* counts,
                            std::vector<int64_t>* offsets) {
  counts->assign(n, 0);
  offsets->assign(n, 0);
  int64_t base = count / n, rem = count % n;
  int64_t off = 0;
  for (int i = 0; i < n; ++i) {
    (*counts)[i] = base + (i < rem ? 1 : 0);
    (*offsets)[i] = off;
    off += (*counts)[i];
  }
}

static int64_t MaxChunk(const std::vector<int64_t>& counts) {
  int64_t m = 0;
  for (int64_t c : counts) m = std::max(m, c);
  return m;
}

// ---------------------------------------------------------------------------
// Pipelined segment engine (docs/AUTOTUNE.md "Pipelined ring transport").
//
// A ring hop's payload is sliced into HVD_TPU_PIPELINE_CHUNK_BYTES
// segments and double-buffered: while segment s's decode+ReduceSum runs
// on the worker thread, the main (background) thread encodes and
// exchanges segment s+1 — so codec work, socket transport, and the
// reduction overlap WITHIN a hop. Every rank derives the segment count
// from the globally-known chunk table and the synchronized chunk knob,
// so the per-segment frames pair up deterministically (zero-length
// sides ride an empty frame).
// ---------------------------------------------------------------------------

// One worker thread with a depth-1 job slot: Submit blocks until the
// previous job retired (which with two rotating buffers is exactly the
// guarantee that a buffer is free for reuse), Drain blocks until idle.
class SegmentWorker {
 public:
  SegmentWorker() : thread_([this] { Loop(); }) {}
  ~SegmentWorker() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    thread_.join();
  }

  void Submit(std::function<void()> fn) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return !busy_; });
    job_ = std::move(fn);
    busy_ = true;
    cv_.notify_all();
  }

  void Drain() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return !busy_; });
  }

 private:
  void Loop() {
    std::unique_lock<std::mutex> lk(mu_);
    for (;;) {
      cv_.wait(lk, [&] { return busy_ || stop_; });
      if (stop_) return;
      std::function<void()> job = std::move(job_);
      lk.unlock();
      job();
      lk.lock();
      busy_ = false;
      cv_.notify_all();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::function<void()> job_;  // guarded_by(mu_)
  bool busy_ = false;          // guarded_by(mu_)
  bool stop_ = false;          // guarded_by(mu_)
  std::thread thread_;
};

// Elements per pipeline segment for the given knob value. Compressed
// payloads align to the int8 quantization block so a per-segment encode
// is bitwise-identical to the whole-chunk encode (block boundaries
// coincide); bf16 has no blocks but keeps the same alignment for free.
static int64_t SegmentElems(int64_t pipe_bytes, std::size_t elem,
                            CompressionMode cmp) {
  if (pipe_bytes <= 0) return 0;
  int64_t n = std::max<int64_t>(1, pipe_bytes / static_cast<int64_t>(elem));
  if (cmp != CompressionMode::NONE) {
    n = std::max<int64_t>(kCompressionBlock,
                          (n / kCompressionBlock) * kCompressionBlock);
  }
  return n;
}

// Ring-global segment count for a hop table: every rank must loop the
// same number of segment exchanges per hop or the frame stream desyncs.
static int64_t SegmentCount(const std::vector<int64_t>& counts, int64_t seg) {
  if (seg <= 0) return 1;
  int64_t max_chunk = MaxChunk(counts);
  return max_chunk <= seg ? 1 : (max_chunk + seg - 1) / seg;
}

static int64_t ClampSeg(int64_t chunk_count, int64_t soff, int64_t seg) {
  return std::max<int64_t>(0, std::min(seg, chunk_count - soff));
}

// Offset used for pointer arithmetic: clamped to the chunk end so a
// zero-length tail segment (short chunk, ring-global segment count)
// forms at most a one-past-the-end pointer — forming one further out
// is UB even when the length-0 exchange never dereferences it.
static int64_t SegOff(int64_t chunk_count, int64_t soff) {
  return std::min(soff, chunk_count);
}

// Reduce-scatter leg of a ring allreduce: after n-1 steps ring rank r owns
// chunk (r+1) % n, reduced over the whole ring.
//
// With cmp != NONE (dtype f32 by negotiation) this is the EQuARX-style
// dequant-reduce-requant pipeline: the accumulator chunk in `buf` stays
// f32; each hop encodes the outgoing chunk (requant), ships the small
// payload, and the receiver decodes (dequant) and ReduceSums in f32 —
// so wire bytes shrink while the sum never accumulates in the narrow
// format. CRC framing in RingExchangeOn covers the compressed payload.
//
// With pipe_bytes > 0 each hop runs the segmented double-buffered
// pipeline above; pipe_bytes == 0 (or a hop smaller than one segment)
// takes the original unsliced exchange.
static Status RingReduceScatterOn(TcpContext& ctx, Ring ring, char* buf,
                                  const std::vector<int64_t>& counts,
                                  const std::vector<int64_t>& offsets,
                                  DataType dtype, CompressionMode cmp,
                                  int64_t pipe_bytes, uint32_t group = 0) {
  // Group-aware coordinates: group != 0 with LOCAL/CROSS rides the
  // group's sub-rings (the hierarchical-composite-for-subgroups legs).
  int n = ctx.RingSizeOn(ring, group);
  int rank = ctx.RingRankOn(ring, group);
  std::size_t elem = DataTypeSize(dtype);
  int64_t seg = SegmentElems(pipe_bytes, elem, cmp);
  int64_t nseg = SegmentCount(counts, seg);
  if (cmp != CompressionMode::NONE) {
    float* f = reinterpret_cast<float*>(buf);
    if (nseg > 1) {
      // Three concurrent stages: the encoder thread requantizes segment
      // s+1 and the reducer thread dequantizes+sums segment s-1 WHILE
      // the main thread's socket exchange moves segment s — per-hop
      // cost drops from encode+transport+decode+reduce in series to
      // ~max(encode, transport, decode+reduce).
      std::vector<char> send_c[2] = {
          std::vector<char>(CompressedSize(seg, cmp)),
          std::vector<char>(CompressedSize(seg, cmp))};
      std::vector<char> recv_c[2] = {
          std::vector<char>(CompressedSize(seg, cmp)),
          std::vector<char>(CompressedSize(seg, cmp))};
      SegmentWorker encoder;  // declared after buffers: join before free
      SegmentWorker reducer;
      Metrics& m = GlobalMetrics();
      for (int step = 0; step < n - 1; ++step) {
        int send_chunk = (rank - step + n) % n;
        int recv_chunk = (rank - step - 1 + n) % n;
        auto encode_seg = [&, send_chunk](int64_t s) {
          int64_t soff = s * seg;
          int64_t sn = ClampSeg(counts[send_chunk], soff, seg);
          const float* src =
              f + offsets[send_chunk] + SegOff(counts[send_chunk], soff);
          char* out = send_c[s & 1].data();
          encoder.Submit([src, sn, cmp, out] {
            CompressBuffer(src, sn, cmp, out);
          });
        };
        encode_seg(0);
        for (int64_t s = 0; s < nseg; ++s) {
          int64_t soff = s * seg;
          int64_t sn = ClampSeg(counts[send_chunk], soff, seg);
          int64_t rn = ClampSeg(counts[recv_chunk], soff, seg);
          // Queue the next segment's encode; either way the depth-1
          // slot guarantees THIS segment's encode has retired before
          // its buffer goes on the wire.
          if (s + 1 < nseg) {
            encode_seg(s + 1);
          } else {
            encoder.Drain();
          }
          char* rc = recv_c[s & 1].data();
          if (!ctx.ExchangeOn(ring, group, send_c[s & 1].data(),
                              CompressedSize(sn, cmp), rc,
                              CompressedSize(rn, cmp))) {
            encoder.Drain();
            reducer.Drain();
            return RingLost(ctx, "ring reduce-scatter exchange failed");
          }
          m.pipeline_segments_total.fetch_add(1, std::memory_order_relaxed);
          if (rn > 0) {
            // Fused dequant-accumulate: no intermediate f32 scratch —
            // the decode and the ReduceSum are one pass (bitwise-equal
            // element math to decompress-then-add).
            float* dst = f + offsets[recv_chunk] + soff;
            reducer.Submit([rc, rn, cmp, dst] {
              DecompressAccumulate(rc, rn, cmp, dst);
            });
          }
        }
        // Hop barrier: the next hop encodes/forwards what this hop
        // reduced.
        reducer.Drain();
      }
      return Status::OK();
    }
    // Unsliced path. Scratch sized by the LARGEST chunk: callers may
    // pass a rotated chunk order (the standalone reduce-scatter op
    // does), so counts[0] is not necessarily the maximum.
    int64_t max_chunk = MaxChunk(counts);
    std::vector<char> send_c(CompressedSize(max_chunk, cmp));
    std::vector<char> recv_c(CompressedSize(max_chunk, cmp));
    std::vector<float> tmp(static_cast<std::size_t>(max_chunk));
    for (int step = 0; step < n - 1; ++step) {
      int send_chunk = (rank - step + n) % n;
      int recv_chunk = (rank - step - 1 + n) % n;
      std::size_t send_len = CompressedSize(counts[send_chunk], cmp);
      std::size_t recv_len = CompressedSize(counts[recv_chunk], cmp);
      CompressBuffer(f + offsets[send_chunk], counts[send_chunk], cmp,
                     send_c.data());
      if (!ctx.ExchangeOn(ring, group, send_c.data(), send_len,
                          recv_c.data(), recv_len)) {
        return RingLost(ctx, "ring reduce-scatter exchange failed");
      }
      DecompressBuffer(recv_c.data(), counts[recv_chunk], cmp, tmp.data());
      ReduceSum(f + offsets[recv_chunk], tmp.data(), counts[recv_chunk],
                dtype);
    }
    return Status::OK();
  }
  if (nseg > 1) {
    std::vector<char> tmp[2] = {
        std::vector<char>(static_cast<std::size_t>(seg) * elem),
        std::vector<char>(static_cast<std::size_t>(seg) * elem)};
    SegmentWorker worker;
    Metrics& m = GlobalMetrics();
    for (int step = 0; step < n - 1; ++step) {
      int send_chunk = (rank - step + n) % n;
      int recv_chunk = (rank - step - 1 + n) % n;
      for (int64_t s = 0; s < nseg; ++s) {
        int64_t soff = s * seg;
        int64_t sn = ClampSeg(counts[send_chunk], soff, seg);
        int64_t rn = ClampSeg(counts[recv_chunk], soff, seg);
        char* rc = tmp[s & 1].data();
        if (!ctx.ExchangeOn(
                ring, group,
                buf + (offsets[send_chunk] +
                       SegOff(counts[send_chunk], soff)) * elem,
                sn * elem, rc, rn * elem)) {
          worker.Drain();
          return RingLost(ctx, "ring reduce-scatter exchange failed");
        }
        m.pipeline_segments_total.fetch_add(1, std::memory_order_relaxed);
        if (rn > 0) {
          char* dst = buf + (offsets[recv_chunk] + soff) * elem;
          worker.Submit([dst, rc, rn, dtype] {
            ReduceSum(dst, rc, rn, dtype);
          });
        }
      }
      worker.Drain();
    }
    return Status::OK();
  }
  std::vector<char> tmp(static_cast<std::size_t>(MaxChunk(counts)) * elem);
  for (int step = 0; step < n - 1; ++step) {
    int send_chunk = (rank - step + n) % n;
    int recv_chunk = (rank - step - 1 + n) % n;
    if (!ctx.ExchangeOn(ring, group, buf + offsets[send_chunk] * elem,
                        counts[send_chunk] * elem, tmp.data(),
                        counts[recv_chunk] * elem)) {
      return RingLost(ctx, "ring reduce-scatter exchange failed");
    }
    ReduceSum(buf + offsets[recv_chunk] * elem, tmp.data(), counts[recv_chunk],
              dtype);
  }
  return Status::OK();
}

// Allgather leg: circulates the fully-reduced chunks (owned per the
// reduce-scatter leg above) until every ring member has all of them.
//
// Compressed variant: each owner encodes its reduced chunk ONCE (per
// segment), decodes its own copy back (so the owner holds exactly what
// everyone else will decode), and the ring then forwards the encoded
// payloads VERBATIM — no per-hop requantization, so there is no
// hop-count-dependent drift and every rank ends with bitwise-identical
// chunk values. With pipe_bytes > 0 the decode of segment s overlaps
// the transport of segment s+1 (the uncompressed leg has no compute to
// overlap and stays unsliced).
static Status RingAllgatherPhaseOn(TcpContext& ctx, Ring ring, char* buf,
                                   const std::vector<int64_t>& counts,
                                   const std::vector<int64_t>& offsets,
                                   DataType dtype, CompressionMode cmp,
                                   int64_t pipe_bytes, uint32_t group = 0) {
  int n = ctx.RingSizeOn(ring, group);
  int rank = ctx.RingRankOn(ring, group);
  std::size_t elem = DataTypeSize(dtype);
  if (cmp != CompressionMode::NONE) {
    float* f = reinterpret_cast<float*>(buf);
    int owned = (rank + 1) % n;
    int64_t seg = SegmentElems(pipe_bytes, elem, cmp);
    int64_t nseg = SegmentCount(counts, seg);
    if (nseg > 1) {
      // Encoded chunks live as nseg fixed-stride slots so forwarding a
      // segment is a pure slice; every rank computes identical slot
      // layout from (counts, seg).
      std::size_t slot = CompressedSize(seg, cmp);
      std::vector<char> cur(static_cast<std::size_t>(nseg) * slot);
      std::vector<char> nxt(static_cast<std::size_t>(nseg) * slot);
      SegmentWorker worker;
      Metrics& m = GlobalMetrics();
      for (int64_t s = 0; s < nseg; ++s) {
        int64_t soff = s * seg;
        int64_t sn = ClampSeg(counts[owned], soff, seg);
        if (sn <= 0) continue;
        CompressBuffer(f + offsets[owned] + soff, sn, cmp,
                       cur.data() + s * slot);
        DecompressBuffer(cur.data() + s * slot, sn, cmp,
                         f + offsets[owned] + soff);
      }
      for (int step = 0; step < n - 1; ++step) {
        int send_chunk = (rank + 1 - step + n) % n;
        int recv_chunk = (rank - step + n) % n;
        for (int64_t s = 0; s < nseg; ++s) {
          int64_t soff = s * seg;
          int64_t sn = ClampSeg(counts[send_chunk], soff, seg);
          int64_t rn = ClampSeg(counts[recv_chunk], soff, seg);
          char* rc = nxt.data() + s * slot;
          if (!ctx.ExchangeOn(ring, group, cur.data() + s * slot,
                              CompressedSize(sn, cmp), rc,
                              CompressedSize(rn, cmp))) {
            worker.Drain();
            return RingLost(ctx, "ring allgather exchange failed");
          }
          m.pipeline_segments_total.fetch_add(1, std::memory_order_relaxed);
          if (rn > 0) {
            float* dst = f + offsets[recv_chunk] + soff;
            worker.Submit([rc, rn, cmp, dst] {
              DecompressBuffer(rc, rn, cmp, dst);
            });
          }
        }
        // Decode jobs read `nxt`; the swap hands it to the next hop's
        // send side, so they must retire first.
        worker.Drain();
        std::swap(cur, nxt);
      }
      return Status::OK();
    }
    // Unsliced: two rotating payload buffers — step s only ever
    // forwards the chunk received at step s-1, so O(1) encoded chunks
    // suffice (matching the uncompressed path's single tmp), not one
    // per rank.
    int64_t max_chunk = MaxChunk(counts);
    std::vector<char> send_c(CompressedSize(max_chunk, cmp));
    std::vector<char> recv_c(CompressedSize(max_chunk, cmp));
    CompressBuffer(f + offsets[owned], counts[owned], cmp, send_c.data());
    DecompressBuffer(send_c.data(), counts[owned], cmp, f + offsets[owned]);
    for (int step = 0; step < n - 1; ++step) {
      int send_chunk = (rank + 1 - step + n) % n;
      int recv_chunk = (rank - step + n) % n;
      if (!ctx.ExchangeOn(ring, group, send_c.data(),
                          CompressedSize(counts[send_chunk], cmp),
                          recv_c.data(),
                          CompressedSize(counts[recv_chunk], cmp))) {
        return RingLost(ctx, "ring allgather exchange failed");
      }
      DecompressBuffer(recv_c.data(), counts[recv_chunk], cmp,
                       f + offsets[recv_chunk]);
      std::swap(send_c, recv_c);
    }
    return Status::OK();
  }
  for (int step = 0; step < n - 1; ++step) {
    int send_chunk = (rank + 1 - step + n) % n;
    int recv_chunk = (rank - step + n) % n;
    if (!ctx.ExchangeOn(ring, group, buf + offsets[send_chunk] * elem,
                        counts[send_chunk] * elem,
                        buf + offsets[recv_chunk] * elem,
                        counts[recv_chunk] * elem)) {
      return RingLost(ctx, "ring allgather exchange failed");
    }
  }
  return Status::OK();
}

Status RingAllreduceOn(TcpContext& ctx, Ring ring, void* buffer, int64_t count,
                       DataType dtype, CompressionMode cmp,
                       int64_t pipe_bytes, uint32_t group) {
  int n = ctx.RingSizeOn(ring, group);
  if (n == 1 || count == 0) return Status::OK();
  std::vector<int64_t> counts, offsets;
  PartitionChunks(count, n, &counts, &offsets);
  char* buf = static_cast<char*>(buffer);
  Status s = RingReduceScatterOn(ctx, ring, buf, counts, offsets, dtype, cmp,
                                 pipe_bytes, group);
  if (!s.ok()) return s;
  return RingAllgatherPhaseOn(ctx, ring, buf, counts, offsets, dtype, cmp,
                              pipe_bytes, group);
}

// Lazily builds (or reuses) the group's data ring before a group op
// executes; a failure is a transport loss (generation restart).
static Status EnsureGroup(TcpContext& ctx, HorovodGlobalState* state,
                          uint32_t group) {
  if (group == 0) return Status::OK();
  std::vector<int> members = state->group_table.Members(group);
  if (members.empty()) {
    return Status::PreconditionError(
        "unknown process group " + std::to_string(group) +
        " at execution time; create it with hvd.new_group on every rank");
  }
  if (!ctx.EnsureGroupRing(group, members)) {
    return RingLost(ctx, "group ring rendezvous failed");
  }
  return Status::OK();
}

bool CpuRingAllreduce::Enabled(const std::vector<TensorTableEntry>& entries,
                               const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuRingAllreduce::ReduceBuffer(void* buffer, int64_t count,
                                      DataType dtype, CompressionMode cmp,
                                      uint32_t group) {
  return RingAllreduceOn(ctx_, Ring::GLOBAL, buffer, count, dtype, cmp,
                         global_state_->parameter_manager
                             .PipelineChunkBytes(),
                         group);
}

Status CpuRingAllreduce::Execute(std::vector<TensorTableEntry>& entries,
                                 const Response& response) {
  auto& timeline = global_state_->timeline;
  void* buffer = nullptr;
  std::size_t buffer_len = 0;
  int64_t total_elements = NumElements(entries);
  const uint32_t group = response.group_id();
  {
    Status s = EnsureGroup(ctx_, global_state_, group);
    if (!s.ok()) return s;
  }

  if (entries.size() > 1) {
    std::vector<std::string> names = response.tensor_names();
    timeline.ActivityStartAll(names, "MEMCPY_IN_FUSION_BUFFER");
    Status s = MemcpyInFusionBuffer(entries, &buffer, &buffer_len);
    timeline.ActivityEndAll(names);
    if (!s.ok()) return s;
  } else {
    auto& e = entries[0];
    if (e.output != e.data) {
      std::memcpy(e.output, e.data, e.SizeBytes());
    }
    buffer = e.output;
    buffer_len = e.SizeBytes();
  }

  // Per-entry prescale on its segment (factors may differ across fused
  // tensors; scaling commutes with the sum).
  {
    char* p = static_cast<char*>(buffer);
    for (auto& e : entries) {
      if (e.prescale_factor != 1.0) {
        ScaleBuffer(p, e.NumElements(), e.dtype, e.prescale_factor);
      }
      p += e.SizeBytes();
    }
  }

  // Belt-and-braces dtype filter: the negotiated mode is already
  // effective (dtype-filtered at enqueue), and fused responses only
  // merge same-mode tensors.
  CompressionMode cmp = EffectiveCompression(
      static_cast<CompressionMode>(response.compression()),
      entries[0].dtype);
  {
    Metrics& m = GlobalMetrics();
    if (cmp == CompressionMode::BF16) {
      m.allreduce_bf16_total.fetch_add(1, std::memory_order_relaxed);
    } else if (cmp == CompressionMode::INT8) {
      m.allreduce_int8_total.fetch_add(1, std::memory_order_relaxed);
    } else {
      m.allreduce_uncompressed_total.fetch_add(1,
                                               std::memory_order_relaxed);
    }
  }

  timeline.ActivityStartAll(response.tensor_names(), ActivityName());
  Status s = ReduceBuffer(buffer, total_elements, entries[0].dtype, cmp,
                          group);
  timeline.ActivityEndAll(response.tensor_names());
  if (!s.ok()) return s;

  {
    char* p = static_cast<char*>(buffer);
    for (auto& e : entries) {
      if (e.postscale_factor != 1.0) {
        ScaleBuffer(p, e.NumElements(), e.dtype, e.postscale_factor);
      }
      p += e.SizeBytes();
    }
  }

  if (entries.size() > 1) {
    timeline.ActivityStartAll(response.tensor_names(),
                              "MEMCPY_OUT_FUSION_BUFFER");
    MemcpyOutFusionBuffer(buffer, entries);
    timeline.ActivityEndAll(response.tensor_names());
  }
  return Status::OK();
}

bool CpuHierarchicalAllreduce::Enabled(
    const std::vector<TensorTableEntry>& entries,
    const Response& response) const {
  // World group: the classic gate. Subgroups additionally qualify when
  // their member set forms a uniform (local, cross) grid — the decision
  // is a pure function of (members, world grid, synchronized knob), so
  // it can never diverge across ranks (docs/TRANSPORT.md).
  if (entries[0].device != HOST_DEVICE_ID ||
      !ctx_.hierarchical_possible() ||
      !global_state_->parameter_manager.HierarchicalAllreduce()) {
    return false;
  }
  if (response.group_id() == 0) return true;
  return ctx_.GroupHierarchicalPossible(
      global_state_->group_table.Members(response.group_id()));
}

Status CpuHierarchicalAllreduce::ReduceBuffer(void* buffer, int64_t count,
                                              DataType dtype,
                                              CompressionMode cmp,
                                              uint32_t group) {
  // Two-level composite (reference: nccl_operations.cc:150-346):
  //   1. local-ring reduce-scatter — local rank lr ends up owning chunk
  //      (lr+1) % ls, reduced over the local group;
  //   2. cross-ring allreduce of the owned chunk (one participant per
  //      local_rank, riding the inter-host links only);
  //   3. local-ring allgather of the now globally-reduced chunks.
  // Group-scoped: the same three stages over the group's sub-rings
  // (local position / per-host member count replace local_rank /
  // local_size; the intra-host legs ride shm when negotiated).
  int ls, lr;
  if (group != 0) {
    std::vector<int> members = global_state_->group_table.Members(group);
    if (!ctx_.EnsureGroupSubRings(group, members)) {
      return RingLost(ctx_, "group sub-ring rendezvous failed");
    }
    ls = ctx_.RingSizeOn(Ring::LOCAL, group);
    lr = ctx_.RingRankOn(Ring::LOCAL, group);
  } else {
    ls = ctx_.local_size();
    lr = ctx_.local_rank();
  }
  if (count == 0) return Status::OK();
  std::size_t elem = DataTypeSize(dtype);
  int64_t pipe = global_state_->parameter_manager.PipelineChunkBytes();

  std::vector<int64_t> counts, offsets;
  PartitionChunks(count, ls, &counts, &offsets);
  char* buf = static_cast<char*>(buffer);

  Status s = RingReduceScatterOn(ctx_, Ring::LOCAL, buf, counts, offsets,
                                 dtype, cmp, pipe, group);
  if (!s.ok()) return s;

  int owned = (lr + 1) % ls;
  s = RingAllreduceOn(ctx_, Ring::CROSS, buf + offsets[owned] * elem,
                      counts[owned], dtype, cmp, pipe, group);
  if (!s.ok()) return s;

  return RingAllgatherPhaseOn(ctx_, Ring::LOCAL, buf, counts, offsets, dtype,
                              cmp, pipe, group);
}

bool CpuRingReduceScatter::Enabled(
    const std::vector<TensorTableEntry>& entries,
    const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuRingReduceScatter::Execute(std::vector<TensorTableEntry>& entries,
                                     const Response& response) {
  // The reduce-scatter leg of the ring as a standalone op (docs/ZERO.md):
  // rank r's output receives logical chunk r of the PartitionChunks
  // partition over the flattened tensor, summed across all ranks. Wire
  // compression applies per hop exactly as in the fused allreduce leg
  // (the f32 accumulator never lives in the narrow format). The
  // controller never fuses REDUCESCATTER responses — sharded callers
  // fuse at the source instead (one flat gradient buffer whose offsets
  // ARE the shard boundaries), so entries is normally a single tensor.
  // Group-scoped: chunks partition over the GROUP and "rank" is the
  // group position (shard i goes to member i).
  const uint32_t group = response.group_id();
  {
    Status s = EnsureGroup(ctx_, global_state_, group);
    if (!s.ok()) return s;
  }
  int n = group ? ctx_.GroupSize(group) : ctx_.size();
  int rank = group ? ctx_.GroupRank(group) : ctx_.rank();
  auto& timeline = global_state_->timeline;
  CompressionMode cmp = EffectiveCompression(
      static_cast<CompressionMode>(response.compression()),
      entries[0].dtype);
  int64_t pipe = global_state_->parameter_manager.PipelineChunkBytes();
  Metrics& m = GlobalMetrics();
  timeline.ActivityStartAll(response.tensor_names(), "REDUCE_SCATTER_RING");
  for (auto& e : entries) {
    int64_t count = e.NumElements();
    std::size_t elem = DataTypeSize(e.dtype);
    std::vector<int64_t> counts, offsets;
    PartitionChunks(count, n, &counts, &offsets);
    m.reduce_scatter_total.fetch_add(1, std::memory_order_relaxed);
    m.reduce_scatter_bytes_total.fetch_add(
        static_cast<uint64_t>(count) * elem, std::memory_order_relaxed);
    if (count == 0) continue;
    if (n == 1) {
      if (e.output != e.data) std::memcpy(e.output, e.data, e.SizeBytes());
      ScaleBuffer(e.output, count, e.dtype,
                  e.prescale_factor * e.postscale_factor);
      continue;
    }
    // The ring leg leaves ring-rank r owning ring chunk (r+1)%n; rank r
    // must own LOGICAL chunk r, so ring chunk j maps onto logical chunk
    // (j+n-1)%n — a pure relabeling (offsets stay the contiguous
    // PartitionChunks layout, identical on every rank).
    std::vector<int64_t> ring_counts(n), ring_offsets(n);
    for (int j = 0; j < n; ++j) {
      int logical = (j + n - 1) % n;
      ring_counts[j] = counts[logical];
      ring_offsets[j] = offsets[logical];
    }
    // Work in a scratch copy: the entry's output buffer is shard-sized
    // (counts[rank] elements), not full-tensor-sized.
    std::vector<char> work(static_cast<std::size_t>(count) * elem);
    std::memcpy(work.data(), e.data, work.size());
    if (e.prescale_factor != 1.0) {
      ScaleBuffer(work.data(), count, e.dtype, e.prescale_factor);
    }
    Status s = RingReduceScatterOn(ctx_, Ring::GLOBAL, work.data(),
                                   ring_counts, ring_offsets, e.dtype, cmp,
                                   pipe, group);
    if (!s.ok()) {
      timeline.ActivityEndAll(response.tensor_names());
      return s;
    }
    std::memcpy(e.output, work.data() + offsets[rank] * elem,
                static_cast<std::size_t>(counts[rank]) * elem);
    if (e.postscale_factor != 1.0) {
      ScaleBuffer(e.output, counts[rank], e.dtype, e.postscale_factor);
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

// ---------------------------------------------------------------------------
// Hierarchical reduce-scatter (docs/ZERO.md + docs/AUTOTUNE.md): intra-host
// reduce -> inter-host ring -> shard distribution, so sharded_update jobs
// get the same two-level treatment allreduce/allgather already have. The
// inter-host links carry each byte once per HOST instead of once per rank.
// ---------------------------------------------------------------------------

// One contiguous span of the flattened tensor belonging to a local
// rank's chunk group.
struct GroupSeg {
  int64_t off;  // elements
  int64_t cnt;  // elements
};

// Reduces decoded elements [a, b) of a group's packed layout into the
// scattered destination spans. Runs on the segment worker thread; the
// destination spans are disjoint from anything the main thread touches
// during the same hop.
static void ReduceScattered(char* buf, const std::vector<GroupSeg>& segs,
                            const char* decoded, int64_t a, int64_t b,
                            std::size_t elem, DataType dtype) {
  int64_t pos = 0;
  for (const auto& s : segs) {
    int64_t s_end = pos + s.cnt;
    if (s_end <= a) {
      pos = s_end;
      continue;
    }
    if (pos >= b) break;
    int64_t lo = std::max(a, pos), hi = std::min(b, s_end);
    if (hi > lo) {
      ReduceSum(buf + (s.off + (lo - pos)) * elem,
                decoded + (lo - a) * elem, hi - lo, dtype);
    }
    pos = s_end;
  }
}

// Ring reduce-scatter over chunk GROUPS: ring position m ends up owning
// ring group (m+1) % n, reduced over the ring — the grouped analogue of
// RingReduceScatterOn for stage 1 of the hierarchical reduce-scatter,
// where one local rank's "chunk" is the set of logical chunks of its
// cross-ring column (scattered spans, so hops stage through a packed
// buffer). Segmented-pipelined exactly like the flat legs: decode +
// scatter-reduce of segment s overlaps the pack/encode/transport of
// segment s+1.
static Status GroupedRingReduceScatter(
    TcpContext& ctx, Ring ring, char* buf,
    const std::vector<std::vector<GroupSeg>>& ring_groups, DataType dtype,
    CompressionMode cmp, int64_t pipe_bytes, uint32_t group = 0) {
  int n = ctx.RingSizeOn(ring, group);
  int rank = ctx.RingRankOn(ring, group);
  std::size_t elem = DataTypeSize(dtype);
  std::vector<int64_t> group_elems(n, 0);
  for (int j = 0; j < n; ++j) {
    for (const auto& s : ring_groups[j]) group_elems[j] += s.cnt;
  }
  int64_t seg = SegmentElems(pipe_bytes, elem, cmp);
  int64_t nseg = SegmentCount(group_elems, seg);
  int64_t max_group = MaxChunk(group_elems);
  if (max_group == 0) return Status::OK();
  if (seg <= 0 || nseg <= 1) {
    seg = max_group;
    nseg = 1;
  }

  std::vector<char> pack(static_cast<std::size_t>(max_group) * elem);
  bool compressed = cmp != CompressionMode::NONE;
  std::vector<char> send_c(compressed ? CompressedSize(seg, cmp) : 0);
  std::vector<char> recv_c[2] = {
      std::vector<char>(compressed ? CompressedSize(seg, cmp)
                                   : static_cast<std::size_t>(seg) * elem),
      std::vector<char>(compressed ? CompressedSize(seg, cmp)
                                   : static_cast<std::size_t>(seg) * elem)};
  std::vector<float> dec[2] = {
      std::vector<float>(compressed ? static_cast<std::size_t>(seg) : 0),
      std::vector<float>(compressed ? static_cast<std::size_t>(seg) : 0)};
  SegmentWorker worker;
  Metrics& m = GlobalMetrics();

  for (int step = 0; step < n - 1; ++step) {
    int send_g = (rank - step + n) % n;
    int recv_g = (rank - step - 1 + n) % n;
    // Pack the outgoing group (it carries every reduction applied so
    // far — the group received and reduced last hop is the one
    // forwarded this hop, as in the flat ring).
    {
      char* p = pack.data();
      for (const auto& s : ring_groups[send_g]) {
        std::memcpy(p, buf + s.off * elem,
                    static_cast<std::size_t>(s.cnt) * elem);
        p += s.cnt * elem;
      }
    }
    const std::vector<GroupSeg>& recv_segs = ring_groups[recv_g];
    for (int64_t s = 0; s < nseg; ++s) {
      int64_t soff = s * seg;
      int64_t sn = ClampSeg(group_elems[send_g], soff, seg);
      int64_t rn = ClampSeg(group_elems[recv_g], soff, seg);
      bool ok;
      char* rc = recv_c[s & 1].data();
      if (compressed) {
        CompressBuffer(
            reinterpret_cast<const float*>(pack.data()) + soff, sn, cmp,
            send_c.data());
        ok = ctx.ExchangeOn(ring, group, send_c.data(),
                            CompressedSize(sn, cmp), rc,
                            CompressedSize(rn, cmp));
      } else {
        ok = ctx.ExchangeOn(ring, group, pack.data() + soff * elem,
                            sn * elem, rc, rn * elem);
      }
      if (!ok) {
        worker.Drain();
        return RingLost(ctx, "hierarchical reduce-scatter local leg failed");
      }
      if (nseg > 1) {
        m.pipeline_segments_total.fetch_add(1, std::memory_order_relaxed);
      }
      if (rn > 0) {
        if (compressed) {
          float* dbuf = dec[s & 1].data();
          worker.Submit([buf, &recv_segs, rc, dbuf, soff, rn, cmp, elem,
                         dtype] {
            DecompressBuffer(rc, rn, cmp, dbuf);
            ReduceScattered(buf, recv_segs,
                            reinterpret_cast<const char*>(dbuf), soff,
                            soff + rn, elem, dtype);
          });
        } else {
          worker.Submit([buf, &recv_segs, rc, soff, rn, elem, dtype] {
            ReduceScattered(buf, recv_segs, rc, soff, soff + rn, elem,
                            dtype);
          });
        }
      }
    }
    // Hop barrier: the pack of the next hop reads what this hop reduced.
    worker.Drain();
  }
  return Status::OK();
}

bool CpuHierarchicalReduceScatter::Enabled(
    const std::vector<TensorTableEntry>& entries,
    const Response& response) const {
  // World group, or a subgroup whose member set forms a uniform
  // (local, cross) grid (docs/TRANSPORT.md) — the decision is a pure
  // function of (members, world grid, synchronized knob) on every rank.
  if (entries[0].device != HOST_DEVICE_ID ||
      !ctx_.hierarchical_possible() ||
      !global_state_->parameter_manager.HierarchicalReduceScatter()) {
    return false;
  }
  if (response.group_id() == 0) return true;
  return ctx_.GroupHierarchicalPossible(
      global_state_->group_table.Members(response.group_id()));
}

Status CpuHierarchicalReduceScatter::Execute(
    std::vector<TensorTableEntry>& entries, const Response& response) {
  // Three stages (grid (local_rank, cross_rank) -> global rank via
  // RankAt; logical chunk r belongs to global rank r):
  //   1. intra-host grouped reduce-scatter: local rank j ends up owning
  //      group_j = { chunk of RankAt(j, c) for every host c }, reduced
  //      over this host's ranks;
  //   2. inter-host ring reduce-scatter of group_j over the cross ring
  //      at local_rank j (relabeled so cross rank c lands on the chunk
  //      of RankAt(j, c) — i.e. every rank ends holding ITS OWN logical
  //      chunk, fully reduced);
  //   3. shard distribution: copy the owned chunk into the shard-sized
  //      output and postscale.
  // Group-scoped (docs/TRANSPORT.md): chunks partition over the GROUP,
  // "rank" is the group position, the stages ride the group's
  // local/cross sub-rings, and the grid lookup maps (local slot, host)
  // to group positions via the uniform-grid table.
  const uint32_t group = response.group_id();
  TcpContext::GroupGrid grid;
  if (group != 0) {
    std::vector<int> members = global_state_->group_table.Members(group);
    if (!ctx_.EnsureGroupSubRings(group, members)) {
      return RingLost(ctx_, "group sub-ring rendezvous failed");
    }
    grid = ctx_.GroupGridOf(members);
  }
  int n = group ? static_cast<int>(grid.pos_grid.size()) : ctx_.size();
  int rank = group
                 ? global_state_->group_table.IndexOf(group, ctx_.rank())
                 : ctx_.rank();
  int ls = group ? grid.local_size : ctx_.local_size();
  int lr = group ? grid.local_pos : ctx_.local_rank();
  int cs = group ? grid.cross_size : ctx_.cross_size();
  auto rank_at = [&](int j, int c) {
    return group ? grid.pos_grid[static_cast<std::size_t>(c) * ls + j]
                 : ctx_.RankAt(j, c);
  };
  auto& timeline = global_state_->timeline;
  CompressionMode cmp = EffectiveCompression(
      static_cast<CompressionMode>(response.compression()),
      entries[0].dtype);
  int64_t pipe = global_state_->parameter_manager.PipelineChunkBytes();
  Metrics& m = GlobalMetrics();
  timeline.ActivityStartAll(response.tensor_names(),
                            "REDUCE_SCATTER_HIERARCHICAL");
  for (auto& e : entries) {
    int64_t count = e.NumElements();
    std::size_t elem = DataTypeSize(e.dtype);
    std::vector<int64_t> counts, offsets;
    PartitionChunks(count, n, &counts, &offsets);
    m.reduce_scatter_total.fetch_add(1, std::memory_order_relaxed);
    m.reduce_scatter_bytes_total.fetch_add(
        static_cast<uint64_t>(count) * elem, std::memory_order_relaxed);
    m.reduce_scatter_hierarchical_total.fetch_add(1,
                                                  std::memory_order_relaxed);
    if (count == 0) continue;

    std::vector<char> work(static_cast<std::size_t>(count) * elem);
    std::memcpy(work.data(), e.data, work.size());
    if (e.prescale_factor != 1.0) {
      ScaleBuffer(work.data(), count, e.dtype, e.prescale_factor);
    }

    // Stage 1 groups, ring-relabeled exactly like the flat op's chunks:
    // ring position m ends owning ring group (m+1)%ls, so ring group m
    // = group (m+ls-1)%ls leaves local rank j with group_j.
    std::vector<std::vector<GroupSeg>> ring_groups(ls);
    for (int mpos = 0; mpos < ls; ++mpos) {
      int j = (mpos + ls - 1) % ls;
      for (int c = 0; c < cs; ++c) {
        int g = rank_at(j, c);
        ring_groups[mpos].push_back({offsets[g], counts[g]});
      }
    }
    Status s = GroupedRingReduceScatter(ctx_, Ring::LOCAL, work.data(),
                                        ring_groups, e.dtype, cmp, pipe,
                                        group);
    if (!s.ok()) {
      timeline.ActivityEndAll(response.tensor_names());
      return s;
    }

    // Stage 2: cross-ring reduce-scatter of my group's per-host chunks
    // (each contiguous; ring chunk m relabeled so cross rank c ends on
    // the chunk of RankAt(lr, c)).
    std::vector<int64_t> ring_counts(cs), ring_offsets(cs);
    for (int mpos = 0; mpos < cs; ++mpos) {
      int g = rank_at(lr, (mpos + cs - 1) % cs);
      ring_counts[mpos] = counts[g];
      ring_offsets[mpos] = offsets[g];
    }
    s = RingReduceScatterOn(ctx_, Ring::CROSS, work.data(), ring_counts,
                            ring_offsets, e.dtype, cmp, pipe, group);
    if (!s.ok()) {
      timeline.ActivityEndAll(response.tensor_names());
      return s;
    }

    // Stage 3: shard distribution.
    std::memcpy(e.output, work.data() + offsets[rank] * elem,
                static_cast<std::size_t>(counts[rank]) * elem);
    if (e.postscale_factor != 1.0) {
      ScaleBuffer(e.output, counts[rank], e.dtype, e.postscale_factor);
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

bool CpuRingAllgather::Enabled(const std::vector<TensorTableEntry>& entries,
                               const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuRingAllgather::Execute(std::vector<TensorTableEntry>& entries,
                                 const Response& response) {
  // Group-scoped: blocks lay out in GROUP order and circulate the
  // group's ring; response.tensor_sizes() is indexed by group position.
  const uint32_t group = response.group_id();
  {
    Status s = EnsureGroup(ctx_, global_state_, group);
    if (!s.ok()) return s;
  }
  int n = group ? ctx_.GroupSize(group) : ctx_.size();
  int rank = group ? ctx_.GroupRank(group) : ctx_.rank();
  auto& timeline = global_state_->timeline;
  timeline.ActivityStartAll(response.tensor_names(), "ALLGATHER_RING");
  for (auto& e : entries) {
    const auto& first_dims = response.tensor_sizes();
    if (static_cast<int>(first_dims.size()) != n) {
      return Status::UnknownError("allgather sizes missing");
    }
    int64_t slice_elems = 1;
    for (int d = 1; d < e.shape.ndims(); ++d) slice_elems *= e.shape.dim_size(d);
    std::size_t elem = DataTypeSize(e.dtype);

    std::vector<int64_t> block_bytes(n), block_offsets(n);
    int64_t total_bytes = 0;
    for (int r = 0; r < n; ++r) {
      block_bytes[r] = first_dims[r] * slice_elems * static_cast<int64_t>(elem);
      block_offsets[r] = total_bytes;
      total_bytes += block_bytes[r];
    }
    e.gathered = std::make_shared<std::vector<char>>(
        static_cast<std::size_t>(total_bytes));
    e.gathered_sizes =
        std::make_shared<std::vector<int64_t>>(first_dims);
    char* out = e.gathered->data();
    std::memcpy(out + block_offsets[rank], e.data,
                static_cast<std::size_t>(block_bytes[rank]));
    // Ring circulation: at step s, forward the block originally owned by
    // (rank - s) and receive the block owned by (rank - s - 1).
    for (int step = 0; step < n - 1; ++step) {
      int send_block = (rank - step + n) % n;
      int recv_block = (rank - step - 1 + n) % n;
      if (!ctx_.ExchangeOn(
              Ring::GLOBAL, group, out + block_offsets[send_block],
              static_cast<std::size_t>(block_bytes[send_block]),
              out + block_offsets[recv_block],
              static_cast<std::size_t>(block_bytes[recv_block]))) {
        timeline.ActivityEndAll(response.tensor_names());
        return RingLost(ctx_, "ring allgather exchange failed");
      }
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

bool CpuHierarchicalAllgather::Enabled(
    const std::vector<TensorTableEntry>& entries,
    const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID &&
         response.group_id() == 0 &&
         ctx_.hierarchical_possible() &&
         global_state_->parameter_manager.HierarchicalAllgather();
}

Status CpuHierarchicalAllgather::Execute(
    std::vector<TensorTableEntry>& entries, const Response& response) {
  // Two-stage allgatherv (role parity with the reference's shared-memory
  // hierarchical allgather, mpi_operations.cc:168-321): blocks circulate
  // the intra-host local ring first, then whole host block-sets circulate
  // the cross ring, so the inter-host links carry each byte once per host
  // instead of once per rank.
  int n = ctx_.size();
  int ls = ctx_.local_size(), lr = ctx_.local_rank();
  int cs = ctx_.cross_size(), cr = ctx_.cross_rank();
  auto& timeline = global_state_->timeline;
  timeline.ActivityStartAll(response.tensor_names(), "ALLGATHER_HIERARCHICAL");
  for (auto& e : entries) {
    const auto& first_dims = response.tensor_sizes();
    if (static_cast<int>(first_dims.size()) != n) {
      timeline.ActivityEndAll(response.tensor_names());
      return Status::UnknownError("allgather sizes missing");
    }
    int64_t slice_elems = 1;
    for (int d = 1; d < e.shape.ndims(); ++d) slice_elems *= e.shape.dim_size(d);
    std::size_t elem = DataTypeSize(e.dtype);

    std::vector<int64_t> block_bytes(n), block_offsets(n);
    int64_t total_bytes = 0;
    for (int r = 0; r < n; ++r) {
      block_bytes[r] = first_dims[r] * slice_elems * static_cast<int64_t>(elem);
      block_offsets[r] = total_bytes;
      total_bytes += block_bytes[r];
    }
    e.gathered = std::make_shared<std::vector<char>>(
        static_cast<std::size_t>(total_bytes));
    e.gathered_sizes = std::make_shared<std::vector<int64_t>>(first_dims);
    char* out = e.gathered->data();
    std::memcpy(out + block_offsets[ctx_.rank()], e.data,
                static_cast<std::size_t>(block_bytes[ctx_.rank()]));

    // Stage 1: circulate single-rank blocks around the CROSS ring (my
    // local_rank's column), writing each at its final (global-rank)
    // offset. Each cross ring carries only its own column, so every byte
    // crosses the inter-host links exactly once in total.
    for (int step = 0; step < cs - 1; ++step) {
      int gs = ctx_.RankAt(lr, (cr - step + cs) % cs);
      int gr = ctx_.RankAt(lr, (cr - step - 1 + cs) % cs);
      if (!ctx_.RingExchangeOn(
              Ring::CROSS, out + block_offsets[gs],
              static_cast<std::size_t>(block_bytes[gs]),
              out + block_offsets[gr],
              static_cast<std::size_t>(block_bytes[gr]))) {
        timeline.ActivityEndAll(response.tensor_names());
        return RingLost(ctx_, "hierarchical allgather cross leg failed");
      }
    }

    // Stage 2: circulate whole column-sets (one local_rank's blocks from
    // every host) around the intra-host local ring. Columns are not
    // contiguous in the global layout, so stage through pack/unpack
    // buffers — cheap, since this leg never leaves the host.
    std::vector<int64_t> col_bytes(ls, 0);
    int64_t max_col = 0;
    for (int j = 0; j < ls; ++j) {
      for (int c = 0; c < cs; ++c) col_bytes[j] += block_bytes[ctx_.RankAt(j, c)];
      max_col = std::max(max_col, col_bytes[j]);
    }
    std::vector<char> tmp_send(static_cast<std::size_t>(max_col));
    std::vector<char> tmp_recv(static_cast<std::size_t>(max_col));
    for (int step = 0; step < ls - 1; ++step) {
      int send_col = (lr - step + ls) % ls;
      int recv_col = (lr - step - 1 + ls) % ls;
      char* p = tmp_send.data();
      for (int c = 0; c < cs; ++c) {
        int g = ctx_.RankAt(send_col, c);
        std::memcpy(p, out + block_offsets[g],
                    static_cast<std::size_t>(block_bytes[g]));
        p += block_bytes[g];
      }
      if (!ctx_.RingExchangeOn(
              Ring::LOCAL, tmp_send.data(),
              static_cast<std::size_t>(col_bytes[send_col]), tmp_recv.data(),
              static_cast<std::size_t>(col_bytes[recv_col]))) {
        timeline.ActivityEndAll(response.tensor_names());
        return RingLost(ctx_, "hierarchical allgather local leg failed");
      }
      const char* q = tmp_recv.data();
      for (int c = 0; c < cs; ++c) {
        int g = ctx_.RankAt(recv_col, c);
        std::memcpy(out + block_offsets[g], q,
                    static_cast<std::size_t>(block_bytes[g]));
        q += block_bytes[g];
      }
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

bool CpuBroadcast::Enabled(const std::vector<TensorTableEntry>& entries,
                           const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuBroadcast::Execute(std::vector<TensorTableEntry>& entries,
                             const Response& response) {
  auto& timeline = global_state_->timeline;
  timeline.ActivityStartAll(response.tensor_names(), "BROADCAST_RING");
  const uint32_t group = response.group_id();
  {
    Status s = EnsureGroup(ctx_, global_state_, group);
    if (!s.ok()) {
      timeline.ActivityEndAll(response.tensor_names());
      return s;
    }
  }
  int rank = ctx_.rank();
  for (auto& e : entries) {
    std::size_t len = e.SizeBytes();
    // Cut-through pipelined broadcast over the global ring (or, for a
    // group collective, the group's ring with the root remapped to its
    // group position): every byte crosses each link once and
    // intermediate ranks forward as they receive, replacing the former
    // star fan-out that serialized N-1 full copies through rank 0.
    if (rank == e.root_rank && e.output != e.data) {
      std::memcpy(e.output, e.data, len);
    }
    bool ok;
    if (group != 0) {
      int root_pos = global_state_->group_table.IndexOf(group, e.root_rank);
      ok = root_pos >= 0 &&
           ctx_.GroupBroadcast(group, e.output, len, root_pos);
    } else {
      ok = ctx_.RingBroadcast(e.output, len, e.root_rank);
    }
    if (!ok) {
      timeline.ActivityEndAll(response.tensor_names());
      return RingLost(ctx_, "ring broadcast failed");
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

}  // namespace hvdtpu
