#include "cpu_operations.h"

#include <algorithm>
#include <cstring>

#include "global_state.h"
#include "half.h"
#include "logging.h"

namespace hvdtpu {

// A failed ring exchange means the data-plane transport is desynced or a
// peer is gone — recoverable only by a generation restart. The status
// carries the CONNECTION_LOST marker (Python's elastic layer rolls back
// on it, and the background loop escalates it to a connection-lost
// shutdown — see PerformOperation) plus the transport-level cause from
// the context, so a chaos run's failure names what was injected
// (checksum mismatch, deadline expiry, peer close).
static Status RingLost(const TcpContext& ctx, const char* what) {
  std::string msg = CONNECTION_LOST_ERROR;
  msg += " [";
  msg += what;
  if (!ctx.last_error().empty()) {
    msg += ": ";
    msg += ctx.last_error();
  }
  msg += "]";
  return Status::UnknownError(msg);
}

template <typename T>
static void ReduceSumT(T* dst, const T* src, int64_t n) {
  for (int64_t i = 0; i < n; ++i) dst[i] += src[i];
}

void ReduceSum(void* dst, const void* src, int64_t count, DataType dtype) {
  switch (dtype) {
    case DataType::HVD_UINT8:
      ReduceSumT(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src),
                 count);
      break;
    case DataType::HVD_INT8:
      ReduceSumT(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
                 count);
      break;
    case DataType::HVD_UINT16:
      ReduceSumT(static_cast<uint16_t*>(dst),
                 static_cast<const uint16_t*>(src), count);
      break;
    case DataType::HVD_INT16:
      ReduceSumT(static_cast<int16_t*>(dst), static_cast<const int16_t*>(src),
                 count);
      break;
    case DataType::HVD_INT32:
      ReduceSumT(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src),
                 count);
      break;
    case DataType::HVD_INT64:
      ReduceSumT(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT32:
      ReduceSumT(static_cast<float*>(dst), static_cast<const float*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT64:
      ReduceSumT(static_cast<double*>(dst), static_cast<const double*>(src),
                 count);
      break;
    case DataType::HVD_FLOAT16: {
      auto* d = static_cast<uint16_t*>(dst);
      const auto* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToHalf(HalfToFloat(d[i]) + HalfToFloat(s[i]));
      }
      break;
    }
    case DataType::HVD_BFLOAT16: {
      auto* d = static_cast<uint16_t*>(dst);
      const auto* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToBFloat16(BFloat16ToFloat(d[i]) + BFloat16ToFloat(s[i]));
      }
      break;
    }
    case DataType::HVD_BOOL: {
      auto* d = static_cast<uint8_t*>(dst);
      const auto* s = static_cast<const uint8_t*>(src);
      for (int64_t i = 0; i < count; ++i) d[i] = d[i] || s[i];
      break;
    }
  }
}

template <typename T>
static void ScaleT(T* buf, int64_t n, double factor) {
  for (int64_t i = 0; i < n; ++i) {
    buf[i] = static_cast<T>(buf[i] * factor);
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::HVD_UINT8:
      ScaleT(static_cast<uint8_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT8:
      ScaleT(static_cast<int8_t*>(buf), count, factor);
      break;
    case DataType::HVD_UINT16:
      ScaleT(static_cast<uint16_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT16:
      ScaleT(static_cast<int16_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT32:
      ScaleT(static_cast<int32_t*>(buf), count, factor);
      break;
    case DataType::HVD_INT64:
      ScaleT(static_cast<int64_t*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT32:
      ScaleT(static_cast<float*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT64:
      ScaleT(static_cast<double*>(buf), count, factor);
      break;
    case DataType::HVD_FLOAT16: {
      auto* b = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i) {
        b[i] = FloatToHalf(static_cast<float>(HalfToFloat(b[i]) * factor));
      }
      break;
    }
    case DataType::HVD_BFLOAT16: {
      auto* b = static_cast<uint16_t*>(buf);
      for (int64_t i = 0; i < count; ++i) {
        b[i] = FloatToBFloat16(
            static_cast<float>(BFloat16ToFloat(b[i]) * factor));
      }
      break;
    }
    case DataType::HVD_BOOL:
      break;  // scaling a bool is meaningless; ignore
  }
}

// Partitions `count` elements into n near-equal chunks. The same math
// lives in horovod_tpu/common/ops.py shard_partition — the Python side
// must size reduce-scatter shard buffers identically.
static void PartitionChunks(int64_t count, int n, std::vector<int64_t>* counts,
                            std::vector<int64_t>* offsets) {
  counts->assign(n, 0);
  offsets->assign(n, 0);
  int64_t base = count / n, rem = count % n;
  int64_t off = 0;
  for (int i = 0; i < n; ++i) {
    (*counts)[i] = base + (i < rem ? 1 : 0);
    (*offsets)[i] = off;
    off += (*counts)[i];
  }
}

static int64_t MaxChunk(const std::vector<int64_t>& counts) {
  int64_t m = 0;
  for (int64_t c : counts) m = std::max(m, c);
  return m;
}

// Reduce-scatter leg of a ring allreduce: after n-1 steps ring rank r owns
// chunk (r+1) % n, reduced over the whole ring.
//
// With cmp != NONE (dtype f32 by negotiation) this is the EQuARX-style
// dequant-reduce-requant pipeline: the accumulator chunk in `buf` stays
// f32; each hop encodes the outgoing chunk (requant), ships the small
// payload, and the receiver decodes (dequant) and ReduceSums in f32 —
// so wire bytes shrink while the sum never accumulates in the narrow
// format. CRC framing in RingExchangeOn covers the compressed payload.
static Status RingReduceScatterOn(TcpContext& ctx, Ring ring, char* buf,
                                  const std::vector<int64_t>& counts,
                                  const std::vector<int64_t>& offsets,
                                  DataType dtype, CompressionMode cmp) {
  int n = ctx.RingSize(ring);
  int rank = ctx.RingRank(ring);
  std::size_t elem = DataTypeSize(dtype);
  if (cmp != CompressionMode::NONE) {
    // Scratch sized by the LARGEST chunk: callers may pass a rotated
    // chunk order (the standalone reduce-scatter op does), so counts[0]
    // is not necessarily the maximum.
    float* f = reinterpret_cast<float*>(buf);
    int64_t max_chunk = MaxChunk(counts);
    std::vector<char> send_c(CompressedSize(max_chunk, cmp));
    std::vector<char> recv_c(CompressedSize(max_chunk, cmp));
    std::vector<float> tmp(static_cast<std::size_t>(max_chunk));
    for (int step = 0; step < n - 1; ++step) {
      int send_chunk = (rank - step + n) % n;
      int recv_chunk = (rank - step - 1 + n) % n;
      std::size_t send_len = CompressedSize(counts[send_chunk], cmp);
      std::size_t recv_len = CompressedSize(counts[recv_chunk], cmp);
      CompressBuffer(f + offsets[send_chunk], counts[send_chunk], cmp,
                     send_c.data());
      if (!ctx.RingExchangeOn(ring, send_c.data(), send_len, recv_c.data(),
                              recv_len)) {
        return RingLost(ctx, "ring reduce-scatter exchange failed");
      }
      DecompressBuffer(recv_c.data(), counts[recv_chunk], cmp, tmp.data());
      ReduceSum(f + offsets[recv_chunk], tmp.data(), counts[recv_chunk],
                dtype);
    }
    return Status::OK();
  }
  std::vector<char> tmp(static_cast<std::size_t>(MaxChunk(counts)) * elem);
  for (int step = 0; step < n - 1; ++step) {
    int send_chunk = (rank - step + n) % n;
    int recv_chunk = (rank - step - 1 + n) % n;
    if (!ctx.RingExchangeOn(ring, buf + offsets[send_chunk] * elem,
                            counts[send_chunk] * elem, tmp.data(),
                            counts[recv_chunk] * elem)) {
      return RingLost(ctx, "ring reduce-scatter exchange failed");
    }
    ReduceSum(buf + offsets[recv_chunk] * elem, tmp.data(), counts[recv_chunk],
              dtype);
  }
  return Status::OK();
}

// Allgather leg: circulates the fully-reduced chunks (owned per the
// reduce-scatter leg above) until every ring member has all of them.
//
// Compressed variant: each owner encodes its reduced chunk ONCE, decodes
// its own copy back (so the owner holds exactly what everyone else will
// decode), and the ring then forwards the encoded payloads VERBATIM —
// no per-hop requantization, so there is no hop-count-dependent drift
// and every rank ends with bitwise-identical chunk values.
static Status RingAllgatherPhaseOn(TcpContext& ctx, Ring ring, char* buf,
                                   const std::vector<int64_t>& counts,
                                   const std::vector<int64_t>& offsets,
                                   DataType dtype, CompressionMode cmp) {
  int n = ctx.RingSize(ring);
  int rank = ctx.RingRank(ring);
  std::size_t elem = DataTypeSize(dtype);
  if (cmp != CompressionMode::NONE) {
    // Two rotating payload buffers: step s only ever forwards the chunk
    // received at step s-1, so O(1) encoded chunks suffice (matching
    // the uncompressed path's single tmp), not one per rank.
    float* f = reinterpret_cast<float*>(buf);
    int owned = (rank + 1) % n;
    int64_t max_chunk = MaxChunk(counts);
    std::vector<char> send_c(CompressedSize(max_chunk, cmp));
    std::vector<char> recv_c(CompressedSize(max_chunk, cmp));
    CompressBuffer(f + offsets[owned], counts[owned], cmp, send_c.data());
    DecompressBuffer(send_c.data(), counts[owned], cmp, f + offsets[owned]);
    for (int step = 0; step < n - 1; ++step) {
      int send_chunk = (rank + 1 - step + n) % n;
      int recv_chunk = (rank - step + n) % n;
      if (!ctx.RingExchangeOn(ring, send_c.data(),
                              CompressedSize(counts[send_chunk], cmp),
                              recv_c.data(),
                              CompressedSize(counts[recv_chunk], cmp))) {
        return RingLost(ctx, "ring allgather exchange failed");
      }
      DecompressBuffer(recv_c.data(), counts[recv_chunk], cmp,
                       f + offsets[recv_chunk]);
      std::swap(send_c, recv_c);
    }
    return Status::OK();
  }
  for (int step = 0; step < n - 1; ++step) {
    int send_chunk = (rank + 1 - step + n) % n;
    int recv_chunk = (rank - step + n) % n;
    if (!ctx.RingExchangeOn(ring, buf + offsets[send_chunk] * elem,
                            counts[send_chunk] * elem,
                            buf + offsets[recv_chunk] * elem,
                            counts[recv_chunk] * elem)) {
      return RingLost(ctx, "ring allgather exchange failed");
    }
  }
  return Status::OK();
}

Status RingAllreduceOn(TcpContext& ctx, Ring ring, void* buffer, int64_t count,
                       DataType dtype, CompressionMode cmp) {
  int n = ctx.RingSize(ring);
  if (n == 1 || count == 0) return Status::OK();
  std::vector<int64_t> counts, offsets;
  PartitionChunks(count, n, &counts, &offsets);
  char* buf = static_cast<char*>(buffer);
  Status s = RingReduceScatterOn(ctx, ring, buf, counts, offsets, dtype, cmp);
  if (!s.ok()) return s;
  return RingAllgatherPhaseOn(ctx, ring, buf, counts, offsets, dtype, cmp);
}

bool CpuRingAllreduce::Enabled(const std::vector<TensorTableEntry>& entries,
                               const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuRingAllreduce::ReduceBuffer(void* buffer, int64_t count,
                                      DataType dtype, CompressionMode cmp) {
  return RingAllreduceOn(ctx_, Ring::GLOBAL, buffer, count, dtype, cmp);
}

Status CpuRingAllreduce::Execute(std::vector<TensorTableEntry>& entries,
                                 const Response& response) {
  auto& timeline = global_state_->timeline;
  void* buffer = nullptr;
  std::size_t buffer_len = 0;
  int64_t total_elements = NumElements(entries);

  if (entries.size() > 1) {
    std::vector<std::string> names = response.tensor_names();
    timeline.ActivityStartAll(names, "MEMCPY_IN_FUSION_BUFFER");
    Status s = MemcpyInFusionBuffer(entries, &buffer, &buffer_len);
    timeline.ActivityEndAll(names);
    if (!s.ok()) return s;
  } else {
    auto& e = entries[0];
    if (e.output != e.data) {
      std::memcpy(e.output, e.data, e.SizeBytes());
    }
    buffer = e.output;
    buffer_len = e.SizeBytes();
  }

  // Per-entry prescale on its segment (factors may differ across fused
  // tensors; scaling commutes with the sum).
  {
    char* p = static_cast<char*>(buffer);
    for (auto& e : entries) {
      if (e.prescale_factor != 1.0) {
        ScaleBuffer(p, e.NumElements(), e.dtype, e.prescale_factor);
      }
      p += e.SizeBytes();
    }
  }

  // Belt-and-braces dtype filter: the negotiated mode is already
  // effective (dtype-filtered at enqueue), and fused responses only
  // merge same-mode tensors.
  CompressionMode cmp = EffectiveCompression(
      static_cast<CompressionMode>(response.compression()),
      entries[0].dtype);
  {
    Metrics& m = GlobalMetrics();
    if (cmp == CompressionMode::BF16) {
      m.allreduce_bf16_total.fetch_add(1, std::memory_order_relaxed);
    } else if (cmp == CompressionMode::INT8) {
      m.allreduce_int8_total.fetch_add(1, std::memory_order_relaxed);
    } else {
      m.allreduce_uncompressed_total.fetch_add(1,
                                               std::memory_order_relaxed);
    }
  }

  timeline.ActivityStartAll(response.tensor_names(), ActivityName());
  Status s = ReduceBuffer(buffer, total_elements, entries[0].dtype, cmp);
  timeline.ActivityEndAll(response.tensor_names());
  if (!s.ok()) return s;

  {
    char* p = static_cast<char*>(buffer);
    for (auto& e : entries) {
      if (e.postscale_factor != 1.0) {
        ScaleBuffer(p, e.NumElements(), e.dtype, e.postscale_factor);
      }
      p += e.SizeBytes();
    }
  }

  if (entries.size() > 1) {
    timeline.ActivityStartAll(response.tensor_names(),
                              "MEMCPY_OUT_FUSION_BUFFER");
    MemcpyOutFusionBuffer(buffer, entries);
    timeline.ActivityEndAll(response.tensor_names());
  }
  return Status::OK();
}

bool CpuHierarchicalAllreduce::Enabled(
    const std::vector<TensorTableEntry>& entries,
    const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID &&
         ctx_.hierarchical_possible() &&
         global_state_->parameter_manager.HierarchicalAllreduce();
}

Status CpuHierarchicalAllreduce::ReduceBuffer(void* buffer, int64_t count,
                                              DataType dtype,
                                              CompressionMode cmp) {
  // Two-level composite (reference: nccl_operations.cc:150-346):
  //   1. local-ring reduce-scatter — local rank lr ends up owning chunk
  //      (lr+1) % ls, reduced over the local group;
  //   2. cross-ring allreduce of the owned chunk (one participant per
  //      local_rank, riding the inter-host links only);
  //   3. local-ring allgather of the now globally-reduced chunks.
  int ls = ctx_.local_size();
  int lr = ctx_.local_rank();
  if (count == 0) return Status::OK();
  std::size_t elem = DataTypeSize(dtype);

  std::vector<int64_t> counts, offsets;
  PartitionChunks(count, ls, &counts, &offsets);
  char* buf = static_cast<char*>(buffer);

  Status s = RingReduceScatterOn(ctx_, Ring::LOCAL, buf, counts, offsets,
                                 dtype, cmp);
  if (!s.ok()) return s;

  int owned = (lr + 1) % ls;
  s = RingAllreduceOn(ctx_, Ring::CROSS, buf + offsets[owned] * elem,
                      counts[owned], dtype, cmp);
  if (!s.ok()) return s;

  return RingAllgatherPhaseOn(ctx_, Ring::LOCAL, buf, counts, offsets, dtype,
                              cmp);
}

bool CpuRingReduceScatter::Enabled(
    const std::vector<TensorTableEntry>& entries,
    const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuRingReduceScatter::Execute(std::vector<TensorTableEntry>& entries,
                                     const Response& response) {
  // The reduce-scatter leg of the ring as a standalone op (docs/ZERO.md):
  // rank r's output receives logical chunk r of the PartitionChunks
  // partition over the flattened tensor, summed across all ranks. Wire
  // compression applies per hop exactly as in the fused allreduce leg
  // (the f32 accumulator never lives in the narrow format). The
  // controller never fuses REDUCESCATTER responses — sharded callers
  // fuse at the source instead (one flat gradient buffer whose offsets
  // ARE the shard boundaries), so entries is normally a single tensor.
  int n = ctx_.size();
  int rank = ctx_.rank();
  auto& timeline = global_state_->timeline;
  CompressionMode cmp = EffectiveCompression(
      static_cast<CompressionMode>(response.compression()),
      entries[0].dtype);
  Metrics& m = GlobalMetrics();
  timeline.ActivityStartAll(response.tensor_names(), "REDUCE_SCATTER_RING");
  for (auto& e : entries) {
    int64_t count = e.NumElements();
    std::size_t elem = DataTypeSize(e.dtype);
    std::vector<int64_t> counts, offsets;
    PartitionChunks(count, n, &counts, &offsets);
    m.reduce_scatter_total.fetch_add(1, std::memory_order_relaxed);
    m.reduce_scatter_bytes_total.fetch_add(
        static_cast<uint64_t>(count) * elem, std::memory_order_relaxed);
    if (count == 0) continue;
    if (n == 1) {
      if (e.output != e.data) std::memcpy(e.output, e.data, e.SizeBytes());
      ScaleBuffer(e.output, count, e.dtype,
                  e.prescale_factor * e.postscale_factor);
      continue;
    }
    // The ring leg leaves ring-rank r owning ring chunk (r+1)%n; rank r
    // must own LOGICAL chunk r, so ring chunk j maps onto logical chunk
    // (j+n-1)%n — a pure relabeling (offsets stay the contiguous
    // PartitionChunks layout, identical on every rank).
    std::vector<int64_t> ring_counts(n), ring_offsets(n);
    for (int j = 0; j < n; ++j) {
      int logical = (j + n - 1) % n;
      ring_counts[j] = counts[logical];
      ring_offsets[j] = offsets[logical];
    }
    // Work in a scratch copy: the entry's output buffer is shard-sized
    // (counts[rank] elements), not full-tensor-sized.
    std::vector<char> work(static_cast<std::size_t>(count) * elem);
    std::memcpy(work.data(), e.data, work.size());
    if (e.prescale_factor != 1.0) {
      ScaleBuffer(work.data(), count, e.dtype, e.prescale_factor);
    }
    Status s = RingReduceScatterOn(ctx_, Ring::GLOBAL, work.data(),
                                   ring_counts, ring_offsets, e.dtype, cmp);
    if (!s.ok()) {
      timeline.ActivityEndAll(response.tensor_names());
      return s;
    }
    std::memcpy(e.output, work.data() + offsets[rank] * elem,
                static_cast<std::size_t>(counts[rank]) * elem);
    if (e.postscale_factor != 1.0) {
      ScaleBuffer(e.output, counts[rank], e.dtype, e.postscale_factor);
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

bool CpuRingAllgather::Enabled(const std::vector<TensorTableEntry>& entries,
                               const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuRingAllgather::Execute(std::vector<TensorTableEntry>& entries,
                                 const Response& response) {
  int n = ctx_.size();
  int rank = ctx_.rank();
  auto& timeline = global_state_->timeline;
  timeline.ActivityStartAll(response.tensor_names(), "ALLGATHER_RING");
  for (auto& e : entries) {
    const auto& first_dims = response.tensor_sizes();
    if (static_cast<int>(first_dims.size()) != n) {
      return Status::UnknownError("allgather sizes missing");
    }
    int64_t slice_elems = 1;
    for (int d = 1; d < e.shape.ndims(); ++d) slice_elems *= e.shape.dim_size(d);
    std::size_t elem = DataTypeSize(e.dtype);

    std::vector<int64_t> block_bytes(n), block_offsets(n);
    int64_t total_bytes = 0;
    for (int r = 0; r < n; ++r) {
      block_bytes[r] = first_dims[r] * slice_elems * static_cast<int64_t>(elem);
      block_offsets[r] = total_bytes;
      total_bytes += block_bytes[r];
    }
    e.gathered = std::make_shared<std::vector<char>>(
        static_cast<std::size_t>(total_bytes));
    e.gathered_sizes =
        std::make_shared<std::vector<int64_t>>(first_dims);
    char* out = e.gathered->data();
    std::memcpy(out + block_offsets[rank], e.data,
                static_cast<std::size_t>(block_bytes[rank]));
    // Ring circulation: at step s, forward the block originally owned by
    // (rank - s) and receive the block owned by (rank - s - 1).
    for (int step = 0; step < n - 1; ++step) {
      int send_block = (rank - step + n) % n;
      int recv_block = (rank - step - 1 + n) % n;
      if (!ctx_.RingExchange(out + block_offsets[send_block],
                             static_cast<std::size_t>(block_bytes[send_block]),
                             out + block_offsets[recv_block],
                             static_cast<std::size_t>(block_bytes[recv_block]))) {
        timeline.ActivityEndAll(response.tensor_names());
        return RingLost(ctx_, "ring allgather exchange failed");
      }
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

bool CpuHierarchicalAllgather::Enabled(
    const std::vector<TensorTableEntry>& entries,
    const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID &&
         ctx_.hierarchical_possible() &&
         global_state_->parameter_manager.HierarchicalAllgather();
}

Status CpuHierarchicalAllgather::Execute(
    std::vector<TensorTableEntry>& entries, const Response& response) {
  // Two-stage allgatherv (role parity with the reference's shared-memory
  // hierarchical allgather, mpi_operations.cc:168-321): blocks circulate
  // the intra-host local ring first, then whole host block-sets circulate
  // the cross ring, so the inter-host links carry each byte once per host
  // instead of once per rank.
  int n = ctx_.size();
  int ls = ctx_.local_size(), lr = ctx_.local_rank();
  int cs = ctx_.cross_size(), cr = ctx_.cross_rank();
  auto& timeline = global_state_->timeline;
  timeline.ActivityStartAll(response.tensor_names(), "ALLGATHER_HIERARCHICAL");
  for (auto& e : entries) {
    const auto& first_dims = response.tensor_sizes();
    if (static_cast<int>(first_dims.size()) != n) {
      timeline.ActivityEndAll(response.tensor_names());
      return Status::UnknownError("allgather sizes missing");
    }
    int64_t slice_elems = 1;
    for (int d = 1; d < e.shape.ndims(); ++d) slice_elems *= e.shape.dim_size(d);
    std::size_t elem = DataTypeSize(e.dtype);

    std::vector<int64_t> block_bytes(n), block_offsets(n);
    int64_t total_bytes = 0;
    for (int r = 0; r < n; ++r) {
      block_bytes[r] = first_dims[r] * slice_elems * static_cast<int64_t>(elem);
      block_offsets[r] = total_bytes;
      total_bytes += block_bytes[r];
    }
    e.gathered = std::make_shared<std::vector<char>>(
        static_cast<std::size_t>(total_bytes));
    e.gathered_sizes = std::make_shared<std::vector<int64_t>>(first_dims);
    char* out = e.gathered->data();
    std::memcpy(out + block_offsets[ctx_.rank()], e.data,
                static_cast<std::size_t>(block_bytes[ctx_.rank()]));

    // Stage 1: circulate single-rank blocks around the CROSS ring (my
    // local_rank's column), writing each at its final (global-rank)
    // offset. Each cross ring carries only its own column, so every byte
    // crosses the inter-host links exactly once in total.
    for (int step = 0; step < cs - 1; ++step) {
      int gs = ctx_.RankAt(lr, (cr - step + cs) % cs);
      int gr = ctx_.RankAt(lr, (cr - step - 1 + cs) % cs);
      if (!ctx_.RingExchangeOn(
              Ring::CROSS, out + block_offsets[gs],
              static_cast<std::size_t>(block_bytes[gs]),
              out + block_offsets[gr],
              static_cast<std::size_t>(block_bytes[gr]))) {
        timeline.ActivityEndAll(response.tensor_names());
        return RingLost(ctx_, "hierarchical allgather cross leg failed");
      }
    }

    // Stage 2: circulate whole column-sets (one local_rank's blocks from
    // every host) around the intra-host local ring. Columns are not
    // contiguous in the global layout, so stage through pack/unpack
    // buffers — cheap, since this leg never leaves the host.
    std::vector<int64_t> col_bytes(ls, 0);
    int64_t max_col = 0;
    for (int j = 0; j < ls; ++j) {
      for (int c = 0; c < cs; ++c) col_bytes[j] += block_bytes[ctx_.RankAt(j, c)];
      max_col = std::max(max_col, col_bytes[j]);
    }
    std::vector<char> tmp_send(static_cast<std::size_t>(max_col));
    std::vector<char> tmp_recv(static_cast<std::size_t>(max_col));
    for (int step = 0; step < ls - 1; ++step) {
      int send_col = (lr - step + ls) % ls;
      int recv_col = (lr - step - 1 + ls) % ls;
      char* p = tmp_send.data();
      for (int c = 0; c < cs; ++c) {
        int g = ctx_.RankAt(send_col, c);
        std::memcpy(p, out + block_offsets[g],
                    static_cast<std::size_t>(block_bytes[g]));
        p += block_bytes[g];
      }
      if (!ctx_.RingExchangeOn(
              Ring::LOCAL, tmp_send.data(),
              static_cast<std::size_t>(col_bytes[send_col]), tmp_recv.data(),
              static_cast<std::size_t>(col_bytes[recv_col]))) {
        timeline.ActivityEndAll(response.tensor_names());
        return RingLost(ctx_, "hierarchical allgather local leg failed");
      }
      const char* q = tmp_recv.data();
      for (int c = 0; c < cs; ++c) {
        int g = ctx_.RankAt(recv_col, c);
        std::memcpy(out + block_offsets[g], q,
                    static_cast<std::size_t>(block_bytes[g]));
        q += block_bytes[g];
      }
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

bool CpuBroadcast::Enabled(const std::vector<TensorTableEntry>& entries,
                           const Response& response) const {
  return entries[0].device == HOST_DEVICE_ID;
}

Status CpuBroadcast::Execute(std::vector<TensorTableEntry>& entries,
                             const Response& response) {
  auto& timeline = global_state_->timeline;
  timeline.ActivityStartAll(response.tensor_names(), "BROADCAST_RING");
  int rank = ctx_.rank();
  for (auto& e : entries) {
    std::size_t len = e.SizeBytes();
    // Cut-through pipelined broadcast over the global ring: every byte
    // crosses each link once and intermediate ranks forward as they
    // receive, replacing the former star fan-out that serialized N-1 full
    // copies through rank 0.
    if (rank == e.root_rank && e.output != e.data) {
      std::memcpy(e.output, e.data, len);
    }
    if (!ctx_.RingBroadcast(e.output, len, e.root_rank)) {
      timeline.ActivityEndAll(response.tensor_names());
      return RingLost(ctx_, "ring broadcast failed");
    }
  }
  timeline.ActivityEndAll(response.tensor_names());
  return Status::OK();
}

}  // namespace hvdtpu
