#include "tensor_queue.h"

namespace hvdtpu {

Status TensorQueue::AddToTensorQueue(TensorTableEntry entry, Request message) {
  std::lock_guard<std::mutex> lk(mutex_);
  if (tensor_table_.find(entry.tensor_name) != tensor_table_.end()) {
    return Status::InvalidArgument(DUPLICATE_NAME_ERROR);
  }
  tensor_table_.emplace(entry.tensor_name, std::move(entry));
  message_queue_.push_back(std::move(message));
  return Status::OK();
}

void TensorQueue::PopMessagesFromQueue(std::deque<Request>& messages) {
  std::lock_guard<std::mutex> lk(mutex_);
  while (!message_queue_.empty()) {
    messages.push_back(std::move(message_queue_.front()));
    message_queue_.pop_front();
  }
}

void TensorQueue::PushMessageToQueue(const Request& message) {
  std::lock_guard<std::mutex> lk(mutex_);
  message_queue_.push_back(message);
}

void TensorQueue::GetTensorEntriesFromResponse(
    const Response& response, std::vector<TensorTableEntry>& entries) {
  std::lock_guard<std::mutex> lk(mutex_);
  for (const auto& name : response.tensor_names()) {
    auto it = tensor_table_.find(name);
    if (it == tensor_table_.end()) continue;
    // Group scoping: a response only claims entries of ITS group — a
    // rank holding "grad.0" pending in group 2 must not execute it
    // against group 1's response for the same name (the 2-D mesh's
    // per-column gradient reduce is exactly this shape).
    if (it->second.group_id != response.group_id()) continue;
    entries.push_back(std::move(it->second));
    tensor_table_.erase(it);
  }
}

const TensorTableEntry& TensorQueue::GetTensorEntry(
    const std::string& name) const {
  std::lock_guard<std::mutex> lk(mutex_);
  return tensor_table_.at(name);
}

bool TensorQueue::HasEntry(const std::string& name) const {
  std::lock_guard<std::mutex> lk(mutex_);
  return tensor_table_.find(name) != tensor_table_.end();
}

void TensorQueue::FinalizeTensorQueue(const Status& status) {
  std::lock_guard<std::mutex> lk(mutex_);
  for (auto& kv : tensor_table_) {
    if (kv.second.callback) kv.second.callback(status, kv.second);
  }
  tensor_table_.clear();
  message_queue_.clear();
}

int64_t TensorQueue::GetTensorDataForAutotuner(
    const std::deque<Request>& messages, int64_t& total_bytes) {
  std::lock_guard<std::mutex> lk(mutex_);
  int64_t count = 0;
  total_bytes = 0;
  for (const auto& msg : messages) {
    auto it = tensor_table_.find(msg.tensor_name());
    if (it == tensor_table_.end()) continue;
    total_bytes += static_cast<int64_t>(it->second.SizeBytes());
    ++count;
  }
  return count;
}

}  // namespace hvdtpu
