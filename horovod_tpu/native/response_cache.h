// LRU cache of negotiated Responses + cross-rank cache-bit coordinator.
//
// Why it exists: once a training loop reaches steady state, every cycle
// queues the same tensors with the same params. Caching the negotiated
// Response lets every cycle skip the coordinator round-trip entirely: ranks
// only exchange one fixed-size bit vector (bitwise AND) to agree on which
// cached entries are globally ready. This is the critical negotiation-latency
// optimization at large rank counts.
//
// Process groups (docs/GROUPS.md): entries are keyed on
// GroupQualifiedName(group, name), so the same tensor name active in two
// groups at once occupies two bits, and a tensor renegotiated under a
// DIFFERENT group id (membership change) reads as INVALID — erase and
// renegotiate, exactly like a compression-mode change. The bit protocol
// requires IDENTICAL cache contents on every rank, so ranks outside a
// response's group still mirror it as a FOREIGN entry (same bit position,
// no validation params) and treat its bit as vacuously ready each cycle
// (`NonMemberBits`) — the global AND then spans exactly the group's
// members, which is what "ready-rank bitmaps sized to the group" means
// on a bit-vector protocol.
//
// Capability parity with /root/reference horovod/common/response_cache.{h,cc}
// (ResponseCache + CacheCoordinator); fresh implementation.
#ifndef HVD_TPU_RESPONSE_CACHE_H
#define HVD_TPU_RESPONSE_CACHE_H

#include <cstdint>
#include <list>
#include <set>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "group_table.h"
#include "message.h"

namespace hvdtpu {

class TensorQueue;
class Controller;

class ResponseCache {
 public:
  enum class CacheState { MISS = 0, HIT = 1, INVALID = 2 };

  void set_capacity(uint32_t capacity);
  uint32_t capacity() const { return capacity_; }
  uint32_t num_active_bits() const;

  // Drops every cached entry and bit assignment. Called on re-init so a new
  // elastic generation (different size/topology) never executes a response
  // negotiated under the old membership.
  void clear();

  // MISS if never seen; HIT if cached with identical params; INVALID if the
  // (group, name) key is cached but shape/dtype/op params changed — or the
  // NAME is cached under a different group id (membership change). Either
  // way the stale entry must be dropped and renegotiated.
  CacheState cached(const Request& request) const;

  // Inserts (or refreshes) the response after a successful execution —
  // called with the IDENTICAL response list on every rank. Ranks outside
  // the response's group insert a foreign placeholder entry so bit
  // positions stay rank-identical; `groups`/`my_rank` decide membership.
  void put(const Response& response, TensorQueue& tensor_queue,
           const GroupTable* groups, int my_rank);

  // Bit <-> response lookups for the fast path.
  const Response& get_response(uint32_t cache_bit);
  const Response& peek_response(uint32_t cache_bit) const;
  uint32_t peek_cache_bit(const Request& request) const;
  // Lookup by composite cache key (GroupQualifiedName) — the stall
  // inspector records cached tensors under this key.
  uint32_t peek_cache_bit(const std::string& cache_key) const;

  // Bits whose entry belongs to a group THIS rank is not a member of —
  // recorded as vacuous hits every cycle so the cross-rank AND only
  // spans actual members.
  void NonMemberBits(std::vector<uint32_t>* out) const;

  void erase_response(uint32_t cache_bit);
  // Re-packs cache bits 0..N-1 in LRU order after evictions/erases so all
  // ranks agree on bit positions (called while ranks are in sync).
  void update_cache_bits();

 private:
  struct CacheEntry {
    Response response;
    std::string key;  // GroupQualifiedName(group_id, name)
    // Params captured from the Request for validity checking.
    DataType dtype;
    std::vector<int64_t> shape;
    int32_t root_rank = 0;
    double prescale_factor = 1.0;
    double postscale_factor = 1.0;
    // Wire-compression mode is part of the cache key: a hit with a
    // different mode is INVALID (renegotiate), never a silent reuse of
    // a response negotiated under another codec.
    uint8_t compression = 0;
    // Process-group scope. group_digest guards against a same-id
    // membership change; is_member gates the vacuous-hit sweep;
    // foreign entries (mirrored on non-members) carry no validation
    // params and read INVALID on any local lookup.
    uint32_t group_id = 0;
    uint64_t group_digest = 0;
    bool is_member = true;
    bool foreign = false;
  };

  void put_entry(CacheEntry entry);  // keyed by entry.key
  void DropNameRef(const std::string& name);

  uint32_t capacity_ = 1024;
  // LRU list of cache bits; most recent at front. cache_[bit] = entry.
  std::vector<CacheEntry> cache_;
  std::vector<std::list<uint32_t>::iterator> cache_iters_;
  std::list<uint32_t> lru_;
  std::unordered_map<std::string, uint32_t> key_to_bit_;
  // BARE tensor name -> number of cached entries with it (any group).
  // Gate for the membership-change INVALID scan in cached(): a plain
  // miss (e.g. every auto-named tensor, which is fresh each call) must
  // stay one hash lookup — the O(entries) scan only runs when the name
  // genuinely exists under some other group.
  std::unordered_map<std::string, uint32_t> name_refs_;
  // Entries with is_member == false — gates NonMemberBits' per-cycle
  // scan off entirely for pure data-parallel jobs.
  uint32_t non_member_entries_ = 0;
  bool bits_outdated_ = false;
};

// Packs per-cycle cache hit/invalid bit sets plus status flags and syncs them
// across ranks with one bitwise-AND allreduce (+ a second OR pass when any
// rank reports invalid entries).
class CacheCoordinator {
 public:
  explicit CacheCoordinator(std::size_t num_active_bits);

  void record_hit(uint32_t bit);
  void record_invalid_bit(uint32_t bit);
  void erase_hit(uint32_t bit);

  void set_should_shut_down(bool v) { should_shut_down_ = v; }
  void set_uncached_in_queue(bool v) { uncached_in_queue_ = v; }

  const std::set<uint32_t>& cache_hits() const { return cache_hits_; }
  const std::set<uint32_t>& invalid_bits() const { return invalid_bits_; }
  const std::set<uint32_t>& timeline_bits() const { return timeline_bits_; }
  bool should_shut_down() const { return should_shut_down_; }
  bool uncached_in_queue() const { return uncached_in_queue_; }
  bool invalid_in_queue() const { return invalid_in_queue_; }

  // Performs the cross-rank sync through the controller's bit-allreduce.
  // After this call, cache_hits() is the global intersection, and
  // invalid_bits() the global union (when any rank had invalids).
  void sync(Controller* controller, bool timeline_enabled);

 private:
  enum StatusBit {
    SHOULD_SHUT_DOWN = 0,
    UNCACHED_IN_QUEUE = 1,
    INVALID_IN_QUEUE = 2,
  };

  std::size_t num_active_bits_;
  std::set<uint32_t> cache_hits_;
  std::set<uint32_t> invalid_bits_;
  // Bits that were hits locally but lost globally — timeline shows these as
  // still negotiating.
  std::set<uint32_t> timeline_bits_;
  bool should_shut_down_ = false;
  bool uncached_in_queue_ = false;
  bool invalid_in_queue_ = false;
  bool synced_ = false;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_RESPONSE_CACHE_H
