// LRU cache of negotiated Responses + cross-rank cache-bit coordinator.
//
// Why it exists: once a training loop reaches steady state, every cycle
// queues the same tensors with the same params. Caching the negotiated
// Response lets every cycle skip the coordinator round-trip entirely: ranks
// only exchange one fixed-size bit vector (bitwise AND) to agree on which
// cached entries are globally ready. This is the critical negotiation-latency
// optimization at large rank counts.
//
// Capability parity with /root/reference horovod/common/response_cache.{h,cc}
// (ResponseCache + CacheCoordinator); fresh implementation.
#ifndef HVD_TPU_RESPONSE_CACHE_H
#define HVD_TPU_RESPONSE_CACHE_H

#include <cstdint>
#include <list>
#include <set>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "message.h"

namespace hvdtpu {

class TensorQueue;
class Controller;

class ResponseCache {
 public:
  enum class CacheState { MISS = 0, HIT = 1, INVALID = 2 };

  void set_capacity(uint32_t capacity);
  uint32_t capacity() const { return capacity_; }
  uint32_t num_active_bits() const;

  // Drops every cached entry and bit assignment. Called on re-init so a new
  // elastic generation (different size/topology) never executes a response
  // negotiated under the old membership.
  void clear();

  // MISS if never seen; HIT if cached with identical params; INVALID if the
  // name is cached but shape/dtype/op params changed (entry must be dropped
  // and renegotiated).
  CacheState cached(const Request& request) const;

  // Inserts (or refreshes) the response after a successful execution.
  void put(const Response& response, TensorQueue& tensor_queue);

  // Bit <-> response lookups for the fast path.
  const Response& get_response(uint32_t cache_bit);
  const Response& peek_response(uint32_t cache_bit) const;
  uint32_t peek_cache_bit(const Request& request) const;
  uint32_t peek_cache_bit(const std::string& tensor_name) const;

  void erase_response(uint32_t cache_bit);
  // Re-packs cache bits 0..N-1 in LRU order after evictions/erases so all
  // ranks agree on bit positions (called while ranks are in sync).
  void update_cache_bits();

 private:
  struct CacheEntry {
    Response response;
    // Params captured from the Request for validity checking.
    DataType dtype;
    std::vector<int64_t> shape;
    int32_t root_rank;
    double prescale_factor;
    double postscale_factor;
    // Wire-compression mode is part of the cache key: a hit with a
    // different mode is INVALID (renegotiate), never a silent reuse of
    // a response negotiated under another codec.
    uint8_t compression = 0;
  };

  void put_entry(const std::string& name, CacheEntry entry);

  uint32_t capacity_ = 1024;
  // LRU list of cache bits; most recent at front. cache_[bit] = entry.
  std::vector<CacheEntry> cache_;
  std::vector<std::list<uint32_t>::iterator> cache_iters_;
  std::list<uint32_t> lru_;
  std::unordered_map<std::string, uint32_t> name_to_bit_;
  bool bits_outdated_ = false;
};

// Packs per-cycle cache hit/invalid bit sets plus status flags and syncs them
// across ranks with one bitwise-AND allreduce (+ a second OR pass when any
// rank reports invalid entries).
class CacheCoordinator {
 public:
  explicit CacheCoordinator(std::size_t num_active_bits);

  void record_hit(uint32_t bit);
  void record_invalid_bit(uint32_t bit);
  void erase_hit(uint32_t bit);

  void set_should_shut_down(bool v) { should_shut_down_ = v; }
  void set_uncached_in_queue(bool v) { uncached_in_queue_ = v; }

  const std::set<uint32_t>& cache_hits() const { return cache_hits_; }
  const std::set<uint32_t>& invalid_bits() const { return invalid_bits_; }
  const std::set<uint32_t>& timeline_bits() const { return timeline_bits_; }
  bool should_shut_down() const { return should_shut_down_; }
  bool uncached_in_queue() const { return uncached_in_queue_; }
  bool invalid_in_queue() const { return invalid_in_queue_; }

  // Performs the cross-rank sync through the controller's bit-allreduce.
  // After this call, cache_hits() is the global intersection, and
  // invalid_bits() the global union (when any rank had invalids).
  void sync(Controller* controller, bool timeline_enabled);

 private:
  enum StatusBit {
    SHOULD_SHUT_DOWN = 0,
    UNCACHED_IN_QUEUE = 1,
    INVALID_IN_QUEUE = 2,
  };

  std::size_t num_active_bits_;
  std::set<uint32_t> cache_hits_;
  std::set<uint32_t> invalid_bits_;
  // Bits that were hits locally but lost globally — timeline shows these as
  // still negotiating.
  std::set<uint32_t> timeline_bits_;
  bool should_shut_down_ = false;
  bool uncached_in_queue_ = false;
  bool invalid_in_queue_ = false;
  bool synced_ = false;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_RESPONSE_CACHE_H
