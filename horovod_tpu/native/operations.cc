// Core runtime entry points: the process-wide singleton state, the
// background coordination thread (the only thread that talks cross-rank),
// the enqueue API, and the extern "C" surface loaded by Python via ctypes.
//
// Capability parity with /root/reference horovod/common/operations.cc
// (InitializeHorovodOnce / BackgroundThreadLoop / RunLoopOnce /
// PerformOperation / EnqueueTensor* / horovod_* C API), redesigned for the
// TPU build: completion is handle-based (HandleManager, mirroring the
// reference torch binding's handle_manager.h) so no foreign thread re-enters
// Python, and the data plane is the host TCP ring — TPU-resident tensors
// ride XLA collectives inside jit and never enter this core.
#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <utility>

#include "bayesian_optimization.h"
#include "collective_operations.h"
#include "common.h"
#include "compression.h"
#include "controller.h"
#include "cpu_operations.h"
#include "global_state.h"
#include "logging.h"
#include "tcp_controller.h"
#include "trace.h"

namespace hvdtpu {

HorovodGlobalState::~HorovodGlobalState() {
  // A joinable std::thread member would std::terminate the process at
  // static destruction (e.g. interpreter exit without hvd.shutdown()).
  shut_down.store(true);
  if (background_thread.joinable()) {
    background_thread.join();
  }
}

namespace {

HorovodGlobalState g_state;
std::mutex g_init_mutex;

// ---------------- HandleManager ----------------

struct HandleEntry {
  bool done = false;
  Status status;
  std::shared_ptr<std::vector<char>> gathered;
  std::shared_ptr<std::vector<int64_t>> gathered_sizes;
};

class HandleManager {
 public:
  int AllocateHandle() {
    std::lock_guard<std::mutex> lk(mutex_);
    int handle = next_handle_++;
    entries_[handle] = std::make_shared<HandleEntry>();
    return handle;
  }

  void MarkDone(int handle, const Status& status,
                std::shared_ptr<std::vector<char>> gathered = nullptr,
                std::shared_ptr<std::vector<int64_t>> sizes = nullptr) {
    {
      std::lock_guard<std::mutex> lk(mutex_);
      auto it = entries_.find(handle);
      if (it == entries_.end()) return;
      it->second->done = true;
      it->second->status = status;
      it->second->gathered = std::move(gathered);
      it->second->gathered_sizes = std::move(sizes);
    }
    cv_.notify_all();
  }

  bool Poll(int handle) {
    std::lock_guard<std::mutex> lk(mutex_);
    auto it = entries_.find(handle);
    return it == entries_.end() || it->second->done;
  }

  std::shared_ptr<HandleEntry> Wait(int handle) {
    std::unique_lock<std::mutex> lk(mutex_);
    auto it = entries_.find(handle);
    if (it == entries_.end()) return nullptr;
    auto entry = it->second;
    cv_.wait(lk, [&] { return entry->done; });
    return entry;
  }

  std::shared_ptr<HandleEntry> Get(int handle) {
    std::lock_guard<std::mutex> lk(mutex_);
    auto it = entries_.find(handle);
    return it == entries_.end() ? nullptr : it->second;
  }

  void Release(int handle) {
    std::lock_guard<std::mutex> lk(mutex_);
    entries_.erase(handle);
  }

  void FailAll(const Status& status) {
    {
      std::lock_guard<std::mutex> lk(mutex_);
      for (auto& kv : entries_) {
        if (!kv.second->done) {
          kv.second->done = true;
          kv.second->status = status;
        }
      }
    }
    cv_.notify_all();
  }

 private:
  std::mutex mutex_;
  std::condition_variable cv_;
  int next_handle_ = 0;  // guarded_by(mutex_)
  std::map<int, std::shared_ptr<HandleEntry>> entries_;  // guarded_by(mutex_)
};

HandleManager g_handles;

// ---------------- fatal-signal flight recorder ----------------
// A crashing rank is about to lose its buffered evidence: the trace ring,
// a possibly-unflushed shard, and an unterminated timeline JSON array.
// Dump a best-effort bundle and finalize the timeline, then restore the
// previous disposition and re-raise so the exit (core dump, abort status)
// is unchanged. Formally async-signal-unsafe (locks, allocation) — but the
// process is dying anyway, and a rare self-deadlock here costs nothing the
// crash wasn't already taking.

struct sigaction g_prev_sigactions[NSIG];
std::atomic<bool> g_fatal_dump_done{false};

void FatalSignalHandler(int sig) {
  // Restore the previous disposition FIRST: if the dump itself faults,
  // the re-entered signal takes the old path and the process still dies.
  if (sig >= 0 && sig < NSIG) {
    sigaction(sig, &g_prev_sigactions[sig], nullptr);
  }
  if (!g_fatal_dump_done.exchange(true)) {
    char reason[32];
    std::snprintf(reason, sizeof(reason), "fatal_signal_%d", sig);
    // No PendingNegotiationJson here: the controller's tables belong to
    // the background thread and are not guarded against this (arbitrary)
    // crashing thread.
    GlobalTrace().DumpBundle(reason, std::string());
    g_state.timeline.EmergencyFinalize();
  }
  raise(sig);
}

void InstallFatalSignalHandlers() {
  static std::atomic<bool> installed{false};
  if (installed.exchange(true)) return;
  struct sigaction sa;
  std::memset(&sa, 0, sizeof(sa));
  sa.sa_handler = FatalSignalHandler;
  sigemptyset(&sa.sa_mask);
  for (int sig : {SIGSEGV, SIGABRT, SIGBUS, SIGFPE}) {
    sigaction(sig, &sa, &g_prev_sigactions[sig]);
  }
  // SIGTERM only when nothing else claimed it: Python/launcher handlers
  // keep precedence, but a default-disposition TERM (the launcher's kill
  // path) should finalize the timeline before the process goes.
  struct sigaction cur;
  if (sigaction(SIGTERM, nullptr, &cur) == 0 && cur.sa_handler == SIG_DFL &&
      (cur.sa_flags & SA_SIGINFO) == 0) {
    sigaction(SIGTERM, &sa, &g_prev_sigactions[SIGTERM]);
  }
}

// ---------------- background loop ----------------
// (env parsing lives in common.h EnvInt64/EnvDouble/EnvBool)

// Returns (tensors, payload bytes) executed so RunLoopOnce can feed the
// per-cycle histograms.
std::pair<int64_t, int64_t> PerformOperation(HorovodGlobalState& state,
                                             const Response& response) {
  // Cache the negotiated response while entries are still in the table.
  // EVERY rank mirrors EVERY response (non-members store foreign
  // placeholder entries) so cache-bit positions stay rank-identical —
  // the bit-vector fast path depends on it (response_cache.h).
  if (response.response_type() != Response::ERROR) {
    state.response_cache.put(response, state.tensor_queue,
                             &state.group_table,
                             state.controller->rank());
  }
  std::vector<TensorTableEntry> entries;
  state.tensor_queue.GetTensorEntriesFromResponse(response, entries);
  if (entries.empty()) return {0, 0};
  if (response.group_id() != 0) {
    state.metrics.group_tensors_total.fetch_add(
        entries.size(), std::memory_order_relaxed);
  }
  // Fusion diagnostics: responses vs tensors executed (a fused response
  // carries several tensors; with fusion off the counts are equal).
  state.responses_performed.fetch_add(1);
  state.tensors_performed.fetch_add(
      static_cast<int64_t>(entries.size()));
  int64_t bytes = 0;
  for (const auto& e : entries) bytes += static_cast<int64_t>(e.SizeBytes());
  Metrics& metrics = state.metrics;
  metrics.responses_performed_total.fetch_add(1, std::memory_order_relaxed);
  metrics.tensors_performed_total.fetch_add(entries.size(),
                                            std::memory_order_relaxed);
  metrics.bytes_performed_total.fetch_add(static_cast<uint64_t>(bytes),
                                          std::memory_order_relaxed);
  if (response.response_type() == Response::ERROR) {
    metrics.error_responses_total.fetch_add(1, std::memory_order_relaxed);
  }
  if (entries.size() > 1) {
    metrics.fused_tensors_total.fetch_add(entries.size(),
                                          std::memory_order_relaxed);
    metrics.fused_bytes_total.fetch_add(static_cast<uint64_t>(bytes),
                                        std::memory_order_relaxed);
    int64_t threshold = state.controller->TensorFusionThresholdBytes();
    if (threshold > 0) {
      double fill = static_cast<double>(bytes) /
                    static_cast<double>(threshold);
      metrics.fusion_fill_ratio.Observe(fill > 1.0 ? 1.0 : fill);
    }
  }
  Trace& trace = state.trace;
  const int64_t t_exec_start = trace.NowNs();
  if (trace.enabled()) {
    // Close the negotiation-wait span opened at enqueue: the gap from
    // submission to execution is what the cross-rank agreement (and any
    // straggler) cost this tensor.
    for (const auto& e : entries) {
      int64_t opened = trace.CloseSpan(
          GroupQualifiedName(response.group_id(), e.tensor_name));
      if (opened >= 0) {
        trace.Record(e.tensor_name.c_str(), TRACE_NEGOTIATE, opened,
                     t_exec_start, static_cast<int64_t>(e.SizeBytes()),
                     response.group_id());
      }
    }
  }
  for (const auto& e : entries) {
    state.timeline.Start(e.tensor_name, response.response_type());
  }
  Status status;
  try {
    status = state.op_manager->ExecuteOperation(entries, response);
  } catch (const std::exception& ex) {
    status = Status::UnknownError(ex.what());
  }
  // One exec span per response: a fused response executes as one wire
  // operation, named by its first tensor.
  const int64_t t_exec_end = trace.NowNs();
  trace.Record(entries[0].tensor_name.c_str(), TRACE_EXEC, t_exec_start,
               t_exec_end, bytes, response.group_id());
  for (auto& e : entries) {
    state.timeline.End(e.tensor_name, status.ok());
    if (e.callback) e.callback(status, e);
  }
  trace.Record(entries[0].tensor_name.c_str(), TRACE_CALLBACK, t_exec_end,
               trace.NowNs(), 0, response.group_id());
  // A data-plane transport loss (ring EOF / checksum mismatch / deadline
  // — cpu_operations.cc RingLost) leaves the ring desynced: later
  // exchanges would pair mismatched steps. Escalate to the same
  // connection-lost shutdown a control-plane failure takes, AFTER the
  // failed tensors' callbacks have delivered the attributable status.
  if (!status.ok() &&
      status.reason().compare(0, CONNECTION_LOST_ERROR.size(),
                              CONNECTION_LOST_ERROR) == 0) {
    throw ConnectionLostError(status.reason());
  }
  return {static_cast<int64_t>(entries.size()), bytes};
}

bool RunLoopOnce(HorovodGlobalState& state,
                 std::chrono::steady_clock::time_point& last_cycle_start) {
  // Pace the cycle.
  auto cycle =
      std::chrono::duration<double, std::milli>(
          state.parameter_manager.CycleTimeMs());
  auto next_start = last_cycle_start +
                    std::chrono::duration_cast<std::chrono::steady_clock::duration>(cycle);
  auto now = std::chrono::steady_clock::now();
  if (next_start > now) {
    std::this_thread::sleep_for(next_start - now);
  }
  last_cycle_start = std::chrono::steady_clock::now();

  if (state.mark_cycles_in_timeline) {
    state.timeline.MarkCycleStart();
  }

  bool was_tuning = state.parameter_manager.IsAutoTuning();

  ResponseList response_list =
      state.controller->ComputeResponseList(state.shut_down.load());

  int64_t cycle_tensors = 0;
  int64_t cycle_bytes = 0;
  for (const auto& response : response_list.responses()) {
    auto executed = PerformOperation(state, response);
    cycle_tensors += executed.first;
    cycle_bytes += executed.second;
  }
  Metrics& metrics = state.metrics;
  metrics.cycles_total.fetch_add(1, std::memory_order_relaxed);
  metrics.cycle_seconds.Observe(
      std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                    last_cycle_start)
          .count());
  if (cycle_tensors > 0) {
    metrics.cycle_tensors.Observe(static_cast<double>(cycle_tensors));
    metrics.cycle_bytes.Observe(static_cast<double>(cycle_bytes));
  }
  metrics.fusion_threshold_bytes.store(
      state.controller->TensorFusionThresholdBytes(),
      std::memory_order_relaxed);

  // Closed-loop tuner (docs/AUTOTUNE.md): the coordinator's Update runs
  // EVERY cycle — it advances sampling while tuning and watches for
  // workload drift while converged (a drift re-arm is bootstrapped to
  // the workers through the next full-cycle ResponseList). The per-cycle
  // parameter broadcast still runs only while every rank knows tuning is
  // live (`was_tuning` is synchronized state), keeping knob application
  // in lockstep across ranks.
  if (state.controller->is_coordinator()) {
    state.parameter_manager.Update(cycle_tensors, cycle_bytes);
  }
  if (was_tuning) {
    state.controller->SynchronizeParameters();
  }
  metrics.autotune_active.store(
      state.parameter_manager.IsAutoTuning() ? 1 : 0,
      std::memory_order_relaxed);
  metrics.pipeline_chunk_bytes.store(
      state.parameter_manager.PipelineChunkBytes(),
      std::memory_order_relaxed);
  // Apply the (cycle-synchronized) shm_transport knob at the cycle
  // boundary: every rank runs this line between the same two response
  // lists, so both ends of any negotiated segment flip together and an
  // exchange can never pair an shm writer with a TCP reader.
  state.tcp_context.SetShmUse(state.parameter_manager.ShmTransport());
  uint64_t rearms = state.parameter_manager.rearms_total();
  uint64_t seen = metrics.autotune_rearms_total.load(
      std::memory_order_relaxed);
  if (rearms > seen) {
    metrics.autotune_rearms_total.fetch_add(rearms - seen,
                                            std::memory_order_relaxed);
  }

  return !response_list.shutdown();
}

void BackgroundThreadLoop(HorovodGlobalState& state) {
  // Generation reset: a re-init after an elastic membership change (or a
  // plain shutdown/init cycle) must not carry over negotiation state from
  // the previous communicator — cached responses reference the old size
  // and bit layout, and the protocol counters would mix generations.
  state.connection_lost.store(false);
  state.response_cache.clear();
  // Groups reference the old membership's ranks; Python re-creates the
  // mesh groups after every (re-)init (docs/GROUPS.md).
  state.group_table.Clear();
  state.tcp_context.ResetProtocolCounters();
  state.responses_performed.store(0);
  state.tensors_performed.store(0);
  // Call-sequence tracking restarts with the generation: survivors of an
  // elastic shrink/regrow and fresh workers must agree on seq 0.
  state.call_tracker.Reset();

  if (!state.tcp_context.Initialize()) {
    state.tcp_context.Finalize();  // release sockets for a re-init retry
    state.initialization_failed.store(true);
    state.initialization_done.store(true);
    return;
  }

  state.controller = std::make_unique<TcpController>(
      state.response_cache, state.tensor_queue, state.timeline,
      state.parameter_manager, state.tcp_context);
  state.controller->Initialize();

  // Runtime knobs (env; autotuner may override non-fixed ones later).
  bool fixed;
  int64_t fusion_threshold =
      EnvInt64(HVD_TPU_FUSION_THRESHOLD, 64 * 1024 * 1024, &fixed);
  state.parameter_manager.SetTensorFusionThresholdBytes(fusion_threshold,
                                                        fixed);
  double cycle_time = EnvDouble(HVD_TPU_CYCLE_TIME, 5.0, &fixed);
  state.parameter_manager.SetCycleTimeMs(cycle_time, fixed);
  int64_t cache_capacity = EnvInt64(HVD_TPU_CACHE_CAPACITY, 1024, &fixed);
  state.response_cache.set_capacity(static_cast<uint32_t>(cache_capacity));
  state.parameter_manager.SetCacheEnabled(cache_capacity > 0, fixed);
  if (state.tcp_context.hierarchical_possible()) {
    bool hier_ar = EnvBool(HVD_TPU_HIERARCHICAL_ALLREDUCE, false, &fixed);
    state.parameter_manager.SetHierarchicalAllreduce(hier_ar, fixed);
    bool hier_ag = EnvBool(HVD_TPU_HIERARCHICAL_ALLGATHER, false, &fixed);
    state.parameter_manager.SetHierarchicalAllgather(hier_ag, fixed);
    bool hier_rs =
        EnvBool(HVD_TPU_HIERARCHICAL_REDUCESCATTER, false, &fixed);
    state.parameter_manager.SetHierarchicalReduceScatter(hier_rs, fixed);
  } else {
    // Flat topology: pin the knobs off and fixed so the autotuner doesn't
    // waste its categorical budget scoring identical configurations.
    state.parameter_manager.SetHierarchicalAllreduce(false, true);
    state.parameter_manager.SetHierarchicalAllgather(false, true);
    state.parameter_manager.SetHierarchicalReduceScatter(false, true);
  }
  // Pipelined ring segment size: env pins it (0 = unsliced); unset
  // leaves the knob to the autotuner, starting at 1 MiB.
  int64_t pipeline_chunk =
      EnvInt64(HVD_TPU_PIPELINE_CHUNK_BYTES, 1 << 20, &fixed);
  state.parameter_manager.SetPipelineChunkBytes(pipeline_chunk, fixed);
  // Shared-memory transport knob (docs/TRANSPORT.md): HVD_TPU_SHM=0/1
  // pins it off/on; unset (or "auto") defaults on and leaves it to the
  // autotuner on shm-capable topologies.
  {
    const char* shm_env = std::getenv("HVD_TPU_SHM");
    if (shm_env != nullptr && (shm_env[0] == '0' || shm_env[0] == '1') &&
        shm_env[1] == '\0') {
      state.parameter_manager.SetShmTransport(shm_env[0] == '1', true);
    } else {
      state.parameter_manager.SetShmTransport(true, false);
    }
  }

  state.controller->stall_inspector().SetStallWarningTimeSeconds(
      static_cast<int>(EnvInt64(HVD_TPU_STALL_CHECK_TIME, 60)));
  state.controller->stall_inspector().SetStallShutdownTimeSeconds(
      static_cast<int>(EnvInt64(HVD_TPU_STALL_SHUTDOWN_TIME, 0)));

  // Metrics plane (metrics.h / docs/METRICS.md): the registry always
  // counts; the PLANE (wire summaries + forced sync cycles + the Python
  // HTTP endpoint keying off the same env) engages when explicitly
  // enabled, so metrics-off jobs see zero wire or cycle-shape change.
  bool metrics_plane = EnvBool(HVD_TPU_METRICS, false) ||
                       std::getenv(HVD_TPU_METRICS_PORT) != nullptr;
  state.metrics.Configure(state.controller->size(),
                          state.controller->rank());
  state.metrics.set_enabled(metrics_plane);
  state.metrics.elastic_generation.store(
      EnvInt64(HVD_TPU_GENERATION_ENV, 0), std::memory_order_relaxed);
  state.metrics.init_total.fetch_add(1, std::memory_order_relaxed);
  state.controller->ConfigureMetrics(
      metrics_plane, EnvDouble(HVD_TPU_METRICS_SYNC, 1.0));

  // Divergence cross-check (divergence.h): progress rule fires after a
  // missing rank advances this many calls past a pending tensor (0 = off);
  // the cross-stall rule after a pending tensor ages this many seconds
  // with every missing rank waiting elsewhere (<=0 = off). Both default
  // on — they only trigger on protocol-divergent programs, which would
  // otherwise hang to the stall timeout.
  state.controller->SetCallTracker(&state.call_tracker);
  state.controller->SetGroupTable(&state.group_table);
  state.controller->ConfigureDivergence(
      EnvInt64(HVD_TPU_DIVERGENCE_CALLS, 64),
      EnvDouble(HVD_TPU_DIVERGENCE_GRACE, 5.0));

  // Span recorder + flight recorder (trace.h, docs/TRACING.md): always on
  // unless HVD_TPU_TRACE=0. The generation tags shard files and bundles so
  // merged traces keep elastic re-inits apart. Fatal-signal hooks ride the
  // same init so a crashing rank still flushes its evidence.
  state.trace.Configure(state.controller->rank(), state.controller->size(),
                        EnvInt64(HVD_TPU_GENERATION_ENV, 0));
  InstallFatalSignalHandlers();

  const char* timeline_path = std::getenv(HVD_TPU_TIMELINE);
  if (timeline_path != nullptr) {
    state.timeline.Initialize(timeline_path,
                              static_cast<unsigned>(state.controller->rank()));
    state.timeline.SetMarkCycles(
        EnvBool(HVD_TPU_TIMELINE_MARK_CYCLES, false));
    state.mark_cycles_in_timeline =
        EnvBool(HVD_TPU_TIMELINE_MARK_CYCLES, false);
  }

  const char* autotune_log = std::getenv(HVD_TPU_AUTOTUNE_LOG);
  state.parameter_manager.Initialize(state.controller->rank(),
                                     autotune_log ? autotune_log : "");
  // Search-space profile seed, identical on every rank (both values come
  // from job-wide env): the coordinator's live observation of negotiated
  // responses refines it later (controller.cc) and re-arms on change.
  state.parameter_manager.ObserveWorkload(
      ParseCompressionMode(std::getenv(HVD_TPU_COMPRESSION_ENV)) !=
          CompressionMode::NONE,
      EnvBool(HVD_TPU_SHARDED_UPDATE_ENV, false),
      /*groups_active=*/false,
      // shm capability is a pure function of (HVD_TPU_SHM, the full
      // address list) — identical on every rank, like the env seeds.
      state.tcp_context.shm_topology_possible());
  // Always-on closed loop (docs/AUTOTUNE.md): tuning defaults ON and
  // re-arms on every generation (this code path runs per elastic
  // re-init) plus on observed workload shifts. HVD_TPU_AUTOTUNE=0 — or
  // single-rank jobs, where every knob scores identically — opts out.
  if (EnvBool(HVD_TPU_AUTOTUNE, state.controller->size() > 1)) {
    state.parameter_manager.SetAutoTuning(true);
  }

  // Data-plane op registry: first Enabled() op per type executes. Ordered
  // most-specific first, CPU ring last, mirroring the reference's registry
  // construction (operations.cc:137-207). The XLA/ICI path for TPU-resident
  // tensors lives inside jit (horovod_tpu/jax) and is deliberately not a
  // registry entry here — it never crosses the host boundary.
  std::vector<std::shared_ptr<AllreduceOp>> allreduce_ops = {
      std::make_shared<CpuHierarchicalAllreduce>(state.tcp_context, &state),
      std::make_shared<CpuRingAllreduce>(state.tcp_context, &state)};
  std::vector<std::shared_ptr<AllgatherOp>> allgather_ops = {
      std::make_shared<CpuHierarchicalAllgather>(state.tcp_context, &state),
      std::make_shared<CpuRingAllgather>(state.tcp_context, &state)};
  std::vector<std::shared_ptr<BroadcastOp>> broadcast_ops = {
      std::make_shared<CpuBroadcast>(state.tcp_context, &state)};
  std::vector<std::shared_ptr<ReduceScatterOp>> reducescatter_ops = {
      std::make_shared<CpuHierarchicalReduceScatter>(state.tcp_context,
                                                     &state),
      std::make_shared<CpuRingReduceScatter>(state.tcp_context, &state)};
  state.op_manager = std::make_unique<OperationManager>(
      std::move(allreduce_ops), std::move(allgather_ops),
      std::move(broadcast_ops), std::move(reducescatter_ops),
      std::make_shared<ErrorOp>(&state));

  state.initialization_done.store(true);
  LOG(DEBUG) << "background loop starting";

  auto last_cycle_start = std::chrono::steady_clock::now();
  try {
    while (RunLoopOnce(state, last_cycle_start)) {
    }
  } catch (const ConnectionLostError& ex) {
    // A peer died mid-protocol. Recoverable: the process survives, and a
    // later shutdown()+init() joins the next elastic generation. Dump the
    // flight recorder first — on the coordinator the pending table names
    // the missing rank and the in-flight tensors.
    LOG(ERROR) << "peer connection lost: " << ex.what();
    state.connection_lost.store(true);
    std::string bundle = state.trace.DumpBundle(
        "connection_lost", state.controller->PendingNegotiationJson());
    if (!bundle.empty()) {
      LOG(ERROR) << "post-mortem bundle: " << bundle;
    }
  } catch (const std::exception& ex) {
    LOG(ERROR) << "background loop terminated: " << ex.what();
  }

  LOG(DEBUG) << "background loop shutting down";
  state.shut_down.store(true);
  const Status fail_status =
      state.connection_lost.load()
          ? Status::UnknownError(CONNECTION_LOST_ERROR)
          : Status::Aborted(SHUT_DOWN_ERROR);
  state.tensor_queue.FinalizeTensorQueue(fail_status);
  g_handles.FailAll(fail_status);
  state.timeline.Shutdown();
  // Drain the ring to the shard file; the drainer thread itself survives
  // the generation (process-lifetime singleton, like the metrics registry)
  // so an elastic re-init just re-Configures.
  state.trace.FlushShard();
  state.tcp_context.Finalize();
}

bool InitializeHorovodOnce() {
  std::lock_guard<std::mutex> lk(g_init_mutex);
  if (!g_state.initialize_flag.load()) {
    g_state.initialize_flag.store(true);
    g_state.shut_down.store(false);
    g_state.initialization_done.store(false);
    g_state.initialization_failed.store(false);
    g_state.background_thread =
        std::thread(BackgroundThreadLoop, std::ref(g_state));
  }
  while (!g_state.initialization_done.load()) {
    // Deliberately under g_init_mutex: the lock IS the once-guard —
    // a concurrent initializer must block until the first init fully
    // resolves (done or failed), and the background thread it waits
    // on never takes g_init_mutex, so this cannot deadlock.
    // lockorder: allow(blocking-call-under-lock)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  if (g_state.initialization_failed.load()) {
    // Leave the state re-initializable: reap the dead thread and clear
    // the flag so a later init() (e.g. with corrected env) can retry.
    if (g_state.background_thread.joinable()) {
      g_state.background_thread.join();
    }
    g_state.initialize_flag.store(false);
    return false;
  }
  return true;
}

Status EnqueueTensor(Request::RequestType type, const char* name,
                     const void* data, void* output, int ndim,
                     const int64_t* shape, int dtype, int root_rank,
                     double prescale, double postscale, int compression,
                     int group, int handle) {
  if (!g_state.initialization_done.load() ||
      g_state.initialization_failed.load()) {
    return Status::PreconditionError("Horovod-TPU has not been initialized.");
  }
  if (g_state.shut_down.load()) {
    // After a peer loss the queue is closed but the condition is
    // recoverable — report it as such so callers roll back instead of
    // treating it like a requested shutdown.
    return g_state.connection_lost.load()
               ? Status::UnknownError(CONNECTION_LOST_ERROR)
               : Status::Aborted(SHUT_DOWN_ERROR);
  }
  // Group scoping (docs/GROUPS.md): validate HERE, on the calling
  // thread, so a scoping mistake surfaces as an immediate Python error
  // instead of a negotiation-time rejection (or a hang).
  uint64_t group_digest = 0;
  if (group < 0) {
    return Status::InvalidArgument("process group id must be >= 0");
  }
  if (group > 0) {
    int my_rank = g_state.controller->rank();
    if (g_state.group_table.Size(static_cast<uint32_t>(group)) == 0) {
      return Status::InvalidArgument(
          "unknown process group " + std::to_string(group) +
          "; create it with hvd.new_group(ranks) on EVERY rank first");
    }
    if (!g_state.group_table.Contains(static_cast<uint32_t>(group),
                                      my_rank)) {
      return Status::InvalidArgument(
          "rank " + std::to_string(my_rank) +
          " is not a member of process group " + std::to_string(group) +
          " " +
          g_state.group_table.DescribeMembers(
              static_cast<uint32_t>(group)) +
          "; only members may submit its collectives");
    }
    if (type == Request::BROADCAST &&
        !g_state.group_table.Contains(static_cast<uint32_t>(group),
                                      root_rank)) {
      return Status::InvalidArgument(
          "broadcast root rank " + std::to_string(root_rank) +
          " is not a member of process group " + std::to_string(group) +
          " " +
          g_state.group_table.DescribeMembers(
              static_cast<uint32_t>(group)));
    }
    group_digest = g_state.group_table.Digest(static_cast<uint32_t>(group));
  }
  TensorShape tensor_shape;
  for (int i = 0; i < ndim; ++i) tensor_shape.AddDim(shape[i]);

  // The EFFECTIVE mode enters negotiation: non-f32 payloads ride
  // uncompressed, computed identically on every rank from the dtype, so
  // a bf16 request for an int64 tensor cannot desync the ring.
  uint8_t effective = static_cast<uint8_t>(EffectiveCompression(
      static_cast<CompressionMode>(compression),
      static_cast<DataType>(dtype)));

  Request message;
  message.set_request_rank(g_state.controller->rank());
  message.set_request_type(type);
  message.set_tensor_name(name);
  message.set_tensor_type(static_cast<DataType>(dtype));
  message.set_tensor_shape(tensor_shape.dims());
  message.set_root_rank(root_rank);
  message.set_device(HOST_DEVICE_ID);
  message.set_prescale_factor(prescale);
  message.set_postscale_factor(postscale);
  message.set_compression(effective);
  message.set_group_id(static_cast<uint32_t>(group));
  message.set_group_digest(group_digest);

  TensorTableEntry entry;
  entry.tensor_name = name;
  entry.data = data;
  entry.output = output;
  entry.dtype = static_cast<DataType>(dtype);
  entry.shape = tensor_shape;
  entry.root_rank = root_rank;
  entry.prescale_factor = prescale;
  entry.postscale_factor = postscale;
  entry.compression = effective;
  entry.group_id = static_cast<uint32_t>(group);
  entry.callback = [handle](const Status& status,
                            const TensorTableEntry& done_entry) {
    LOG(TRACE) << "done " << done_entry.tensor_name << " handle " << handle
               << " status " << static_cast<int>(status.type());
    g_handles.MarkDone(handle, status, done_entry.gathered,
                       done_entry.gathered_sizes);
  };
  LOG(TRACE) << "enqueue " << name << " handle " << handle;
  const int64_t payload_bytes = static_cast<int64_t>(entry.SizeBytes());
  Status status = g_state.tensor_queue.AddToTensorQueue(std::move(entry),
                                                        std::move(message));
  // Only ADMITTED calls enter the fingerprint: a rejected enqueue (e.g.
  // DUPLICATE_NAME while the prior async op is in flight) never reaches
  // negotiation, and counting it would diverge this rank's seq/digest
  // from peers on a protocol-consistent program.
  if (status.ok()) {
    // Group-qualified tracker name: the call fingerprint (and the
    // divergence reports built from it) must distinguish the same
    // tensor name used in different groups.
    g_state.call_tracker.Record(
        static_cast<uint8_t>(type), static_cast<uint8_t>(dtype), ndim,
        GroupQualifiedName(static_cast<uint32_t>(group), name));
    g_state.metrics.tensors_enqueued_total.fetch_add(
        1, std::memory_order_relaxed);
    Trace& trace = g_state.trace;
    if (trace.enabled()) {
      // Instant enqueue span + the open negotiation-wait span
      // PerformOperation closes when this tensor finally executes.
      const int64_t now = trace.NowNs();
      trace.Record(name, TRACE_ENQUEUE, now, now, payload_bytes,
                   static_cast<uint32_t>(group));
      trace.OpenSpan(GroupQualifiedName(static_cast<uint32_t>(group), name),
                     now);
    }
  }
  return status;
}

}  // namespace

}  // namespace hvdtpu

// ---------------- extern "C" API ----------------

using namespace hvdtpu;

extern "C" {

int horovod_tpu_init() { return InitializeHorovodOnce() ? 1 : 0; }

void horovod_tpu_request_shutdown() { g_state.shut_down.store(true); }

void horovod_tpu_shutdown() {
  std::lock_guard<std::mutex> lk(g_init_mutex);
  if (!g_state.initialize_flag.load()) return;
  g_state.shut_down.store(true);
  if (g_state.background_thread.joinable()) {
    g_state.background_thread.join();
  }
  g_state.initialize_flag.store(false);
  g_state.initialization_done.store(false);
}

int horovod_tpu_initialized() {
  return g_state.initialization_done.load() &&
                 !g_state.initialization_failed.load()
             ? 1
             : 0;
}

// True when the background loop died because a peer connection was lost
// (elastic-recoverable), as opposed to a requested shutdown. Python's
// elastic layer uses this to decide between rollback-and-reinit and a
// plain teardown.
int horovod_tpu_connection_lost() {
  return g_state.connection_lost.load() ? 1 : 0;
}

int horovod_tpu_rank() {
  return g_state.controller ? g_state.controller->rank() : -1;
}
int horovod_tpu_local_rank() {
  return g_state.controller ? g_state.controller->local_rank() : -1;
}
int horovod_tpu_cross_rank() {
  return g_state.controller ? g_state.controller->cross_rank() : -1;
}
int horovod_tpu_size() {
  return g_state.controller ? g_state.controller->size() : -1;
}
int horovod_tpu_local_size() {
  return g_state.controller ? g_state.controller->local_size() : -1;
}
int horovod_tpu_cross_size() {
  return g_state.controller ? g_state.controller->cross_size() : -1;
}
int horovod_tpu_is_homogeneous() {
  return g_state.controller && g_state.controller->is_homogeneous() ? 1 : 0;
}

// Build/capability probes (reference: horovod_mpi_built etc.).
int horovod_tpu_tcp_built() { return 1; }
int horovod_tpu_cpu_ops_built() { return 1; }

// Fusion diagnostics: executed responses vs tensors (tensors >
// responses means fusion grouped tensors into shared cycles), and the
// controller's effective (divisibility-rounded) fusion threshold.
void horovod_tpu_perf_counters(int64_t* responses, int64_t* tensors) {
  if (responses) *responses = g_state.responses_performed.load();
  if (tensors) *tensors = g_state.tensors_performed.load();
}
int64_t horovod_tpu_effective_fusion_threshold() {
  return g_state.controller
             ? g_state.controller->TensorFusionThresholdBytes()
             : -1;
}

// Protocol-level negotiation accounting: control-star bytes/messages
// this rank moved (12-byte frame headers included; data-plane ring
// traffic excluded; idle heartbeat cycles contribute bytes but not
// cycle counts) and work-cycle counts by kind. Measures the quantity
// the response cache exists to shrink — negotiation traffic —
// directly (reference design: response_cache.cc:308-409).
// out[0]=ctrl_bytes_sent out[1]=ctrl_bytes_recv out[2]=ctrl_msgs
// out[3]=cycles_fast     out[4]=cycles_full
void horovod_tpu_protocol_counters(uint64_t* out) {
  if (!out) return;
  out[0] = g_state.tcp_context.ctrl_bytes_sent();
  out[1] = g_state.tcp_context.ctrl_bytes_recv();
  out[2] = g_state.tcp_context.ctrl_msgs();
  out[3] = g_state.controller ? g_state.controller->cycles_fast() : 0;
  out[4] = g_state.controller ? g_state.controller->cycles_full() : 0;
}

// Live metrics snapshots (metrics.h / docs/METRICS.md). Callable from
// any thread at any time — before init, mid-run, after shutdown; the
// registry is a process singleton of atomics. thread_local storage so
// concurrent scrapers never share a buffer.
const char* horovod_tpu_metrics_json() {
  static thread_local std::string out;
  out = GlobalMetrics().SnapshotJson();
  return out.c_str();
}

// Rank 0's job-wide view: every rank's piggybacked summary + the
// per-rank announce-lag table (straggler identification). "{}" on
// non-coordinator ranks.
const char* horovod_tpu_job_metrics_json() {
  static thread_local std::string out;
  out = GlobalMetrics().JobJson();
  return out.c_str();
}

// Durable-checkpoint accounting (elastic/durable.py's writer thread
// reports through here so the ckpt_* counters ride the same registry,
// wire summaries, /job view, and hvd-top column as everything else).
// All arguments are DELTAS except last_step (absolute; < 0 = no
// update) and write_seconds (one histogram observation; < 0 = none).
// Relaxed atomics — safe from any thread, any time.
void horovod_tpu_ckpt_metrics(int64_t writes, int64_t failures,
                              int64_t bytes, int64_t restores,
                              int64_t restore_failures, int64_t last_step,
                              double write_seconds) {
  auto& m = GlobalMetrics();
  if (writes > 0) m.ckpt_writes_total.fetch_add(
      static_cast<uint64_t>(writes), std::memory_order_relaxed);
  if (failures > 0) m.ckpt_write_failures_total.fetch_add(
      static_cast<uint64_t>(failures), std::memory_order_relaxed);
  if (bytes > 0) m.ckpt_bytes_total.fetch_add(
      static_cast<uint64_t>(bytes), std::memory_order_relaxed);
  if (restores > 0) m.ckpt_restores_total.fetch_add(
      static_cast<uint64_t>(restores), std::memory_order_relaxed);
  if (restore_failures > 0) m.ckpt_restore_failures_total.fetch_add(
      static_cast<uint64_t>(restore_failures), std::memory_order_relaxed);
  if (last_step >= 0) {
    // Monotonic max: a late-finishing older write must not move the
    // gauge backwards past a newer one.
    int64_t cur = m.last_durable_step.load(std::memory_order_relaxed);
    while (last_step > cur &&
           !m.last_durable_step.compare_exchange_weak(
               cur, last_step, std::memory_order_relaxed)) {
    }
  }
  if (write_seconds >= 0.0) m.ckpt_write_seconds.Observe(write_seconds);
}

// Graceful-drain accounting (elastic/run.py's drain handler reports
// through here; docs/FLEET.md). `requested` is a delta; `draining` is
// the absolute posture gauge (1 = victim, 0 = survivor, < -1 is
// ignored so callers can update one without the other). Relaxed
// atomics — safe from any thread, any time.
void horovod_tpu_drain_metrics(int64_t requested, int64_t draining) {
  auto& m = GlobalMetrics();
  if (requested > 0) m.drains_requested_total.fetch_add(
      static_cast<uint64_t>(requested), std::memory_order_relaxed);
  if (draining >= -1) m.draining.store(draining, std::memory_order_relaxed);
  if (draining == 1) {
    // A drain victim is about to leave the job: preserve its evidence
    // window while the ring still holds the final cycles.
    GlobalTrace().DumpBundle("drain", std::string());
  }
}

// This rank's collective call-sequence fingerprint: seq = number of
// collectives enqueued since init, digest = rolling FNV-1a over each
// call's (op, dtype, shape-rank, name). Ranks that executed identical
// call sequences have identical (seq, digest) — the runtime divergence
// assertion (hvd.jax.assert_synchronized) compares them across ranks.
void horovod_tpu_call_digest(uint64_t* seq, uint64_t* digest) {
  g_state.call_tracker.Snapshot(seq, digest);
}

void horovod_tpu_protocol_counters_reset() {
  g_state.tcp_context.ResetProtocolCounters();
  if (g_state.controller) g_state.controller->ResetCycleCounters();
}

// BayesianOptimizer handle API: unit-tests the autotune math from
// Python (not part of the training path).
void* horovod_tpu_bo_create(double lo0, double hi0, double lo1, double hi1,
                            uint64_t seed) {
  return new BayesianOptimizer({{lo0, hi0}, {lo1, hi1}}, seed);
}
void horovod_tpu_bo_next(void* bo, double* out2) {
  auto next = static_cast<BayesianOptimizer*>(bo)->NextSample();
  out2[0] = next[0];
  out2[1] = next[1];
}
void horovod_tpu_bo_add(void* bo, const double* x2, double y) {
  static_cast<BayesianOptimizer*>(bo)->AddSample({x2[0], x2[1]}, y);
}
void horovod_tpu_bo_best(void* bo, double* out2, double* best_y) {
  auto* opt = static_cast<BayesianOptimizer*>(bo);
  auto best = opt->BestSample();
  out2[0] = best.size() > 0 ? best[0] : 0.0;
  out2[1] = best.size() > 1 ? best[1] : 0.0;
  *best_y = opt->BestValue();
}
void horovod_tpu_bo_destroy(void* bo) {
  delete static_cast<BayesianOptimizer*>(bo);
}

// Live closed-loop tuner state (docs/AUTOTUNE.md) as JSON — knobs,
// fixed flags, workload profile, re-arm counters, convergence baseline.
// Callable from any thread at any time (the manager is mutex-guarded);
// thread_local storage so concurrent scrapers never share a buffer.
const char* horovod_tpu_autotune_json() {
  static thread_local std::string out;
  out = g_state.parameter_manager.Json();
  return out.c_str();
}

// Autotune introspection (tests + diagnostics): current synchronized
// knob values and whether tuning is still active.
void horovod_tpu_autotune_params(double* fusion_mb, double* cycle_ms,
                                 int* cache_enabled, int* hier_allreduce,
                                 int* hier_allgather, int* active) {
  ParameterManager::Params p = g_state.parameter_manager.GetParams();
  if (fusion_mb) *fusion_mb = p.fusion_mb;
  if (cycle_ms) *cycle_ms = p.cycle_time_ms;
  if (cache_enabled) *cache_enabled = p.cache_enabled;
  if (hier_allreduce) *hier_allreduce = p.hierarchical_allreduce;
  if (hier_allgather) *hier_allgather = p.hierarchical_allgather;
  if (active) *active = p.active;
}

int horovod_tpu_enqueue_allreduce(const char* name, const void* data,
                                  void* output, int ndim, const int64_t* shape,
                                  int dtype, double prescale,
                                  double postscale, int compression) {
  int handle = g_handles.AllocateHandle();
  Status s = EnqueueTensor(Request::ALLREDUCE, name, data, output, ndim, shape,
                           dtype, 0, prescale, postscale, compression,
                           /*group=*/0, handle);
  if (!s.ok()) {
    g_handles.MarkDone(handle, s);
  }
  return handle;
}

// ---------------- process groups (docs/GROUPS.md) ----------------

// Registers a process group over `ranks` (strictly ascending world
// ranks). COLLECTIVE BY CONVENTION: every rank — members and
// non-members alike — must call it with the identical list in the
// identical order; ids come from a per-process counter, so the same
// call sequence yields the same ids everywhere (mismatched membership
// is additionally rejected at negotiation via the group digest).
// Returns the group id (>= 1) or a negative error code.
int horovod_tpu_new_group(const int32_t* ranks, int nranks) {
  if (!g_state.initialization_done.load() ||
      g_state.initialization_failed.load() || !g_state.controller) {
    return -1;  // not initialized
  }
  if (ranks == nullptr || nranks <= 0) return -2;
  int world = g_state.controller->size();
  std::vector<int> members(ranks, ranks + nranks);
  for (int r : members) {
    if (r < 0 || r >= world) return -3;  // rank out of range
  }
  uint32_t id = g_state.group_table.Register(std::move(members));
  if (id == 0) return -4;  // not strictly ascending / duplicates
  GlobalMetrics().groups.store(
      static_cast<int64_t>(g_state.group_table.Count()),
      std::memory_order_relaxed);
  return static_cast<int>(id);
}

int horovod_tpu_group_size(int group) {
  if (group == 0) {
    return g_state.controller ? g_state.controller->size() : -1;
  }
  int n = g_state.group_table.Size(static_cast<uint32_t>(group));
  return n == 0 ? -1 : n;
}

// This rank's position in the group's ring order; -1 when not a member.
int horovod_tpu_group_rank(int group) {
  if (!g_state.controller) return -1;
  if (group == 0) return g_state.controller->rank();
  return g_state.group_table.IndexOf(static_cast<uint32_t>(group),
                                     g_state.controller->rank());
}

int horovod_tpu_group_count() {
  return static_cast<int>(g_state.group_table.Count());
}

int horovod_tpu_enqueue_allreduce_grp(const char* name, const void* data,
                                      void* output, int ndim,
                                      const int64_t* shape, int dtype,
                                      double prescale, double postscale,
                                      int compression, int group) {
  int handle = g_handles.AllocateHandle();
  Status s = EnqueueTensor(Request::ALLREDUCE, name, data, output, ndim,
                           shape, dtype, 0, prescale, postscale, compression,
                           group, handle);
  if (!s.ok()) {
    g_handles.MarkDone(handle, s);
  }
  return handle;
}

int horovod_tpu_enqueue_reduce_scatter_grp(const char* name,
                                           const void* data, void* output,
                                           int ndim, const int64_t* shape,
                                           int dtype, double prescale,
                                           double postscale, int compression,
                                           int group) {
  int handle = g_handles.AllocateHandle();
  Status s = EnqueueTensor(Request::REDUCESCATTER, name, data, output, ndim,
                           shape, dtype, 0, prescale, postscale, compression,
                           group, handle);
  if (!s.ok()) {
    g_handles.MarkDone(handle, s);
  }
  return handle;
}

int horovod_tpu_enqueue_allgather_grp(const char* name, const void* data,
                                      int ndim, const int64_t* shape,
                                      int dtype, int group) {
  int handle = g_handles.AllocateHandle();
  Status s = EnqueueTensor(Request::ALLGATHER, name, data, nullptr, ndim,
                           shape, dtype, 0, 1.0, 1.0, 0, group, handle);
  if (!s.ok()) {
    g_handles.MarkDone(handle, s);
  }
  return handle;
}

int horovod_tpu_enqueue_broadcast_grp(const char* name, const void* data,
                                      void* output, int ndim,
                                      const int64_t* shape, int dtype,
                                      int root_rank, int group) {
  int handle = g_handles.AllocateHandle();
  Status s = EnqueueTensor(Request::BROADCAST, name, data, output, ndim,
                           shape, dtype, root_rank, 1.0, 1.0, 0, group,
                           handle);
  if (!s.ok()) {
    g_handles.MarkDone(handle, s);
  }
  return handle;
}

// Compression-mode helpers for the Python binding: parse the canonical
// spelling ("none"/"bf16"/"int8"; numeric strings accepted) and expose
// the mode a given dtype would actually ride the wire with.
int horovod_tpu_parse_compression(const char* s) {
  return static_cast<int>(ParseCompressionMode(s));
}
// The HVD_TPU_COMPRESSION job default, for bindings without their own
// per-call compression plumbing (tf_ops.cc, torch_cext.c). Read fresh
// each call — negotiation validates it cross-rank anyway.
int horovod_tpu_default_compression() {
  return static_cast<int>(
      ParseCompressionMode(std::getenv(HVD_TPU_COMPRESSION_ENV)));
}
int horovod_tpu_effective_compression(int compression, int dtype) {
  return static_cast<int>(
      EffectiveCompression(static_cast<CompressionMode>(compression),
                           static_cast<DataType>(dtype)));
}
// Wire bytes `count` f32 elements occupy under `compression`
// (compression.cc layout — tests pin the size math against this).
int64_t horovod_tpu_compressed_size(int64_t count, int compression) {
  return static_cast<int64_t>(CompressedSize(
      count, static_cast<CompressionMode>(compression)));
}

// Reduce-scatter enqueue (docs/ZERO.md): `output` must hold this rank's
// shard — PartitionChunks over the flattened element count (chunk r to
// rank r; Python mirrors the math in common/ops.py shard_partition).
// Compression rides the negotiation exactly like allreduce.
int horovod_tpu_enqueue_reduce_scatter(const char* name, const void* data,
                                       void* output, int ndim,
                                       const int64_t* shape, int dtype,
                                       double prescale, double postscale,
                                       int compression) {
  int handle = g_handles.AllocateHandle();
  Status s = EnqueueTensor(Request::REDUCESCATTER, name, data, output, ndim,
                           shape, dtype, 0, prescale, postscale, compression,
                           /*group=*/0, handle);
  if (!s.ok()) {
    g_handles.MarkDone(handle, s);
  }
  return handle;
}

// The HVD_TPU_SHARDED_UPDATE job default, read fresh each call (the
// negotiation validates the mode cross-rank anyway).
int horovod_tpu_sharded_update_default() {
  const char* v = std::getenv(HVD_TPU_SHARDED_UPDATE_ENV);
  return v != nullptr && std::strtol(v, nullptr, 10) != 0 ? 1 : 0;
}

// Sharded-optimizer accounting (docs/ZERO.md): the absolute number of
// optimizer-state bytes this rank holds (gauge; < 0 leaves it
// unchanged). Reported by the framework wrappers on init and resize so
// the memory claim is observable (hvd-top, bench A/B).
void horovod_tpu_opt_state_metrics(int64_t bytes) {
  if (bytes >= 0) {
    GlobalMetrics().opt_state_bytes.store(bytes, std::memory_order_relaxed);
  }
}

int horovod_tpu_enqueue_allgather(const char* name, const void* data, int ndim,
                                  const int64_t* shape, int dtype) {
  int handle = g_handles.AllocateHandle();
  // The op writes the gathered result into core-owned buffers; the entry
  // callback surfaces them through the handle for copy-out.
  Status s = EnqueueTensor(Request::ALLGATHER, name, data, nullptr, ndim,
                           shape, dtype, 0, 1.0, 1.0, 0, /*group=*/0,
                           handle);
  if (!s.ok()) {
    g_handles.MarkDone(handle, s);
  }
  return handle;
}

int horovod_tpu_enqueue_broadcast(const char* name, const void* data,
                                  void* output, int ndim, const int64_t* shape,
                                  int dtype, int root_rank) {
  int handle = g_handles.AllocateHandle();
  Status s = EnqueueTensor(Request::BROADCAST, name, data, output, ndim, shape,
                           dtype, root_rank, 1.0, 1.0, 0, /*group=*/0,
                           handle);
  if (!s.ok()) {
    g_handles.MarkDone(handle, s);
  }
  return handle;
}

int horovod_tpu_poll(int handle) { return g_handles.Poll(handle) ? 1 : 0; }

int horovod_tpu_wait(int handle) {
  auto entry = g_handles.Wait(handle);
  if (entry == nullptr) return static_cast<int>(StatusType::INVALID_ARGUMENT);
  return static_cast<int>(entry->status.type());
}

const char* horovod_tpu_error_string(int handle) {
  static thread_local std::string err;
  auto entry = g_handles.Get(handle);
  err = entry ? entry->status.reason() : "unknown handle";
  return err.c_str();
}

int64_t horovod_tpu_allgather_bytes(int handle) {
  auto entry = g_handles.Get(handle);
  if (entry == nullptr || entry->gathered == nullptr) return -1;
  return static_cast<int64_t>(entry->gathered->size());
}

int64_t horovod_tpu_allgather_rank_dim(int handle, int rank) {
  auto entry = g_handles.Get(handle);
  if (entry == nullptr || entry->gathered_sizes == nullptr ||
      rank >= static_cast<int>(entry->gathered_sizes->size())) {
    return -1;
  }
  return (*entry->gathered_sizes)[rank];
}

int horovod_tpu_allgather_copy(int handle, void* out) {
  auto entry = g_handles.Get(handle);
  if (entry == nullptr || entry->gathered == nullptr) return 0;
  std::memcpy(out, entry->gathered->data(), entry->gathered->size());
  return 1;
}

// Zero-copy access: the returned pointer stays valid until
// horovod_tpu_release(handle) (the Python side wraps it in a numpy view
// whose finalizer performs the release).
const void* horovod_tpu_allgather_data(int handle) {
  auto entry = g_handles.Get(handle);
  if (entry == nullptr || entry->gathered == nullptr) return nullptr;
  return entry->gathered->data();
}

void horovod_tpu_release(int handle) { g_handles.Release(handle); }

// ---------------- distributed tracing (trace.h / docs/TRACING.md) ------

// Monotonic trace-clock ns (per-process epoch): Python-emitted spans land
// on the same clock the native ring uses.
int64_t horovod_tpu_trace_now_ns() { return GlobalTrace().NowNs(); }

// Record a span from Python (serve plane, tests). `phase` takes the wire
// values from trace.h (TRACE_ENQUEUE..TRACE_REQUEST); group 0 = world.
// No-op until init configures the recorder or when HVD_TPU_TRACE=0.
void horovod_tpu_trace_record(const char* name, int phase, int64_t start_ns,
                              int64_t end_ns, int64_t bytes, int group) {
  GlobalTrace().Record(name == nullptr ? "" : name, phase, start_ns, end_ns,
                       bytes, group < 0 ? 0u : static_cast<uint32_t>(group));
}

// Force a flight-recorder bundle (drain handlers, tests). Returns the
// bundle path, or "" when HVD_TPU_BUNDLE_DIR is unset / the per-process
// cap is hit. Pending-negotiation state is deliberately omitted: this is
// callable from any thread, and the controller's tables belong to the
// background thread.
const char* horovod_tpu_trace_dump_bundle(const char* reason) {
  static thread_local std::string out;
  out = GlobalTrace().DumpBundle(reason == nullptr ? "manual" : reason,
                                 std::string());
  return out.c_str();
}

// out[0]=spans recorded  out[1]=spans dropped (ring overrun)  out[2]=bundles
void horovod_tpu_trace_counters(uint64_t* out) {
  if (out == nullptr) return;
  Trace& t = GlobalTrace();
  out[0] = t.spans_total.load(std::memory_order_relaxed);
  out[1] = t.spans_dropped.load(std::memory_order_relaxed);
  out[2] = t.bundles_written.load(std::memory_order_relaxed);
}

}  // extern "C"
