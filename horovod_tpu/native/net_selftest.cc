// In-process transport selftests, exposed over the C API so the Python
// suite can unit-test the frame layer's failure paths (CRC detection,
// recv deadlines, oversize rejection, handshake timeouts) without
// spawning a multi-process job. Each scenario builds its sockets from
// scratch (socketpair / loopback listener), so these run tier-1-safe on
// any CPU-only host.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <string>
#include <thread>

#include "checksum.h"
#include "fault.h"
#include "logging.h"
#include "net.h"

namespace hvdtpu {
namespace {

struct ConnPair {
  Conn a;
  Conn b;
  bool ok = false;

  ConnPair() {
    int fds[2];
    if (::socketpair(AF_UNIX, SOCK_STREAM, 0, fds) != 0) return;
    a = Conn(fds[0]);
    b = Conn(fds[1]);
    ok = true;
  }
};

// A frame survives the wire and verifies, for both recv flavors.
bool CrcRoundtrip() {
  ConnPair p;
  if (!p.ok) return false;
  std::string payload = "the quick brown fox jumps over the lazy dog";
  if (!p.a.SendFrame(0x42, payload)) return false;
  uint32_t tag = 0;
  std::string got;
  if (!p.b.RecvFrame(&tag, &got)) return false;
  if (tag != 0x42 || got != payload) return false;
  if (!p.a.SendFrame(0x43, payload)) return false;
  std::string fixed(payload.size(), '\0');
  if (!p.b.RecvFrameInto(&tag, &fixed[0], fixed.size())) return false;
  return tag == 0x43 && fixed == payload;
}

// A flipped payload byte is detected as a checksum mismatch, not
// returned as data.
bool CrcCorruptDetected() {
  ConnPair p;
  if (!p.ok) return false;
  std::string payload(4096, 'G');  // a "gradient"
  uint64_t len = payload.size();
  uint32_t tag = 0x42;
  char prefix[12];
  std::memcpy(prefix, &tag, 4);
  std::memcpy(prefix + 4, &len, 8);
  uint32_t crc = Crc32c(prefix, sizeof(prefix));
  crc = Crc32c(payload.data(), payload.size(), crc);
  payload[1000] ^= 0x1;  // the wire flip
  char hdr[kFrameHeaderBytes];
  BuildFrameHeader(hdr, tag, len, crc);
  if (!p.a.SendAll(hdr, sizeof(hdr))) return false;
  if (!p.a.SendAll(payload.data(), payload.size())) return false;
  std::string got;
  uint32_t rtag;
  if (p.b.RecvFrame(&rtag, &got)) return false;  // MUST fail
  return p.b.last_error() == NetError::CRC;
}

// A peer that sends nothing trips the recv deadline promptly (bounded,
// not forever).
bool RecvDeadline() {
  ConnPair p;
  if (!p.ok) return false;
  p.b.SetTimeouts(1);
  auto t0 = std::chrono::steady_clock::now();
  uint32_t tag;
  std::string got;
  bool recv_ok = p.b.RecvFrame(&tag, &got);
  double elapsed =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();
  return !recv_ok && p.b.last_error() == NetError::TIMEOUT &&
         elapsed < 5.0;
}

// A corrupt length field is rejected before allocation, not OOM'd on.
bool MaxFrameRejected() {
  ConnPair p;
  if (!p.ok) return false;
  char hdr[kFrameHeaderBytes];
  BuildFrameHeader(hdr, 0x42, ~0ull >> 1, 0);  // ~9 EB "frame"
  if (!p.a.SendAll(hdr, sizeof(hdr))) return false;
  uint32_t tag;
  std::string got;
  if (p.b.RecvFrame(&tag, &got)) return false;  // MUST fail
  return p.b.last_error() == NetError::TOO_BIG;
}

// A client that connects and never handshakes (port scanner, health
// probe) cannot wedge the accept loop: AcceptPeer returns within its
// deadline, and a REAL peer arriving later still gets through.
bool HandshakeTimeout() {
  Listener l;
  if (!l.Start(0)) return false;
  int silent = ::socket(AF_INET, SOCK_STREAM, 0);
  if (silent < 0) return false;
  sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(0x7F000001);  // 127.0.0.1
  addr.sin_port = htons(static_cast<uint16_t>(l.port()));
  if (::connect(silent, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
      0) {
    ::close(silent);
    return false;
  }
  // ... and says nothing. Accept must give up within the deadline.
  auto t0 = std::chrono::steady_clock::now();
  PeerHandshake hs;
  int fd = l.AcceptPeer(&hs, 500, /*expected_generation=*/0);
  double elapsed =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();
  bool timed_out = fd < 0 && elapsed < 5.0;

  // A real peer still gets accepted while the scanner dangles.
  std::thread peer([&] {
    Conn c = ConnectPeer("127.0.0.1", l.port(), /*my_rank=*/3,
                         Channel::CONTROL, /*timeout_ms=*/3000,
                         /*generation=*/7);
    // Hold the conn open until the acceptor has read the handshake.
    std::this_thread::sleep_for(std::chrono::milliseconds(300));
  });
  int fd2 = l.AcceptPeer(&hs, 3000, /*expected_generation=*/7);
  peer.join();
  bool accepted = fd2 >= 0 && hs.rank == 3 &&
                  hs.channel == Channel::CONTROL && hs.generation == 7;
  if (fd2 >= 0) ::close(fd2);
  ::close(silent);
  return timed_out && accepted;
}

// A stale-generation peer is rejected; a current-generation peer is not.
bool StaleGenerationRejected() {
  Listener l;
  if (!l.Start(0)) return false;
  std::thread stale([&] {
    ConnectPeer("127.0.0.1", l.port(), /*my_rank=*/1, Channel::CONTROL,
                /*timeout_ms=*/2000, /*generation=*/3);
  });
  std::thread current([&] {
    // Give the stale connect a head start so rejection is exercised.
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
    Conn c = ConnectPeer("127.0.0.1", l.port(), /*my_rank=*/2,
                         Channel::CONTROL, /*timeout_ms=*/3000,
                         /*generation=*/4);
    std::this_thread::sleep_for(std::chrono::milliseconds(300));
  });
  PeerHandshake hs;
  int fd = l.AcceptPeer(&hs, 4000, /*expected_generation=*/4);
  stale.join();
  current.join();
  bool ok = fd >= 0 && hs.rank == 2 && hs.generation == 4;
  if (fd >= 0) ::close(fd);
  return ok;
}

// The fault-spec parser + seeded determinism: frame= fires exactly once
// at the right index; prob= replays identically for the same seed.
bool FaultSpecDeterministic() {
  FaultInjector inj;
  inj.Configure("seed=5;rank=1,chan=control,dir=send,frame=2,action=close",
                /*rank=*/1);
  if (!inj.active()) return false;
  for (int i = 0; i < 2; ++i) {
    if (inj.OnFrame(Channel::CONTROL, true).action != FaultAction::NONE) {
      return false;
    }
  }
  if (inj.OnFrame(Channel::CONTROL, true).action != FaultAction::CLOSE) {
    return false;
  }
  // count defaults to 1 for frame rules: never fires again.
  for (int i = 0; i < 8; ++i) {
    if (inj.OnFrame(Channel::CONTROL, true).action != FaultAction::NONE) {
      return false;
    }
  }
  // Rank filter: a rule for rank 1 never fires on rank 2.
  inj.Configure("rank=1,frame=0,action=drop", /*rank=*/2);
  if (inj.OnFrame(Channel::RING, true).action != FaultAction::NONE) {
    return false;
  }
  // Seeded prob= replay: identical decision streams for identical seeds.
  auto stream = [](uint64_t seed) {
    FaultInjector x;
    std::string spec =
        "seed=" + std::to_string(seed) + ";prob=0.3,action=delay,delay_ms=1";
    x.Configure(spec.c_str(), /*rank=*/0);
    std::string bits;
    for (int i = 0; i < 64; ++i) {
      bits.push_back(
          x.OnFrame(Channel::RING, false).action == FaultAction::NONE ? '0'
                                                                      : '1');
    }
    return bits;
  };
  std::string s1 = stream(99), s2 = stream(99), s3 = stream(100);
  if (s1 != s2) return false;
  if (s1.find('1') == std::string::npos) return false;  // must fire some
  return s1 != s3;  // and differ across seeds (64 frames: ~certain)
}

}  // namespace
}  // namespace hvdtpu

extern "C" {

// CRC32C of a buffer (known-answer tests from Python; also handy for
// tooling that wants to pre-checksum payloads).
uint32_t horovod_tpu_crc32c(const void* data, uint64_t len) {
  return hvdtpu::Crc32c(data, static_cast<std::size_t>(len));
}

// Incremental flavor: extend `crc` over another chunk.
uint32_t horovod_tpu_crc32c_extend(uint32_t crc, const void* data,
                                   uint64_t len) {
  return hvdtpu::Crc32c(data, static_cast<std::size_t>(len), crc);
}

// Runs the named transport selftest; 1 = pass, 0 = fail, -1 = unknown
// name. Scenarios: crc_roundtrip, crc_corrupt_detected, recv_deadline,
// max_frame, handshake_timeout, stale_generation, fault_spec.
int horovod_tpu_net_selftest(const char* name) {
  using namespace hvdtpu;
  std::string n(name ? name : "");
  if (n == "crc_roundtrip") return CrcRoundtrip() ? 1 : 0;
  if (n == "crc_corrupt_detected") return CrcCorruptDetected() ? 1 : 0;
  if (n == "recv_deadline") return RecvDeadline() ? 1 : 0;
  if (n == "max_frame") return MaxFrameRejected() ? 1 : 0;
  if (n == "handshake_timeout") return HandshakeTimeout() ? 1 : 0;
  if (n == "stale_generation") return StaleGenerationRejected() ? 1 : 0;
  if (n == "fault_spec") return FaultSpecDeterministic() ? 1 : 0;
  return -1;
}

}  // extern "C"
