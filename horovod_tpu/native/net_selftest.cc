// In-process transport selftests, exposed over the C API so the Python
// suite can unit-test the frame layer's failure paths (CRC detection,
// recv deadlines, oversize rejection, handshake timeouts) without
// spawning a multi-process job. Each scenario builds its sockets from
// scratch (socketpair / loopback listener), so these run tier-1-safe on
// any CPU-only host.
#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <string>
#include <thread>

#include "checksum.h"
#include "fault.h"
#include "logging.h"
#include "net.h"
#include "shm_context.h"

namespace hvdtpu {
namespace {

struct ConnPair {
  Conn a;
  Conn b;
  bool ok = false;

  ConnPair() {
    int fds[2];
    if (::socketpair(AF_UNIX, SOCK_STREAM, 0, fds) != 0) return;
    a = Conn(fds[0]);
    b = Conn(fds[1]);
    ok = true;
  }
};

// A frame survives the wire and verifies, for both recv flavors.
bool CrcRoundtrip() {
  ConnPair p;
  if (!p.ok) return false;
  std::string payload = "the quick brown fox jumps over the lazy dog";
  if (!p.a.SendFrame(0x42, payload)) return false;
  uint32_t tag = 0;
  std::string got;
  if (!p.b.RecvFrame(&tag, &got)) return false;
  if (tag != 0x42 || got != payload) return false;
  if (!p.a.SendFrame(0x43, payload)) return false;
  std::string fixed(payload.size(), '\0');
  if (!p.b.RecvFrameInto(&tag, &fixed[0], fixed.size())) return false;
  return tag == 0x43 && fixed == payload;
}

// A flipped payload byte is detected as a checksum mismatch, not
// returned as data.
bool CrcCorruptDetected() {
  ConnPair p;
  if (!p.ok) return false;
  std::string payload(4096, 'G');  // a "gradient"
  uint64_t len = payload.size();
  uint32_t tag = 0x42;
  char prefix[12];
  std::memcpy(prefix, &tag, 4);
  std::memcpy(prefix + 4, &len, 8);
  uint32_t crc = Crc32c(prefix, sizeof(prefix));
  crc = Crc32c(payload.data(), payload.size(), crc);
  payload[1000] ^= 0x1;  // the wire flip
  char hdr[kFrameHeaderBytes];
  BuildFrameHeader(hdr, tag, len, crc);
  if (!p.a.SendAll(hdr, sizeof(hdr))) return false;
  if (!p.a.SendAll(payload.data(), payload.size())) return false;
  std::string got;
  uint32_t rtag;
  if (p.b.RecvFrame(&rtag, &got)) return false;  // MUST fail
  return p.b.last_error() == NetError::CRC;
}

// A peer that sends nothing trips the recv deadline promptly (bounded,
// not forever).
bool RecvDeadline() {
  ConnPair p;
  if (!p.ok) return false;
  p.b.SetTimeouts(1);
  auto t0 = std::chrono::steady_clock::now();
  uint32_t tag;
  std::string got;
  bool recv_ok = p.b.RecvFrame(&tag, &got);
  double elapsed =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();
  return !recv_ok && p.b.last_error() == NetError::TIMEOUT &&
         elapsed < 5.0;
}

// A corrupt length field is rejected before allocation, not OOM'd on.
bool MaxFrameRejected() {
  ConnPair p;
  if (!p.ok) return false;
  char hdr[kFrameHeaderBytes];
  BuildFrameHeader(hdr, 0x42, ~0ull >> 1, 0);  // ~9 EB "frame"
  if (!p.a.SendAll(hdr, sizeof(hdr))) return false;
  uint32_t tag;
  std::string got;
  if (p.b.RecvFrame(&tag, &got)) return false;  // MUST fail
  return p.b.last_error() == NetError::TOO_BIG;
}

// A client that connects and never handshakes (port scanner, health
// probe) cannot wedge the accept loop: AcceptPeer returns within its
// deadline, and a REAL peer arriving later still gets through.
bool HandshakeTimeout() {
  Listener l;
  if (!l.Start(0)) return false;
  int silent = ::socket(AF_INET, SOCK_STREAM, 0);
  if (silent < 0) return false;
  sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(0x7F000001);  // 127.0.0.1
  addr.sin_port = htons(static_cast<uint16_t>(l.port()));
  if (::connect(silent, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
      0) {
    ::close(silent);
    return false;
  }
  // ... and says nothing. Accept must give up within the deadline.
  auto t0 = std::chrono::steady_clock::now();
  PeerHandshake hs;
  int fd = l.AcceptPeer(&hs, 500, /*expected_generation=*/0);
  double elapsed =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();
  bool timed_out = fd < 0 && elapsed < 5.0;

  // A real peer still gets accepted while the scanner dangles.
  std::thread peer([&] {
    Conn c = ConnectPeer("127.0.0.1", l.port(), /*my_rank=*/3,
                         Channel::CONTROL, /*timeout_ms=*/3000,
                         /*generation=*/7);
    // Hold the conn open until the acceptor has read the handshake.
    std::this_thread::sleep_for(std::chrono::milliseconds(300));
  });
  int fd2 = l.AcceptPeer(&hs, 3000, /*expected_generation=*/7);
  peer.join();
  bool accepted = fd2 >= 0 && hs.rank == 3 &&
                  hs.channel == Channel::CONTROL && hs.generation == 7;
  if (fd2 >= 0) ::close(fd2);
  ::close(silent);
  return timed_out && accepted;
}

// A stale-generation peer is rejected; a current-generation peer is not.
bool StaleGenerationRejected() {
  Listener l;
  if (!l.Start(0)) return false;
  std::thread stale([&] {
    ConnectPeer("127.0.0.1", l.port(), /*my_rank=*/1, Channel::CONTROL,
                /*timeout_ms=*/2000, /*generation=*/3);
  });
  std::thread current([&] {
    // Give the stale connect a head start so rejection is exercised.
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
    Conn c = ConnectPeer("127.0.0.1", l.port(), /*my_rank=*/2,
                         Channel::CONTROL, /*timeout_ms=*/3000,
                         /*generation=*/4);
    std::this_thread::sleep_for(std::chrono::milliseconds(300));
  });
  PeerHandshake hs;
  int fd = l.AcceptPeer(&hs, 4000, /*expected_generation=*/4);
  stale.join();
  current.join();
  bool ok = fd >= 0 && hs.rank == 2 && hs.generation == 4;
  if (fd >= 0) ::close(fd);
  return ok;
}

// The fault-spec parser + seeded determinism: frame= fires exactly once
// at the right index; prob= replays identically for the same seed.
bool FaultSpecDeterministic() {
  FaultInjector inj;
  inj.Configure("seed=5;rank=1,chan=control,dir=send,frame=2,action=close",
                /*rank=*/1);
  if (!inj.active()) return false;
  for (int i = 0; i < 2; ++i) {
    if (inj.OnFrame(Channel::CONTROL, true).action != FaultAction::NONE) {
      return false;
    }
  }
  if (inj.OnFrame(Channel::CONTROL, true).action != FaultAction::CLOSE) {
    return false;
  }
  // count defaults to 1 for frame rules: never fires again.
  for (int i = 0; i < 8; ++i) {
    if (inj.OnFrame(Channel::CONTROL, true).action != FaultAction::NONE) {
      return false;
    }
  }
  // Rank filter: a rule for rank 1 never fires on rank 2.
  inj.Configure("rank=1,frame=0,action=drop", /*rank=*/2);
  if (inj.OnFrame(Channel::RING, true).action != FaultAction::NONE) {
    return false;
  }
  // Seeded prob= replay: identical decision streams for identical seeds.
  auto stream = [](uint64_t seed) {
    FaultInjector x;
    std::string spec =
        "seed=" + std::to_string(seed) + ";prob=0.3,action=delay,delay_ms=1";
    x.Configure(spec.c_str(), /*rank=*/0);
    std::string bits;
    for (int i = 0; i < 64; ++i) {
      bits.push_back(
          x.OnFrame(Channel::RING, false).action == FaultAction::NONE ? '0'
                                                                      : '1');
    }
    return bits;
  };
  std::string s1 = stream(99), s2 = stream(99), s3 = stream(100);
  if (s1 != s2) return false;
  if (s1.find('1') == std::string::npos) return false;  // must fire some
  return s1 != s3;  // and differ across seeds (64 frames: ~certain)
}

// ---- shared-memory transport scenarios (shm_context.{h,cc}) ----

static std::string UniqueShmName(const char* tag) {
  return std::string("/hvdtpu-selftest-") + tag + "-" +
         std::to_string(::getpid());
}

// A frame (header + payload) round-trips the SPSC ring bitwise,
// including a wrap-around (payload larger than the remaining tail of
// the ring), and the writer/reader counters agree.
bool ShmRoundtrip() {
  std::string name = UniqueShmName("rt");
  auto w = ShmRing::Create(name, 4096);
  if (w == nullptr) return false;
  auto r = ShmRing::Attach(name);
  if (r == nullptr) return false;
  w->MarkExchanged();
  std::string payload;
  for (int i = 0; i < 6000; ++i) payload.push_back(static_cast<char>(i));
  uint32_t crc = Crc32c(payload.data(), payload.size());
  // Pump concurrently: the payload exceeds the ring capacity, so the
  // writer must block on space while the reader drains — exactly the
  // double-buffered flow of a real hop.
  std::string got(payload.size(), '\0');
  std::thread reader([&] { r->ReadAll(&got[0], got.size(), 5000); });
  bool wrote = w->WriteAll(payload.data(), payload.size(), 5000);
  reader.join();
  if (!wrote || got != payload) return false;
  if (Crc32c(got.data(), got.size()) != crc) return false;
  // Orderly hangup: the reader drains leftovers then sees EOF.
  char c = 'x';
  if (w->WriteSome(&c, 1) != 1) return false;
  w->Close();
  char back;
  if (r->ReadSome(&back, 1) != 1 || back != 'x') return false;
  return r->ReadSome(&back, 1) == -1;  // closed AND drained = EOF
}

// A byte flipped INSIDE the mapped segment after the CRC was computed is
// a detected mismatch at verification time — the shm plane keeps the
// frame-CRC discipline (corruption surfaces as an error, never data).
bool ShmCorruptDetected() {
  std::string name = UniqueShmName("crc");
  auto w = ShmRing::Create(name, 1 << 16);
  if (w == nullptr) return false;
  auto r = ShmRing::Attach(name);
  if (r == nullptr) return false;
  w->MarkExchanged();
  std::string payload(4096, 'G');
  uint64_t len = payload.size();
  uint32_t tag = 0x20;
  uint32_t crc = FrameHeaderCrc(tag, len);
  crc = Crc32c(payload.data(), payload.size(), crc);
  payload[1000] ^= 0x1;  // the "wire" flip, after the checksum
  char hdr[kFrameHeaderBytes];
  BuildFrameHeader(hdr, tag, len, crc);
  if (!w->WriteAll(hdr, sizeof(hdr), 1000)) return false;
  if (!w->WriteAll(payload.data(), payload.size(), 1000)) return false;
  char rhdr[kFrameHeaderBytes];
  if (!r->ReadAll(rhdr, sizeof(rhdr), 1000)) return false;
  uint32_t rtag, rcrc;
  uint64_t rlen;
  ParseFrameHeader(rhdr, &rtag, &rlen, &rcrc);
  std::string got(static_cast<std::size_t>(rlen), '\0');
  if (!r->ReadAll(&got[0], got.size(), 1000)) return false;
  uint32_t acc = FrameHeaderCrc(rtag, rlen);
  acc = Crc32c(got.data(), got.size(), acc);
  return acc != rcrc;  // MUST mismatch — detected, not silently wrong
}

// Attach-side fallback negotiation: a nonexistent name, and a segment
// whose header does not parse, both refuse cleanly (nullptr — the
// caller's "ride TCP instead" path), and a good segment still attaches
// afterwards.
bool ShmFallbackNegotiation() {
  if (ShmRing::Attach(UniqueShmName("nonexistent")) != nullptr) return false;
  // A raw shm object with garbage where the header should be.
  std::string bogus = UniqueShmName("bogus");
  int fd = ::shm_open(bogus.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return false;
  if (::ftruncate(fd, 8192) != 0) {
    ::close(fd);
    ::shm_unlink(bogus.c_str());
    return false;
  }
  ::close(fd);
  bool refused = ShmRing::Attach(bogus) == nullptr;
  ::shm_unlink(bogus.c_str());
  if (!refused) return false;
  // And the happy path still works after the refusals.
  std::string good = UniqueShmName("good");
  auto w = ShmRing::Create(good, 4096);
  if (w == nullptr) return false;
  auto r = ShmRing::Attach(good);
  return r != nullptr && r->capacity() == 4096;
}

// Closing the writer wakes a parked reader promptly (no deadline-long
// hang), and a reader parked on an empty ring respects its timeout.
bool ShmClosedWakesPeer() {
  std::string name = UniqueShmName("close");
  auto w = ShmRing::Create(name, 4096);
  if (w == nullptr) return false;
  auto r = ShmRing::Attach(name);
  if (r == nullptr) return false;
  w->MarkExchanged();
  auto t0 = std::chrono::steady_clock::now();
  std::thread closer([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    w->Close();
  });
  char buf;
  bool read_failed = !r->ReadAll(&buf, 1, 10000);
  closer.join();
  double elapsed =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();
  return read_failed && elapsed < 5.0;
}

// ---- per-hop transport microbench (bench.py --shm) ----
//
// One ring hop = a full-duplex neighbor exchange: each side sends
// `nbytes` while receiving `nbytes` (exactly PairExchange's payload
// pump), including the 16-byte frame header and the receive-side
// incremental CRC. Two threads on this host play the two ranks; each
// direction gets its own transport pair (an SPSC shm ring, or one side
// of a socketpair) — the in-process setup isolates the TRANSPORT cost
// from the negotiation/control plane that dominates end-to-end op time
// on small hosts.

struct HopEnd {
  // shm transport
  ShmRing* out_ring = nullptr;
  ShmRing* in_ring = nullptr;
  // tcp transport
  int out_fd = -1;
  int in_fd = -1;
};

static bool HopExchange(HopEnd& e, const char* sbuf, char* rbuf,
                        std::size_t nbytes) {
  char shdr[kFrameHeaderBytes];
  uint32_t scrc = FrameCrc(0x20, nbytes, sbuf, nbytes);
  BuildFrameHeader(shdr, 0x20, nbytes, scrc);
  std::size_t hsent = 0, hrecv = 0, sent = 0, received = 0;
  char rhdr[kFrameHeaderBytes];
  uint32_t crc_acc = 0;
  bool crc_seeded = false;
  while (hsent < sizeof(shdr) || hrecv < sizeof(rhdr) ||
         sent < nbytes || received < nbytes) {
    bool progress = false;
    if (e.out_ring != nullptr) {
      if (hsent < sizeof(shdr)) {
        int64_t w = e.out_ring->WriteSome(shdr + hsent,
                                          sizeof(shdr) - hsent);
        if (w < 0) return false;
        if (w > 0) { hsent += w; progress = true; }
      } else if (sent < nbytes) {
        int64_t w = e.out_ring->WriteSome(sbuf + sent, nbytes - sent);
        if (w < 0) return false;
        if (w > 0) { sent += w; progress = true; }
      }
      if (hrecv < sizeof(rhdr)) {
        int64_t r = e.in_ring->ReadSome(rhdr + hrecv,
                                        sizeof(rhdr) - hrecv);
        if (r < 0) return false;
        if (r > 0) { hrecv += r; progress = true; }
      } else if (received < nbytes) {
        if (!crc_seeded) {
          uint32_t rtag, rcrc;
          uint64_t rlen;
          ParseFrameHeader(rhdr, &rtag, &rlen, &rcrc);
          crc_acc = NetCrcEnabled() ? FrameHeaderCrc(rtag, rlen) : 0;
          crc_seeded = true;
        }
        int64_t r = e.in_ring->ReadSome(rbuf + received,
                                        nbytes - received);
        if (r < 0) return false;
        if (r > 0) {
          if (NetCrcEnabled()) {
            crc_acc = Crc32c(rbuf + received, static_cast<std::size_t>(r),
                             crc_acc);
          }
          received += r;
          progress = true;
        }
      }
      if (!progress) {
        if (received < nbytes || hrecv < sizeof(rhdr)) {
          e.in_ring->WaitReadable(2);
        } else {
          e.out_ring->WaitWritable(2);
        }
      }
      continue;
    }
    // TCP: nonblocking duplex pump with poll, the production shape.
    struct pollfd pfds[2];
    int n = 0;
    if (hsent < sizeof(shdr) || sent < nbytes) {
      pfds[n++] = {e.out_fd, POLLOUT, 0};
    }
    if (hrecv < sizeof(rhdr) || received < nbytes) {
      pfds[n++] = {e.in_fd, POLLIN, 0};
    }
    if (::poll(pfds, n, 1000) < 0) return false;
    if (hsent < sizeof(shdr)) {
      ssize_t w = ::send(e.out_fd, shdr + hsent, sizeof(shdr) - hsent,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w > 0) hsent += w;
    } else if (sent < nbytes) {
      ssize_t w = ::send(e.out_fd, sbuf + sent, nbytes - sent,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK) return false;
      if (w > 0) sent += w;
    }
    if (hrecv < sizeof(rhdr)) {
      ssize_t r = ::recv(e.in_fd, rhdr + hrecv, sizeof(rhdr) - hrecv,
                         MSG_DONTWAIT);
      if (r == 0) return false;
      if (r > 0) hrecv += r;
    } else if (received < nbytes) {
      if (!crc_seeded) {
        uint32_t rtag, rcrc;
        uint64_t rlen;
        ParseFrameHeader(rhdr, &rtag, &rlen, &rcrc);
        crc_acc = NetCrcEnabled() ? FrameHeaderCrc(rtag, rlen) : 0;
        crc_seeded = true;
      }
      ssize_t r = ::recv(e.in_fd, rbuf + received, nbytes - received,
                         MSG_DONTWAIT);
      if (r == 0) return false;
      if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK) return false;
      if (r > 0) {
        if (NetCrcEnabled()) {
          crc_acc = Crc32c(rbuf + received, static_cast<std::size_t>(r),
                           crc_acc);
        }
        received += r;
      }
    }
  }
  // Verify like the production pump (keeps the CRC pass in the timing).
  uint32_t rtag, rcrc;
  uint64_t rlen;
  ParseFrameHeader(rhdr, &rtag, &rlen, &rcrc);
  return !NetCrcEnabled() || crc_acc == rcrc;
}

static double HopBench(bool use_shm, std::size_t nbytes, int iters) {
  HopEnd a, b;
  std::unique_ptr<ShmRing> rings[4];
  int fds_ab[2] = {-1, -1}, fds_ba[2] = {-1, -1};
  if (use_shm) {
    std::string base = UniqueShmName("hop");
    rings[0] = ShmRing::Create(base + "-ab", ShmSegmentBytes());
    rings[1] = ShmRing::Attach(base + "-ab");
    rings[2] = ShmRing::Create(base + "-ba", ShmSegmentBytes());
    rings[3] = ShmRing::Attach(base + "-ba");
    for (auto& r : rings) {
      if (r == nullptr) return -1.0;
    }
    rings[0]->MarkExchanged();
    rings[2]->MarkExchanged();
    a.out_ring = rings[0].get();
    b.in_ring = rings[1].get();
    b.out_ring = rings[2].get();
    a.in_ring = rings[3].get();
  } else {
    // The baseline is genuine TCP LOOPBACK (what the production data
    // plane rides intra-host without shm), not an AF_UNIX socketpair —
    // Unix sockets skip the TCP stack and would flatter the baseline.
    auto tcp_pair = [](int out[2]) {
      Listener l;
      if (!l.Start(0)) return false;
      int cfd = ::socket(AF_INET, SOCK_STREAM, 0);
      if (cfd < 0) return false;
      sockaddr_in addr;
      std::memset(&addr, 0, sizeof(addr));
      addr.sin_family = AF_INET;
      addr.sin_addr.s_addr = htonl(0x7F000001);
      addr.sin_port = htons(static_cast<uint16_t>(l.port()));
      if (::connect(cfd, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) != 0) {
        ::close(cfd);
        return false;
      }
      int sfd = ::accept(l.fd(), nullptr, nullptr);
      if (sfd < 0) {
        ::close(cfd);
        return false;
      }
      ConfigureSocket(cfd);
      ConfigureSocket(sfd);
      out[0] = cfd;
      out[1] = sfd;
      return true;
    };
    if (!tcp_pair(fds_ab) || !tcp_pair(fds_ba)) return -1.0;
    a.out_fd = fds_ab[0];
    b.in_fd = fds_ab[1];
    b.out_fd = fds_ba[0];
    a.in_fd = fds_ba[1];
  }
  std::string sa(nbytes, 'a'), sb(nbytes, 'b');
  std::string ra(nbytes, 0), rb(nbytes, 0);
  std::atomic<bool> ok{true};
  double us = -1.0;
  {
    std::thread peer([&] {
      for (int i = 0; i < iters + 1 && ok.load(); ++i) {
        if (!HopExchange(b, sb.data(), &rb[0], nbytes)) ok.store(false);
      }
    });
    // Warmup hop, then the timed run.
    if (!HopExchange(a, sa.data(), &ra[0], nbytes)) ok.store(false);
    auto t0 = std::chrono::steady_clock::now();
    for (int i = 0; i < iters && ok.load(); ++i) {
      if (!HopExchange(a, sa.data(), &ra[0], nbytes)) ok.store(false);
    }
    us = std::chrono::duration<double, std::micro>(
             std::chrono::steady_clock::now() - t0)
             .count() /
         iters;
    peer.join();
  }
  if (fds_ab[0] >= 0) {
    ::close(fds_ab[0]);
    ::close(fds_ab[1]);
    ::close(fds_ba[0]);
    ::close(fds_ba[1]);
  }
  if (!ok.load() || ra != sb) return -1.0;
  return us;
}

}  // namespace
}  // namespace hvdtpu

extern "C" {

// CRC32C of a buffer (known-answer tests from Python; also handy for
// tooling that wants to pre-checksum payloads).
uint32_t horovod_tpu_crc32c(const void* data, uint64_t len) {
  return hvdtpu::Crc32c(data, static_cast<std::size_t>(len));
}

// Incremental flavor: extend `crc` over another chunk.
uint32_t horovod_tpu_crc32c_extend(uint32_t crc, const void* data,
                                   uint64_t len) {
  return hvdtpu::Crc32c(data, static_cast<std::size_t>(len), crc);
}

// Per-hop transport microbench (bench.py --shm): microseconds for one
// full-duplex `nbytes` neighbor exchange (header + incremental CRC, the
// production pump shape) between two in-process threads over shared
// memory (use_shm=1) or a socketpair (0). Returns -1.0 on failure.
double horovod_tpu_hop_bench(int use_shm, int64_t nbytes, int iters) {
  return hvdtpu::HopBench(use_shm != 0,
                          static_cast<std::size_t>(nbytes),
                          iters < 1 ? 1 : iters);
}

// Runs the named transport selftest; 1 = pass, 0 = fail, -1 = unknown
// name. Scenarios: crc_roundtrip, crc_corrupt_detected, recv_deadline,
// max_frame, handshake_timeout, stale_generation, fault_spec.
int horovod_tpu_net_selftest(const char* name) {
  using namespace hvdtpu;
  std::string n(name ? name : "");
  if (n == "crc_roundtrip") return CrcRoundtrip() ? 1 : 0;
  if (n == "crc_corrupt_detected") return CrcCorruptDetected() ? 1 : 0;
  if (n == "recv_deadline") return RecvDeadline() ? 1 : 0;
  if (n == "max_frame") return MaxFrameRejected() ? 1 : 0;
  if (n == "handshake_timeout") return HandshakeTimeout() ? 1 : 0;
  if (n == "stale_generation") return StaleGenerationRejected() ? 1 : 0;
  if (n == "fault_spec") return FaultSpecDeterministic() ? 1 : 0;
  if (n == "shm_roundtrip") return ShmRoundtrip() ? 1 : 0;
  if (n == "shm_corrupt_detected") return ShmCorruptDetected() ? 1 : 0;
  if (n == "shm_fallback") return ShmFallbackNegotiation() ? 1 : 0;
  if (n == "shm_closed_wakes_peer") return ShmClosedWakesPeer() ? 1 : 0;
  return -1;
}

}  // extern "C"
