#include "collective_operations.h"

#include <cstring>

#include "global_state.h"
#include "logging.h"

namespace hvdtpu {

int64_t HorovodOp::NumElements(
    const std::vector<TensorTableEntry>& entries) const {
  int64_t n = 0;
  for (const auto& e : entries) n += e.NumElements();
  return n;
}

Status HorovodOp::MemcpyInFusionBuffer(std::vector<TensorTableEntry>& entries,
                                       void** buffer_data,
                                       std::size_t* buffer_len) {
  Trace& trace = global_state_->trace;
  const int64_t t_fuse_start = trace.NowNs();
  std::size_t total = 0;
  for (const auto& e : entries) total += e.SizeBytes();
  Status status = global_state_->fusion_buffer.InitializeBuffer(
      static_cast<int64_t>(total), /*key=*/0);
  if (!status.ok()) return status;
  char* buf = static_cast<char*>(global_state_->fusion_buffer.GetBuffer(0));
  std::size_t offset = 0;
  for (const auto& e : entries) {
    std::memcpy(buf + offset, e.data, e.SizeBytes());
    offset += e.SizeBytes();
  }
  *buffer_data = buf;
  *buffer_len = total;
  trace.Record(entries.empty() ? "fuse" : entries[0].tensor_name.c_str(),
               TRACE_FUSE, t_fuse_start, trace.NowNs(),
               static_cast<int64_t>(total),
               entries.empty() ? 0 : entries[0].group_id);
  return Status::OK();
}

void HorovodOp::MemcpyOutFusionBuffer(const void* buffer_data,
                                      std::vector<TensorTableEntry>& entries) {
  const char* buf = static_cast<const char*>(buffer_data);
  std::size_t offset = 0;
  for (auto& e : entries) {
    std::memcpy(e.output, buf + offset, e.SizeBytes());
    offset += e.SizeBytes();
  }
}

template <typename Op>
Status OperationManager::ExecuteFirstEnabled(
    const std::vector<std::shared_ptr<Op>>& ops,
    std::vector<TensorTableEntry>& entries, const Response& response) {
  for (const auto& op : ops) {
    if (op->Enabled(entries, response)) {
      return op->Execute(entries, response);
    }
  }
  return Status::PreconditionError(
      "No enabled operation found to execute response of type " +
      std::string(Response::ResponseTypeName(response.response_type())));
}

Status OperationManager::ExecuteOperation(
    std::vector<TensorTableEntry>& entries, const Response& response) {
  switch (response.response_type()) {
    case Response::ALLREDUCE:
      return ExecuteFirstEnabled(allreduce_ops_, entries, response);
    case Response::ALLGATHER:
      return ExecuteFirstEnabled(allgather_ops_, entries, response);
    case Response::BROADCAST:
      return ExecuteFirstEnabled(broadcast_ops_, entries, response);
    case Response::REDUCESCATTER:
      return ExecuteFirstEnabled(reducescatter_ops_, entries, response);
    case Response::ERROR:
      return error_op_->Execute(entries, response);
  }
  return Status::UnknownError("unknown response type");
}

}  // namespace hvdtpu
