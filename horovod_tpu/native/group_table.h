// Process-group registry (docs/GROUPS.md): id -> ordered member ranks.
//
// Group 0 is the implicit world group and is never stored. Every other
// group is created by horovod_tpu_new_group, which EVERY rank must call
// with the identical rank list in the identical order — ids are assigned
// from a per-process counter, so the same call sequence yields the same
// ids on every rank (the same discipline the auto-name counter uses).
// Non-members register too: the response cache needs every rank to know
// every group's membership so the cache-bit protocol can treat "not my
// group" as vacuously ready (response_cache.h).
//
// The registry is immutable per entry (groups are never resized — an
// elastic membership change clears the table on re-init and Python
// re-creates the mesh groups), so readers only race the registration
// writes, which the mutex covers. Horovod's own coordinator never had
// communicator support (SURVEY §0); this table is the core of it.
#ifndef HVD_TPU_GROUP_TABLE_H
#define HVD_TPU_GROUP_TABLE_H

#include <algorithm>
#include <cstdint>
#include <mutex>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace hvdtpu {

// Composite "tensor in group" key: the coordinator's pending table, the
// response cache, the stall inspector, and the call tracker all key on
// this so the SAME tensor name active in two disjoint groups at once
// (the 2-D mesh's per-column gradient reduce) never collides. The @g
// suffix is deliberately human-readable — it appears verbatim in stall
// and divergence diagnostics, which must name the group.
inline std::string GroupQualifiedName(uint32_t group,
                                      const std::string& name) {
  if (group == 0) return name;
  return name + "@g" + std::to_string(group);
}

class GroupTable {
 public:
  // Registers a group; `members` must be strictly ascending world ranks.
  // Returns the new id (>= 1), or 0 on invalid input. The caller
  // (operations.cc) validates ranks against world size.
  uint32_t Register(std::vector<int> members) {
    if (members.empty()) return 0;
    for (std::size_t i = 1; i < members.size(); ++i) {
      if (members[i] <= members[i - 1]) return 0;
    }
    uint64_t digest = 14695981039346656037ULL;  // FNV-1a offset basis
    for (int r : members) {
      for (int b = 0; b < 4; ++b) {
        digest = (digest ^ ((static_cast<uint32_t>(r) >> (8 * b)) & 0xFF)) *
                 1099511628211ULL;
      }
    }
    std::lock_guard<std::mutex> lk(mu_);
    uint32_t id = next_id_++;
    groups_.emplace(id, Entry{std::move(members), digest});
    return id;
  }

  // Member ranks (ascending); empty when the id is unknown.
  std::vector<int> Members(uint32_t id) const {
    if (id == 0) return {};
    std::lock_guard<std::mutex> lk(mu_);
    auto it = groups_.find(id);
    return it == groups_.end() ? std::vector<int>() : it->second.members;
  }

  // Group size; 0 when unknown (group 0 is the caller's world size).
  int Size(uint32_t id) const {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = groups_.find(id);
    return it == groups_.end() ? 0 : static_cast<int>(it->second.members.size());
  }

  // Rank's position in the group's ring order; -1 when not a member (or
  // the id is unknown).
  int IndexOf(uint32_t id, int rank) const {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = groups_.find(id);
    if (it == groups_.end()) return -1;
    const auto& m = it->second.members;
    auto pos = std::lower_bound(m.begin(), m.end(), rank);
    if (pos == m.end() || *pos != rank) return -1;
    return static_cast<int>(pos - m.begin());
  }

  bool Contains(uint32_t id, int rank) const { return IndexOf(id, rank) >= 0; }

  // Membership digest — rides every group Request so ranks that called
  // new_group with DIFFERENT rank lists for the same id are rejected by
  // name at negotiation (mixed membership) instead of hanging.
  uint64_t Digest(uint32_t id) const {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = groups_.find(id);
    return it == groups_.end() ? 0 : it->second.digest;
  }

  std::size_t Count() const {
    std::lock_guard<std::mutex> lk(mu_);
    return groups_.size();
  }

  std::string DescribeMembers(uint32_t id) const {
    std::ostringstream os;
    os << "[";
    bool first = true;
    for (int r : Members(id)) {
      if (!first) os << ", ";
      os << r;
      first = false;
    }
    os << "]";
    return os.str();
  }

  // Generation reset (elastic re-init): the old membership's groups
  // reference dead ranks; Python re-creates the mesh groups after init.
  void Clear() {
    std::lock_guard<std::mutex> lk(mu_);
    groups_.clear();
    next_id_ = 1;
  }

 private:
  struct Entry {
    std::vector<int> members;
    uint64_t digest;
  };

  mutable std::mutex mu_;
  std::unordered_map<uint32_t, Entry> groups_;  // guarded_by(mu_)
  uint32_t next_id_ = 1;                        // guarded_by(mu_)
};

}  // namespace hvdtpu

#endif  // HVD_TPU_GROUP_TABLE_H
