#include "logging.h"

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

namespace hvdtpu {

static std::atomic<int> g_log_rank{-1};

void SetLogRank(int rank) { g_log_rank.store(rank); }

static LogLevel ParseLevel(const char* s) {
  if (s == nullptr) return LogLevel::WARNING;
  std::string v(s);
  for (auto& c : v) c = static_cast<char>(tolower(c));
  if (v == "trace" || v == "0") return LogLevel::TRACE;
  if (v == "debug" || v == "1") return LogLevel::DEBUG;
  if (v == "info" || v == "2") return LogLevel::INFO;
  if (v == "warning" || v == "warn" || v == "3") return LogLevel::WARNING;
  if (v == "error" || v == "4") return LogLevel::ERROR;
  if (v == "fatal" || v == "5") return LogLevel::FATAL;
  return LogLevel::WARNING;
}

LogLevel MinLogLevelFromEnv() {
  static LogLevel cached = ParseLevel(std::getenv("HVD_TPU_LOG_LEVEL"));
  return cached;
}

static bool HideTime() {
  static bool cached = [] {
    const char* v = std::getenv("HVD_TPU_LOG_HIDE_TIME");
    return v != nullptr && std::strtol(v, nullptr, 10) != 0;
  }();
  return cached;
}

static const char* LevelName(LogLevel level) {
  switch (level) {
    case LogLevel::TRACE: return "TRACE";
    case LogLevel::DEBUG: return "DEBUG";
    case LogLevel::INFO: return "INFO";
    case LogLevel::WARNING: return "WARNING";
    case LogLevel::ERROR: return "ERROR";
    case LogLevel::FATAL: return "FATAL";
  }
  return "?";
}

LogMessage::LogMessage(const char* file, int line, LogLevel level)
    : file_(file), line_(line), level_(level) {}

LogMessage::~LogMessage() {
  if (level_ < MinLogLevelFromEnv()) return;
  std::ostringstream prefix;
  if (!HideTime()) {
    auto now = std::chrono::system_clock::now();
    auto us = std::chrono::duration_cast<std::chrono::microseconds>(
                  now.time_since_epoch())
                  .count();
    std::time_t secs = static_cast<std::time_t>(us / 1000000);
    struct tm tmv;
    localtime_r(&secs, &tmv);
    char buf[32];
    std::strftime(buf, sizeof(buf), "%H:%M:%S", &tmv);
    prefix << "[" << buf << "." << (us % 1000000) / 1000 << "]";
  }
  int rank = g_log_rank.load();
  if (rank >= 0) prefix << "[" << rank << "]";
  std::fprintf(stderr, "%s[%s] %s:%d: %s\n", prefix.str().c_str(),
               LevelName(level_), file_, line_, str().c_str());
}

LogMessageFatal::LogMessageFatal(const char* file, int line)
    : LogMessage(file, line, LogLevel::FATAL) {}

LogMessageFatal::~LogMessageFatal() {
  std::fprintf(stderr, "[FATAL] %s\n", str().c_str());
  std::abort();
}

}  // namespace hvdtpu
