#include "tcp_context.h"

#include <poll.h>
#include <sys/socket.h>

#include <cstdlib>
#include <cstring>

#include "logging.h"

namespace hvdtpu {

static int EnvInt(const char* name, int dflt) {
  const char* v = std::getenv(name);
  return v == nullptr ? dflt : std::atoi(v);
}

static constexpr uint32_t kTagGather = 0x11;
static constexpr uint32_t kTagBcast = 0x12;
static constexpr uint32_t kTagBits = 0x13;
static constexpr uint32_t kTagBarrier = 0x14;
static constexpr uint32_t kTagRing = 0x20;

bool TcpContext::Initialize() {
  rank_ = EnvInt("HVD_TPU_RANK", 0);
  size_ = EnvInt("HVD_TPU_SIZE", 1);
  local_rank_ = EnvInt("HVD_TPU_LOCAL_RANK", rank_);
  local_size_ = EnvInt("HVD_TPU_LOCAL_SIZE", size_);
  cross_rank_ = EnvInt("HVD_TPU_CROSS_RANK", 0);
  cross_size_ = EnvInt("HVD_TPU_CROSS_SIZE", 1);
  SetLogRank(rank_);

  if (size_ == 1) {
    initialized_ = true;
    return true;
  }

  const char* addrs_env = std::getenv("HVD_TPU_ADDRS");
  if (addrs_env == nullptr) {
    LOG(ERROR) << "HVD_TPU_ADDRS not set but size > 1";
    return false;
  }
  std::vector<std::string> addrs = SplitString(addrs_env, ',');
  if (static_cast<int>(addrs.size()) != size_) {
    LOG(ERROR) << "HVD_TPU_ADDRS has " << addrs.size() << " entries, expected "
               << size_;
    return false;
  }
  std::string my_host;
  int my_port = 0;
  if (!ParseHostPort(addrs[rank_], &my_host, &my_port)) {
    LOG(ERROR) << "bad address " << addrs[rank_];
    return false;
  }
  if (!listener_.Start(my_port)) return false;

  int timeout_ms = EnvInt("HVD_TPU_START_TIMEOUT", 60) * 1000;

  // Expected inbound connections: the ring predecessor, plus (rank 0 only)
  // every worker's control connection.
  int expected = 1 + (rank_ == 0 ? size_ - 1 : 0);
  control_conns_.resize(rank_ == 0 ? size_ : 1);

  std::atomic<int> accepted{0};
  std::atomic<bool> accept_ok{true};
  std::thread acceptor([&] {
    for (int i = 0; i < expected; ++i) {
      int peer_rank;
      Channel channel;
      int fd = listener_.AcceptPeer(&peer_rank, &channel, timeout_ms);
      if (fd < 0) {
        accept_ok.store(false);
        return;
      }
      if (channel == Channel::RING) {
        ring_prev_ = Conn(fd);
      } else if (rank_ == 0 && peer_rank >= 1 && peer_rank < size_) {
        control_conns_[peer_rank] = Conn(fd);
      } else {
        LOG(ERROR) << "unexpected control connection from rank " << peer_rank;
        accept_ok.store(false);
        return;
      }
      ++accepted;
    }
  });

  // Outbound: ring successor, and (workers) control to rank 0.
  bool ok = true;
  {
    int next = (rank_ + 1) % size_;
    std::string host;
    int port;
    ParseHostPort(addrs[next], &host, &port);
    ring_next_ = ConnectPeer(host, port, rank_, Channel::RING, timeout_ms);
    ok = ok && ring_next_.valid();
  }
  if (ok && rank_ != 0) {
    std::string host;
    int port;
    ParseHostPort(addrs[0], &host, &port);
    control_conns_[0] =
        ConnectPeer(host, port, rank_, Channel::CONTROL, timeout_ms);
    ok = ok && control_conns_[0].valid();
  }
  acceptor.join();
  if (!ok || !accept_ok.load()) {
    LOG(ERROR) << "rendezvous failed (rank " << rank_ << ")";
    return false;
  }
  initialized_ = true;
  LOG(DEBUG) << "TcpContext initialized: rank " << rank_ << "/" << size_;
  return true;
}

void TcpContext::Finalize() {
  for (auto& c : control_conns_) c.Close();
  control_conns_.clear();
  ring_next_.Close();
  ring_prev_.Close();
  listener_.Close();
  initialized_ = false;
}

bool TcpContext::GatherBlobs(const std::string& mine,
                             std::vector<std::string>* all) {
  if (size_ == 1) {
    if (all != nullptr) {
      all->assign(1, mine);
    }
    return true;
  }
  if (rank_ == 0) {
    all->assign(size_, std::string());
    (*all)[0] = mine;
    for (int r = 1; r < size_; ++r) {
      uint32_t tag;
      if (!control_conns_[r].RecvFrame(&tag, &(*all)[r]) ||
          tag != kTagGather) {
        return false;
      }
    }
    return true;
  }
  return control_conns_[0].SendFrame(kTagGather, mine);
}

bool TcpContext::BroadcastBlob(std::string* blob) {
  if (size_ == 1) return true;
  if (rank_ == 0) {
    for (int r = 1; r < size_; ++r) {
      if (!control_conns_[r].SendFrame(kTagBcast, *blob)) return false;
    }
    return true;
  }
  uint32_t tag;
  return control_conns_[0].RecvFrame(&tag, blob) && tag == kTagBcast;
}

bool TcpContext::BitwiseSync(std::vector<uint64_t>& bits, bool is_or) {
  if (size_ == 1) return true;
  std::size_t nbytes = bits.size() * sizeof(uint64_t);
  if (rank_ == 0) {
    std::vector<uint64_t> peer(bits.size());
    for (int r = 1; r < size_; ++r) {
      uint32_t tag;
      if (!control_conns_[r].RecvFrameInto(&tag, peer.data(), nbytes) ||
          tag != kTagBits) {
        return false;
      }
      for (std::size_t i = 0; i < bits.size(); ++i) {
        bits[i] = is_or ? (bits[i] | peer[i]) : (bits[i] & peer[i]);
      }
    }
    for (int r = 1; r < size_; ++r) {
      if (!control_conns_[r].SendFrame(kTagBits, bits.data(), nbytes)) {
        return false;
      }
    }
    return true;
  }
  uint32_t tag;
  return control_conns_[0].SendFrame(kTagBits, bits.data(), nbytes) &&
         control_conns_[0].RecvFrameInto(&tag, bits.data(), nbytes) &&
         tag == kTagBits;
}

static constexpr uint32_t kTagData = 0x21;

bool TcpContext::StarSend(int peer, const void* data, std::size_t len) {
  if (rank_ == 0) {
    if (peer <= 0 || peer >= size_) return false;
    return control_conns_[peer].SendFrame(kTagData, data, len);
  }
  if (peer != 0) return false;
  return control_conns_[0].SendFrame(kTagData, data, len);
}

bool TcpContext::StarRecv(int peer, void* buf, std::size_t len) {
  uint32_t tag;
  if (rank_ == 0) {
    if (peer <= 0 || peer >= size_) return false;
    return control_conns_[peer].RecvFrameInto(&tag, buf, len) &&
           tag == kTagData;
  }
  if (peer != 0) return false;
  return control_conns_[0].RecvFrameInto(&tag, buf, len) && tag == kTagData;
}

bool TcpContext::Barrier() {
  std::vector<uint64_t> bits(1, ~0ull);
  return BitwiseSync(bits, false);
}

bool TcpContext::RingExchange(const void* send_buf, std::size_t send_len,
                              void* recv_buf, std::size_t recv_len) {
  if (size_ == 1) {
    if (recv_len > 0 && recv_buf != send_buf) {
      std::memcpy(recv_buf, send_buf, std::min(send_len, recv_len));
    }
    return true;
  }
  // Frame headers first (blocking, tiny), then pump payloads full-duplex so
  // a ring of simultaneous large sends can't deadlock on socket buffers.
  char shdr[12];
  uint64_t slen = send_len;
  std::memcpy(shdr, &kTagRing, 4);
  std::memcpy(shdr + 4, &slen, 8);
  if (!ring_next_.SendAll(shdr, 12)) return false;
  char rhdr[12];
  if (!ring_prev_.RecvAll(rhdr, 12)) return false;
  uint32_t rtag;
  uint64_t rlen;
  std::memcpy(&rtag, rhdr, 4);
  std::memcpy(&rlen, rhdr + 4, 8);
  if (rtag != kTagRing || rlen != recv_len) {
    LOG(ERROR) << "ring exchange mismatch: tag " << rtag << " len " << rlen
               << " expected " << recv_len;
    return false;
  }

  const char* sp = static_cast<const char*>(send_buf);
  char* rp = static_cast<char*>(recv_buf);
  std::size_t sent = 0, received = 0;
  while (sent < send_len || received < recv_len) {
    struct pollfd pfds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (sent < send_len) {
      pfds[n] = {ring_next_.fd(), POLLOUT, 0};
      send_idx = n++;
    }
    if (received < recv_len) {
      pfds[n] = {ring_prev_.fd(), POLLIN, 0};
      recv_idx = n++;
    }
    if (::poll(pfds, n, 60000) <= 0) {
      LOG(ERROR) << "ring exchange poll timeout/error";
      return false;
    }
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR))) {
      ssize_t w = ::send(ring_next_.fd(), sp + sent, send_len - sent,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        return false;
      }
      if (w > 0) sent += static_cast<std::size_t>(w);
    }
    if (recv_idx >= 0 && (pfds[recv_idx].revents & (POLLIN | POLLERR))) {
      ssize_t r = ::recv(ring_prev_.fd(), rp + received, recv_len - received,
                         MSG_DONTWAIT);
      if (r == 0) return false;
      if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        return false;
      }
      if (r > 0) received += static_cast<std::size_t>(r);
    }
  }
  return true;
}

}  // namespace hvdtpu
