#include "tcp_context.h"

#include <poll.h>
#include <sys/socket.h>

#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <sstream>

#include "logging.h"

namespace hvdtpu {

static int EnvInt(const char* name, int dflt) {
  const char* v = std::getenv(name);
  return v == nullptr ? dflt : std::atoi(v);
}

// Blocking control/ring poll window. 60 s is generous for any real
// deployment; a heavily oversubscribed localhost fleet (the 1024-rank
// protocol sweep runs 1024 processes on one core) can starve the
// coordinator past it mid-gather — raise via env there. Clamped so
// seconds*1000 cannot overflow int (poll(2) treats negative timeouts
// as INFINITE — a dead peer would hang forever, silently).
static int ControlPollMs() {
  static int ms = [] {
    long long s = EnvInt("HVD_TPU_CONTROL_POLL_TIMEOUT_SECONDS", 60);
    if (s <= 0) s = 60;
    if (s > 2147483) s = 2147483;
    return static_cast<int>(s * 1000);
  }();
  return ms;
}

static constexpr uint32_t kTagGather = 0x11;
static constexpr uint32_t kTagBcast = 0x12;
static constexpr uint32_t kTagBits = 0x13;
static constexpr uint32_t kTagRing = 0x20;

bool TcpContext::Initialize() {
  rank_ = EnvInt("HVD_TPU_RANK", 0);
  size_ = EnvInt("HVD_TPU_SIZE", 1);
  local_rank_ = EnvInt("HVD_TPU_LOCAL_RANK", rank_);
  local_size_ = EnvInt("HVD_TPU_LOCAL_SIZE", size_);
  cross_rank_ = EnvInt("HVD_TPU_CROSS_RANK", 0);
  cross_size_ = EnvInt("HVD_TPU_CROSS_SIZE", 1);
  SetLogRank(rank_);

  if (size_ == 1) {
    is_homogeneous_ = true;
    rank_grid_.assign(1, 0);
    initialized_ = true;
    return true;
  }

  const char* addrs_env = std::getenv("HVD_TPU_ADDRS");
  if (addrs_env == nullptr) {
    LOG(ERROR) << "HVD_TPU_ADDRS not set but size > 1";
    return false;
  }
  std::vector<std::string> addrs = SplitString(addrs_env, ',');
  if (static_cast<int>(addrs.size()) != size_) {
    LOG(ERROR) << "HVD_TPU_ADDRS has " << addrs.size() << " entries, expected "
               << size_;
    return false;
  }
  std::string my_host;
  int my_port = 0;
  if (!ParseHostPort(addrs[rank_], &my_host, &my_port)) {
    LOG(ERROR) << "bad address " << addrs[rank_];
    return false;
  }
  if (!listener_.Start(my_port)) return false;

  int timeout_ms = EnvInt("HVD_TPU_START_TIMEOUT", 60) * 1000;

  // Phase 1 inbound: the global-ring predecessor, plus (rank 0 only)
  // every worker's control connection.
  int expected = 1 + (rank_ == 0 ? size_ - 1 : 0);
  control_conns_.resize(rank_ == 0 ? size_ : 1);

  std::atomic<bool> accept_ok{true};
  std::thread acceptor([&] {
    for (int i = 0; i < expected; ++i) {
      int peer_rank;
      Channel channel;
      int fd = listener_.AcceptPeer(&peer_rank, &channel, timeout_ms);
      if (fd < 0) {
        accept_ok.store(false);
        return;
      }
      if (channel == Channel::RING) {
        ring_prev_ = Conn(fd);
      } else if (rank_ == 0 && channel == Channel::CONTROL && peer_rank >= 1 &&
                 peer_rank < size_) {
        control_conns_[peer_rank] = Conn(fd);
      } else {
        LOG(ERROR) << "unexpected connection from rank " << peer_rank;
        accept_ok.store(false);
        return;
      }
    }
  });

  // Outbound: global-ring successor, and (workers) control to rank 0.
  bool ok = true;
  {
    int next = (rank_ + 1) % size_;
    std::string host;
    int port;
    ParseHostPort(addrs[next], &host, &port);
    ring_next_ = ConnectPeer(host, port, rank_, Channel::RING, timeout_ms);
    ok = ok && ring_next_.valid();
  }
  if (ok && rank_ != 0) {
    std::string host;
    int port;
    ParseHostPort(addrs[0], &host, &port);
    control_conns_[0] =
        ConnectPeer(host, port, rank_, Channel::CONTROL, timeout_ms);
    ok = ok && control_conns_[0].valid();
  }
  acceptor.join();
  if (!ok || !accept_ok.load()) {
    LOG(ERROR) << "rendezvous failed (rank " << rank_ << ")";
    return false;
  }

  // Phase 2: learn every rank's (local_rank, cross_rank) over the star and
  // build the local/cross rings the two-level collectives ride (the role
  // MPI_Comm_split_type/split fill in the reference, mpi_context.cc:149-158).
  if (!ExchangeTopology()) return false;
  if (hierarchical_possible()) {
    if (!ConnectSubRings(timeout_ms)) {
      LOG(ERROR) << "sub-ring rendezvous failed (rank " << rank_ << ")";
      return false;
    }
  }

  initialized_ = true;
  LOG(DEBUG) << "TcpContext initialized: rank " << rank_ << "/" << size_
             << (hierarchical_possible() ? " (hierarchical)" : "");
  return true;
}

bool TcpContext::ExchangeTopology() {
  std::ostringstream mine;
  mine << local_rank_ << " " << local_size_ << " " << cross_rank_ << " "
       << cross_size_;
  std::vector<std::string> all;
  if (!GatherBlobs(mine.str(), rank_ == 0 ? &all : nullptr)) return false;

  std::string grid_blob;
  if (rank_ == 0) {
    // Validate homogeneity: every rank reports the same local/cross sizes
    // and the (local_rank, cross_rank) grid is a complete bijection.
    bool homogeneous = local_size_ * cross_size_ == size_;
    std::vector<int> grid(static_cast<std::size_t>(size_), -1);
    for (int r = 0; r < size_ && homogeneous; ++r) {
      std::istringstream in(all[r]);
      int lr, ls, cr, cs;
      if (!(in >> lr >> ls >> cr >> cs)) {
        homogeneous = false;
        break;
      }
      if (ls != local_size_ || cs != cross_size_ || lr < 0 ||
          lr >= local_size_ || cr < 0 || cr >= cross_size_) {
        homogeneous = false;
        break;
      }
      int cell = cr * local_size_ + lr;
      if (grid[cell] != -1) {
        homogeneous = false;
        break;
      }
      grid[cell] = r;
    }
    std::ostringstream out;
    out << (homogeneous ? 1 : 0);
    if (homogeneous) {
      for (int g : grid) out << " " << g;
    }
    grid_blob = out.str();
  }
  if (!BroadcastBlob(&grid_blob)) return false;

  std::istringstream in(grid_blob);
  int homogeneous = 0;
  in >> homogeneous;
  is_homogeneous_ = homogeneous != 0;
  rank_grid_.clear();
  if (is_homogeneous_) {
    rank_grid_.resize(static_cast<std::size_t>(size_));
    for (int i = 0; i < size_; ++i) in >> rank_grid_[i];
  }
  return true;
}

int TcpContext::RankAt(int local_rank, int cross_rank) const {
  if (!is_homogeneous_ || local_rank < 0 || local_rank >= local_size_ ||
      cross_rank < 0 || cross_rank >= cross_size_) {
    return -1;
  }
  return rank_grid_[static_cast<std::size_t>(cross_rank) * local_size_ +
                    local_rank];
}

bool TcpContext::ConnectSubRings(int timeout_ms) {
  const char* addrs_env = std::getenv("HVD_TPU_ADDRS");
  std::vector<std::string> addrs = SplitString(addrs_env ? addrs_env : "", ',');

  int expected = (local_size_ > 1 ? 1 : 0) + (cross_size_ > 1 ? 1 : 0);
  std::atomic<bool> accept_ok{true};
  std::thread acceptor([&] {
    for (int i = 0; i < expected; ++i) {
      int peer_rank;
      Channel channel;
      int fd = listener_.AcceptPeer(&peer_rank, &channel, timeout_ms);
      if (fd < 0) {
        accept_ok.store(false);
        return;
      }
      if (channel == Channel::LOCAL_RING && !local_prev_.valid()) {
        local_prev_ = Conn(fd);
      } else if (channel == Channel::CROSS_RING && !cross_prev_.valid()) {
        cross_prev_ = Conn(fd);
      } else {
        LOG(ERROR) << "unexpected sub-ring connection from rank " << peer_rank;
        accept_ok.store(false);
        return;
      }
    }
  });

  bool ok = true;
  if (local_size_ > 1) {
    int next = RankAt((local_rank_ + 1) % local_size_, cross_rank_);
    std::string host;
    int port;
    ok = ok && next >= 0 && ParseHostPort(addrs[next], &host, &port);
    if (ok) {
      local_next_ =
          ConnectPeer(host, port, rank_, Channel::LOCAL_RING, timeout_ms);
      ok = local_next_.valid();
    }
  }
  if (ok && cross_size_ > 1) {
    int next = RankAt(local_rank_, (cross_rank_ + 1) % cross_size_);
    std::string host;
    int port;
    ok = ok && next >= 0 && ParseHostPort(addrs[next], &host, &port);
    if (ok) {
      cross_next_ =
          ConnectPeer(host, port, rank_, Channel::CROSS_RING, timeout_ms);
      ok = cross_next_.valid();
    }
  }
  acceptor.join();
  return ok && accept_ok.load();
}

void TcpContext::Finalize() {
  for (auto& c : control_conns_) c.Close();
  control_conns_.clear();
  ring_next_.Close();
  ring_prev_.Close();
  local_next_.Close();
  local_prev_.Close();
  cross_next_.Close();
  cross_prev_.Close();
  listener_.Close();
  rank_grid_.clear();
  is_homogeneous_ = false;
  initialized_ = false;
}

// ---------------- poll-multiplexed control star (rank 0) ----------------
//
// The reference's coordinator leans on MPI_Gatherv/MPI_Bcast, which the MPI
// library parallelizes internally; a naive per-socket loop here would
// serialize the whole negotiation through rank 0 (the SURVEY §7.3
// "negotiation latency at 256 chips" wall). These helpers service every
// worker socket concurrently with one poll loop.

namespace {

struct FrameRecvState {
  char header[12];
  std::size_t hoff = 0;
  std::string payload;
  std::size_t poff = 0;
  uint32_t tag = 0;
  bool have_header = false;
  bool done = false;
};

struct FrameSendState {
  char header[12];
  std::size_t hoff = 0;
  const char* payload = nullptr;
  std::size_t len = 0;
  std::size_t poff = 0;
  bool done = false;
};

}  // namespace

bool TcpContext::MultiRecvFrames(uint32_t expect_tag,
                                 std::vector<std::string>* blobs) {
  int n = size_ - 1;  // workers 1..size_-1
  std::vector<FrameRecvState> st(static_cast<std::size_t>(n));
  int remaining = n;
  std::vector<struct pollfd> pfds;
  std::vector<int> idx;
  while (remaining > 0) {
    pfds.clear();
    idx.clear();
    for (int i = 0; i < n; ++i) {
      if (!st[i].done) {
        pfds.push_back({control_conns_[i + 1].fd(), POLLIN, 0});
        idx.push_back(i);
      }
    }
    if (::poll(pfds.data(), pfds.size(), ControlPollMs()) <= 0) {
      LOG(ERROR) << "control gather poll timeout/error";
      return false;
    }
    for (std::size_t k = 0; k < pfds.size(); ++k) {
      if (!(pfds[k].revents & (POLLIN | POLLERR | POLLHUP))) continue;
      int i = idx[k];
      auto& s = st[i];
      int fd = control_conns_[i + 1].fd();
      if (!s.have_header) {
        ssize_t r = ::recv(fd, s.header + s.hoff, sizeof(s.header) - s.hoff,
                           MSG_DONTWAIT);
        if (r == 0) return false;
        if (r < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
            continue;
          return false;
        }
        s.hoff += static_cast<std::size_t>(r);
        if (s.hoff == sizeof(s.header)) {
          uint64_t len;
          std::memcpy(&s.tag, s.header, 4);
          std::memcpy(&len, s.header + 4, 8);
          if (s.tag != expect_tag) {
            LOG(ERROR) << "control gather: unexpected tag " << s.tag;
            return false;
          }
          s.payload.resize(static_cast<std::size_t>(len));
          s.have_header = true;
          if (len == 0) {
            s.done = true;
            --remaining;
          }
        }
      }
      if (s.have_header && !s.done) {
        ssize_t r = ::recv(fd, &s.payload[s.poff], s.payload.size() - s.poff,
                           MSG_DONTWAIT);
        if (r == 0) return false;
        if (r < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
            continue;
          return false;
        }
        s.poff += static_cast<std::size_t>(r);
        if (s.poff == s.payload.size()) {
          s.done = true;
          --remaining;
        }
      }
    }
  }
  if (blobs != nullptr) {
    for (int i = 0; i < n; ++i) (*blobs)[i + 1] = std::move(st[i].payload);
  }
  return true;
}

bool TcpContext::MultiSendFrames(
    uint32_t tag,
    const std::vector<std::pair<const void*, std::size_t>>& payloads) {
  int n = size_ - 1;
  std::vector<FrameSendState> st(static_cast<std::size_t>(n));
  for (int i = 0; i < n; ++i) {
    auto& s = st[i];
    uint64_t len = payloads[i].second;
    std::memcpy(s.header, &tag, 4);
    std::memcpy(s.header + 4, &len, 8);
    s.payload = static_cast<const char*>(payloads[i].first);
    s.len = payloads[i].second;
  }
  int remaining = n;
  std::vector<struct pollfd> pfds;
  std::vector<int> idx;
  while (remaining > 0) {
    pfds.clear();
    idx.clear();
    for (int i = 0; i < n; ++i) {
      if (!st[i].done) {
        pfds.push_back({control_conns_[i + 1].fd(), POLLOUT, 0});
        idx.push_back(i);
      }
    }
    if (::poll(pfds.data(), pfds.size(), ControlPollMs()) <= 0) {
      LOG(ERROR) << "control bcast poll timeout/error";
      return false;
    }
    for (std::size_t k = 0; k < pfds.size(); ++k) {
      if (!(pfds[k].revents & (POLLOUT | POLLERR))) continue;
      int i = idx[k];
      auto& s = st[i];
      int fd = control_conns_[i + 1].fd();
      if (s.hoff < sizeof(s.header)) {
        ssize_t w = ::send(fd, s.header + s.hoff, sizeof(s.header) - s.hoff,
                           MSG_NOSIGNAL | MSG_DONTWAIT);
        if (w < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
            continue;
          return false;
        }
        s.hoff += static_cast<std::size_t>(w);
        if (s.hoff < sizeof(s.header)) continue;
      }
      if (s.poff < s.len) {
        ssize_t w = ::send(fd, s.payload + s.poff, s.len - s.poff,
                           MSG_NOSIGNAL | MSG_DONTWAIT);
        if (w < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
            continue;
          return false;
        }
        s.poff += static_cast<std::size_t>(w);
      }
      if (s.poff == s.len) {
        s.done = true;
        --remaining;
      }
    }
  }
  return true;
}

// Control frames are 12 bytes of header (4 tag + 8 length) + payload.
static constexpr uint64_t kFrameHeaderBytes = 12;

bool TcpContext::GatherBlobs(const std::string& mine,
                             std::vector<std::string>* all) {
  if (size_ == 1) {
    if (all != nullptr) {
      all->assign(1, mine);
    }
    return true;
  }
  if (rank_ == 0) {
    all->assign(size_, std::string());
    (*all)[0] = mine;
    if (!MultiRecvFrames(kTagGather, all)) return false;
    uint64_t recvd = 0;
    for (int r = 1; r < size_; ++r) recvd += (*all)[r].size();
    ctrl_bytes_recv_ += recvd + kFrameHeaderBytes * (size_ - 1);
    ctrl_msgs_ += size_ - 1;
    return true;
  }
  if (!control_conns_[0].SendFrame(kTagGather, mine)) return false;
  ctrl_bytes_sent_ += mine.size() + kFrameHeaderBytes;
  ctrl_msgs_ += 1;
  return true;
}

bool TcpContext::BroadcastBlob(std::string* blob) {
  if (size_ == 1) return true;
  if (rank_ == 0) {
    std::vector<std::pair<const void*, std::size_t>> payloads(
        static_cast<std::size_t>(size_ - 1),
        {blob->data(), blob->size()});
    if (!MultiSendFrames(kTagBcast, payloads)) return false;
    ctrl_bytes_sent_ +=
        (blob->size() + kFrameHeaderBytes) * uint64_t(size_ - 1);
    ctrl_msgs_ += size_ - 1;
    return true;
  }
  uint32_t tag;
  if (!(control_conns_[0].RecvFrame(&tag, blob) && tag == kTagBcast)) {
    return false;
  }
  ctrl_bytes_recv_ += blob->size() + kFrameHeaderBytes;
  ctrl_msgs_ += 1;
  return true;
}

bool TcpContext::BitwiseSync(std::vector<uint64_t>& bits, bool is_or) {
  if (size_ == 1) return true;
  std::size_t nbytes = bits.size() * sizeof(uint64_t);
  if (rank_ == 0) {
    std::vector<std::string> blobs(static_cast<std::size_t>(size_));
    if (!MultiRecvFrames(kTagBits, &blobs)) return false;
    for (int r = 1; r < size_; ++r) {
      if (blobs[r].size() != nbytes) {
        LOG(ERROR) << "bit sync size mismatch from rank " << r;
        return false;
      }
      const uint64_t* peer =
          reinterpret_cast<const uint64_t*>(blobs[r].data());
      for (std::size_t i = 0; i < bits.size(); ++i) {
        bits[i] = is_or ? (bits[i] | peer[i]) : (bits[i] & peer[i]);
      }
    }
    std::vector<std::pair<const void*, std::size_t>> payloads(
        static_cast<std::size_t>(size_ - 1), {bits.data(), nbytes});
    if (!MultiSendFrames(kTagBits, payloads)) return false;
    ctrl_bytes_recv_ += (nbytes + kFrameHeaderBytes) * uint64_t(size_ - 1);
    ctrl_bytes_sent_ += (nbytes + kFrameHeaderBytes) * uint64_t(size_ - 1);
    ctrl_msgs_ += 2 * uint64_t(size_ - 1);
    return true;
  }
  uint32_t tag;
  if (!(control_conns_[0].SendFrame(kTagBits, bits.data(), nbytes) &&
        control_conns_[0].RecvFrameInto(&tag, bits.data(), nbytes) &&
        tag == kTagBits)) {
    return false;
  }
  ctrl_bytes_sent_ += nbytes + kFrameHeaderBytes;
  ctrl_bytes_recv_ += nbytes + kFrameHeaderBytes;
  ctrl_msgs_ += 2;
  return true;
}

bool TcpContext::Barrier() {
  std::vector<uint64_t> bits(1, ~0ull);
  return BitwiseSync(bits, false);
}

// ---------------- data rings ----------------

int TcpContext::RingRank(Ring ring) const {
  switch (ring) {
    case Ring::GLOBAL:
      return rank_;
    case Ring::LOCAL:
      return local_rank_;
    case Ring::CROSS:
      return cross_rank_;
  }
  return rank_;
}

int TcpContext::RingSize(Ring ring) const {
  switch (ring) {
    case Ring::GLOBAL:
      return size_;
    case Ring::LOCAL:
      return local_size_;
    case Ring::CROSS:
      return cross_size_;
  }
  return size_;
}

bool TcpContext::RingExchangeOn(Ring ring, const void* send_buf,
                                std::size_t send_len, void* recv_buf,
                                std::size_t recv_len) {
  Conn* next = &ring_next_;
  Conn* prev = &ring_prev_;
  if (ring == Ring::LOCAL) {
    next = &local_next_;
    prev = &local_prev_;
  } else if (ring == Ring::CROSS) {
    next = &cross_next_;
    prev = &cross_prev_;
  }
  if (RingSize(ring) == 1) {
    if (recv_len > 0 && recv_buf != send_buf) {
      std::memcpy(recv_buf, send_buf, std::min(send_len, recv_len));
    }
    return true;
  }
  if (!next->valid() || !prev->valid()) {
    LOG(ERROR) << "ring exchange on unconnected ring";
    return false;
  }
  // Frame headers first (blocking, tiny), then pump payloads full-duplex so
  // a ring of simultaneous large sends can't deadlock on socket buffers.
  char shdr[12];
  uint64_t slen = send_len;
  std::memcpy(shdr, &kTagRing, 4);
  std::memcpy(shdr + 4, &slen, 8);
  if (!next->SendAll(shdr, 12)) return false;
  char rhdr[12];
  if (!prev->RecvAll(rhdr, 12)) return false;
  uint32_t rtag;
  uint64_t rlen;
  std::memcpy(&rtag, rhdr, 4);
  std::memcpy(&rlen, rhdr + 4, 8);
  if (rtag != kTagRing || rlen != recv_len) {
    LOG(ERROR) << "ring exchange mismatch: tag " << rtag << " len " << rlen
               << " expected " << recv_len;
    return false;
  }

  const char* sp = static_cast<const char*>(send_buf);
  char* rp = static_cast<char*>(recv_buf);
  std::size_t sent = 0, received = 0;
  while (sent < send_len || received < recv_len) {
    struct pollfd pfds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (sent < send_len) {
      pfds[n] = {next->fd(), POLLOUT, 0};
      send_idx = n++;
    }
    if (received < recv_len) {
      pfds[n] = {prev->fd(), POLLIN, 0};
      recv_idx = n++;
    }
    if (::poll(pfds, n, ControlPollMs()) <= 0) {
      LOG(ERROR) << "ring exchange poll timeout/error";
      return false;
    }
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR))) {
      ssize_t w = ::send(next->fd(), sp + sent, send_len - sent,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        return false;
      }
      if (w > 0) sent += static_cast<std::size_t>(w);
    }
    if (recv_idx >= 0 && (pfds[recv_idx].revents & (POLLIN | POLLERR))) {
      ssize_t r = ::recv(prev->fd(), rp + received, recv_len - received,
                         MSG_DONTWAIT);
      if (r == 0) return false;
      if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        return false;
      }
      if (r > 0) received += static_cast<std::size_t>(r);
    }
  }
  return true;
}

bool TcpContext::RingBroadcast(void* buf, std::size_t len, int root) {
  if (size_ == 1 || len == 0) return true;
  int next = (rank_ + 1) % size_;
  char* p = static_cast<char*>(buf);
  if (rank_ == root) {
    // Root only streams downstream (size_ > 1 so next != root).
    return ring_next_.SendAll(p, len);
  }
  // Non-root: stream from the predecessor, forwarding bytes as they arrive
  // (cut-through, not store-and-forward — total time ~ len/BW + hop latency).
  bool forward = next != root;
  std::size_t received = 0, sent = 0;
  while (received < len || (forward && sent < len)) {
    struct pollfd pfds[2];
    int n = 0;
    int recv_idx = -1, send_idx = -1;
    if (received < len) {
      pfds[n] = {ring_prev_.fd(), POLLIN, 0};
      recv_idx = n++;
    }
    if (forward && sent < received) {
      pfds[n] = {ring_next_.fd(), POLLOUT, 0};
      send_idx = n++;
    }
    if (n == 0) break;
    if (::poll(pfds, n, ControlPollMs()) <= 0) {
      LOG(ERROR) << "ring broadcast poll timeout/error";
      return false;
    }
    if (recv_idx >= 0 && (pfds[recv_idx].revents & (POLLIN | POLLERR))) {
      ssize_t r = ::recv(ring_prev_.fd(), p + received, len - received,
                         MSG_DONTWAIT);
      if (r == 0) return false;
      if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        return false;
      }
      if (r > 0) received += static_cast<std::size_t>(r);
    }
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR))) {
      ssize_t w = ::send(ring_next_.fd(), p + sent, received - sent,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        return false;
      }
      if (w > 0) sent += static_cast<std::size_t>(w);
    }
  }
  return true;
}

}  // namespace hvdtpu
