#include "tcp_context.h"

#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <thread>

#include "checksum.h"
#include "fault.h"
#include "logging.h"
#include "metrics.h"
#include "trace.h"

namespace hvdtpu {

static int EnvInt(const char* name, int dflt) {
  const char* v = std::getenv(name);
  return v == nullptr ? dflt : std::atoi(v);
}

// Blocking control/ring poll window. 60 s is generous for any real
// deployment; a heavily oversubscribed localhost fleet (the 1024-rank
// protocol sweep runs 1024 processes on one core) can starve the
// coordinator past it mid-gather — raise via env there. Clamped so
// seconds*1000 cannot overflow int (poll(2) treats negative timeouts
// as INFINITE — a dead peer would hang forever, silently).
static int ControlPollMs() {
  static int ms = [] {
    long long s = EnvInt("HVD_TPU_CONTROL_POLL_TIMEOUT_SECONDS", 60);
    if (s <= 0) s = 60;
    if (s > 2147483) s = 2147483;
    return static_cast<int>(s * 1000);
  }();
  return ms;
}

// How long a broken control connection may take to come back before the
// other side declares it lost: the worker retries with capped
// exponential backoff inside this budget; the coordinator holds the
// dead peer's slot open for it. 0 disables reconnect entirely (a
// control failure then fails over immediately, the pre-chaos behavior).
// Elastic jobs default much shorter: their supervisor rebuilds
// membership on failure anyway, and a long hold only delays the
// shrink rendezvous past the driver's blacklist cooldown.
static int ReconnectWindowMs() {
  static int ms = [] {
    const char* elastic = std::getenv("HVD_TPU_ELASTIC");
    double s = (elastic != nullptr && elastic[0] == '1') ? 1.0 : 5.0;
    const char* v = std::getenv("HVD_TPU_RECONNECT_SECONDS");
    if (v != nullptr) s = std::atof(v);
    if (s < 0) s = 0;
    if (s > 2147483) s = 2147483;
    return static_cast<int>(s * 1000);
  }();
  return ms;
}

static const char* ChannelName(Channel c) {
  switch (c) {
    case Channel::CONTROL: return "control";
    case Channel::RING: return "ring";
    case Channel::LOCAL_RING: return "local-ring";
    case Channel::CROSS_RING: return "cross-ring";
    case Channel::SHM: return "shm";
  }
  return "?";
}

void TcpContext::SetLastError(Channel chan, NetError err) {
  last_error_ = std::string(NetErrorName(err)) + " on " +
                ChannelName(chan) + " channel";
}

static constexpr uint32_t kTagGather = 0x11;
static constexpr uint32_t kTagBcast = 0x12;
static constexpr uint32_t kTagBits = 0x13;
static constexpr uint32_t kTagRing = 0x20;
// One-time shm negotiation frames (docs/TRANSPORT.md), exchanged right
// after rendezvous on each data conn whose connector advertised
// kHandshakeShmCap.
static constexpr uint32_t kTagShmSetup = 0x30;
static constexpr uint32_t kTagShmAck = 0x31;

// Raw framed I/O for the negotiation frames: deliberately bypasses the
// fault injector and the wire byte counters — negotiation is init-time
// plumbing, and consulting the injector here would shift every
// deterministic chaos frame index by one per negotiated conn.
static bool SendRawFrame(Conn* c, uint32_t tag, const std::string& payload) {
  char hdr[kFrameHeaderBytes];
  BuildFrameHeader(hdr, tag, payload.size(),
                   FrameCrc(tag, payload.size(), payload.data(),
                            payload.size()));
  return c->SendAll(hdr, sizeof(hdr)) &&
         (payload.empty() || c->SendAll(payload.data(), payload.size()));
}

static bool RecvRawFrame(Conn* c, uint32_t expect_tag, std::string* payload) {
  char hdr[kFrameHeaderBytes];
  if (!c->RecvAll(hdr, sizeof(hdr))) return false;
  uint32_t tag, crc;
  uint64_t len;
  ParseFrameHeader(hdr, &tag, &len, &crc);
  if (tag != expect_tag || len > 65536) {
    LOG(ERROR) << "shm negotiation: unexpected frame (tag " << tag
               << ", len " << len << ")";
    return false;
  }
  payload->resize(static_cast<std::size_t>(len));
  if (len > 0 && !c->RecvAll(&(*payload)[0], payload->size())) return false;
  if (NetCrcEnabled() &&
      FrameCrc(tag, len, payload->data(), payload->size()) != crc) {
    LOG(ERROR) << "shm negotiation: frame checksum mismatch";
    return false;
  }
  return true;
}


bool TcpContext::Initialize() {
  rank_ = EnvInt("HVD_TPU_RANK", 0);
  size_ = EnvInt("HVD_TPU_SIZE", 1);
  local_rank_ = EnvInt("HVD_TPU_LOCAL_RANK", rank_);
  local_size_ = EnvInt("HVD_TPU_LOCAL_SIZE", size_);
  cross_rank_ = EnvInt("HVD_TPU_CROSS_RANK", 0);
  cross_size_ = EnvInt("HVD_TPU_CROSS_SIZE", 1);
  generation_ = static_cast<uint32_t>(EnvInt("HVD_TPU_GENERATION", 0));
  SetLogRank(rank_);
  last_error_.clear();

  // Chaos hooks (fault.h): parsed per init so an elastic re-init replays
  // the spec from frame 0 of the new generation.
  GlobalFaultInjector().Configure(std::getenv("HVD_TPU_FAULT_SPEC"), rank_);

  // Emulated data-ring bandwidth (docs/AUTOTUNE.md "Bench"): pace ring
  // TX to N MB/s so single-host runs reproduce a real inter-host link's
  // serialization delay. 0/unset = full loopback speed.
  {
    double mbps = 0.0;
    const char* v = std::getenv("HVD_TPU_RING_BANDWIDTH_MBPS");
    if (v != nullptr) mbps = std::atof(v);
    ring_tx_bytes_per_us_ = mbps > 0.0 ? mbps : 0.0;  // 1 MB/s == 1 B/us
    ring_tx_ready_us_ = 0.0;
  }

  my_ctrl_opseq_ = 0;
  ctrl_opseq_.assign(static_cast<std::size_t>(size_ > 0 ? size_ : 1), 0);

  if (size_ == 1) {
    is_homogeneous_ = true;
    rank_grid_.assign(1, 0);
    shm_topology_possible_ = false;
    initialized_ = true;
    return true;
  }

  const char* addrs_env = std::getenv("HVD_TPU_ADDRS");
  if (addrs_env == nullptr) {
    LOG(ERROR) << "HVD_TPU_ADDRS not set but size > 1";
    return false;
  }
  std::vector<std::string> addrs = SplitString(addrs_env, ',');
  if (static_cast<int>(addrs.size()) != size_) {
    LOG(ERROR) << "HVD_TPU_ADDRS has " << addrs.size() << " entries, expected "
               << size_;
    return false;
  }
  std::string my_host;
  int my_port = 0;
  if (!ParseHostPort(addrs[rank_], &my_host, &my_port)) {
    LOG(ERROR) << "bad address " << addrs[rank_];
    return false;
  }
  // Per-rank address hosts, kept for the shm same-host checks. The
  // topology-possible bit is computed from the FULL list (identical on
  // every rank — the autotuner's capability-profile seed must agree
  // everywhere): any address host with two or more ranks means at
  // least one pair can ride shared memory.
  addr_hosts_.assign(static_cast<std::size_t>(size_), std::string());
  shm_topology_possible_ = false;
  for (int r = 0; r < size_; ++r) {
    std::string h;
    int p = 0;
    if (ParseHostPort(addrs[r], &h, &p)) addr_hosts_[r] = h;
  }
  if (ShmEnabled()) {
    for (int r = 0; r < size_ && !shm_topology_possible_; ++r) {
      for (int q = r + 1; q < size_; ++q) {
        if (!addr_hosts_[r].empty() && addr_hosts_[r] == addr_hosts_[q]) {
          shm_topology_possible_ = true;
          break;
        }
      }
    }
  }
  shm_use_ = true;
  if (!ParseHostPort(addrs[0], &coord_host_, &coord_port_)) {
    LOG(ERROR) << "bad coordinator address " << addrs[0];
    return false;
  }
  if (!listener_.Start(my_port)) return false;

  int timeout_ms = EnvInt("HVD_TPU_START_TIMEOUT", 60) * 1000;

  // Phase 1 inbound: the global-ring predecessor, plus (rank 0 only)
  // every worker's control connection.
  int expected = 1 + (rank_ == 0 ? size_ - 1 : 0);
  control_conns_.resize(rank_ == 0 ? size_ : 1);

  std::atomic<bool> accept_ok{true};
  std::thread acceptor([&] {
    for (int i = 0; i < expected; ++i) {
      PeerHandshake hs;
      int fd = listener_.AcceptPeer(&hs, timeout_ms, generation_);
      if (fd < 0) {
        accept_ok.store(false);
        return;
      }
      if (hs.channel == Channel::RING && !(hs.flags & kHandshakeReconnect)) {
        ring_prev_ = Conn(fd, Channel::RING);
        ring_prev_flags_ = hs.flags;
      } else if (rank_ == 0 && hs.channel == Channel::CONTROL &&
                 !(hs.flags & kHandshakeReconnect) && hs.rank >= 1 &&
                 hs.rank < size_) {
        control_conns_[hs.rank] = Conn(fd, Channel::CONTROL);
      } else {
        LOG(ERROR) << "unexpected connection from rank " << hs.rank;
        ::close(fd);
        accept_ok.store(false);
        return;
      }
    }
  });

  // Outbound: global-ring successor, and (workers) control to rank 0.
  bool ok = true;
  {
    int next = (rank_ + 1) % size_;
    std::string host;
    int port;
    ParseHostPort(addrs[next], &host, &port);
    ring_next_ = ConnectPeer(host, port, rank_, Channel::RING, timeout_ms,
                             generation_, /*opseq=*/0, /*reconnect=*/false,
                             /*group_ring=*/false, /*shm_cap=*/ShmEnabled());
    ok = ok && ring_next_.valid();
  }
  if (ok && rank_ != 0) {
    control_conns_[0] = ConnectPeer(coord_host_, coord_port_, rank_,
                                    Channel::CONTROL, timeout_ms,
                                    generation_);
    ok = ok && control_conns_[0].valid();
  }
  acceptor.join();
  if (!ok || !accept_ok.load()) {
    LOG(ERROR) << "rendezvous failed (rank " << rank_ << ")";
    return false;
  }

  // Phase 2: learn every rank's (local_rank, cross_rank) over the star and
  // build the local/cross rings the two-level collectives ride (the role
  // MPI_Comm_split_type/split fill in the reference, mpi_context.cc:149-158).
  if (!ExchangeTopology()) return false;
  if (hierarchical_possible()) {
    if (!ConnectSubRings(timeout_ms)) {
      LOG(ERROR) << "sub-ring rendezvous failed (rank " << rank_ << ")";
      return false;
    }
  }

  // Shared-memory negotiation over the freshly built data conns
  // (docs/TRANSPORT.md). Runs AFTER the topology exchange so the
  // same-host keys can honor a forced (local, cross) grid; soft
  // failures transparently leave pairs on TCP.
  if (!NegotiateShmInit()) {
    LOG(ERROR) << "shm negotiation protocol failed (rank " << rank_ << ")";
    return false;
  }

  initialized_ = true;
  LOG(DEBUG) << "TcpContext initialized: rank " << rank_ << "/" << size_
             << " generation " << generation_
             << (hierarchical_possible() ? " (hierarchical)" : "");
  return true;
}

bool TcpContext::ExchangeTopology() {
  std::ostringstream mine;
  mine << local_rank_ << " " << local_size_ << " " << cross_rank_ << " "
       << cross_size_;
  std::vector<std::string> all;
  if (!GatherBlobs(mine.str(), rank_ == 0 ? &all : nullptr)) return false;

  std::string grid_blob;
  if (rank_ == 0) {
    // Validate homogeneity: every rank reports the same local/cross sizes
    // and the (local_rank, cross_rank) grid is a complete bijection.
    bool homogeneous = local_size_ * cross_size_ == size_;
    std::vector<int> grid(static_cast<std::size_t>(size_), -1);
    for (int r = 0; r < size_ && homogeneous; ++r) {
      std::istringstream in(all[r]);
      int lr, ls, cr, cs;
      if (!(in >> lr >> ls >> cr >> cs)) {
        homogeneous = false;
        break;
      }
      if (ls != local_size_ || cs != cross_size_ || lr < 0 ||
          lr >= local_size_ || cr < 0 || cr >= cross_size_) {
        homogeneous = false;
        break;
      }
      int cell = cr * local_size_ + lr;
      if (grid[cell] != -1) {
        homogeneous = false;
        break;
      }
      grid[cell] = r;
    }
    std::ostringstream out;
    out << (homogeneous ? 1 : 0);
    if (homogeneous) {
      for (int g : grid) out << " " << g;
    }
    grid_blob = out.str();
  }
  if (!BroadcastBlob(&grid_blob)) return false;

  std::istringstream in(grid_blob);
  int homogeneous = 0;
  in >> homogeneous;
  is_homogeneous_ = homogeneous != 0;
  rank_grid_.clear();
  rank_cross_.clear();
  if (is_homogeneous_) {
    rank_grid_.resize(static_cast<std::size_t>(size_));
    for (int i = 0; i < size_; ++i) in >> rank_grid_[i];
    // Reverse lookup for the shm host keys and the group grids: which
    // host (cross index) each rank lives on.
    rank_cross_.assign(static_cast<std::size_t>(size_), 0);
    for (int i = 0; i < size_; ++i) {
      int r = rank_grid_[static_cast<std::size_t>(i)];
      if (r >= 0 && r < size_) {
        rank_cross_[static_cast<std::size_t>(r)] = i / local_size_;
      }
    }
  }
  return true;
}

int TcpContext::RankAt(int local_rank, int cross_rank) const {
  if (!is_homogeneous_ || local_rank < 0 || local_rank >= local_size_ ||
      cross_rank < 0 || cross_rank >= cross_size_) {
    return -1;
  }
  return rank_grid_[static_cast<std::size_t>(cross_rank) * local_size_ +
                    local_rank];
}

bool TcpContext::ConnectSubRings(int timeout_ms) {
  const char* addrs_env = std::getenv("HVD_TPU_ADDRS");
  std::vector<std::string> addrs = SplitString(addrs_env ? addrs_env : "", ',');

  int expected = (local_size_ > 1 ? 1 : 0) + (cross_size_ > 1 ? 1 : 0);
  std::atomic<bool> accept_ok{true};
  std::thread acceptor([&] {
    for (int i = 0; i < expected; ++i) {
      PeerHandshake hs;
      int fd = listener_.AcceptPeer(&hs, timeout_ms, generation_);
      if (fd < 0) {
        accept_ok.store(false);
        return;
      }
      if (hs.channel == Channel::LOCAL_RING && !local_prev_.valid()) {
        local_prev_ = Conn(fd, Channel::LOCAL_RING);
        local_prev_flags_ = hs.flags;
      } else if (hs.channel == Channel::CROSS_RING && !cross_prev_.valid()) {
        cross_prev_ = Conn(fd, Channel::CROSS_RING);
        cross_prev_flags_ = hs.flags;
      } else {
        LOG(ERROR) << "unexpected sub-ring connection from rank " << hs.rank;
        ::close(fd);
        accept_ok.store(false);
        return;
      }
    }
  });

  bool ok = true;
  if (local_size_ > 1) {
    int next = RankAt((local_rank_ + 1) % local_size_, cross_rank_);
    std::string host;
    int port;
    ok = ok && next >= 0 && ParseHostPort(addrs[next], &host, &port);
    if (ok) {
      local_next_ = ConnectPeer(host, port, rank_, Channel::LOCAL_RING,
                                timeout_ms, generation_, /*opseq=*/0,
                                /*reconnect=*/false, /*group_ring=*/false,
                                /*shm_cap=*/ShmEnabled());
      ok = local_next_.valid();
    }
  }
  if (ok && cross_size_ > 1) {
    int next = RankAt(local_rank_, (cross_rank_ + 1) % cross_size_);
    std::string host;
    int port;
    ok = ok && next >= 0 && ParseHostPort(addrs[next], &host, &port);
    if (ok) {
      cross_next_ = ConnectPeer(host, port, rank_, Channel::CROSS_RING,
                                timeout_ms, generation_, /*opseq=*/0,
                                /*reconnect=*/false, /*group_ring=*/false,
                                /*shm_cap=*/ShmEnabled());
      ok = cross_next_.valid();
    }
  }
  acceptor.join();
  return ok && accept_ok.load();
}

void TcpContext::Finalize() {
  for (auto& c : control_conns_) c.Close();
  control_conns_.clear();
  ctrl_opseq_.clear();
  my_ctrl_opseq_ = 0;
  ring_next_.Close();
  ring_prev_.Close();
  local_next_.Close();
  local_prev_.Close();
  cross_next_.Close();
  cross_prev_.Close();
  for (auto& kv : group_rings_) {
    kv.second.next.Close();
    kv.second.prev.Close();
  }
  group_rings_.clear();
  for (auto& kv : group_subrings_) {
    kv.second.lnext.Close();
    kv.second.lprev.Close();
    kv.second.cnext.Close();
    kv.second.cprev.Close();
  }
  group_subrings_.clear();
  for (auto& kv : pending_group_fds_) ::close(kv.second.fd);
  pending_group_fds_.clear();
  listener_.Close();
  rank_grid_.clear();
  rank_cross_.clear();
  addr_hosts_.clear();
  ring_prev_flags_ = local_prev_flags_ = cross_prev_flags_ = 0;
  // Crash hygiene: any creator-side segment name that never reached
  // MarkExchanged (peer died mid-negotiation) is unlinked here.
  GlobalShmSegments().SweepNames();
  is_homogeneous_ = false;
  initialized_ = false;
}

// ---------------- shared-memory negotiation (docs/TRANSPORT.md) ------------

std::string TcpContext::DefaultHostKey(int rank) const {
  std::string host =
      rank >= 0 && rank < static_cast<int>(addr_hosts_.size())
          ? addr_hosts_[static_cast<std::size_t>(rank)]
          : std::string();
  int cr = 0, cs = 1;
  if (is_homogeneous_ && cross_size_ > 1 &&
      rank < static_cast<int>(rank_cross_.size())) {
    cr = rank_cross_[static_cast<std::size_t>(rank)];
    cs = cross_size_;
  }
  return ShmHostKey(host, cr, cs);
}

std::string TcpContext::MyHostKey() const {
  const char* e = std::getenv("HVD_TPU_HOST_KEY");
  if (e != nullptr && e[0] != '\0') return e;
  return DefaultHostKey(rank_);
}

bool TcpContext::ShmSetupSend(Conn* conn, int peer_rank, Channel chan,
                              std::vector<ShmPending>* pending) {
  if (!conn->valid()) return true;
  // Attempt only for a provably same-host peer (both keys computed the
  // symmetric, env-free way); the acceptor's comparison of the ACTUAL
  // keys in the setup frame is the authoritative check — a distinct
  // HVD_TPU_HOST_KEY on either side nacks the attach.
  std::unique_ptr<ShmRing> ring;
  std::string name;
  if (DefaultHostKey(rank_) == DefaultHostKey(peer_rank)) {
    name = ShmSegmentName(rank_, peer_rank, static_cast<int>(chan),
                          generation_);
    ring = ShmRing::Create(name, ShmSegmentBytes());
    if (ring == nullptr) name.clear();  // no /dev/shm etc. -> TCP
  }
  std::string payload = MyHostKey() + "\n" + name;
  if (!SendRawFrame(conn, kTagShmSetup, payload)) {
    SetLastError(chan, conn->last_error());
    return false;
  }
  pending->push_back(ShmPending{conn, std::move(ring)});
  return true;
}

bool TcpContext::ShmSetupRecv(Conn* conn, uint8_t peer_flags) {
  if (!conn->valid() || !(peer_flags & kHandshakeShmCap)) return true;
  std::string payload;
  if (!RecvRawFrame(conn, kTagShmSetup, &payload)) {
    SetLastError(conn->channel(), conn->last_error());
    return false;
  }
  std::string peer_key, name;
  auto nl = payload.find('\n');
  if (nl != std::string::npos) {
    peer_key = payload.substr(0, nl);
    name = payload.substr(nl + 1);
  }
  char status = 0;
  if (ShmEnabled() && !name.empty() && peer_key == MyHostKey()) {
    auto ring = ShmRing::Attach(name);
    if (ring != nullptr) {
      conn->AttachShm(ring.release());
      status = 1;
    }
  } else if (!name.empty()) {
    LOG(DEBUG) << "shm setup refused (host key / capability mismatch): "
               << "pair stays on TCP";
  }
  if (!SendRawFrame(conn, kTagShmAck, std::string(1, status))) {
    SetLastError(conn->channel(), conn->last_error());
    return false;
  }
  return true;
}

bool TcpContext::ShmAckRecv(ShmPending* p) {
  std::string payload;
  if (!RecvRawFrame(p->conn, kTagShmAck, &payload)) {
    SetLastError(p->conn->channel(), p->conn->last_error());
    return false;
  }
  bool accepted = payload.size() == 1 && payload[0] == 1;
  if (p->ring != nullptr) {
    if (accepted) {
      // Peer has mapped the segment: unlink the name now so the kernel
      // reclaims it with the last mapping even on a crash.
      p->ring->MarkExchanged();
      p->conn->AttachShm(p->ring.release());
      LOG(DEBUG) << "shm segment attached ("
                 << ChannelName(p->conn->channel()) << " sender side)";
    } else {
      p->ring.reset();  // Close + unlink: transparent TCP fallback
    }
  }
  return true;
}

bool TcpContext::NegotiateShmInit() {
  if (size_ == 1) return true;
  std::vector<ShmPending> pending;
  // Phase 1: every outbound data conn gets its setup frame (tiny; fits
  // any socket buffer, so sending all before reading anything cannot
  // deadlock).
  if (ShmEnabled()) {
    if (!ShmSetupSend(&ring_next_, (rank_ + 1) % size_, Channel::RING,
                      &pending)) {
      return false;
    }
    if (local_next_.valid() &&
        !ShmSetupSend(&local_next_,
                      RankAt((local_rank_ + 1) % local_size_, cross_rank_),
                      Channel::LOCAL_RING, &pending)) {
      return false;
    }
    if (cross_next_.valid() &&
        !ShmSetupSend(&cross_next_,
                      RankAt(local_rank_, (cross_rank_ + 1) % cross_size_),
                      Channel::CROSS_RING, &pending)) {
      return false;
    }
  }
  // Phase 2: serve the inbound side (the flagged connectors' setups are
  // already in flight).
  if (!ShmSetupRecv(&ring_prev_, ring_prev_flags_)) return false;
  if (local_prev_.valid() && !ShmSetupRecv(&local_prev_, local_prev_flags_)) {
    return false;
  }
  if (cross_prev_.valid() && !ShmSetupRecv(&cross_prev_, cross_prev_flags_)) {
    return false;
  }
  // Phase 3: collect the verdicts.
  for (auto& p : pending) {
    if (!ShmAckRecv(&p)) return false;
  }
  return true;
}

bool TcpContext::NegotiateShmPair(Conn* next, int next_rank, Conn* prev,
                                  uint8_t prev_flags, Channel chan) {
  std::vector<ShmPending> pending;
  if (ShmEnabled() && next->valid() &&
      !ShmSetupSend(next, next_rank, chan, &pending)) {
    return false;
  }
  if (prev->valid() && !ShmSetupRecv(prev, prev_flags)) return false;
  for (auto& p : pending) {
    if (!ShmAckRecv(&p)) return false;
  }
  return true;
}

// ---------------- process-group rings (docs/GROUPS.md) ----------------

// Stash key for an accepted group connect: (channel, group, peer rank).
// The channel matters since PR 15: a group's flat-ring connect and its
// local/cross sub-ring connects can come from the SAME peer.
static uint64_t GroupFdKey(uint32_t gid, Channel chan, int rank) {
  return (static_cast<uint64_t>(chan) << 60) |
         (static_cast<uint64_t>(gid) << 24) |
         static_cast<uint64_t>(rank & 0xFFFFFF);
}

int TcpContext::GroupRank(uint32_t group_id) const {
  auto it = group_rings_.find(group_id);
  return it == group_rings_.end() ? -1 : it->second.pos;
}

int TcpContext::GroupSize(uint32_t group_id) const {
  auto it = group_rings_.find(group_id);
  return it == group_rings_.end() ? 0 : it->second.size;
}

bool TcpContext::GroupPairConnect(uint32_t group_id, Channel chan,
                                  int next_rank, int prev_rank, Conn* next,
                                  Conn* prev, uint8_t* prev_flags) {
  const char* addrs_env = std::getenv("HVD_TPU_ADDRS");
  std::vector<std::string> addrs =
      SplitString(addrs_env ? addrs_env : "", ',');
  std::string host;
  int port = 0;
  if (next_rank >= static_cast<int>(addrs.size()) ||
      !ParseHostPort(addrs[next_rank], &host, &port)) {
    LOG(ERROR) << "group " << group_id << ": no address for member rank "
               << next_rank;
    return false;
  }
  int timeout_ms = EnvInt("HVD_TPU_START_TIMEOUT", 60) * 1000;
  // Connect to the ring successor FIRST: the peer's listener backlog
  // completes the TCP connect even before it accepts, so every member
  // running connect-then-accept in the same order cannot deadlock.
  // The handshake carries the group id in the opseq field; the channel
  // distinguishes the flat ring from the local/cross sub-rings.
  *next = ConnectPeer(host, port, rank_, chan, timeout_ms, generation_,
                      /*opseq=*/group_id, /*reconnect=*/false,
                      /*group_ring=*/true, /*shm_cap=*/ShmEnabled());
  if (!next->valid()) {
    LOG(ERROR) << "group " << group_id << ": connect to member rank "
               << next_rank << " on " << ChannelName(chan) << " failed";
    return false;
  }
  // Accept from the ring predecessor. Group-ring connects for OTHER
  // (group, channel) pairs may arrive first (a member of a later
  // response's group racing ahead of this op); stash them for their own
  // build instead of dropping them.
  auto stashed = pending_group_fds_.find(GroupFdKey(group_id, chan,
                                                    prev_rank));
  if (stashed != pending_group_fds_.end()) {
    *prev = Conn(stashed->second.fd, chan);
    *prev_flags = stashed->second.flags;
    pending_group_fds_.erase(stashed);
    return true;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (!prev->valid()) {
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    if (left <= 0) {
      LOG(ERROR) << "group " << group_id
                 << ": timed out waiting for member rank " << prev_rank;
      return false;
    }
    PeerHandshake hs;
    int fd = listener_.AcceptPeer(&hs, static_cast<int>(left), generation_);
    if (fd < 0) {
      LOG(ERROR) << "group " << group_id
                 << ": accept failed waiting for member rank " << prev_rank;
      return false;
    }
    if (!(hs.flags & kHandshakeGroupRing)) {
      // Not a group-ring connect (e.g. a control reconnect racing a
      // group build). Dropping it is safe: reconnects retry with
      // backoff until their window expires.
      LOG(WARNING) << "unexpected non-group connection from rank "
                   << hs.rank << " during group ring build; dropping";
      ::close(fd);
      continue;
    }
    uint32_t peer_gid = static_cast<uint32_t>(hs.opseq);
    if (peer_gid == group_id && hs.channel == chan && hs.rank == prev_rank) {
      *prev = Conn(fd, chan);
      *prev_flags = hs.flags;
    } else {
      auto key = GroupFdKey(peer_gid, hs.channel, hs.rank);
      auto old = pending_group_fds_.find(key);
      if (old != pending_group_fds_.end()) {
        ::close(old->second.fd);
        old->second = PendingGroupFd{fd, hs.flags};
      } else {
        pending_group_fds_.emplace(key, PendingGroupFd{fd, hs.flags});
      }
    }
  }
  return true;
}

bool TcpContext::EnsureGroupRing(uint32_t group_id,
                                 const std::vector<int>& members) {
  if (group_rings_.count(group_id)) return true;
  int k = static_cast<int>(members.size());
  int pos = -1;
  for (int i = 0; i < k; ++i) {
    if (members[i] == rank_) pos = i;
  }
  if (pos < 0) {
    LOG(ERROR) << "rank " << rank_ << " is not a member of group "
               << group_id << "; refusing to build its ring";
    return false;
  }
  GroupRing gr;
  gr.pos = pos;
  gr.size = k;
  if (k > 1) {
    int next = members[(pos + 1) % k];
    int prev = members[(pos - 1 + k) % k];
    uint8_t prev_flags = 0;
    if (!GroupPairConnect(group_id, Channel::RING, next, prev, &gr.next,
                          &gr.prev, &prev_flags)) {
      return false;
    }
    // Intra-host members of the group ring ride shared memory exactly
    // like the enum rings (docs/TRANSPORT.md).
    if (!NegotiateShmPair(&gr.next, next, &gr.prev, prev_flags,
                          Channel::RING)) {
      return false;
    }
  }
  LOG(DEBUG) << "group " << group_id << " ring built: position " << pos
             << "/" << k;
  group_rings_.emplace(group_id, std::move(gr));
  return true;
}

// ---------------- group grids + sub-rings (docs/TRANSPORT.md) --------------

TcpContext::GroupGrid TcpContext::GroupGridOf(
    const std::vector<int>& members) const {
  GroupGrid g;
  if (!is_homogeneous_ || rank_cross_.empty()) return g;
  // Bucket members by host (world cross index), hosts ordered by cross
  // index, members within a host ordered by world local_rank — which
  // equals member-list order within a host only incidentally, so sort
  // explicitly by grid cell.
  std::vector<std::vector<int>> hosts(
      static_cast<std::size_t>(cross_size_));
  for (int i = 0; i < static_cast<int>(members.size()); ++i) {
    int r = members[static_cast<std::size_t>(i)];
    if (r < 0 || r >= static_cast<int>(rank_cross_.size())) return g;
    hosts[static_cast<std::size_t>(rank_cross_[r])].push_back(i);
  }
  int k = -1;
  std::vector<int> present;  // cross indices with members
  for (int c = 0; c < cross_size_; ++c) {
    if (hosts[static_cast<std::size_t>(c)].empty()) continue;
    int count = static_cast<int>(hosts[static_cast<std::size_t>(c)].size());
    if (k < 0) k = count;
    if (count != k) return g;  // ragged: not a uniform grid
    present.push_back(c);
  }
  if (k <= 0 || present.empty()) return g;
  g.uniform = true;
  g.local_size = k;
  g.cross_size = static_cast<int>(present.size());
  g.pos_grid.assign(static_cast<std::size_t>(k) * present.size(), -1);
  for (int ci = 0; ci < g.cross_size; ++ci) {
    auto& col = hosts[static_cast<std::size_t>(present[ci])];
    // Order within a host by world local_rank (grid cell order).
    std::sort(col.begin(), col.end(), [&](int a, int b) {
      return LocalRankOfWorld(members[a]) < LocalRankOfWorld(members[b]);
    });
    for (int j = 0; j < k; ++j) {
      int mpos = col[static_cast<std::size_t>(j)];
      g.pos_grid[static_cast<std::size_t>(ci) * k + j] = mpos;
      if (members[static_cast<std::size_t>(mpos)] == rank_) {
        g.local_pos = j;
        g.cross_pos = ci;
      }
    }
  }
  return g;
}

int TcpContext::LocalRankOfWorld(int rank) const {
  // Scan the grid column of the rank's host for its local index.
  if (rank < 0 || rank >= static_cast<int>(rank_cross_.size())) return -1;
  int c = rank_cross_[static_cast<std::size_t>(rank)];
  for (int j = 0; j < local_size_; ++j) {
    if (rank_grid_[static_cast<std::size_t>(c) * local_size_ + j] == rank) {
      return j;
    }
  }
  return -1;
}

bool TcpContext::GroupHierarchicalPossible(
    const std::vector<int>& members) const {
  GroupGrid g = GroupGridOf(members);
  return g.uniform && g.local_size > 1 && g.cross_size > 1;
}

bool TcpContext::EnsureGroupSubRings(uint32_t group_id,
                                     const std::vector<int>& members) {
  if (group_subrings_.count(group_id)) return true;
  GroupGrid grid = GroupGridOf(members);
  if (!grid.uniform || grid.local_pos < 0) {
    LOG(ERROR) << "group " << group_id
               << " is not a uniform (local, cross) grid containing this "
                  "rank; hierarchical sub-rings unavailable";
    return false;
  }
  GroupSubRings sr;
  sr.grid = grid;
  int k = grid.local_size, C = grid.cross_size;
  auto member_at = [&](int c, int j) {
    return members[static_cast<std::size_t>(
        grid.pos_grid[static_cast<std::size_t>(c) * k + j])];
  };
  // Intra-host ring among my host's group members, then the cross ring
  // at my local position — every member executes the two builds in the
  // same order at the same schedule point, and unrelated connects
  // arriving early are stashed by (group, channel, rank), so the
  // connect-before-accept pairing cannot deadlock.
  if (k > 1) {
    int next = member_at(grid.cross_pos, (grid.local_pos + 1) % k);
    int prev = member_at(grid.cross_pos, (grid.local_pos - 1 + k) % k);
    uint8_t prev_flags = 0;
    if (!GroupPairConnect(group_id, Channel::LOCAL_RING, next, prev,
                          &sr.lnext, &sr.lprev, &prev_flags)) {
      return false;
    }
    if (!NegotiateShmPair(&sr.lnext, next, &sr.lprev, prev_flags,
                          Channel::LOCAL_RING)) {
      return false;
    }
  }
  if (C > 1) {
    int next = member_at((grid.cross_pos + 1) % C, grid.local_pos);
    int prev = member_at((grid.cross_pos - 1 + C) % C, grid.local_pos);
    uint8_t prev_flags = 0;
    if (!GroupPairConnect(group_id, Channel::CROSS_RING, next, prev,
                          &sr.cnext, &sr.cprev, &prev_flags)) {
      return false;
    }
    if (!NegotiateShmPair(&sr.cnext, next, &sr.cprev, prev_flags,
                          Channel::CROSS_RING)) {
      return false;
    }
  }
  LOG(DEBUG) << "group " << group_id << " sub-rings built: local "
             << grid.local_pos << "/" << k << ", cross " << grid.cross_pos
             << "/" << C;
  group_subrings_.emplace(group_id, std::move(sr));
  return true;
}

int TcpContext::RingRankOn(Ring ring, uint32_t group) const {
  if (group == 0) return RingRank(ring);
  if (ring == Ring::GLOBAL) return GroupRank(group);
  auto it = group_subrings_.find(group);
  if (it == group_subrings_.end()) return -1;
  return ring == Ring::LOCAL ? it->second.grid.local_pos
                             : it->second.grid.cross_pos;
}

int TcpContext::RingSizeOn(Ring ring, uint32_t group) const {
  if (group == 0) return RingSize(ring);
  if (ring == Ring::GLOBAL) return GroupSize(group);
  auto it = group_subrings_.find(group);
  if (it == group_subrings_.end()) return 0;
  return ring == Ring::LOCAL ? it->second.grid.local_size
                             : it->second.grid.cross_size;
}

bool TcpContext::GroupSubExchange(uint32_t group_id, Ring ring,
                                  const void* send_buf, std::size_t send_len,
                                  void* recv_buf, std::size_t recv_len) {
  auto it = group_subrings_.find(group_id);
  if (it == group_subrings_.end()) {
    LOG(ERROR) << "group " << group_id
               << " sub-rings not built (EnsureGroupSubRings must run "
                  "first)";
    last_error_ = "group sub-ring missing on ring channel";
    return false;
  }
  auto& sr = it->second;
  bool local = ring == Ring::LOCAL;
  return PairExchange(local ? &sr.lnext : &sr.cnext,
                      local ? &sr.lprev : &sr.cprev,
                      local ? Channel::LOCAL_RING : Channel::CROSS_RING,
                      local ? sr.grid.local_size : sr.grid.cross_size,
                      send_buf, send_len, recv_buf, recv_len);
}

// ---------------- worker-side control star with reconnect ----------------

bool TcpContext::ReconnectControl() {
  if (ReconnectWindowMs() <= 0 || coord_port_ == 0) return false;
  Metrics& metrics = GlobalMetrics();
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(ReconnectWindowMs());
  int backoff_ms = 50;
  int attempt = 0;
  while (std::chrono::steady_clock::now() < deadline) {
    ++attempt;
    metrics.net_reconnect_attempts_total.fetch_add(1,
                                                   std::memory_order_relaxed);
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    if (left < 1) break;
    int attempt_ms = static_cast<int>(left < 2000 ? left : 2000);
    Conn c = ConnectPeer(coord_host_, coord_port_, rank_, Channel::CONTROL,
                         attempt_ms, generation_, my_ctrl_opseq_,
                         /*reconnect=*/true);
    if (c.valid()) {
      control_conns_[0] = std::move(c);
      metrics.net_reconnects_total.fetch_add(1, std::memory_order_relaxed);
      LOG(WARNING) << "control connection re-established to coordinator "
                   << "(attempt " << attempt << ", opseq "
                   << my_ctrl_opseq_ << ", generation " << generation_
                   << ")";
      return true;
    }
    // Capped exponential backoff: fast first retries for a blip, bounded
    // pressure on a coordinator digging out from under a failure.
    std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
    backoff_ms = backoff_ms * 2 > 1000 ? 1000 : backoff_ms * 2;
  }
  LOG(ERROR) << "control reconnect failed after " << attempt
             << " attempt(s) — giving up (elastic recovery takes over)";
  return false;
}

bool TcpContext::ControlSendFrame(uint32_t tag, const void* payload,
                                  std::size_t len) {
  while (true) {
    if (control_conns_[0].SendFrame(tag, payload, len)) {
      ++my_ctrl_opseq_;
      GlobalTrace().NoteControlFrame(tag, /*send=*/true,
                                     len + kFrameHeaderBytes);
      return true;
    }
    NetError err = control_conns_[0].last_error();
    SetLastError(Channel::CONTROL, err);
    // Only a broken CONNECTION is worth a reconnect; a deadline or
    // checksum failure means the stream itself is unrecoverable.
    if (err != NetError::CLOSED || !ReconnectControl()) return false;
  }
}

bool TcpContext::ControlRecvFrame(uint32_t expect_tag, std::string* payload) {
  while (true) {
    uint32_t tag;
    if (control_conns_[0].RecvFrame(&tag, payload)) {
      if (tag != expect_tag) {
        LOG(ERROR) << "control frame: unexpected tag " << tag;
        SetLastError(Channel::CONTROL, NetError::PROTOCOL);
        return false;
      }
      ++my_ctrl_opseq_;
      GlobalTrace().NoteControlFrame(tag, /*send=*/false,
                                     payload->size() + kFrameHeaderBytes);
      return true;
    }
    NetError err = control_conns_[0].last_error();
    SetLastError(Channel::CONTROL, err);
    if (err != NetError::CLOSED || !ReconnectControl()) return false;
  }
}

bool TcpContext::ControlRecvFrameInto(uint32_t expect_tag, void* buf,
                                      std::size_t len) {
  while (true) {
    uint32_t tag;
    if (control_conns_[0].RecvFrameInto(&tag, buf, len)) {
      if (tag != expect_tag) {
        LOG(ERROR) << "control frame: unexpected tag " << tag;
        SetLastError(Channel::CONTROL, NetError::PROTOCOL);
        return false;
      }
      ++my_ctrl_opseq_;
      GlobalTrace().NoteControlFrame(tag, /*send=*/false,
                                     len + kFrameHeaderBytes);
      return true;
    }
    NetError err = control_conns_[0].last_error();
    SetLastError(Channel::CONTROL, err);
    if (err != NetError::CLOSED || !ReconnectControl()) return false;
  }
}

// ---------------- poll-multiplexed control star (rank 0) ----------------
//
// The reference's coordinator leans on MPI_Gatherv/MPI_Bcast, which the MPI
// library parallelizes internally; a naive per-socket loop here would
// serialize the whole negotiation through rank 0 (the SURVEY §7.3
// "negotiation latency at 256 chips" wall). These helpers service every
// worker socket concurrently with one poll loop.
//
// Peer-failure handling: a worker whose socket breaks mid-frame is NOT
// immediately fatal — its slot is held open for ReconnectWindowMs while
// the listener waits for a RECONNECT handshake carrying the matching
// (generation, opseq) cursor; the in-flight frame then restarts from
// byte 0 on both sides. A worker that never comes back (process death)
// fails the op when its window expires, which is what hands control to
// the elastic recovery path.

namespace {

struct FrameRecvState {
  char header[kFrameHeaderBytes];
  std::size_t hoff = 0;
  std::string payload;
  std::size_t poff = 0;
  uint32_t tag = 0;
  uint32_t crc = 0;
  bool have_header = false;
  bool done = false;
  // One injector consult per frame, even when the first poll wakeups
  // drain zero bytes (EAGAIN) — repeated consults would skew the
  // deterministic frame counters.
  bool fault_checked = false;
  // Injected recv-corruption: applied to the payload just before the
  // checksum verify (same semantics as Conn::RecvFrame).
  bool corrupt = false;

  void Restart() {
    hoff = 0;
    poff = 0;
    payload.clear();
    have_header = false;
    fault_checked = false;
    corrupt = false;
  }
};

struct FrameSendState {
  char header[kFrameHeaderBytes];
  std::size_t hoff = 0;
  const char* payload = nullptr;
  std::size_t len = 0;
  std::size_t poff = 0;
  bool done = false;
  bool fault_checked = false;

  void Restart() {
    hoff = 0;
    poff = 0;
    fault_checked = false;
  }
};

}  // namespace

int TcpContext::TryAcceptControlReconnect(const std::vector<bool>& dead) {
  PeerHandshake hs;
  // Short accept window: the listener was already readable, so this is
  // bounded by the handshake read (silent clients get dropped inside).
  int fd = listener_.AcceptPeer(&hs, 100, generation_);
  if (fd < 0) return 0;
  // A group member's ring connect (docs/GROUPS.md) can land while a
  // control-reconnect window has this thread polling the listener —
  // the connector is one-shot (no verdict wait), so closing it would
  // wedge that group's ring build until its timeout. Stash it for the
  // group's own EnsureGroupRing, exactly like the build-time race.
  if (hs.flags & kHandshakeGroupRing) {
    auto key = GroupFdKey(static_cast<uint32_t>(hs.opseq), hs.channel,
                          hs.rank);
    auto old = pending_group_fds_.find(key);
    if (old != pending_group_fds_.end()) {
      ::close(old->second.fd);
      old->second = PendingGroupFd{fd, hs.flags};
    } else {
      pending_group_fds_.emplace(key, PendingGroupFd{fd, hs.flags});
    }
    return 0;
  }
  char verdict = 0;
  if (hs.channel != Channel::CONTROL || !(hs.flags & kHandshakeReconnect) ||
      hs.rank < 1 || hs.rank >= size_ ||
      !dead[static_cast<std::size_t>(hs.rank)]) {
    LOG(WARNING) << "rejecting unexpected control connection from rank "
                 << hs.rank << " (not awaiting reconnect)";
    ::send(fd, &verdict, 1, MSG_NOSIGNAL);
    ::close(fd);
    return 0;
  }
  if (hs.opseq != ctrl_opseq_[static_cast<std::size_t>(hs.rank)]) {
    // The two sides disagree about which frame is in flight (e.g. a
    // response was fully sent but never received). Resuming would
    // desync the lockstep protocol — reject into elastic recovery.
    LOG(ERROR) << "control reconnect from rank " << hs.rank
               << " desynced: its opseq " << hs.opseq << " != expected "
               << ctrl_opseq_[static_cast<std::size_t>(hs.rank)]
               << " — failing over";
    ::send(fd, &verdict, 1, MSG_NOSIGNAL);
    ::close(fd);
    last_error_ = "control reconnect resume cursor mismatch (desynced "
                  "worker) on control channel";
    return -1;
  }
  verdict = 1;
  if (::send(fd, &verdict, 1, MSG_NOSIGNAL) != 1) {
    ::close(fd);
    return 0;
  }
  control_conns_[static_cast<std::size_t>(hs.rank)] =
      Conn(fd, Channel::CONTROL);
  LOG(WARNING) << "accepted control reconnect from rank " << hs.rank
               << " (opseq " << hs.opseq << ")";
  return hs.rank;
}

bool TcpContext::MultiRecvFrames(uint32_t expect_tag,
                                 std::vector<std::string>* blobs) {
  int n = size_ - 1;  // workers 1..size_-1
  std::vector<FrameRecvState> st(static_cast<std::size_t>(n));
  std::vector<bool> dead(static_cast<std::size_t>(size_), false);
  std::vector<std::chrono::steady_clock::time_point> dead_deadline(
      static_cast<std::size_t>(size_));
  int remaining = n;
  int num_dead = 0;
  FaultInjector& inj = GlobalFaultInjector();
  std::vector<struct pollfd> pfds;
  std::vector<int> idx;

  // Declares worker w's connection broken: hold its slot open for the
  // reconnect window (restarting its frame), or fail the op when
  // reconnect is disabled.
  auto peer_down = [&](int w, NetError err) -> bool {
    SetLastError(Channel::CONTROL, err);
    if (err != NetError::CLOSED || ReconnectWindowMs() <= 0) return false;
    control_conns_[w + 1].Close();
    dead[static_cast<std::size_t>(w + 1)] = true;
    dead_deadline[static_cast<std::size_t>(w + 1)] =
        std::chrono::steady_clock::now() +
        std::chrono::milliseconds(ReconnectWindowMs());
    ++num_dead;
    st[w].Restart();
    LOG(WARNING) << "control connection to rank " << w + 1
                 << " lost mid-gather; holding its slot for reconnect";
    return true;
  };

  while (remaining > 0) {
    auto now = std::chrono::steady_clock::now();
    for (int w = 1; w < size_; ++w) {
      if (dead[static_cast<std::size_t>(w)] &&
          now >= dead_deadline[static_cast<std::size_t>(w)]) {
        LOG(ERROR) << "rank " << w << " did not reconnect within "
                   << ReconnectWindowMs() << "ms — connection lost";
        last_error_ = "peer did not reconnect within the window on "
                      "control channel";
        return false;
      }
    }
    pfds.clear();
    idx.clear();
    for (int i = 0; i < n; ++i) {
      if (!st[i].done && !dead[static_cast<std::size_t>(i + 1)]) {
        pfds.push_back({control_conns_[i + 1].fd(), POLLIN, 0});
        idx.push_back(i);
      }
    }
    if (num_dead > 0) {
      pfds.push_back({listener_.fd(), POLLIN, 0});
      idx.push_back(-1);
    }
    int wait_ms = ControlPollMs();
    if (num_dead > 0 && wait_ms > 200) wait_ms = 200;  // re-check windows
    int pr = ::poll(pfds.data(), pfds.size(), wait_ms);
    if (pr < 0 && errno == EINTR) continue;
    if (pr < 0 || (pr == 0 && num_dead == 0)) {
      LOG(ERROR) << "control gather poll timeout/error";
      SetLastError(Channel::CONTROL, NetError::TIMEOUT);
      return false;
    }
    for (std::size_t k = 0; k < pfds.size(); ++k) {
      if (idx[k] < 0) {
        if (pfds[k].revents & POLLIN) {
          int back = TryAcceptControlReconnect(dead);
          if (back < 0) return false;
          if (back > 0) {
            dead[static_cast<std::size_t>(back)] = false;
            --num_dead;
            st[back - 1].Restart();
          }
        }
        continue;
      }
      if (!(pfds[k].revents & (POLLIN | POLLERR | POLLHUP))) continue;
      int i = idx[k];
      auto& s = st[i];
      int fd = control_conns_[i + 1].fd();
      if (!s.have_header) {
        if (!s.fault_checked && inj.active()) {
          // Coordinator-side chaos hook, once per frame start.
          s.fault_checked = true;
          FaultDecision d = inj.OnFrame(Channel::CONTROL, /*send=*/false);
          if (d.action == FaultAction::DELAY ||
              d.action == FaultAction::STALL) {
            std::this_thread::sleep_for(
                std::chrono::milliseconds(d.delay_ms));
          } else if (d.action == FaultAction::CLOSE) {
            if (!peer_down(i, NetError::CLOSED)) return false;
            continue;
          } else if (d.action == FaultAction::CORRUPT) {
            s.corrupt = true;
          }
        }
        ssize_t r = ::recv(fd, s.header + s.hoff, sizeof(s.header) - s.hoff,
                           MSG_DONTWAIT);
        if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                       errno != EINTR)) {
          if (!peer_down(i, NetError::CLOSED)) return false;
          continue;
        }
        if (r < 0) continue;
        s.hoff += static_cast<std::size_t>(r);
        if (s.hoff == sizeof(s.header)) {
          uint64_t len;
          ParseFrameHeader(s.header, &s.tag, &len, &s.crc);
          if (s.tag != expect_tag) {
            LOG(ERROR) << "control gather: unexpected tag " << s.tag;
            SetLastError(Channel::CONTROL, NetError::PROTOCOL);
            return false;
          }
          if (len > MaxFrameBytes()) {
            LOG(ERROR) << "control gather: frame length " << len
                       << " exceeds max " << MaxFrameBytes();
            SetLastError(Channel::CONTROL, NetError::TOO_BIG);
            GlobalMetrics().net_oversize_frames_total.fetch_add(
                1, std::memory_order_relaxed);
            return false;
          }
          s.payload.resize(static_cast<std::size_t>(len));
          s.have_header = true;
        }
      }
      if (s.have_header && !s.done && s.poff < s.payload.size()) {
        ssize_t r = ::recv(fd, &s.payload[s.poff], s.payload.size() - s.poff,
                           MSG_DONTWAIT);
        if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                       errno != EINTR)) {
          if (!peer_down(i, NetError::CLOSED)) return false;
          continue;
        }
        if (r < 0) continue;
        s.poff += static_cast<std::size_t>(r);
      }
      if (s.have_header && !s.done && s.poff == s.payload.size()) {
        uint64_t len = s.payload.size();
        if (s.corrupt) {
          if (len > 0) {
            s.payload[len / 2] ^= 0x20;
          } else {
            s.crc ^= 0x1;
          }
        }
        if (NetCrcEnabled() &&
            FrameCrc(s.tag, len, s.payload.data(), s.payload.size()) !=
                s.crc) {
          LOG(ERROR) << "control gather: checksum mismatch from rank "
                     << i + 1 << " — corrupted frame detected";
          SetLastError(Channel::CONTROL, NetError::CRC);
          GlobalMetrics().net_crc_errors_total.fetch_add(
              1, std::memory_order_relaxed);
          return false;
        }
        s.done = true;
        --remaining;
        ++ctrl_opseq_[static_cast<std::size_t>(i + 1)];
      }
    }
  }
  if (blobs != nullptr) {
    for (int i = 0; i < n; ++i) (*blobs)[i + 1] = std::move(st[i].payload);
  }
  return true;
}

bool TcpContext::MultiSendFrames(
    uint32_t tag,
    const std::vector<std::pair<const void*, std::size_t>>& payloads) {
  int n = size_ - 1;
  std::vector<FrameSendState> st(static_cast<std::size_t>(n));
  for (int i = 0; i < n; ++i) {
    auto& s = st[i];
    uint64_t len = payloads[i].second;
    BuildFrameHeader(s.header, tag, len,
                     FrameCrc(tag, len, payloads[i].first, len));
    s.payload = static_cast<const char*>(payloads[i].first);
    s.len = payloads[i].second;
  }
  std::vector<bool> dead(static_cast<std::size_t>(size_), false);
  std::vector<std::chrono::steady_clock::time_point> dead_deadline(
      static_cast<std::size_t>(size_));
  int remaining = n;
  int num_dead = 0;
  FaultInjector& inj = GlobalFaultInjector();
  std::vector<struct pollfd> pfds;
  std::vector<int> idx;

  auto peer_down = [&](int w, NetError err) -> bool {
    SetLastError(Channel::CONTROL, err);
    if (err != NetError::CLOSED || ReconnectWindowMs() <= 0) return false;
    control_conns_[w + 1].Close();
    dead[static_cast<std::size_t>(w + 1)] = true;
    dead_deadline[static_cast<std::size_t>(w + 1)] =
        std::chrono::steady_clock::now() +
        std::chrono::milliseconds(ReconnectWindowMs());
    ++num_dead;
    st[w].Restart();
    LOG(WARNING) << "control connection to rank " << w + 1
                 << " lost mid-bcast; holding its slot for reconnect";
    return true;
  };

  while (remaining > 0) {
    auto now = std::chrono::steady_clock::now();
    for (int w = 1; w < size_; ++w) {
      if (dead[static_cast<std::size_t>(w)] &&
          now >= dead_deadline[static_cast<std::size_t>(w)]) {
        LOG(ERROR) << "rank " << w << " did not reconnect within "
                   << ReconnectWindowMs() << "ms — connection lost";
        last_error_ = "peer did not reconnect within the window on "
                      "control channel";
        return false;
      }
    }
    pfds.clear();
    idx.clear();
    for (int i = 0; i < n; ++i) {
      if (!st[i].done && !dead[static_cast<std::size_t>(i + 1)]) {
        pfds.push_back({control_conns_[i + 1].fd(), POLLOUT, 0});
        idx.push_back(i);
      }
    }
    if (num_dead > 0) {
      pfds.push_back({listener_.fd(), POLLIN, 0});
      idx.push_back(-1);
    }
    int wait_ms = ControlPollMs();
    if (num_dead > 0 && wait_ms > 200) wait_ms = 200;
    int pr = ::poll(pfds.data(), pfds.size(), wait_ms);
    if (pr < 0 && errno == EINTR) continue;
    if (pr < 0 || (pr == 0 && num_dead == 0)) {
      LOG(ERROR) << "control bcast poll timeout/error";
      SetLastError(Channel::CONTROL, NetError::TIMEOUT);
      return false;
    }
    for (std::size_t k = 0; k < pfds.size(); ++k) {
      if (idx[k] < 0) {
        if (pfds[k].revents & POLLIN) {
          int back = TryAcceptControlReconnect(dead);
          if (back < 0) return false;
          if (back > 0) {
            dead[static_cast<std::size_t>(back)] = false;
            --num_dead;
            st[back - 1].Restart();
          }
        }
        continue;
      }
      if (!(pfds[k].revents & (POLLOUT | POLLERR | POLLHUP))) continue;
      int i = idx[k];
      auto& s = st[i];
      int fd = control_conns_[i + 1].fd();
      if (!s.fault_checked && inj.active()) {
        s.fault_checked = true;
        FaultDecision d = inj.OnFrame(Channel::CONTROL, /*send=*/true);
        switch (d.action) {
          case FaultAction::DROP:
            s.done = true;  // never sent: the worker's deadline fires
            --remaining;
            ++ctrl_opseq_[static_cast<std::size_t>(i + 1)];
            continue;
          case FaultAction::DELAY:
          case FaultAction::STALL:
            std::this_thread::sleep_for(
                std::chrono::milliseconds(d.delay_ms));
            break;
          case FaultAction::CLOSE:
            if (!peer_down(i, NetError::CLOSED)) return false;
            continue;
          case FaultAction::CORRUPT:
            // Flip a CRC byte in this worker's header copy: the wire
            // carries a checksum that no longer matches the payload.
            s.header[12] = static_cast<char>(s.header[12] ^ 0x1);
            break;
          case FaultAction::NONE:
            break;
        }
      }
      if (s.hoff < sizeof(s.header)) {
        ssize_t w = ::send(fd, s.header + s.hoff, sizeof(s.header) - s.hoff,
                           MSG_NOSIGNAL | MSG_DONTWAIT);
        if (w < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
            continue;
          if (!peer_down(i, NetError::CLOSED)) return false;
          continue;
        }
        s.hoff += static_cast<std::size_t>(w);
        if (s.hoff < sizeof(s.header)) continue;
      }
      if (s.poff < s.len) {
        ssize_t w = ::send(fd, s.payload + s.poff, s.len - s.poff,
                           MSG_NOSIGNAL | MSG_DONTWAIT);
        if (w < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
            continue;
          if (!peer_down(i, NetError::CLOSED)) return false;
          continue;
        }
        s.poff += static_cast<std::size_t>(w);
      }
      if (s.poff == s.len) {
        s.done = true;
        --remaining;
        ++ctrl_opseq_[static_cast<std::size_t>(i + 1)];
      }
    }
  }
  return true;
}

bool TcpContext::GatherBlobs(const std::string& mine,
                             std::vector<std::string>* all) {
  if (size_ == 1) {
    if (all != nullptr) {
      all->assign(1, mine);
    }
    return true;
  }
  if (rank_ == 0) {
    all->assign(size_, std::string());
    (*all)[0] = mine;
    if (!MultiRecvFrames(kTagGather, all)) return false;
    uint64_t recvd = 0;
    for (int r = 1; r < size_; ++r) recvd += (*all)[r].size();
    ctrl_bytes_recv_ += recvd + kFrameHeaderBytes * (size_ - 1);
    ctrl_msgs_ += size_ - 1;
    GlobalTrace().NoteControlFrame(kTagGather, /*send=*/false,
                                   recvd + kFrameHeaderBytes * (size_ - 1));
    return true;
  }
  if (!ControlSendFrame(kTagGather, mine.data(), mine.size())) return false;
  ctrl_bytes_sent_ += mine.size() + kFrameHeaderBytes;
  ctrl_msgs_ += 1;
  return true;
}

bool TcpContext::BroadcastBlob(std::string* blob) {
  if (size_ == 1) return true;
  if (rank_ == 0) {
    std::vector<std::pair<const void*, std::size_t>> payloads(
        static_cast<std::size_t>(size_ - 1),
        {blob->data(), blob->size()});
    if (!MultiSendFrames(kTagBcast, payloads)) return false;
    ctrl_bytes_sent_ +=
        (blob->size() + kFrameHeaderBytes) * uint64_t(size_ - 1);
    ctrl_msgs_ += size_ - 1;
    GlobalTrace().NoteControlFrame(
        kTagBcast, /*send=*/true,
        (blob->size() + kFrameHeaderBytes) * uint64_t(size_ - 1));
    return true;
  }
  if (!ControlRecvFrame(kTagBcast, blob)) return false;
  ctrl_bytes_recv_ += blob->size() + kFrameHeaderBytes;
  ctrl_msgs_ += 1;
  return true;
}

bool TcpContext::BitwiseSync(std::vector<uint64_t>& bits, bool is_or) {
  if (size_ == 1) return true;
  std::size_t nbytes = bits.size() * sizeof(uint64_t);
  if (rank_ == 0) {
    std::vector<std::string> blobs(static_cast<std::size_t>(size_));
    if (!MultiRecvFrames(kTagBits, &blobs)) return false;
    for (int r = 1; r < size_; ++r) {
      if (blobs[r].size() != nbytes) {
        LOG(ERROR) << "bit sync size mismatch from rank " << r;
        return false;
      }
      const uint64_t* peer =
          reinterpret_cast<const uint64_t*>(blobs[r].data());
      for (std::size_t i = 0; i < bits.size(); ++i) {
        bits[i] = is_or ? (bits[i] | peer[i]) : (bits[i] & peer[i]);
      }
    }
    std::vector<std::pair<const void*, std::size_t>> payloads(
        static_cast<std::size_t>(size_ - 1), {bits.data(), nbytes});
    if (!MultiSendFrames(kTagBits, payloads)) return false;
    ctrl_bytes_recv_ += (nbytes + kFrameHeaderBytes) * uint64_t(size_ - 1);
    ctrl_bytes_sent_ += (nbytes + kFrameHeaderBytes) * uint64_t(size_ - 1);
    ctrl_msgs_ += 2 * uint64_t(size_ - 1);
    GlobalTrace().NoteControlFrame(
        kTagBits, /*send=*/true,
        (nbytes + kFrameHeaderBytes) * uint64_t(size_ - 1));
    return true;
  }
  if (!(ControlSendFrame(kTagBits, bits.data(), nbytes) &&
        ControlRecvFrameInto(kTagBits, bits.data(), nbytes))) {
    return false;
  }
  ctrl_bytes_sent_ += nbytes + kFrameHeaderBytes;
  ctrl_bytes_recv_ += nbytes + kFrameHeaderBytes;
  ctrl_msgs_ += 2;
  return true;
}

bool TcpContext::Barrier() {
  std::vector<uint64_t> bits(1, ~0ull);
  return BitwiseSync(bits, false);
}

// ---------------- data rings ----------------

int TcpContext::RingRank(Ring ring) const {
  switch (ring) {
    case Ring::GLOBAL:
      return rank_;
    case Ring::LOCAL:
      return local_rank_;
    case Ring::CROSS:
      return cross_rank_;
  }
  return rank_;
}

int TcpContext::RingSize(Ring ring) const {
  switch (ring) {
    case Ring::GLOBAL:
      return size_;
    case Ring::LOCAL:
      return local_size_;
    case Ring::CROSS:
      return cross_size_;
  }
  return size_;
}

bool TcpContext::RingExchangeOn(Ring ring, const void* send_buf,
                                std::size_t send_len, void* recv_buf,
                                std::size_t recv_len) {
  Conn* next = &ring_next_;
  Conn* prev = &ring_prev_;
  Channel chan = Channel::RING;
  if (ring == Ring::LOCAL) {
    next = &local_next_;
    prev = &local_prev_;
    chan = Channel::LOCAL_RING;
  } else if (ring == Ring::CROSS) {
    next = &cross_next_;
    prev = &cross_prev_;
    chan = Channel::CROSS_RING;
  }
  return PairExchange(next, prev, chan, RingSize(ring), send_buf, send_len,
                      recv_buf, recv_len);
}

bool TcpContext::GroupExchange(uint32_t group_id, const void* send_buf,
                               std::size_t send_len, void* recv_buf,
                               std::size_t recv_len) {
  auto it = group_rings_.find(group_id);
  if (it == group_rings_.end()) {
    LOG(ERROR) << "group " << group_id
               << " ring not built (EnsureGroupRing must run first)";
    last_error_ = "group ring missing on ring channel";
    return false;
  }
  return PairExchange(&it->second.next, &it->second.prev, Channel::RING,
                      it->second.size, send_buf, send_len, recv_buf,
                      recv_len);
}

// Per-leg CRC switch: shm legs follow HVD_TPU_SHM_CRC (default: the
// net setting), socket legs follow HVD_TPU_NET_CRC.
static uint32_t LegFrameCrc(bool shm_leg, uint32_t tag, uint64_t len,
                            const void* payload, std::size_t n) {
  bool on = shm_leg ? ShmCrcEnabled() : NetCrcEnabled();
  if (!on) return 0;
  uint32_t crc = FrameHeaderCrc(tag, len);
  if (n > 0) crc = Crc32c(payload, n, crc);
  return crc;
}

bool TcpContext::PairExchange(Conn* next, Conn* prev, Channel chan,
                              int ring_size, const void* send_buf,
                              std::size_t send_len, void* recv_buf,
                              std::size_t recv_len) {
  if (ring_size == 1) {
    if (recv_len > 0 && recv_buf != send_buf) {
      std::memcpy(recv_buf, send_buf, std::min(send_len, recv_len));
    }
    return true;
  }
  if (!next->valid() || !prev->valid()) {
    LOG(ERROR) << "ring exchange on unconnected ring";
    return false;
  }

  // Wire-hop span (trace.h): one per exchange, both directions. Ring
  // exchanges run in lockstep, so the per-channel hop sequence pairs the
  // same logical hop across ranks; the causal check compares the
  // sender's start against its next-neighbor's end after clock
  // correction. Only the GLOBAL ring has a rank-derivable peer.
  Trace& hop_trace = GlobalTrace();
  const uint64_t hop_seq = trace_hop_seq_[static_cast<int>(chan)]++;
  const int64_t hop_start = hop_trace.NowNs();

  // Transport selection (docs/TRANSPORT.md): a leg rides its negotiated
  // shm ring only while the cycle-synchronized shm_transport knob says
  // so — both endpoints read the same knob value for any given
  // exchange, so the two sides can never disagree on the transport.
  ShmRing* sshm = shm_use_ ? next->shm() : nullptr;
  ShmRing* rshm = shm_use_ ? prev->shm() : nullptr;

  // Chaos hooks, once per exchange (send side, exactly as pre-shm so
  // logical-channel frame counters replay identically; the shm flag
  // feeds the chan=shm transport filter). corrupt flips the outgoing
  // header's CRC byte (the payload is the caller's gradient buffer —
  // never mutated); close/stall exercise the peer's deadline; close on
  // an shm leg also closes the ring, which the peer observes promptly.
  bool corrupt_out = false;
  FaultInjector& inj = GlobalFaultInjector();
  if (inj.active()) {
    FaultDecision d = inj.OnFrame(chan, /*send=*/true, sshm != nullptr);
    switch (d.action) {
      case FaultAction::DELAY:
      case FaultAction::STALL:
        std::this_thread::sleep_for(std::chrono::milliseconds(d.delay_ms));
        break;
      case FaultAction::CLOSE:
        next->Close();
        sshm = nullptr;
        break;
      case FaultAction::CORRUPT:
        corrupt_out = true;
        break;
      case FaultAction::DROP:
        // Dropping a ring frame = never sending it; the peer's recv
        // deadline fires. Model it as closing our send side silently.
        next->Close();
        sshm = nullptr;
        break;
      case FaultAction::NONE:
        break;
    }
  }
  if (!next->valid()) {
    SetLastError(chan, NetError::CLOSED);
    return false;
  }

  // Frame headers first (blocking, tiny), then pump payloads full-duplex so
  // a ring of simultaneous large sends can't deadlock on socket buffers.
  // The send CRC covers the whole payload (computed up front — one pass
  // over the buffer); the receive side accumulates incrementally as
  // chunks arrive and verifies at the end, so a corrupted frame becomes
  // a detected error, never silently wrong gradients — on shm legs
  // exactly as on sockets (memory is not a network, but the check is
  // cheap and keeps the chaos invariant uniform).
  uint64_t slen = send_len;
  uint32_t scrc = LegFrameCrc(sshm != nullptr, kTagRing, slen, send_buf,
                              send_len);
  if (corrupt_out) scrc ^= 0x1;
  char shdr[kFrameHeaderBytes];
  BuildFrameHeader(shdr, kTagRing, slen, scrc);
  int hdr_deadline_ms = NetTimeoutSeconds() * 1000;
  if (sshm != nullptr) {
    // The ring is empty between exchanges and capacity >= one header,
    // so this never blocks on a live peer.
    if (!sshm->WriteAll(shdr, sizeof(shdr), hdr_deadline_ms)) {
      SetLastError(Channel::SHM,
                   sshm->closed() ? NetError::CLOSED : NetError::TIMEOUT);
      return false;
    }
  } else if (!next->SendAll(shdr, sizeof(shdr))) {
    SetLastError(chan, next->last_error());
    return false;
  }
  char rhdr[kFrameHeaderBytes];
  if (rshm != nullptr) {
    if (!rshm->ReadAll(rhdr, sizeof(rhdr), hdr_deadline_ms)) {
      SetLastError(Channel::SHM,
                   rshm->closed() ? NetError::CLOSED : NetError::TIMEOUT);
      return false;
    }
  } else if (!prev->RecvAll(rhdr, sizeof(rhdr))) {
    SetLastError(chan, prev->last_error());
    return false;
  }
  uint32_t rtag;
  uint64_t rlen;
  uint32_t rcrc;
  ParseFrameHeader(rhdr, &rtag, &rlen, &rcrc);
  if (rtag != kTagRing || rlen != recv_len) {
    LOG(ERROR) << "ring exchange mismatch: tag " << rtag << " len " << rlen
               << " expected " << recv_len;
    SetLastError(chan, NetError::PROTOCOL);
    return false;
  }
  bool recv_crc_on = rshm != nullptr ? ShmCrcEnabled() : NetCrcEnabled();
  uint32_t crc_acc = recv_crc_on ? FrameHeaderCrc(rtag, rlen) : 0;

  const char* sp = static_cast<const char*>(send_buf);
  char* rp = static_cast<char*>(recv_buf);
  std::size_t sent = 0, received = 0;
  if (sshm != nullptr || rshm != nullptr) {
    if (!PumpShmAware(next, prev, chan, sshm, rshm, sp, send_len, rp,
                      recv_len, recv_crc_on, &crc_acc)) {
      return false;
    }
    sent = send_len;
    received = recv_len;
  } else {
  // Emulated-link TX pacing: when the token bucket is empty the send
  // side simply withholds POLLOUT until its ready time (receives keep
  // draining), then accounts the bytes it wrote. Quantized writes keep
  // the pacing granular so a receiver sees a stream, not a burst.
  const double rate = ring_tx_bytes_per_us_;
  auto now_us = [] {
    return std::chrono::duration<double, std::micro>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  };
  while (sent < send_len || received < recv_len) {
    // (all-TCP pump; shm-touched exchanges took PumpShmAware above)
    struct pollfd pfds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    int timeout_ms = ControlPollMs();
    bool throttle_wait = false;
    if (sent < send_len) {
      double wait_us =
          rate > 0.0 ? ring_tx_ready_us_ - now_us() : 0.0;
      if (wait_us > 0.0) {
        // Bucket empty: wake when it refills (or when bytes arrive).
        // poll(2) only has millisecond granularity; sub-ms refills use
        // a precise sleep below instead of a padded poll timeout —
        // padding compounds across pipeline segments.
        int wait_ms = static_cast<int>(wait_us / 1000.0);
        if (wait_ms < 1) wait_ms = 1;
        if (wait_ms < timeout_ms) timeout_ms = wait_ms;
        throttle_wait = true;
      } else {
        pfds[n] = {next->fd(), POLLOUT, 0};
        send_idx = n++;
      }
      if (throttle_wait && received >= recv_len) {
        // Only the throttled send remains: precise sleep, then retry.
        std::this_thread::sleep_for(
            std::chrono::duration<double, std::micro>(wait_us));
        continue;
      }
    }
    if (received < recv_len) {
      pfds[n] = {prev->fd(), POLLIN, 0};
      recv_idx = n++;
    }
    if (n == 0) {
      continue;  // unreachable; defensive
    }
    int rv = ::poll(pfds, n, timeout_ms);
    if (rv < 0 || (rv == 0 && !throttle_wait)) {
      LOG(ERROR) << "ring exchange poll timeout/error";
      SetLastError(chan, NetError::TIMEOUT);
      return false;
    }
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR))) {
      std::size_t quantum = send_len - sent;
      if (rate > 0.0 && quantum > 262144) quantum = 262144;
      ssize_t w = ::send(next->fd(), sp + sent, quantum,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        SetLastError(chan, NetError::CLOSED);
        return false;
      }
      if (w > 0) {
        sent += static_cast<std::size_t>(w);
        if (rate > 0.0) {
          double now = now_us();
          ring_tx_ready_us_ = std::max(ring_tx_ready_us_, now) + w / rate;
        }
      }
    }
    if (recv_idx >= 0 && (pfds[recv_idx].revents & (POLLIN | POLLERR))) {
      ssize_t r = ::recv(prev->fd(), rp + received, recv_len - received,
                         MSG_DONTWAIT);
      if (r == 0) {
        SetLastError(chan, NetError::CLOSED);
        return false;
      }
      if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        SetLastError(chan, NetError::CLOSED);
        return false;
      }
      if (r > 0) {
        if (recv_crc_on) {
          crc_acc = Crc32c(rp + received, static_cast<std::size_t>(r),
                           crc_acc);
        }
        received += static_cast<std::size_t>(r);
      }
    }
  }
  }  // all-TCP pump
  if (recv_crc_on && crc_acc != rcrc) {
    LOG(ERROR) << "ring exchange checksum mismatch (" << recv_len
               << " bytes) — corrupted frame detected";
    SetLastError(rshm != nullptr ? Channel::SHM : chan, NetError::CRC);
    GlobalMetrics().net_crc_errors_total.fetch_add(1,
                                                   std::memory_order_relaxed);
    return false;
  }
  // Data-ring accounting (headers included): the quantity the
  // compression stage shrinks, counted at the transport layer so a
  // bench/test A/B measures actual bytes moved, not payload intent —
  // whatever the transport. The net_shm_* counters split out the
  // shared-memory share (bench.py --shm's engagement proof).
  Metrics& m = GlobalMetrics();
  m.net_ring_bytes_sent_total.fetch_add(
      static_cast<uint64_t>(send_len) + kFrameHeaderBytes,
      std::memory_order_relaxed);
  m.net_ring_bytes_recv_total.fetch_add(
      static_cast<uint64_t>(recv_len) + kFrameHeaderBytes,
      std::memory_order_relaxed);
  if (sshm != nullptr) {
    m.net_shm_bytes_sent_total.fetch_add(
        static_cast<uint64_t>(send_len) + kFrameHeaderBytes,
        std::memory_order_relaxed);
  }
  if (rshm != nullptr) {
    m.net_shm_bytes_recv_total.fetch_add(
        static_cast<uint64_t>(recv_len) + kFrameHeaderBytes,
        std::memory_order_relaxed);
  }
  if (hop_trace.enabled()) {
    static const char* kChanNames[] = {"hop.control", "hop.ring",
                                       "hop.local", "hop.cross"};
    int ci = static_cast<int>(chan);
    hop_trace.Record(ci >= 0 && ci < 4 ? kChanNames[ci] : "hop.?",
                     TRACE_WIRE_HOP, hop_start, hop_trace.NowNs(),
                     static_cast<int64_t>(send_len), /*group=*/0,
                     chan == Channel::RING ? (rank_ + 1) % size_ : -1,
                     hop_seq,
                     sshm != nullptr ? TRACE_FLAG_SHM : 0);
  }
  return true;
}

// Duplex payload pump for exchanges where at least one leg rides shared
// memory: both directions make nonblocking progress each iteration
// (socket legs via MSG_DONTWAIT, shm legs via Write/ReadSome), so a
// ring of simultaneous large sends cannot deadlock whatever the
// transport mix. TX pacing (the emulated inter-host link) applies to
// the TCP send leg only — shm is intra-host by construction. A quiet
// interval waits briefly (poll on socket legs, spin-then-futex on shm
// legs) and a no-progress stretch past the net deadline fails as a
// TIMEOUT; a peer that died without closing is additionally caught by
// an EOF probe on the shm legs' liveness sockets.
bool TcpContext::PumpShmAware(Conn* next, Conn* prev, Channel chan,
                              ShmRing* sshm, ShmRing* rshm, const char* sp,
                              std::size_t send_len, char* rp,
                              std::size_t recv_len, bool recv_crc_on,
                              uint32_t* crc_acc) {
  std::size_t sent = 0, received = 0;
  const double rate = ring_tx_bytes_per_us_;
  auto now_us = [] {
    return std::chrono::duration<double, std::micro>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  };
  auto last_progress = std::chrono::steady_clock::now();
  const auto stall_budget =
      std::chrono::milliseconds(NetTimeoutSeconds() * 1000);
  int quiet = 0;  // consecutive no-progress waits since last progress
  while (sent < send_len || received < recv_len) {
    bool progress = false;
    double throttle_wait_us = 0.0;  // >0: TCP send leg paced (bucket empty)
    if (sent < send_len) {
      if (sshm != nullptr) {
        int64_t w = sshm->WriteSome(sp + sent, send_len - sent);
        if (w < 0) {
          SetLastError(Channel::SHM, NetError::CLOSED);
          return false;
        }
        if (w > 0) {
          sent += static_cast<std::size_t>(w);
          progress = true;
        }
      } else {
        double wait_us = rate > 0.0 ? ring_tx_ready_us_ - now_us() : 0.0;
        if (wait_us > 0.0) throttle_wait_us = wait_us;
        if (wait_us <= 0.0) {
          std::size_t quantum = send_len - sent;
          if (rate > 0.0 && quantum > 262144) quantum = 262144;
          ssize_t w = ::send(next->fd(), sp + sent, quantum,
                             MSG_NOSIGNAL | MSG_DONTWAIT);
          if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
              errno != EINTR) {
            SetLastError(chan, NetError::CLOSED);
            return false;
          }
          if (w > 0) {
            sent += static_cast<std::size_t>(w);
            progress = true;
            if (rate > 0.0) {
              double now = now_us();
              ring_tx_ready_us_ =
                  std::max(ring_tx_ready_us_, now) + w / rate;
            }
          }
        }
      }
    }
    if (received < recv_len) {
      if (rshm != nullptr) {
        int64_t r = rshm->ReadSome(rp + received, recv_len - received);
        if (r < 0) {
          SetLastError(Channel::SHM, NetError::CLOSED);
          return false;
        }
        if (r > 0) {
          if (recv_crc_on) {
            *crc_acc = Crc32c(rp + received, static_cast<std::size_t>(r),
                              *crc_acc);
          }
          received += static_cast<std::size_t>(r);
          progress = true;
        }
      } else {
        ssize_t r = ::recv(prev->fd(), rp + received, recv_len - received,
                           MSG_DONTWAIT);
        if (r == 0) {
          SetLastError(chan, NetError::CLOSED);
          return false;
        }
        if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
            errno != EINTR) {
          SetLastError(chan, NetError::CLOSED);
          return false;
        }
        if (r > 0) {
          if (recv_crc_on) {
            *crc_acc = Crc32c(rp + received, static_cast<std::size_t>(r),
                              *crc_acc);
          }
          received += static_cast<std::size_t>(r);
          progress = true;
        }
      }
    }
    if (progress) {
      last_progress = std::chrono::steady_clock::now();
      quiet = 0;
      continue;
    }
    auto now = std::chrono::steady_clock::now();
    if (now - last_progress > stall_budget) {
      SetLastError(sshm != nullptr || rshm != nullptr ? Channel::SHM : chan,
                   NetError::TIMEOUT);
      LOG(ERROR) << "ring exchange stalled past the transport deadline";
      return false;
    }
    // Peer-death probe on shm legs: an orderly Close sets the ring's
    // closed flag, but a SIGKILL'd peer cannot — its TCP liveness
    // socket delivers the EOF instead, making death prompt, not a
    // deadline expiry. Probed only on SUSTAINED quiet (each probe is a
    // syscall; the active pump's brief stalls must stay syscall-free).
    if (++quiet >= 8) {
      char probe;
      if (rshm != nullptr && received < recv_len &&
          ::recv(prev->fd(), &probe, 1, MSG_DONTWAIT | MSG_PEEK) == 0) {
        SetLastError(Channel::SHM, NetError::CLOSED);
        return false;
      }
      if (sshm != nullptr && sent < send_len &&
          ::recv(next->fd(), &probe, 1, MSG_DONTWAIT | MSG_PEEK) == 0) {
        SetLastError(Channel::SHM, NetError::CLOSED);
        return false;
      }
    }
    struct pollfd pfds[2];
    int n = 0;
    // A paced send leg with an empty token bucket must NOT poll for
    // POLLOUT — the socket is writable, so the poll would return
    // instantly and the throttle window would become a busy-loop of
    // syscalls (the all-TCP pump withholds POLLOUT the same way).
    if (sent < send_len && sshm == nullptr && throttle_wait_us <= 0.0) {
      pfds[n++] = {next->fd(), POLLOUT, 0};
    }
    if (received < recv_len && rshm == nullptr) {
      pfds[n++] = {prev->fd(), POLLIN, 0};
    }
    if (n > 0) {
      ::poll(pfds, n, 1);
    } else if (received < recv_len && rshm != nullptr) {
      rshm->WaitReadable(2);
    } else if (sshm != nullptr && sent < send_len) {
      sshm->WaitWritable(2);
    } else if (throttle_wait_us > 0.0) {
      // Only the throttled send remains: precise sleep to the bucket's
      // refill (capped so the loop re-checks deadlines regularly).
      std::this_thread::sleep_for(std::chrono::duration<double, std::micro>(
          std::min(throttle_wait_us, 1000.0)));
    }
  }
  return true;
}

bool TcpContext::RingBroadcast(void* buf, std::size_t len, int root) {
  return PairBroadcast(&ring_next_, &ring_prev_, rank_, size_, buf, len,
                       root);
}

bool TcpContext::GroupBroadcast(uint32_t group_id, void* buf,
                                std::size_t len, int root_pos) {
  auto it = group_rings_.find(group_id);
  if (it == group_rings_.end()) {
    LOG(ERROR) << "group " << group_id
               << " ring not built (EnsureGroupRing must run first)";
    last_error_ = "group ring missing on ring channel";
    return false;
  }
  return PairBroadcast(&it->second.next, &it->second.prev, it->second.pos,
                       it->second.size, buf, len, root_pos);
}

bool TcpContext::PairBroadcast(Conn* next_conn, Conn* prev_conn, int pos,
                               int n, void* buf, std::size_t len,
                               int root_pos) {
  if (n == 1 || len == 0) return true;
  int next = (pos + 1) % n;
  char* p = static_cast<char*>(buf);
  uint64_t len64 = len;
  // The broadcast CRC travels END TO END (one header, every hop
  // verifies it), so it is governed by HVD_TPU_NET_CRC uniformly — a
  // per-leg HVD_TPU_SHM_CRC opt-out cannot apply when some downstream
  // hop may ride a socket.
  if (pos == root_pos) {
    ShmRing* sshm = shm_use_ ? next_conn->shm() : nullptr;
    // Root only streams downstream (n > 1 so next != root). One
    // frame header up front carries the CRC every hop verifies.
    uint32_t crc = FrameCrc(kTagRing, len64, p, len);
    FaultInjector& inj = GlobalFaultInjector();
    if (inj.active()) {
      FaultDecision d = inj.OnFrame(Channel::RING, /*send=*/true,
                                    sshm != nullptr);
      if (d.action == FaultAction::DELAY || d.action == FaultAction::STALL) {
        std::this_thread::sleep_for(std::chrono::milliseconds(d.delay_ms));
      } else if (d.action == FaultAction::CLOSE ||
                 d.action == FaultAction::DROP) {
        next_conn->Close();
        sshm = nullptr;
      } else if (d.action == FaultAction::CORRUPT) {
        crc ^= 0x1;
      }
    }
    if (!next_conn->valid()) {
      SetLastError(Channel::RING, NetError::CLOSED);
      return false;
    }
    char hdr[kFrameHeaderBytes];
    BuildFrameHeader(hdr, kTagRing, len64, crc);
    if (sshm != nullptr) {
      int deadline_ms = NetTimeoutSeconds() * 1000;
      if (!sshm->WriteAll(hdr, sizeof(hdr), deadline_ms)) {
        SetLastError(Channel::SHM,
                     sshm->closed() ? NetError::CLOSED : NetError::TIMEOUT);
        return false;
      }
      if (!StreamIntoShm(sshm, next_conn, p, len)) {
        return false;  // StreamIntoShm set last_error
      }
    } else if (!next_conn->SendAll(hdr, sizeof(hdr)) ||
               !next_conn->SendAll(p, len)) {
      SetLastError(Channel::RING, next_conn->last_error());
      return false;
    }
    GlobalMetrics().net_ring_bytes_sent_total.fetch_add(
        static_cast<uint64_t>(len) + kFrameHeaderBytes,
        std::memory_order_relaxed);
    if (sshm != nullptr) {
      GlobalMetrics().net_shm_bytes_sent_total.fetch_add(
          static_cast<uint64_t>(len) + kFrameHeaderBytes,
          std::memory_order_relaxed);
    }
    return true;
  }
  // Non-root: read the header, forward it downstream if we forward at
  // all, then stream from the predecessor, forwarding bytes as they
  // arrive (cut-through, not store-and-forward — total time ~ len/BW +
  // hop latency). The CRC is verified at the END on every hop: bytes
  // already forwarded may be corrupt, but every downstream hop detects
  // the same mismatch, so corruption surfaces as a detected error
  // everywhere, never as silently wrong data.
  ShmRing* rshm = shm_use_ ? prev_conn->shm() : nullptr;
  char rhdr[kFrameHeaderBytes];
  if (rshm != nullptr) {
    if (!rshm->ReadAll(rhdr, sizeof(rhdr), NetTimeoutSeconds() * 1000)) {
      SetLastError(Channel::SHM,
                   rshm->closed() ? NetError::CLOSED : NetError::TIMEOUT);
      return false;
    }
  } else if (!prev_conn->RecvAll(rhdr, sizeof(rhdr))) {
    SetLastError(Channel::RING, prev_conn->last_error());
    return false;
  }
  uint32_t rtag;
  uint64_t rlen;
  uint32_t rcrc;
  ParseFrameHeader(rhdr, &rtag, &rlen, &rcrc);
  if (rtag != kTagRing || rlen != len64) {
    LOG(ERROR) << "ring broadcast mismatch: tag " << rtag << " len " << rlen
               << " expected " << len64;
    SetLastError(Channel::RING, NetError::PROTOCOL);
    return false;
  }
  bool forward = next != root_pos;
  ShmRing* fshm = forward && shm_use_ ? next_conn->shm() : nullptr;
  if (forward) {
    if (fshm != nullptr) {
      if (!fshm->WriteAll(rhdr, sizeof(rhdr), NetTimeoutSeconds() * 1000)) {
        SetLastError(Channel::SHM,
                     fshm->closed() ? NetError::CLOSED : NetError::TIMEOUT);
        return false;
      }
    } else if (!next_conn->SendAll(rhdr, sizeof(rhdr))) {
      SetLastError(Channel::RING, next_conn->last_error());
      return false;
    }
  }
  uint32_t crc_acc = NetCrcEnabled() ? FrameHeaderCrc(rtag, rlen) : 0;
  std::size_t received = 0, sent = 0;
  if (rshm != nullptr || fshm != nullptr) {
    // Mixed-transport cut-through: nonblocking progress on both legs
    // per iteration, forwarding only bytes already received, with a
    // no-progress deadline and peer-death EOF probes on shm legs.
    auto last_progress = std::chrono::steady_clock::now();
    const auto stall_budget =
        std::chrono::milliseconds(NetTimeoutSeconds() * 1000);
    while (received < len || (forward && sent < len)) {
      bool progress = false;
      if (received < len) {
        if (rshm != nullptr) {
          int64_t r = rshm->ReadSome(p + received, len - received);
          if (r < 0) {
            SetLastError(Channel::SHM, NetError::CLOSED);
            return false;
          }
          if (r > 0) {
            if (NetCrcEnabled()) {
              crc_acc = Crc32c(p + received, static_cast<std::size_t>(r),
                               crc_acc);
            }
            received += static_cast<std::size_t>(r);
            progress = true;
          }
        } else {
          ssize_t r = ::recv(prev_conn->fd(), p + received, len - received,
                             MSG_DONTWAIT);
          if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                         errno != EINTR)) {
            SetLastError(Channel::RING, NetError::CLOSED);
            return false;
          }
          if (r > 0) {
            if (NetCrcEnabled()) {
              crc_acc = Crc32c(p + received, static_cast<std::size_t>(r),
                               crc_acc);
            }
            received += static_cast<std::size_t>(r);
            progress = true;
          }
        }
      }
      if (forward && sent < received) {
        if (fshm != nullptr) {
          int64_t w = fshm->WriteSome(p + sent, received - sent);
          if (w < 0) {
            SetLastError(Channel::SHM, NetError::CLOSED);
            return false;
          }
          if (w > 0) {
            sent += static_cast<std::size_t>(w);
            progress = true;
          }
        } else {
          ssize_t w = ::send(next_conn->fd(), p + sent, received - sent,
                             MSG_NOSIGNAL | MSG_DONTWAIT);
          if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
              errno != EINTR) {
            SetLastError(Channel::RING, NetError::CLOSED);
            return false;
          }
          if (w > 0) {
            sent += static_cast<std::size_t>(w);
            progress = true;
          }
        }
      }
      if (progress) {
        last_progress = std::chrono::steady_clock::now();
        continue;
      }
      if (std::chrono::steady_clock::now() - last_progress > stall_budget) {
        LOG(ERROR) << "ring broadcast stalled past the transport deadline";
        SetLastError(Channel::SHM, NetError::TIMEOUT);
        return false;
      }
      char probe;
      if (rshm != nullptr && received < len &&
          ::recv(prev_conn->fd(), &probe, 1, MSG_DONTWAIT | MSG_PEEK) == 0) {
        SetLastError(Channel::SHM, NetError::CLOSED);
        return false;
      }
      // Forward-leg liveness: a SIGKILL'd downstream peer never sets
      // the forward ring's closed flag — its socket's EOF is what makes
      // its death prompt instead of a stall-deadline expiry.
      if (fshm != nullptr && sent < len &&
          ::recv(next_conn->fd(), &probe, 1, MSG_DONTWAIT | MSG_PEEK) == 0) {
        SetLastError(Channel::SHM, NetError::CLOSED);
        return false;
      }
      struct pollfd pfds[2];
      int nfds = 0;
      if (received < len && rshm == nullptr) {
        pfds[nfds++] = {prev_conn->fd(), POLLIN, 0};
      }
      if (forward && sent < received && fshm == nullptr) {
        pfds[nfds++] = {next_conn->fd(), POLLOUT, 0};
      }
      if (nfds > 0) {
        ::poll(pfds, nfds, 1);
      } else if (received < len && rshm != nullptr) {
        rshm->WaitReadable(2);
      } else if (fshm != nullptr) {
        fshm->WaitWritable(2);
      }
    }
  } else {
  while (received < len || (forward && sent < len)) {
    struct pollfd pfds[2];
    int nfds = 0;
    int recv_idx = -1, send_idx = -1;
    if (received < len) {
      pfds[nfds] = {prev_conn->fd(), POLLIN, 0};
      recv_idx = nfds++;
    }
    if (forward && sent < received) {
      pfds[nfds] = {next_conn->fd(), POLLOUT, 0};
      send_idx = nfds++;
    }
    if (nfds == 0) break;
    if (::poll(pfds, nfds, ControlPollMs()) <= 0) {
      LOG(ERROR) << "ring broadcast poll timeout/error";
      SetLastError(Channel::RING, NetError::TIMEOUT);
      return false;
    }
    if (recv_idx >= 0 && (pfds[recv_idx].revents & (POLLIN | POLLERR))) {
      ssize_t r = ::recv(prev_conn->fd(), p + received, len - received,
                         MSG_DONTWAIT);
      if (r == 0) {
        SetLastError(Channel::RING, NetError::CLOSED);
        return false;
      }
      if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        SetLastError(Channel::RING, NetError::CLOSED);
        return false;
      }
      if (r > 0) {
        if (NetCrcEnabled()) {
          crc_acc = Crc32c(p + received, static_cast<std::size_t>(r),
                           crc_acc);
        }
        received += static_cast<std::size_t>(r);
      }
    }
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR))) {
      ssize_t w = ::send(next_conn->fd(), p + sent, received - sent,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        SetLastError(Channel::RING, NetError::CLOSED);
        return false;
      }
      if (w > 0) sent += static_cast<std::size_t>(w);
    }
  }
  }  // all-TCP pump
  if (NetCrcEnabled() && crc_acc != rcrc) {
    LOG(ERROR) << "ring broadcast checksum mismatch (" << len
               << " bytes) — corrupted frame detected";
    SetLastError(rshm != nullptr ? Channel::SHM : Channel::RING,
                 NetError::CRC);
    GlobalMetrics().net_crc_errors_total.fetch_add(1,
                                                   std::memory_order_relaxed);
    return false;
  }
  GlobalMetrics().net_ring_bytes_recv_total.fetch_add(
      static_cast<uint64_t>(len) + kFrameHeaderBytes,
      std::memory_order_relaxed);
  if (rshm != nullptr) {
    GlobalMetrics().net_shm_bytes_recv_total.fetch_add(
        static_cast<uint64_t>(len) + kFrameHeaderBytes,
        std::memory_order_relaxed);
  }
  if (forward) {
    GlobalMetrics().net_ring_bytes_sent_total.fetch_add(
        static_cast<uint64_t>(len) + kFrameHeaderBytes,
        std::memory_order_relaxed);
    if (fshm != nullptr) {
      GlobalMetrics().net_shm_bytes_sent_total.fetch_add(
          static_cast<uint64_t>(len) + kFrameHeaderBytes,
          std::memory_order_relaxed);
    }
  }
  return true;
}

// Root-side shm streaming body for PairBroadcast: pushes `len` bytes
// into the ring with the spin-then-sleep waits, the no-progress
// deadline, and the peer-death EOF probe.
bool TcpContext::StreamIntoShm(ShmRing* ring, Conn* conn, const char* p,
                               std::size_t len) {
  std::size_t sent = 0;
  auto last_progress = std::chrono::steady_clock::now();
  const auto stall_budget =
      std::chrono::milliseconds(NetTimeoutSeconds() * 1000);
  while (sent < len) {
    int64_t w = ring->WriteSome(p + sent, len - sent);
    if (w < 0) {
      SetLastError(Channel::SHM, NetError::CLOSED);
      return false;
    }
    if (w > 0) {
      sent += static_cast<std::size_t>(w);
      last_progress = std::chrono::steady_clock::now();
      continue;
    }
    if (std::chrono::steady_clock::now() - last_progress > stall_budget) {
      SetLastError(Channel::SHM, NetError::TIMEOUT);
      return false;
    }
    char probe;
    if (::recv(conn->fd(), &probe, 1, MSG_DONTWAIT | MSG_PEEK) == 0) {
      SetLastError(Channel::SHM, NetError::CLOSED);
      return false;
    }
    ring->WaitWritable(2);
  }
  return true;
}

}  // namespace hvdtpu
