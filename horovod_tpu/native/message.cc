#include "message.h"

#include <cstring>
#include <sstream>

namespace hvdtpu {

const char* DataTypeName(DataType dt) {
  switch (dt) {
    case DataType::HVD_UINT8: return "uint8";
    case DataType::HVD_INT8: return "int8";
    case DataType::HVD_UINT16: return "uint16";
    case DataType::HVD_INT16: return "int16";
    case DataType::HVD_INT32: return "int32";
    case DataType::HVD_INT64: return "int64";
    case DataType::HVD_FLOAT16: return "float16";
    case DataType::HVD_FLOAT32: return "float32";
    case DataType::HVD_FLOAT64: return "float64";
    case DataType::HVD_BOOL: return "bool";
    case DataType::HVD_BFLOAT16: return "bfloat16";
  }
  return "unknown";
}

std::size_t DataTypeSize(DataType dt) {
  switch (dt) {
    case DataType::HVD_UINT8:
    case DataType::HVD_INT8:
    case DataType::HVD_BOOL:
      return 1;
    case DataType::HVD_UINT16:
    case DataType::HVD_INT16:
    case DataType::HVD_FLOAT16:
    case DataType::HVD_BFLOAT16:
      return 2;
    case DataType::HVD_INT32:
    case DataType::HVD_FLOAT32:
      return 4;
    case DataType::HVD_INT64:
    case DataType::HVD_FLOAT64:
      return 8;
  }
  return 0;
}

const char* Request::RequestTypeName(RequestType t) {
  switch (t) {
    case ALLREDUCE: return "ALLREDUCE";
    case ALLGATHER: return "ALLGATHER";
    case BROADCAST: return "BROADCAST";
    case REDUCESCATTER: return "REDUCESCATTER";
  }
  return "?";
}

const char* Response::ResponseTypeName(ResponseType t) {
  switch (t) {
    case ALLREDUCE: return "ALLREDUCE";
    case ALLGATHER: return "ALLGATHER";
    case BROADCAST: return "BROADCAST";
    case ERROR: return "ERROR";
    case REDUCESCATTER: return "REDUCESCATTER";
  }
  return "?";
}

namespace wire {

void PutU8(std::string* out, uint8_t v) {
  out->push_back(static_cast<char>(v));
}
void PutU32(std::string* out, uint32_t v) {
  char b[4];
  std::memcpy(b, &v, 4);
  out->append(b, 4);
}
void PutI32(std::string* out, int32_t v) {
  char b[4];
  std::memcpy(b, &v, 4);
  out->append(b, 4);
}
void PutI64(std::string* out, int64_t v) {
  char b[8];
  std::memcpy(b, &v, 8);
  out->append(b, 8);
}
void PutF64(std::string* out, double v) {
  char b[8];
  std::memcpy(b, &v, 8);
  out->append(b, 8);
}
void PutStr(std::string* out, const std::string& s) {
  PutU32(out, static_cast<uint32_t>(s.size()));
  out->append(s);
}

bool Reader::GetU8(uint8_t* v) {
  if (p_ + 1 > end_) return false;
  *v = static_cast<uint8_t>(*p_++);
  return true;
}
bool Reader::GetU32(uint32_t* v) {
  if (p_ + 4 > end_) return false;
  std::memcpy(v, p_, 4);
  p_ += 4;
  return true;
}
bool Reader::GetI32(int32_t* v) {
  if (p_ + 4 > end_) return false;
  std::memcpy(v, p_, 4);
  p_ += 4;
  return true;
}
bool Reader::GetI64(int64_t* v) {
  if (p_ + 8 > end_) return false;
  std::memcpy(v, p_, 8);
  p_ += 8;
  return true;
}
bool Reader::GetF64(double* v) {
  if (p_ + 8 > end_) return false;
  std::memcpy(v, p_, 8);
  p_ += 8;
  return true;
}
bool Reader::GetStr(std::string* s) {
  uint32_t n;
  if (!GetU32(&n)) return false;
  if (p_ + n > end_) return false;
  s->assign(p_, n);
  p_ += n;
  return true;
}

}  // namespace wire

using namespace wire;

void Request::SerializeTo(std::string* out) const {
  PutI32(out, request_rank_);
  PutU8(out, static_cast<uint8_t>(request_type_));
  PutU8(out, static_cast<uint8_t>(tensor_type_));
  PutI32(out, root_rank_);
  PutI32(out, device_);
  PutStr(out, tensor_name_);
  PutU32(out, static_cast<uint32_t>(tensor_shape_.size()));
  for (int64_t d : tensor_shape_) PutI64(out, d);
  PutF64(out, prescale_factor_);
  PutF64(out, postscale_factor_);
  PutU8(out, compression_);
  PutU32(out, group_id_);
  PutI64(out, static_cast<int64_t>(group_digest_));
}

std::size_t Request::ParseFrom(const char* data, std::size_t len) {
  Reader r(data, len);
  uint8_t rt, tt;
  uint32_t ndim;
  if (!r.GetI32(&request_rank_) || !r.GetU8(&rt) || !r.GetU8(&tt) ||
      !r.GetI32(&root_rank_) || !r.GetI32(&device_) ||
      !r.GetStr(&tensor_name_) || !r.GetU32(&ndim))
    return 0;
  request_type_ = static_cast<RequestType>(rt);
  tensor_type_ = static_cast<DataType>(tt);
  tensor_shape_.clear();
  for (uint32_t i = 0; i < ndim; ++i) {
    int64_t d;
    if (!r.GetI64(&d)) return 0;
    tensor_shape_.push_back(d);
  }
  if (!r.GetF64(&prescale_factor_) || !r.GetF64(&postscale_factor_)) return 0;
  if (!r.GetU8(&compression_)) return 0;
  int64_t digest;
  if (!r.GetU32(&group_id_) || !r.GetI64(&digest)) return 0;
  group_digest_ = static_cast<uint64_t>(digest);
  return r.consumed(data);
}

void RequestList::SerializeTo(std::string* out) const {
  PutU8(out, shutdown_ ? 1 : 0);
  PutU32(out, static_cast<uint32_t>(requests_.size()));
  for (const auto& req : requests_) req.SerializeTo(out);
  PutI64(out, static_cast<int64_t>(call_seq_));
  PutI64(out, static_cast<int64_t>(call_digest_));
  PutU32(out, static_cast<uint32_t>(recent_calls_.size()));
  for (const auto& rec : recent_calls_) {
    PutI64(out, static_cast<int64_t>(rec.seq));
    PutU8(out, rec.op);
    PutU8(out, rec.dtype);
    PutU8(out, rec.ndim);
    PutStr(out, rec.name);
  }
  PutU32(out, static_cast<uint32_t>(metrics_summary_.size()));
  for (double v : metrics_summary_) PutF64(out, v);
}

bool RequestList::ParseFrom(const char* data, std::size_t len) {
  Reader r(data, len);
  uint8_t sd;
  uint32_t n;
  if (!r.GetU8(&sd) || !r.GetU32(&n)) return false;
  shutdown_ = sd != 0;
  requests_.clear();
  std::size_t off = r.consumed(data);
  for (uint32_t i = 0; i < n; ++i) {
    Request req;
    std::size_t used = req.ParseFrom(data + off, len - off);
    if (used == 0) return false;
    off += used;
    requests_.push_back(std::move(req));
  }
  Reader tail(data + off, len - off);
  int64_t seq, digest;
  uint32_t nrec;
  if (!tail.GetI64(&seq) || !tail.GetI64(&digest) || !tail.GetU32(&nrec))
    return false;
  call_seq_ = static_cast<uint64_t>(seq);
  call_digest_ = static_cast<uint64_t>(digest);
  recent_calls_.clear();
  for (uint32_t i = 0; i < nrec; ++i) {
    CallRecord rec;
    int64_t rseq;
    if (!tail.GetI64(&rseq) || !tail.GetU8(&rec.op) ||
        !tail.GetU8(&rec.dtype) || !tail.GetU8(&rec.ndim) ||
        !tail.GetStr(&rec.name))
      return false;
    rec.seq = static_cast<uint64_t>(rseq);
    recent_calls_.push_back(std::move(rec));
  }
  // Metrics summary tail: absent on a short (older-writer) blob — treat
  // as "no summary attached", not a parse error.
  metrics_summary_.clear();
  uint32_t nsum;
  if (tail.GetU32(&nsum)) {
    for (uint32_t i = 0; i < nsum; ++i) {
      double v;
      if (!tail.GetF64(&v)) return false;
      metrics_summary_.push_back(v);
    }
  }
  return true;
}

std::string Response::tensor_names_string() const {
  std::ostringstream os;
  for (std::size_t i = 0; i < tensor_names_.size(); ++i) {
    if (i > 0) os << ", ";
    os << tensor_names_[i];
  }
  return os.str();
}

void Response::SerializeTo(std::string* out) const {
  PutU8(out, static_cast<uint8_t>(response_type_));
  PutU8(out, static_cast<uint8_t>(tensor_type_));
  PutU8(out, compression_);
  PutU32(out, group_id_);
  PutI32(out, devices_);
  PutStr(out, error_message_);
  PutU32(out, static_cast<uint32_t>(tensor_names_.size()));
  for (const auto& n : tensor_names_) PutStr(out, n);
  PutU32(out, static_cast<uint32_t>(tensor_sizes_.size()));
  for (int64_t s : tensor_sizes_) PutI64(out, s);
}

std::size_t Response::ParseFrom(const char* data, std::size_t len) {
  Reader r(data, len);
  uint8_t rt, tt;
  uint32_t nn, ns;
  if (!r.GetU8(&rt) || !r.GetU8(&tt) || !r.GetU8(&compression_) ||
      !r.GetU32(&group_id_) || !r.GetI32(&devices_) ||
      !r.GetStr(&error_message_) || !r.GetU32(&nn))
    return 0;
  response_type_ = static_cast<ResponseType>(rt);
  tensor_type_ = static_cast<DataType>(tt);
  tensor_names_.clear();
  for (uint32_t i = 0; i < nn; ++i) {
    std::string s;
    if (!r.GetStr(&s)) return 0;
    tensor_names_.push_back(std::move(s));
  }
  if (!r.GetU32(&ns)) return 0;
  tensor_sizes_.clear();
  for (uint32_t i = 0; i < ns; ++i) {
    int64_t v;
    if (!r.GetI64(&v)) return 0;
    tensor_sizes_.push_back(v);
  }
  return r.consumed(data);
}

void ResponseList::SerializeTo(std::string* out) const {
  PutU8(out, shutdown_ ? 1 : 0);
  PutU32(out, static_cast<uint32_t>(responses_.size()));
  for (const auto& resp : responses_) resp.SerializeTo(out);
  PutI64(out, static_cast<int64_t>(autotune_wire_));
  // Clock-alignment tail (after the autotune word; same
  // forward-compatibility rule — older decoders ignore it).
  PutI64(out, clock_t2_);
  PutI64(out, clock_t3_);
  PutU8(out, trace_flags_);
}

bool ResponseList::ParseFrom(const char* data, std::size_t len) {
  Reader r(data, len);
  uint8_t sd;
  uint32_t n;
  if (!r.GetU8(&sd) || !r.GetU32(&n)) return false;
  shutdown_ = sd != 0;
  responses_.clear();
  std::size_t off = r.consumed(data);
  for (uint32_t i = 0; i < n; ++i) {
    Response resp;
    std::size_t used = resp.ParseFrom(data + off, len - off);
    if (used == 0) return false;
    off += used;
    responses_.push_back(std::move(resp));
  }
  // Autotune bootstrap tail: absent on a short (older-writer) blob —
  // "no information", not a parse error.
  Reader tail(data + off, len - off);
  int64_t wire;
  autotune_wire_ = tail.GetI64(&wire) ? static_cast<uint64_t>(wire)
                                      : kAutotuneAbsent;
  // Clock-alignment tail (trace.h): continue reading the same tail —
  // absent on a pre-trace writer's blob means "no sample", not an
  // error.
  int64_t t2, t3;
  uint8_t tf;
  if (tail.GetI64(&t2) && tail.GetI64(&t3)) {
    clock_t2_ = t2;
    clock_t3_ = t3;
  } else {
    clock_t2_ = -1;
    clock_t3_ = -1;
  }
  trace_flags_ = tail.GetU8(&tf) ? tf : 0;
  return true;
}

}  // namespace hvdtpu
