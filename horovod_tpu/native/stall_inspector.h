// Coordinator-side distributed-straggler detector: records when each tensor
// was first requested and by which ranks; warns when a tensor has been
// waiting on missing ranks longer than the check interval, and optionally
// triggers a coordinated shutdown past the shutdown threshold.
//
// Capability parity with /root/reference horovod/common/stall_inspector.{h,cc}.
#ifndef HVD_TPU_STALL_INSPECTOR_H
#define HVD_TPU_STALL_INSPECTOR_H

#include <chrono>
#include <cstdint>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace hvdtpu {

class ResponseCache;

class StallInspector {
 public:
  void SetStallWarningTimeSeconds(int seconds) { warning_seconds_ = seconds; }
  void SetStallShutdownTimeSeconds(int seconds) { shutdown_seconds_ = seconds; }
  int stall_warning_time_seconds() const { return warning_seconds_; }
  int stall_shutdown_time_seconds() const { return shutdown_seconds_; }

  // Coordinator: a rank announced readiness for this tensor. `members`,
  // when non-null, scopes the tensor to a process group — only those
  // ranks are ever reported missing (docs/GROUPS.md).
  void RecordUncachedTensorStart(const std::string& tensor_name, int rank,
                                 int global_size,
                                 const std::vector<int>* members = nullptr);
  // Coordinator: tensor completed negotiation — forget it.
  void RemoveUncachedTensor(const std::string& tensor_name);

  // Worker-side accounting for cached tensors (they bypass the coordinator).
  void RecordCachedTensorStart(const std::string& tensor_name);
  void RemoveCachedTensor(const std::string& tensor_name);

  // Scans for stalls; logs warnings listing missing ranks. Returns true if
  // the shutdown threshold was crossed (caller propagates shutdown).
  bool CheckForStalledTensors(int global_size);
  // Invalidates cache entries for stalled cached tensors so they renegotiate;
  // fills `invalid_bits` for the cache coordinator.
  void InvalidateStalledCachedTensors(ResponseCache& cache,
                                      std::vector<uint32_t>& invalid_bits);

  bool ShouldPerformCheck();
  void UpdateCheckTime();

 private:
  using Clock = std::chrono::steady_clock;
  int warning_seconds_ = 60;
  int shutdown_seconds_ = 0;  // 0 = never shut down
  // name -> (first-request time, set of ready ranks, expected member
  // ranks — empty = every rank in 0..global_size)
  struct Uncached {
    Clock::time_point first;
    std::unordered_set<int> ready;
    std::vector<int> members;
  };
  std::unordered_map<std::string, Uncached> uncached_;
  std::unordered_map<std::string, Clock::time_point> cached_;
  Clock::time_point last_check_ = Clock::now();
  // Missing-rank sets already warned about, with repeat counts: identical
  // sets across consecutive checks log one compact line instead of the
  // full per-tensor listing (spam rate-limit; suppressed repeats still
  // count into the stall_warnings_total metric).
  std::unordered_map<std::string, uint64_t> warned_sets_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_STALL_INSPECTOR_H
