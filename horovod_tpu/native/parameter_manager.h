// Autotuner for the runtime knobs that govern negotiation efficiency:
//   - tensor fusion threshold (MB, continuous in [0, 64])
//   - cycle time (ms, continuous in [1, 100])
//   - response cache enabled (categorical)
//   - hierarchical allreduce / allgather (categorical)
// Joint search: for each categorical combination, Bayesian optimization
// (Gaussian process + expected improvement) over the two continuous knobs.
// Score = bytes processed per microsecond over a sampling window; warmup
// discards the first samples. Best parameters are broadcast from rank 0 via
// Controller::SynchronizeParameters.
//
// Capability parity with /root/reference
// horovod/common/parameter_manager.{h,cc} + optim/bayesian_optimization.cc;
// fresh implementation with hand-rolled small-matrix GP math (no Eigen).
#ifndef HVD_TPU_PARAMETER_MANAGER_H
#define HVD_TPU_PARAMETER_MANAGER_H

#include <array>
#include <cstdint>
#include <fstream>
#include <memory>
#include <string>
#include <vector>

namespace hvdtpu {

class BayesianOptimizer;

class ParameterManager {
 public:
  ParameterManager();
  ~ParameterManager();

  void Initialize(int32_t rank, const std::string& autotune_log_file);
  void SetAutoTuning(bool active);
  bool IsAutoTuning() const { return active_; }

  int64_t TensorFusionThresholdBytes() const;
  void SetTensorFusionThresholdBytes(int64_t threshold, bool fixed = false);
  double CycleTimeMs() const;
  void SetCycleTimeMs(double cycle_time_ms, bool fixed = false);
  bool CacheEnabled() const;
  void SetCacheEnabled(bool enabled, bool fixed = false);
  bool HierarchicalAllreduce() const;
  void SetHierarchicalAllreduce(bool enabled, bool fixed = false);
  bool HierarchicalAllgather() const;
  void SetHierarchicalAllgather(bool enabled, bool fixed = false);

  // Called once per cycle with the bytes negotiated+executed this cycle.
  // Returns true when tuned parameter values changed (caller re-syncs ranks).
  bool Update(const std::vector<std::string>& tensor_names, int64_t bytes);

  // POD snapshot for cross-rank parameter broadcast.
  struct Params {
    double fusion_mb;
    double cycle_time_ms;
    uint8_t cache_enabled;
    uint8_t hierarchical_allreduce;
    uint8_t hierarchical_allgather;
    uint8_t active;
  };
  Params GetParams() const;
  void SetParams(const Params& p);

 private:
  bool Tune(double score);
  void ReadyTune();
  void LogSample(double score);

  // Current values.
  double fusion_mb_ = 64.0;
  double cycle_time_ms_ = 5.0;
  bool cache_enabled_ = true;
  bool hierarchical_allreduce_ = false;
  bool hierarchical_allgather_ = false;

  // Fixed-by-env flags exclude a knob from tuning.
  bool fusion_fixed_ = false;
  bool cycle_fixed_ = false;
  bool cache_fixed_ = false;
  bool hier_ar_fixed_ = false;
  bool hier_ag_fixed_ = false;

  bool active_ = false;
  int32_t rank_ = -1;
  int warmup_remaining_ = 3;
  int cycles_in_sample_ = 0;
  int64_t bytes_in_sample_ = 0;
  double sample_start_us_ = 0.0;
  int sample_count_ = 0;
  static constexpr int kCyclesPerSample = 10;
  static constexpr int kMaxSamples = 40;

  // Best seen.
  double best_score_ = 0.0;
  double best_fusion_mb_ = 64.0;
  double best_cycle_ms_ = 5.0;
  bool best_cache_ = true;
  bool best_hier_ar_ = false;
  bool best_hier_ag_ = false;

  // Categorical sweep state: index into combos; each combo gets its own BO.
  std::vector<std::array<bool, 3>> categorical_combos_;
  std::size_t combo_index_ = 0;
  int samples_in_combo_ = 0;
  static constexpr int kSamplesPerCombo = 10;

  std::vector<std::unique_ptr<BayesianOptimizer>> optimizers_;
  std::ofstream log_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_PARAMETER_MANAGER_H
