// Always-on closed-loop autotuner for the runtime knobs that govern
// negotiation and data-plane efficiency:
//   - tensor fusion threshold (MB, continuous in [0, 64])
//   - cycle time (ms, continuous in [1, 100])
//   - pipelined-ring chunk size (KB, continuous; bounds shrink when wire
//     compression is active — the wire payload per element shrinks, so
//     smaller slices saturate the socket)
//   - response cache enabled (categorical)
//   - hierarchical allreduce / allgather / reduce-scatter (categorical;
//     collapsed on flat topologies, and the reduce-scatter knob only
//     opens once the job actually executes reduce-scatters)
// Joint search: for each categorical combination, Bayesian optimization
// (Gaussian process + expected improvement) over the continuous knobs.
// Score = bytes processed per microsecond over a sampling window; warmup
// discards the first samples. Best parameters are broadcast from rank 0
// via Controller::SynchronizeParameters.
//
// Closed loop (docs/AUTOTUNE.md): after convergence the manager keeps
// watching the per-cycle bytes/tensors distributions; when the workload
// drifts past HVD_TPU_AUTOTUNE_DRIFT of the converged baseline (or the
// job's capability profile changes — compression engages, reduce-scatter
// appears), it RE-ARMS. The re-arm is bootstrapped through the
// ResponseList wire (a (epoch, profile) tail on the next full-cycle
// broadcast) so every rank re-enters tuning at the same cycle; elastic
// re-initialization re-arms naturally because tuning defaults on.
//
// Concurrency: all tuning decisions happen on the background
// coordination thread. A single mutex makes the knob reads/writes safe
// against the C snapshot API (horovod_tpu_autotune_json), which any
// thread may call at any time.
//
// Capability parity with /root/reference
// horovod/common/parameter_manager.{h,cc} + optim/bayesian_optimization.cc;
// fresh implementation with hand-rolled small-matrix GP math (no Eigen).
#ifndef HVD_TPU_PARAMETER_MANAGER_H
#define HVD_TPU_PARAMETER_MANAGER_H

#include <array>
#include <cstdint>
#include <fstream>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace hvdtpu {

class BayesianOptimizer;

class ParameterManager {
 public:
  ParameterManager();
  ~ParameterManager();

  void Initialize(int32_t rank, const std::string& autotune_log_file);
  void SetAutoTuning(bool active);
  bool IsAutoTuning() const;

  int64_t TensorFusionThresholdBytes() const;
  void SetTensorFusionThresholdBytes(int64_t threshold, bool fixed = false);
  double CycleTimeMs() const;
  void SetCycleTimeMs(double cycle_time_ms, bool fixed = false);
  bool CacheEnabled() const;
  void SetCacheEnabled(bool enabled, bool fixed = false);
  bool HierarchicalAllreduce() const;
  void SetHierarchicalAllreduce(bool enabled, bool fixed = false);
  bool HierarchicalAllgather() const;
  void SetHierarchicalAllgather(bool enabled, bool fixed = false);
  bool HierarchicalReduceScatter() const;
  void SetHierarchicalReduceScatter(bool enabled, bool fixed = false);
  // Shared-memory transport for intra-host ring legs (docs/TRANSPORT.md):
  // categorical auto/on/off — HVD_TPU_SHM=0/1 pins it, unset leaves it
  // to the tuner (default on). Applied cycle-synchronized via
  // TcpContext::SetShmUse; the dimension only opens in the search space
  // when the topology is shm-capable (profile bit, like
  // reduce-scatter's).
  bool ShmTransport() const;
  void SetShmTransport(bool enabled, bool fixed = false);
  // Pipelined-ring segment size in bytes (0 = slicing disabled). The
  // data-plane ops read this per execution; the tuner searches it in KB.
  int64_t PipelineChunkBytes() const;
  void SetPipelineChunkBytes(int64_t bytes, bool fixed = false);

  // Capability profile of the running job, observed by the coordinator
  // from negotiated responses (and seeded from env before the first
  // cycle). A profile change after convergence triggers a re-arm so the
  // search space is rebuilt compression-, sharded-update-, and
  // group-aware (a first subgroup collective changes the traffic mix
  // the knobs were scored under — the tuner must re-score under it).
  void ObserveWorkload(bool compression_active, bool reduce_scatter_active,
                       bool groups_active = false,
                       bool shm_capable = false);

  // Called once per cycle on the coordinator with the tensors/bytes the
  // cycle executed. Advances sampling while tuning; tracks workload
  // drift while converged (re-arming past the threshold). Returns true
  // when tuned parameter values changed (caller re-syncs ranks).
  bool Update(int64_t tensors, int64_t bytes);

  // --- closed-loop re-arm protocol (controller.cc) ---
  // True while a re-arm awaits its wire bootstrap; the coordinator
  // forces full negotiation cycles until delivered.
  bool RearmPending() const;
  // Coordinator, at full-cycle serialize time: consume a pending re-arm
  // (bump epoch, rebuild the search space, apply the first sample).
  // Always returns the current wire word: (epoch << 8) | profile bits.
  uint64_t WireEpochForBroadcast();
  // Worker, at full-cycle parse time: adopt a changed wire word — apply
  // the profile and re-enter tuning at the same cycle the coordinator
  // did. The search-space rebuild and first sample are deterministic
  // (fixed seeds), so every rank lands on identical knob values.
  void NoteWireEpoch(uint64_t wire);

  uint32_t rearm_epoch() const;
  uint64_t rearms_total() const;

  // POD snapshot for cross-rank parameter broadcast.
  struct Params {
    double fusion_mb;
    double cycle_time_ms;
    double pipeline_chunk_kb;
    uint8_t cache_enabled;
    uint8_t hierarchical_allreduce;
    uint8_t hierarchical_allgather;
    uint8_t hierarchical_reduce_scatter;
    uint8_t shm_transport;
    uint8_t active;
  };
  Params GetParams() const;
  void SetParams(const Params& p);

  // Live tuner state as JSON (the horovod_tpu_autotune_json C export →
  // hvd.autotune()). Safe from any thread.
  std::string Json() const;

 private:
  bool Tune(double score);
  void ReadyTune();
  void LogSample(double score, const char* event);
  void BuildSearchSpace();  // combos + optimizers from profile/fixed flags
  void Arm();               // reset sampling state, BuildSearchSpace, ReadyTune
  Params GetParamsLocked() const;
  bool TriggerRearm(const char* reason);

  mutable std::mutex mu_;

  // Current values.
  double fusion_mb_ = 64.0;           // guarded_by(mu_)
  double cycle_time_ms_ = 5.0;        // guarded_by(mu_)
  double pipeline_chunk_kb_ = 1024.0; // guarded_by(mu_)
  bool cache_enabled_ = true;
  bool hierarchical_allreduce_ = false;
  bool hierarchical_allgather_ = false;
  bool hierarchical_reduce_scatter_ = false;
  bool shm_transport_ = true;

  // Fixed-by-env flags exclude a knob from tuning.
  bool fusion_fixed_ = false;
  bool cycle_fixed_ = false;
  bool pipeline_fixed_ = false;
  bool cache_fixed_ = false;
  bool hier_ar_fixed_ = false;
  bool hier_ag_fixed_ = false;
  bool hier_rs_fixed_ = false;
  bool shm_fixed_ = false;

  // Workload profile (search-space shaping + re-arm trigger).
  bool profile_compression_ = false;
  bool profile_reduce_scatter_ = false;
  bool profile_groups_ = false;
  bool profile_shm_ = false;

  bool active_ = false;
  int32_t rank_ = -1;
  uint64_t seed_salt_ = 0;  // elastic generation, set at Initialize
  int warmup_remaining_ = 3;
  int cycles_in_sample_ = 0;
  int64_t bytes_in_sample_ = 0;
  double sample_start_us_ = 0.0;
  int sample_count_ = 0;
  // Sampling pace (env-overridable for tests/bench: see Initialize).
  int cycles_per_sample_ = 10;
  int max_samples_ = 40;
  int warmup_samples_ = 3;

  // Best seen (this arm).
  double best_score_ = 0.0;
  double best_fusion_mb_ = 64.0;
  double best_cycle_ms_ = 5.0;
  double best_pipeline_kb_ = 1024.0;
  bool best_cache_ = true;
  bool best_hier_ar_ = false;
  bool best_hier_ag_ = false;
  bool best_hier_rs_ = false;
  bool best_shm_ = true;

  // Categorical sweep state: index into combos; each combo gets its own
  // BO over the continuous knobs (cache, hier_ar, hier_ag, hier_rs,
  // shm_transport).
  std::vector<std::array<bool, 5>> categorical_combos_;
  std::size_t combo_index_ = 0;
  int samples_in_combo_ = 0;
  int samples_per_combo_ = 10;

  // --- closed loop ---
  // Converged-workload baseline (work cycles only) + rolling window.
  double baseline_bytes_per_cycle_ = 0.0;
  double baseline_tensors_per_cycle_ = 0.0;
  // First post-convergence window captures the baseline (knobs-
  // consistent measurement) instead of checking drift against it.
  bool baseline_pending_ = false;
  int64_t drift_bytes_acc_ = 0;
  int64_t drift_tensors_acc_ = 0;
  int drift_cycles_acc_ = 0;
  int drift_window_cycles_ = 40;
  double drift_threshold_ = 2.0;  // re-arm past x2 / below 1/x2
  bool rearm_pending_ = false;
  bool armed_once_ = false;
  uint32_t rearm_epoch_ = 0;
  uint64_t rearms_total_ = 0;
  std::string last_rearm_reason_;

  std::vector<std::unique_ptr<BayesianOptimizer>> optimizers_;
  std::ofstream log_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_PARAMETER_MANAGER_H
