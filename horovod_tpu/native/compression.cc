#include "compression.h"

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstring>
#include <limits>
#include <string>

#include "half.h"
#include "metrics.h"
#include "trace.h"

namespace hvdtpu {

const char* CompressionModeName(CompressionMode m) {
  switch (m) {
    case CompressionMode::NONE: return "none";
    case CompressionMode::BF16: return "bf16";
    case CompressionMode::INT8: return "int8";
  }
  return "unknown";
}

CompressionMode ParseCompressionMode(const char* s) {
  if (s == nullptr) return CompressionMode::NONE;
  // Case-insensitive to match the Python resolver's .lower() — the env
  // default must mean the same thing on every binding.
  std::string v(s);
  for (char& c : v) c = static_cast<char>(std::tolower(c));
  if (v == "bf16" || v == "1") return CompressionMode::BF16;
  if (v == "int8" || v == "2") return CompressionMode::INT8;
  return CompressionMode::NONE;
}

CompressionMode EffectiveCompression(CompressionMode m, DataType dtype) {
  return dtype == DataType::HVD_FLOAT32 ? m : CompressionMode::NONE;
}

std::size_t CompressedSize(int64_t count, CompressionMode mode) {
  switch (mode) {
    case CompressionMode::NONE:
      return static_cast<std::size_t>(count) * sizeof(float);
    case CompressionMode::BF16:
      return static_cast<std::size_t>(count) * sizeof(uint16_t);
    case CompressionMode::INT8: {
      int64_t nblocks =
          (count + kCompressionBlock - 1) / kCompressionBlock;
      return static_cast<std::size_t>(nblocks) * sizeof(float) +
             static_cast<std::size_t>(count);
    }
  }
  return static_cast<std::size_t>(count) * sizeof(float);
}

namespace {

void CountCodecWork(CompressionMode mode, int64_t count,
                    std::size_t wire_bytes, double seconds, bool compress) {
  Metrics& m = GlobalMetrics();
  if (compress) {
    m.compression_bytes_in_total.fetch_add(
        static_cast<uint64_t>(count) * sizeof(float),
        std::memory_order_relaxed);
    m.compression_bytes_out_total.fetch_add(
        static_cast<uint64_t>(wire_bytes), std::memory_order_relaxed);
    if (mode == CompressionMode::BF16) {
      m.compression_bf16_total.fetch_add(1, std::memory_order_relaxed);
    } else if (mode == CompressionMode::INT8) {
      m.compression_int8_total.fetch_add(1, std::memory_order_relaxed);
    }
  }
  m.compression_seconds.Observe(seconds);
  // Codec span (trace.h): records from the worker threads the pipelined
  // ring runs codecs on — the ring write is lock-free and thread-safe.
  Trace& t = GlobalTrace();
  if (t.enabled()) {
    const int64_t end_ns = t.NowNs();
    t.Record(compress ? "encode" : "decode",
             compress ? TRACE_ENCODE : TRACE_DECODE,
             end_ns - static_cast<int64_t>(seconds * 1e9), end_ns,
             static_cast<int64_t>(wire_bytes));
  }
}

}  // namespace

void CompressBuffer(const float* src, int64_t count, CompressionMode mode,
                    char* dst) {
  auto t0 = std::chrono::steady_clock::now();
  switch (mode) {
    case CompressionMode::NONE:
      std::memcpy(dst, src, static_cast<std::size_t>(count) * sizeof(float));
      return;  // not a codec op; no metrics
    case CompressionMode::BF16: {
      auto* out = reinterpret_cast<uint16_t*>(dst);
      for (int64_t i = 0; i < count; ++i) out[i] = FloatToBFloat16(src[i]);
      break;
    }
    case CompressionMode::INT8: {
      int64_t nblocks =
          (count + kCompressionBlock - 1) / kCompressionBlock;
      auto* scales = reinterpret_cast<float*>(dst);
      auto* q = reinterpret_cast<int8_t*>(dst + nblocks * sizeof(float));
      for (int64_t b = 0; b < nblocks; ++b) {
        int64_t lo = b * kCompressionBlock;
        int64_t hi = std::min(lo + kCompressionBlock, count);
        float amax = 0.0f;
        bool finite = true;
        for (int64_t i = lo; i < hi; ++i) {
          float a = std::fabs(src[i]);
          if (!std::isfinite(a)) finite = false;
          amax = std::max(amax, a);
        }
        // Symmetric [-127, 127]: -128 is never produced, so dequant is
        // sign-symmetric and |x - scale*q| <= scale/2 within the block.
        // A nonfinite input (overflowed mixed-precision gradient) makes
        // the IN-BAND SCALE NaN, so the whole block decodes nonfinite —
        // downstream isfinite / loss-scale skip-step guards still fire
        // instead of silently training on a finite-ized block.
        float scale = !finite ? std::numeric_limits<float>::quiet_NaN()
                              : (amax > 0.0f ? amax / 127.0f : 0.0f);
        scales[b] = scale;
        float inv = scale > 0.0f ? 1.0f / scale : 0.0f;
        for (int64_t i = lo; i < hi; ++i) {
          float v = src[i] * inv;
          v = std::max(-127.0f, std::min(127.0f, v));
          q[i] = static_cast<int8_t>(std::lrintf(v));
        }
      }
      break;
    }
  }
  CountCodecWork(mode, count, CompressedSize(count, mode),
                 std::chrono::duration<double>(
                     std::chrono::steady_clock::now() - t0)
                     .count(),
                 /*compress=*/true);
}

void DecompressBuffer(const char* src, int64_t count, CompressionMode mode,
                      float* dst) {
  auto t0 = std::chrono::steady_clock::now();
  switch (mode) {
    case CompressionMode::NONE:
      std::memcpy(dst, src, static_cast<std::size_t>(count) * sizeof(float));
      return;
    case CompressionMode::BF16: {
      const auto* in = reinterpret_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) dst[i] = BFloat16ToFloat(in[i]);
      break;
    }
    case CompressionMode::INT8: {
      int64_t nblocks =
          (count + kCompressionBlock - 1) / kCompressionBlock;
      const auto* scales = reinterpret_cast<const float*>(src);
      const auto* q =
          reinterpret_cast<const int8_t*>(src + nblocks * sizeof(float));
      for (int64_t b = 0; b < nblocks; ++b) {
        int64_t lo = b * kCompressionBlock;
        int64_t hi = std::min(lo + kCompressionBlock, count);
        float scale = scales[b];
        for (int64_t i = lo; i < hi; ++i) {
          dst[i] = static_cast<float>(q[i]) * scale;
        }
      }
      break;
    }
  }
  CountCodecWork(mode, count, CompressedSize(count, mode),
                 std::chrono::duration<double>(
                     std::chrono::steady_clock::now() - t0)
                     .count(),
                 /*compress=*/false);
}

void DecompressAccumulate(const char* src, int64_t count,
                          CompressionMode mode, float* dst) {
  auto t0 = std::chrono::steady_clock::now();
  switch (mode) {
    case CompressionMode::NONE: {
      const auto* in = reinterpret_cast<const float*>(src);
      for (int64_t i = 0; i < count; ++i) dst[i] += in[i];
      return;  // not a codec op; no metrics
    }
    case CompressionMode::BF16: {
      const auto* in = reinterpret_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) dst[i] += BFloat16ToFloat(in[i]);
      break;
    }
    case CompressionMode::INT8: {
      int64_t nblocks =
          (count + kCompressionBlock - 1) / kCompressionBlock;
      const auto* scales = reinterpret_cast<const float*>(src);
      const auto* q =
          reinterpret_cast<const int8_t*>(src + nblocks * sizeof(float));
      for (int64_t b = 0; b < nblocks; ++b) {
        int64_t lo = b * kCompressionBlock;
        int64_t hi = std::min(lo + kCompressionBlock, count);
        float scale = scales[b];
        for (int64_t i = lo; i < hi; ++i) {
          dst[i] += static_cast<float>(q[i]) * scale;
        }
      }
      break;
    }
  }
  CountCodecWork(mode, count, CompressedSize(count, mode),
                 std::chrono::duration<double>(
                     std::chrono::steady_clock::now() - t0)
                     .count(),
                 /*compress=*/false);
}

}  // namespace hvdtpu
