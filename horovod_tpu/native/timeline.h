// Chrome-tracing (about://tracing) JSON timeline of every tensor's lifecycle
// (NEGOTIATING -> TOP_LEVEL -> per-op ACTIVITY), written by a dedicated
// writer thread fed through a bounded lock-free-ish MPSC queue so the
// coordination loop never blocks on file IO. Rank 0 only.
//
// Capability parity with /root/reference horovod/common/timeline.{h,cc}
// (which uses a boost spsc_queue + writer thread); this implementation uses a
// mutex-guarded ring buffer — contention is negligible at the event rates
// involved and it keeps the build dependency-free.
//
// Env: HVD_TPU_TIMELINE=<path>, HVD_TPU_TIMELINE_MARK_CYCLES=1.
#ifndef HVD_TPU_TIMELINE_H
#define HVD_TPU_TIMELINE_H

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "message.h"

namespace hvdtpu {

enum class TimelineRecordType : uint8_t {
  EVENT = 0,
  MARKER = 1,
};

struct TimelineRecord {
  TimelineRecordType record_type;
  std::string tensor_name;
  char phase;  // 'B' begin, 'E' end, 'X' complete, 'i' instant
  std::string op_name;
  std::string args;
  int64_t ts_us;
};

class TimelineWriter {
 public:
  void Initialize(const std::string& file_name);
  void Shutdown();
  // Fatal-signal best effort: terminate the JSON array in place, no
  // locks, no thread join — the process is about to die and an
  // unterminated file helps nobody (operations.cc FatalSignalHandler).
  void EmergencyFinalize();
  bool active() const { return active_.load(); }
  void EnqueueWriteEvent(const std::string& tensor_name, char phase,
                         const std::string& op_name, const std::string& args,
                         int64_t ts_us);
  void EnqueueWriteMarker(const std::string& name, int64_t ts_us);

 private:
  void WriterLoop();
  void BeginRecord();
  void DoWriteEvent(const TimelineRecord& r);
  void DoWriteMarker(const TimelineRecord& r);

  std::atomic<bool> active_{false};
  std::atomic<bool> shutdown_{false};
  // Comma-before-record state; writer thread only (Shutdown touches it
  // after the join).
  bool first_record_ = true;
  std::FILE* file_ = nullptr;
  std::thread writer_thread_;
  std::mutex mutex_;
  std::condition_variable cv_;
  std::deque<TimelineRecord> queue_;  // guarded_by(mutex_)
  // tensor name -> stable integer "pid" for chrome tracing rows.
  std::unordered_map<std::string, int> tensor_table_;
  int next_tensor_id_ = 0;
};

enum class TimelineState : uint8_t {
  UNKNOWN = 0,
  NEGOTIATING = 1,
  TOP_LEVEL = 2,
  ACTIVITY = 3,
};

// Records state transitions for named tensors. Thread-compatible with the
// single background coordination thread plus enqueue threads (guarded).
class Timeline {
 public:
  void Initialize(const std::string& file_name, unsigned int rank);
  void Shutdown();
  void EmergencyFinalize();
  bool Initialized() const { return initialized_.load(); }

  void NegotiateStart(const std::string& tensor_name,
                      Request::RequestType request_type);
  void NegotiateRankReady(const std::string& tensor_name, int rank);
  void NegotiateEnd(const std::string& tensor_name);

  void Start(const std::string& tensor_name,
             Response::ResponseType response_type);
  void ActivityStartAll(const std::vector<std::string>& tensor_names,
                        const std::string& activity);
  void ActivityStart(const std::string& tensor_name,
                     const std::string& activity);
  void ActivityEndAll(const std::vector<std::string>& tensor_names);
  void ActivityEnd(const std::string& tensor_name);
  void End(const std::string& tensor_name, bool ok);

  void MarkCycleStart();
  void SetMarkCycles(bool v) { mark_cycles_ = v; }

 private:
  int64_t TimeSinceStartMicros() const;
  void WriteEvent(const std::string& tensor_name, char phase,
                  const std::string& op_name = "",
                  const std::string& args = "");

  std::atomic<bool> initialized_{false};
  bool mark_cycles_ = false;
  std::chrono::steady_clock::time_point start_time_;
  TimelineWriter writer_;
  std::recursive_mutex mutex_;
  std::unordered_map<std::string, TimelineState> tensor_states_;  // guarded_by(mutex_)
};

}  // namespace hvdtpu

#endif  // HVD_TPU_TIMELINE_H
