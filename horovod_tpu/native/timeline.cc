#include "timeline.h"

#include "logging.h"

namespace hvdtpu {

void TimelineWriter::Initialize(const std::string& file_name) {
  file_ = std::fopen(file_name.c_str(), "w");
  if (file_ == nullptr) {
    LOG(ERROR) << "Could not open " << file_name << " for timeline output; "
               << "timeline disabled.";
    return;
  }
  std::fputs("[", file_);
  first_record_ = true;
  active_.store(true);
  shutdown_.store(false);
  writer_thread_ = std::thread(&TimelineWriter::WriterLoop, this);
}

void TimelineWriter::Shutdown() {
  if (!active_.load()) return;
  {
    std::lock_guard<std::mutex> lk(mutex_);
    shutdown_.store(true);
  }
  cv_.notify_all();
  if (writer_thread_.joinable()) writer_thread_.join();
  active_.store(false);
  if (file_ != nullptr) {
    // Close the array so the file is strictly valid chrome-tracing JSON
    // (the record separators are comma-BEFORE, so there is no trailing
    // comma to strip). Only a clean shutdown guarantees validity; a
    // crashed run leaves an unterminated array, same as the reference.
    std::fputs("\n]\n", file_);
    std::fclose(file_);
    file_ = nullptr;
  }
}

void TimelineWriter::EmergencyFinalize() {
  // Signal context: mark inactive so enqueues stop, then close the array
  // directly. The writer thread may be mid-record — a torn tail is what
  // `hvd-trace --repair` exists for; an unterminated array is strictly
  // worse.
  if (!active_.exchange(false)) return;
  if (file_ != nullptr) {
    std::fputs("\n]\n", file_);
    std::fflush(file_);
  }
}

// Comma-before-record separation: every record is preceded by ",\n"
// except the first. Runs on the writer thread (and Shutdown after join),
// so first_record_ needs no lock.
void TimelineWriter::BeginRecord() {
  if (first_record_) {
    std::fputs("\n", file_);
    first_record_ = false;
  } else {
    std::fputs(",\n", file_);
  }
}

void TimelineWriter::EnqueueWriteEvent(const std::string& tensor_name,
                                       char phase, const std::string& op_name,
                                       const std::string& args, int64_t ts_us) {
  if (!active_.load()) return;
  {
    std::lock_guard<std::mutex> lk(mutex_);
    // Bound the queue so a wedged disk can't eat the heap (reference caps at
    // 1M records; we do the same and drop on overflow).
    if (queue_.size() >= 1000000) return;
    queue_.push_back(
        TimelineRecord{TimelineRecordType::EVENT, tensor_name, phase, op_name,
                       args, ts_us});
  }
  cv_.notify_one();
}

void TimelineWriter::EnqueueWriteMarker(const std::string& name,
                                        int64_t ts_us) {
  if (!active_.load()) return;
  {
    std::lock_guard<std::mutex> lk(mutex_);
    if (queue_.size() >= 1000000) return;
    queue_.push_back(TimelineRecord{TimelineRecordType::MARKER, "", 'i', name,
                                    "", ts_us});
  }
  cv_.notify_one();
}

static std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      default: out += c;
    }
  }
  return out;
}

void TimelineWriter::DoWriteEvent(const TimelineRecord& r) {
  auto it = tensor_table_.find(r.tensor_name);
  int tid;
  if (it == tensor_table_.end()) {
    tid = next_tensor_id_++;
    tensor_table_[r.tensor_name] = tid;
    // Metadata record names the row.
    BeginRecord();
    std::fprintf(file_,
                 "{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": %d, "
                 "\"args\": {\"name\": \"%s\"}}",
                 tid, JsonEscape(r.tensor_name).c_str());
    BeginRecord();
    std::fprintf(file_,
                 "{\"name\": \"process_sort_index\", \"ph\": \"M\", \"pid\": "
                 "%d, \"args\": {\"sort_index\": %d}}",
                 tid, tid);
  } else {
    tid = it->second;
  }
  if (r.phase == 'B') {
    BeginRecord();
    std::fprintf(file_,
                 "{\"name\": \"%s\", \"ph\": \"B\", \"ts\": %lld, \"pid\": "
                 "%d%s}",
                 JsonEscape(r.op_name).c_str(),
                 static_cast<long long>(r.ts_us), tid,
                 r.args.empty()
                     ? ""
                     : (", \"args\": {" + r.args + "}").c_str());
  } else if (r.phase == 'E') {
    BeginRecord();
    std::fprintf(file_, "{\"ph\": \"E\", \"ts\": %lld, \"pid\": %d}",
                 static_cast<long long>(r.ts_us), tid);
  } else if (r.phase == 'i') {
    BeginRecord();
    std::fprintf(file_,
                 "{\"name\": \"%s\", \"ph\": \"i\", \"ts\": %lld, \"pid\": %d, "
                 "\"s\": \"p\"}",
                 JsonEscape(r.op_name).c_str(),
                 static_cast<long long>(r.ts_us), tid);
  }
}

void TimelineWriter::DoWriteMarker(const TimelineRecord& r) {
  BeginRecord();
  std::fprintf(file_,
               "{\"name\": \"%s\", \"ph\": \"i\", \"ts\": %lld, \"pid\": -1, "
               "\"s\": \"g\"}",
               JsonEscape(r.op_name).c_str(), static_cast<long long>(r.ts_us));
}

void TimelineWriter::WriterLoop() {
  while (true) {
    std::deque<TimelineRecord> batch;
    {
      std::unique_lock<std::mutex> lk(mutex_);
      cv_.wait(lk, [&] { return !queue_.empty() || shutdown_.load(); });
      batch.swap(queue_);
      if (batch.empty() && shutdown_.load()) break;
    }
    for (const auto& r : batch) {
      if (r.record_type == TimelineRecordType::EVENT) {
        DoWriteEvent(r);
      } else {
        DoWriteMarker(r);
      }
    }
    std::fflush(file_);
  }
}

void Timeline::Initialize(const std::string& file_name, unsigned int rank) {
  if (initialized_.load() || rank != 0) return;
  start_time_ = std::chrono::steady_clock::now();
  writer_.Initialize(file_name);
  if (writer_.active()) initialized_.store(true);
}

void Timeline::Shutdown() {
  if (!initialized_.load()) return;
  writer_.Shutdown();
  initialized_.store(false);
}

void Timeline::EmergencyFinalize() {
  if (!initialized_.load()) return;
  writer_.EmergencyFinalize();
}

int64_t Timeline::TimeSinceStartMicros() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - start_time_)
      .count();
}

void Timeline::WriteEvent(const std::string& tensor_name, char phase,
                          const std::string& op_name, const std::string& args) {
  writer_.EnqueueWriteEvent(tensor_name, phase, op_name, args,
                            TimeSinceStartMicros());
}

void Timeline::NegotiateStart(const std::string& tensor_name,
                              Request::RequestType request_type) {
  if (!initialized_.load()) return;
  std::lock_guard<std::recursive_mutex> lk(mutex_);
  std::string event =
      std::string("NEGOTIATE_") + Request::RequestTypeName(request_type);
  WriteEvent(tensor_name, 'B', event);
  tensor_states_[tensor_name] = TimelineState::NEGOTIATING;
}

void Timeline::NegotiateRankReady(const std::string& tensor_name, int rank) {
  if (!initialized_.load()) return;
  std::lock_guard<std::recursive_mutex> lk(mutex_);
  WriteEvent(tensor_name, 'i', std::to_string(rank));
}

void Timeline::NegotiateEnd(const std::string& tensor_name) {
  if (!initialized_.load()) return;
  std::lock_guard<std::recursive_mutex> lk(mutex_);
  WriteEvent(tensor_name, 'E');
  tensor_states_.erase(tensor_name);
}

void Timeline::Start(const std::string& tensor_name,
                     Response::ResponseType response_type) {
  if (!initialized_.load()) return;
  std::lock_guard<std::recursive_mutex> lk(mutex_);
  WriteEvent(tensor_name, 'B', Response::ResponseTypeName(response_type));
  tensor_states_[tensor_name] = TimelineState::TOP_LEVEL;
}

void Timeline::ActivityStartAll(const std::vector<std::string>& tensor_names,
                                const std::string& activity) {
  for (const auto& n : tensor_names) ActivityStart(n, activity);
}

void Timeline::ActivityStart(const std::string& tensor_name,
                             const std::string& activity) {
  if (!initialized_.load()) return;
  std::lock_guard<std::recursive_mutex> lk(mutex_);
  WriteEvent(tensor_name, 'B', activity);
  tensor_states_[tensor_name] = TimelineState::ACTIVITY;
}

void Timeline::ActivityEndAll(const std::vector<std::string>& tensor_names) {
  for (const auto& n : tensor_names) ActivityEnd(n);
}

void Timeline::ActivityEnd(const std::string& tensor_name) {
  if (!initialized_.load()) return;
  std::lock_guard<std::recursive_mutex> lk(mutex_);
  WriteEvent(tensor_name, 'E');
  tensor_states_[tensor_name] = TimelineState::TOP_LEVEL;
}

void Timeline::End(const std::string& tensor_name, bool ok) {
  if (!initialized_.load()) return;
  std::lock_guard<std::recursive_mutex> lk(mutex_);
  // Close any open activity then the top-level span.
  auto it = tensor_states_.find(tensor_name);
  if (it != tensor_states_.end() && it->second == TimelineState::ACTIVITY) {
    WriteEvent(tensor_name, 'E');
  }
  WriteEvent(tensor_name, 'E', "",
             ok ? "" : "\"error\": true");
  tensor_states_.erase(tensor_name);
}

void Timeline::MarkCycleStart() {
  if (!initialized_.load() || !mark_cycles_) return;
  writer_.EnqueueWriteMarker("CYCLE_START", TimeSinceStartMicros());
}

}  // namespace hvdtpu
