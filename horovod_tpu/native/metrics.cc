#include "metrics.h"

#include <cmath>
#include <cstdio>
#include <sstream>

#include "trace.h"

namespace hvdtpu {

MetricHistogram::MetricHistogram(std::vector<double> bounds, double scale)
    : bounds_(std::move(bounds)),
      scale_(scale),
      counts_(new std::atomic<uint64_t>[bounds_.size() + 1]) {
  for (std::size_t i = 0; i <= bounds_.size(); ++i) counts_[i].store(0);
}

void MetricHistogram::Observe(double v) {
  std::size_t i = 0;
  while (i < bounds_.size() && v > bounds_[i]) ++i;
  counts_[i].fetch_add(1, std::memory_order_relaxed);
  sum_scaled_.fetch_add(static_cast<int64_t>(std::llround(v * scale_)),
                        std::memory_order_relaxed);
  count_.fetch_add(1, std::memory_order_relaxed);
}

MetricHistogram::Snapshot MetricHistogram::snapshot() const {
  Snapshot s;
  s.bounds = bounds_;
  s.counts.resize(bounds_.size() + 1);
  for (std::size_t i = 0; i <= bounds_.size(); ++i) {
    s.counts[i] = counts_[i].load(std::memory_order_relaxed);
  }
  s.sum = sum();
  s.count = count_.load(std::memory_order_relaxed);
  return s;
}

double MetricHistogram::sum() const {
  return static_cast<double>(sum_scaled_.load(std::memory_order_relaxed)) /
         scale_;
}

const char* SummaryFieldName(int field) {
  switch (field) {
    case SUM_CYCLES_TOTAL: return "cycles_total";
    case SUM_CYCLES_FAST: return "cycles_fast_total";
    case SUM_CYCLES_FULL: return "cycles_full_total";
    case SUM_CYCLE_SECONDS_SUM: return "cycle_seconds_sum";
    case SUM_TENSORS_ENQUEUED: return "tensors_enqueued_total";
    case SUM_TENSORS_PERFORMED: return "tensors_performed_total";
    case SUM_RESPONSES_PERFORMED: return "responses_performed_total";
    case SUM_BYTES_PERFORMED: return "bytes_performed_total";
    case SUM_FUSED_TENSORS: return "fused_tensors_total";
    case SUM_FUSED_BYTES: return "fused_bytes_total";
    case SUM_CACHE_HIT: return "cache_hit_total";
    case SUM_CACHE_MISS: return "cache_miss_total";
    case SUM_QUEUE_DEPTH: return "queue_depth";
    case SUM_STALL_WARNINGS: return "stall_warnings_total";
    case SUM_DIVERGENCE_ERRORS: return "divergence_errors_total";
    case SUM_NEGOTIATION_SECONDS_SUM: return "negotiation_seconds_sum";
    case SUM_NEGOTIATION_COUNT: return "negotiation_count";
    case SUM_NET_CRC_ERRORS: return "net_crc_errors_total";
    case SUM_NET_TIMEOUTS: return "net_timeouts_total";
    case SUM_NET_RECONNECTS: return "net_reconnects_total";
    case SUM_FAULTS_INJECTED: return "faults_injected_total";
    case SUM_CKPT_WRITES: return "ckpt_writes_total";
    case SUM_CKPT_WRITE_FAILURES: return "ckpt_write_failures_total";
    case SUM_LAST_DURABLE_STEP: return "last_durable_step";
    case SUM_COMPRESSION_BYTES_IN: return "compression_bytes_in_total";
    case SUM_COMPRESSION_BYTES_OUT: return "compression_bytes_out_total";
    case SUM_NET_RING_BYTES_SENT: return "net_ring_bytes_sent_total";
    case SUM_DRAINS_REQUESTED: return "drains_requested_total";
    case SUM_DRAINING: return "draining";
    case SUM_REDUCE_SCATTER: return "reduce_scatter_total";
    case SUM_OPT_STATE_BYTES: return "opt_state_bytes";
    case SUM_AUTOTUNE_ACTIVE: return "autotune_active";
    case SUM_AUTOTUNE_REARMS: return "autotune_rearms_total";
    case SUM_GROUPS: return "groups";
    case SUM_GROUP_TENSORS: return "group_tensors_total";
    case SUM_SHM_SEGMENTS: return "shm_segments_active";
    case SUM_SHM_BYTES_SENT: return "net_shm_bytes_sent_total";
    case SUM_TRACE_SPANS: return "trace_spans_total";
    case SUM_TRACE_SPANS_DROPPED: return "trace_spans_dropped_total";
    case SUM_BUNDLES_WRITTEN: return "bundles_written_total";
  }
  return "unknown";
}

// Bucket ladders: latencies cover 100us..10s (one cycle at default 5ms
// pacing up to a stall); tensors/bytes per cycle cover a lone scalar up
// to a full gradient bucket.
Metrics::Metrics()
    : cycle_seconds({1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
                     5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0},
                    1e6),
      negotiation_seconds({1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                           2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0},
                          1e6),
      cycle_tensors({1, 2, 4, 8, 16, 32, 64, 128, 256, 512}, 1.0),
      cycle_bytes({1024, 16384, 262144, 1048576, 4194304, 16777216, 67108864,
                   268435456},
                  1.0),
      fusion_fill_ratio({0.1, 0.25, 0.5, 0.75, 0.9, 1.0}, 1e6),
      // Durable writes run in a background thread against real storage:
      // 1ms (page-cache local disk) up to minutes (an overloaded object
      // store with injected slow-fsync faults).
      ckpt_write_seconds({1e-3, 5e-3, 2.5e-2, 0.1, 0.5, 1.0, 2.5, 5.0,
                          10.0, 30.0, 60.0, 120.0},
                         1e6),
      // One encode/decode call spans a ring chunk: ~us for KB chunks up
      // to ~100ms for a full 64MB fusion buffer on one core.
      compression_seconds({1e-6, 1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2,
                           5e-2, 0.1, 0.5, 1.0},
                          1e9) {}

void Metrics::Configure(int world_size_in, int rank_in) {
  world_size.store(world_size_in, std::memory_order_relaxed);
  rank.store(rank_in, std::memory_order_relaxed);
  queue_depth.store(0, std::memory_order_relaxed);
  pending_negotiation.store(0, std::memory_order_relaxed);
  opt_state_bytes.store(-1, std::memory_order_relaxed);
  // Groups are per-generation (the registry clears on re-init and
  // Python re-creates the mesh groups after it).
  groups.store(0, std::memory_order_relaxed);
  std::lock_guard<std::mutex> lk(rank_mutex_);
  is_coordinator_ = rank_in == 0;
  rank_lag_seconds_.assign(world_size_in, 0.0);
  rank_lag_count_.assign(world_size_in, 0);
  rank_summaries_.assign(world_size_in, {});
  rank_summary_time_.assign(world_size_in, Clock::time_point{});
}

void Metrics::AddRankLag(int r, double seconds) {
  std::lock_guard<std::mutex> lk(rank_mutex_);
  if (r < 0 || r >= static_cast<int>(rank_lag_seconds_.size())) return;
  rank_lag_seconds_[r] += seconds;
  rank_lag_count_[r] += 1;
}

std::vector<double> Metrics::Summary() const {
  std::vector<double> v(SUM_FIELD_COUNT, 0.0);
  v[SUM_CYCLES_TOTAL] = static_cast<double>(cycles_total.load());
  v[SUM_CYCLES_FAST] = static_cast<double>(cycles_fast_total.load());
  v[SUM_CYCLES_FULL] = static_cast<double>(cycles_full_total.load());
  v[SUM_CYCLE_SECONDS_SUM] = cycle_seconds.sum();
  v[SUM_TENSORS_ENQUEUED] = static_cast<double>(tensors_enqueued_total.load());
  v[SUM_TENSORS_PERFORMED] =
      static_cast<double>(tensors_performed_total.load());
  v[SUM_RESPONSES_PERFORMED] =
      static_cast<double>(responses_performed_total.load());
  v[SUM_BYTES_PERFORMED] = static_cast<double>(bytes_performed_total.load());
  v[SUM_FUSED_TENSORS] = static_cast<double>(fused_tensors_total.load());
  v[SUM_FUSED_BYTES] = static_cast<double>(fused_bytes_total.load());
  v[SUM_CACHE_HIT] = static_cast<double>(cache_hit_total.load());
  v[SUM_CACHE_MISS] = static_cast<double>(cache_miss_total.load());
  v[SUM_QUEUE_DEPTH] = static_cast<double>(queue_depth.load());
  v[SUM_STALL_WARNINGS] = static_cast<double>(stall_warnings_total.load());
  v[SUM_DIVERGENCE_ERRORS] =
      static_cast<double>(divergence_errors_total.load());
  v[SUM_NEGOTIATION_SECONDS_SUM] = negotiation_seconds.sum();
  v[SUM_NEGOTIATION_COUNT] =
      static_cast<double>(negotiation_seconds.count());
  v[SUM_NET_CRC_ERRORS] = static_cast<double>(net_crc_errors_total.load());
  v[SUM_NET_TIMEOUTS] =
      static_cast<double>(net_recv_timeouts_total.load() +
                          net_send_timeouts_total.load());
  v[SUM_NET_RECONNECTS] = static_cast<double>(net_reconnects_total.load());
  v[SUM_FAULTS_INJECTED] = static_cast<double>(faults_injected_total.load());
  v[SUM_CKPT_WRITES] = static_cast<double>(ckpt_writes_total.load());
  v[SUM_CKPT_WRITE_FAILURES] =
      static_cast<double>(ckpt_write_failures_total.load());
  v[SUM_LAST_DURABLE_STEP] = static_cast<double>(last_durable_step.load());
  v[SUM_COMPRESSION_BYTES_IN] =
      static_cast<double>(compression_bytes_in_total.load());
  v[SUM_COMPRESSION_BYTES_OUT] =
      static_cast<double>(compression_bytes_out_total.load());
  v[SUM_NET_RING_BYTES_SENT] =
      static_cast<double>(net_ring_bytes_sent_total.load());
  v[SUM_DRAINS_REQUESTED] =
      static_cast<double>(drains_requested_total.load());
  v[SUM_DRAINING] = static_cast<double>(draining.load());
  v[SUM_REDUCE_SCATTER] = static_cast<double>(reduce_scatter_total.load());
  v[SUM_OPT_STATE_BYTES] = static_cast<double>(opt_state_bytes.load());
  v[SUM_AUTOTUNE_ACTIVE] = static_cast<double>(autotune_active.load());
  v[SUM_AUTOTUNE_REARMS] =
      static_cast<double>(autotune_rearms_total.load());
  v[SUM_GROUPS] = static_cast<double>(groups.load());
  v[SUM_GROUP_TENSORS] = static_cast<double>(group_tensors_total.load());
  v[SUM_SHM_SEGMENTS] = static_cast<double>(shm_segments_active.load());
  v[SUM_SHM_BYTES_SENT] =
      static_cast<double>(net_shm_bytes_sent_total.load());
  {
    // The trace recorder owns its counters (trace.h); the summary wire
    // carries them like any registry field so hvd-top's `trc` column
    // and the job view see every rank's span/drop/bundle totals.
    const Trace& t = GlobalTrace();
    v[SUM_TRACE_SPANS] = static_cast<double>(t.spans_total.load());
    v[SUM_TRACE_SPANS_DROPPED] =
        static_cast<double>(t.spans_dropped.load());
    v[SUM_BUNDLES_WRITTEN] = static_cast<double>(t.bundles_written.load());
  }
  return v;
}

void Metrics::SetRankSummary(int r, const std::vector<double>& values) {
  std::lock_guard<std::mutex> lk(rank_mutex_);
  if (r < 0 || r >= static_cast<int>(rank_summaries_.size())) return;
  rank_summaries_[r] = values;
  rank_summary_time_[r] = Clock::now();
}

namespace {

// Integral values (the counters, which can pass 1e10 on a real job)
// render exactly via the integer path; everything else gets %.17g,
// enough digits for a lossless double round trip.
void AppendNum(std::string* out, double v) {
  char buf[40];
  if (v == static_cast<double>(static_cast<int64_t>(v)) &&
      std::fabs(v) < 9.2e18) {
    std::snprintf(buf, sizeof(buf), "%lld",
                  static_cast<long long>(v));
  } else {
    std::snprintf(buf, sizeof(buf), "%.17g", v);
  }
  out->append(buf);
}

void AppendKV(std::string* out, const char* key, double v, bool* first) {
  if (!*first) out->append(",");
  *first = false;
  out->append("\"");
  out->append(key);
  out->append("\":");
  AppendNum(out, v);
}

void AppendHistogram(std::string* out, const char* name,
                     const MetricHistogram& h, bool* first) {
  if (!*first) out->append(",");
  *first = false;
  MetricHistogram::Snapshot s = h.snapshot();
  out->append("\"");
  out->append(name);
  out->append("\":{\"bounds\":[");
  for (std::size_t i = 0; i < s.bounds.size(); ++i) {
    if (i) out->append(",");
    AppendNum(out, s.bounds[i]);
  }
  out->append("],\"counts\":[");
  for (std::size_t i = 0; i < s.counts.size(); ++i) {
    if (i) out->append(",");
    AppendNum(out, static_cast<double>(s.counts[i]));
  }
  out->append("],\"sum\":");
  AppendNum(out, s.sum);
  out->append(",\"count\":");
  AppendNum(out, static_cast<double>(s.count));
  out->append("}");
}

}  // namespace

std::string Metrics::SnapshotJson() const {
  std::string out;
  out.reserve(2048);
  out.append("{\"counters\":{");
  bool first = true;
  AppendKV(&out, "cycles_total", cycles_total.load(), &first);
  AppendKV(&out, "cycles_fast_total", cycles_fast_total.load(), &first);
  AppendKV(&out, "cycles_full_total", cycles_full_total.load(), &first);
  AppendKV(&out, "tensors_enqueued_total", tensors_enqueued_total.load(),
           &first);
  AppendKV(&out, "responses_performed_total", responses_performed_total.load(),
           &first);
  AppendKV(&out, "tensors_performed_total", tensors_performed_total.load(),
           &first);
  AppendKV(&out, "bytes_performed_total", bytes_performed_total.load(),
           &first);
  AppendKV(&out, "fused_tensors_total", fused_tensors_total.load(), &first);
  AppendKV(&out, "fused_bytes_total", fused_bytes_total.load(), &first);
  AppendKV(&out, "cache_hit_total", cache_hit_total.load(), &first);
  AppendKV(&out, "cache_miss_total", cache_miss_total.load(), &first);
  AppendKV(&out, "cache_invalid_total", cache_invalid_total.load(), &first);
  AppendKV(&out, "stall_warnings_total", stall_warnings_total.load(), &first);
  AppendKV(&out, "stall_missing_rank_seconds_total",
           static_cast<double>(stall_missing_rank_micros_total.load()) / 1e6,
           &first);
  AppendKV(&out, "divergence_errors_total", divergence_errors_total.load(),
           &first);
  AppendKV(&out, "error_responses_total", error_responses_total.load(),
           &first);
  AppendKV(&out, "init_total", init_total.load(), &first);
  AppendKV(&out, "net_crc_errors_total", net_crc_errors_total.load(),
           &first);
  AppendKV(&out, "net_recv_timeouts_total", net_recv_timeouts_total.load(),
           &first);
  AppendKV(&out, "net_send_timeouts_total", net_send_timeouts_total.load(),
           &first);
  AppendKV(&out, "net_oversize_frames_total",
           net_oversize_frames_total.load(), &first);
  AppendKV(&out, "net_reconnect_attempts_total",
           net_reconnect_attempts_total.load(), &first);
  AppendKV(&out, "net_reconnects_total", net_reconnects_total.load(),
           &first);
  AppendKV(&out, "faults_injected_total", faults_injected_total.load(),
           &first);
  AppendKV(&out, "fault_drop_total", fault_drop_total.load(), &first);
  AppendKV(&out, "fault_delay_total", fault_delay_total.load(), &first);
  AppendKV(&out, "fault_corrupt_total", fault_corrupt_total.load(), &first);
  AppendKV(&out, "fault_close_total", fault_close_total.load(), &first);
  AppendKV(&out, "fault_stall_total", fault_stall_total.load(), &first);
  AppendKV(&out, "compression_bytes_in_total",
           compression_bytes_in_total.load(), &first);
  AppendKV(&out, "compression_bytes_out_total",
           compression_bytes_out_total.load(), &first);
  AppendKV(&out, "compression_bf16_total", compression_bf16_total.load(),
           &first);
  AppendKV(&out, "compression_int8_total", compression_int8_total.load(),
           &first);
  AppendKV(&out, "allreduce_uncompressed_total",
           allreduce_uncompressed_total.load(), &first);
  AppendKV(&out, "allreduce_bf16_total", allreduce_bf16_total.load(),
           &first);
  AppendKV(&out, "allreduce_int8_total", allreduce_int8_total.load(),
           &first);
  AppendKV(&out, "net_ring_bytes_sent_total",
           net_ring_bytes_sent_total.load(), &first);
  AppendKV(&out, "net_ring_bytes_recv_total",
           net_ring_bytes_recv_total.load(), &first);
  AppendKV(&out, "net_shm_bytes_sent_total",
           net_shm_bytes_sent_total.load(), &first);
  AppendKV(&out, "net_shm_bytes_recv_total",
           net_shm_bytes_recv_total.load(), &first);
  AppendKV(&out, "ckpt_writes_total", ckpt_writes_total.load(), &first);
  AppendKV(&out, "ckpt_write_failures_total",
           ckpt_write_failures_total.load(), &first);
  AppendKV(&out, "ckpt_bytes_total", ckpt_bytes_total.load(), &first);
  AppendKV(&out, "ckpt_restores_total", ckpt_restores_total.load(), &first);
  AppendKV(&out, "ckpt_restore_failures_total",
           ckpt_restore_failures_total.load(), &first);
  AppendKV(&out, "drains_requested_total", drains_requested_total.load(),
           &first);
  AppendKV(&out, "reduce_scatter_total", reduce_scatter_total.load(),
           &first);
  AppendKV(&out, "reduce_scatter_bytes_total",
           reduce_scatter_bytes_total.load(), &first);
  AppendKV(&out, "reduce_scatter_hierarchical_total",
           reduce_scatter_hierarchical_total.load(), &first);
  AppendKV(&out, "pipeline_segments_total",
           pipeline_segments_total.load(), &first);
  AppendKV(&out, "autotune_rearms_total",
           autotune_rearms_total.load(), &first);
  AppendKV(&out, "group_tensors_total", group_tensors_total.load(), &first);
  AppendKV(&out, "group_negotiated_overflow_total",
           group_negotiated_overflow_total.load(), &first);
  AppendKV(&out, "trace_spans_total", GlobalTrace().spans_total.load(),
           &first);
  AppendKV(&out, "trace_spans_dropped_total",
           GlobalTrace().spans_dropped.load(), &first);
  AppendKV(&out, "bundles_written_total",
           GlobalTrace().bundles_written.load(), &first);
  out.append("},\"gauges\":{");
  first = true;
  AppendKV(&out, "queue_depth", static_cast<double>(queue_depth.load()),
           &first);
  AppendKV(&out, "pending_negotiation",
           static_cast<double>(pending_negotiation.load()), &first);
  AppendKV(&out, "elastic_generation",
           static_cast<double>(elastic_generation.load()), &first);
  AppendKV(&out, "world_size", static_cast<double>(world_size.load()),
           &first);
  AppendKV(&out, "rank", static_cast<double>(rank.load()), &first);
  AppendKV(&out, "fusion_threshold_bytes",
           static_cast<double>(fusion_threshold_bytes.load()), &first);
  AppendKV(&out, "last_durable_step",
           static_cast<double>(last_durable_step.load()), &first);
  AppendKV(&out, "draining", static_cast<double>(draining.load()), &first);
  AppendKV(&out, "opt_state_bytes",
           static_cast<double>(opt_state_bytes.load()), &first);
  AppendKV(&out, "autotune_active",
           static_cast<double>(autotune_active.load()), &first);
  AppendKV(&out, "pipeline_chunk_bytes",
           static_cast<double>(pipeline_chunk_bytes.load()), &first);
  AppendKV(&out, "groups", static_cast<double>(groups.load()), &first);
  AppendKV(&out, "shm_segments_active",
           static_cast<double>(shm_segments_active.load()), &first);
  out.append("},\"per_group\":{");
  // Group-labeled negotiation counters (docs/GROUPS.md): one entry per
  // tracked group id with at least one negotiated tensor. The Python
  // renderer turns these into
  // hvdtpu_group_negotiated_total{group="<id>"} families.
  first = true;
  for (int g = 0; g < kGroupStatSlots; ++g) {
    uint64_t n = group_negotiated_total[g].load(std::memory_order_relaxed);
    if (n == 0) continue;
    if (!first) out.append(",");
    first = false;
    out.append("\"");
    out.append(std::to_string(g + 1));
    out.append("\":{\"negotiated_total\":");
    AppendNum(&out, static_cast<double>(n));
    out.append("}");
  }
  out.append("},\"histograms\":{");
  first = true;
  AppendHistogram(&out, "cycle_seconds", cycle_seconds, &first);
  AppendHistogram(&out, "negotiation_seconds", negotiation_seconds, &first);
  AppendHistogram(&out, "cycle_tensors", cycle_tensors, &first);
  AppendHistogram(&out, "cycle_bytes", cycle_bytes, &first);
  AppendHistogram(&out, "fusion_fill_ratio", fusion_fill_ratio, &first);
  AppendHistogram(&out, "ckpt_write_seconds", ckpt_write_seconds, &first);
  AppendHistogram(&out, "compression_seconds", compression_seconds, &first);
  out.append("},\"rank_lag_seconds\":[");
  {
    std::lock_guard<std::mutex> lk(rank_mutex_);
    for (std::size_t i = 0; i < rank_lag_seconds_.size(); ++i) {
      if (i) out.append(",");
      AppendNum(&out, rank_lag_seconds_[i]);
    }
    out.append("],\"rank_lag_count\":[");
    for (std::size_t i = 0; i < rank_lag_count_.size(); ++i) {
      if (i) out.append(",");
      AppendNum(&out, static_cast<double>(rank_lag_count_[i]));
    }
  }
  out.append("],\"enabled\":");
  out.append(enabled() ? "true" : "false");
  out.append("}");
  return out;
}

std::string Metrics::JobJson() const {
  std::vector<double> own = Summary();
  std::string out;
  std::lock_guard<std::mutex> lk(rank_mutex_);
  if (!is_coordinator_) return "{}";
  auto now = Clock::now();
  out.reserve(2048);
  out.append("{\"size\":");
  AppendNum(&out, static_cast<double>(world_size.load()));
  out.append(",\"generation\":");
  AppendNum(&out, static_cast<double>(elastic_generation.load()));
  out.append(",\"per_rank\":{");
  bool first_rank = true;
  for (std::size_t r = 0; r < rank_summaries_.size(); ++r) {
    const std::vector<double>& vals = r == 0 ? own : rank_summaries_[r];
    if (vals.empty()) continue;
    if (!first_rank) out.append(",");
    first_rank = false;
    out.append("\"");
    AppendNum(&out, static_cast<double>(r));
    out.append("\":{");
    bool first = true;
    for (std::size_t f = 0; f < vals.size() && f < SUM_FIELD_COUNT; ++f) {
      AppendKV(&out, SummaryFieldName(static_cast<int>(f)), vals[f], &first);
    }
    out.append("}");
  }
  out.append("},\"age_seconds\":{");
  bool first = true;
  for (std::size_t r = 0; r < rank_summaries_.size(); ++r) {
    if (rank_summaries_[r].empty() && r != 0) continue;
    double age =
        r == 0 ? 0.0
               : std::chrono::duration<double>(now - rank_summary_time_[r])
                     .count();
    if (!first) out.append(",");
    first = false;
    out.append("\"");
    AppendNum(&out, static_cast<double>(r));
    out.append("\":");
    AppendNum(&out, age);
  }
  out.append("},\"rank_lag_seconds\":[");
  for (std::size_t i = 0; i < rank_lag_seconds_.size(); ++i) {
    if (i) out.append(",");
    AppendNum(&out, rank_lag_seconds_[i]);
  }
  out.append("]}");
  return out;
}

Metrics& GlobalMetrics() {
  static Metrics* metrics = new Metrics();  // leaked: outlives all threads
  return *metrics;
}

}  // namespace hvdtpu
