// Process-wide singleton state for the coordination runtime: the background
// thread, its components, and the knobs they share.
// Capability parity with /root/reference horovod/common/global_state.h.
#ifndef HVD_TPU_GLOBAL_STATE_H
#define HVD_TPU_GLOBAL_STATE_H

#include <atomic>
#include <cstdint>
#include <memory>
#include <thread>

#include "divergence.h"
#include "fusion_buffer_manager.h"
#include "group_table.h"
#include "metrics.h"
#include "parameter_manager.h"
#include "response_cache.h"
#include "tcp_context.h"
#include "tensor_queue.h"
#include "timeline.h"
#include "trace.h"

namespace hvdtpu {

class Controller;
class OperationManager;

struct HorovodGlobalState {
  // Background coordination thread (the only thread that talks cross-rank).
  std::thread background_thread;
  std::atomic<bool> initialize_flag{false};
  std::atomic<bool> initialization_done{false};
  std::atomic<bool> initialization_failed{false};
  std::atomic<bool> shut_down{false};
  // Set when the background loop died because a peer connection was lost
  // (vs a requested shutdown) — outstanding and future work then fails
  // with the recoverable CONNECTION_LOST_ERROR so Python can roll back
  // and re-initialize (elastic recovery).
  std::atomic<bool> connection_lost{false};

  // Fusion diagnostics (see PerformOperation).
  std::atomic<int64_t> responses_performed{0};
  std::atomic<int64_t> tensors_performed{0};

  TcpContext tcp_context;
  TensorQueue tensor_queue;
  Timeline timeline;
  bool mark_cycles_in_timeline = false;
  ParameterManager parameter_manager;
  ResponseCache response_cache;
  // Every rank's collective call sequence (seq / rolling digest / recent
  // ring) — fed by EnqueueTensor, cross-checked by the coordinator's
  // DivergenceDetector and exposed to Python via horovod_tpu_call_digest.
  CallTracker call_tracker;
  // Process-group registry (docs/GROUPS.md): written by
  // horovod_tpu_new_group on API threads, read by the controller and
  // the data-plane ops on the background thread; mutex inside.
  GroupTable group_table;
  FusionBufferManager fusion_buffer;
  // Live metrics registry (metrics.h). A reference to the process
  // singleton: leaf components without a state pointer (stall inspector,
  // the C snapshot API) reach the same registry via GlobalMetrics().
  Metrics& metrics = GlobalMetrics();
  // Always-on span recorder + flight recorder (trace.h). Same singleton
  // pattern as metrics: leaf components reach it via GlobalTrace().
  Trace& trace = GlobalTrace();
  std::unique_ptr<Controller> controller;
  std::unique_ptr<OperationManager> op_manager;

  ~HorovodGlobalState();
};

}  // namespace hvdtpu

#endif  // HVD_TPU_GLOBAL_STATE_H
