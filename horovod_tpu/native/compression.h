// Wire compression for the host data plane (docs/COMPRESSION.md):
// the tensor (and the fusion buffer it rides in) stays float32 end to
// end — only the bytes each ring hop puts ON THE WIRE are encoded.
//
//   NONE  — payload is the raw buffer (bitwise-identical behavior to a
//           build without this stage).
//   BF16  — each f32 element is round-to-nearest bfloat16 on the wire:
//           2x fewer bytes per hop. Reduction still accumulates in f32
//           (the receiver widens before ReduceSum), so precision loss
//           is one rounding per hop, not a bf16 accumulator.
//   INT8  — EQuARX-style block-scaled quantization (PAPERS.md, arxiv
//           2506.17615): per kCompressionBlock(=256)-element block, an
//           f32 scale = max|x|/127 carried in-band ahead of the int8
//           payload. ~3.9x fewer bytes per hop; per-element error is
//           bounded by scale/2 (see CompressBuffer).
//
// The mode is negotiated per tensor (Request/Response carry it; the
// response cache keys on it), so every rank encodes/decodes identically
// or the coordinator rejects the op by name. CRC32C framing in
// RingExchangeOn covers the COMPRESSED payload — a corrupted compressed
// frame is a detected transport error, never silently wrong gradients.
#ifndef HVD_TPU_COMPRESSION_H
#define HVD_TPU_COMPRESSION_H

#include <cstddef>
#include <cstdint>

#include "message.h"

namespace hvdtpu {

enum class CompressionMode : uint8_t {
  NONE = 0,
  BF16 = 1,
  INT8 = 2,
};

// Elements per int8 quantization block (one in-band f32 scale each).
constexpr int64_t kCompressionBlock = 256;

const char* CompressionModeName(CompressionMode m);
// Parses "none"/"bf16"/"int8" (or "0"/"1"/"2"); NONE on anything else.
CompressionMode ParseCompressionMode(const char* s);

// Compression applies to float32 payloads only; every other dtype rides
// the wire untouched. Computed identically on every rank from the
// (negotiated) dtype, so the effective mode can never diverge.
CompressionMode EffectiveCompression(CompressionMode m, DataType dtype);

// Wire bytes for `count` f32 elements under `mode` — a pure function of
// (count, mode), so sender and receiver size their buffers without any
// extra header exchange.
std::size_t CompressedSize(int64_t count, CompressionMode mode);

// Encodes `count` f32 elements from `src` into `dst` (CompressedSize
// bytes). INT8 layout: [f32 scale x nblocks][int8 q x count], blocks of
// kCompressionBlock elements (last may be short). Counts bytes in/out
// and time into the metrics registry.
void CompressBuffer(const float* src, int64_t count, CompressionMode mode,
                    char* dst);

// Decodes `count` elements from `src` (CompressedSize bytes) into f32
// `dst`. Exact inverse of CompressBuffer up to the codec's rounding.
void DecompressBuffer(const char* src, int64_t count, CompressionMode mode,
                      float* dst);

// Fused dequant-accumulate: dst[i] += decode(src)[i] in ONE pass — the
// pipelined ring's segment consumer (cpu_operations.cc) uses this to
// skip the intermediate f32 scratch entirely (per hop that removes a
// full write+read of the chunk from the memory-traffic bill; the
// element math is identical to DecompressBuffer-then-add, so results
// stay bitwise equal to the unsliced path). Also accepts NONE (plain
// f32 accumulate) so callers need not branch.
void DecompressAccumulate(const char* src, int64_t count,
                          CompressionMode mode, float* dst);

}  // namespace hvdtpu

#endif  // HVD_TPU_COMPRESSION_H
