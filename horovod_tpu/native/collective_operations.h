// Op base classes + fusion-buffer pack/unpack helpers, and the
// OperationManager registry (ordered per-op-type lists; the first op whose
// Enabled() returns true executes the response).
//
// Capability parity with /root/reference
// horovod/common/ops/collective_operations.{h,cc} and
// ops/operation_manager.{h,cc}.
#ifndef HVD_TPU_COLLECTIVE_OPERATIONS_H
#define HVD_TPU_COLLECTIVE_OPERATIONS_H

#include <memory>
#include <vector>

#include "common.h"
#include "message.h"

namespace hvdtpu {

struct HorovodGlobalState;

class HorovodOp {
 public:
  explicit HorovodOp(HorovodGlobalState* state) : global_state_(state) {}
  virtual ~HorovodOp() = default;

  virtual bool Enabled(const std::vector<TensorTableEntry>& entries,
                       const Response& response) const = 0;
  virtual Status Execute(std::vector<TensorTableEntry>& entries,
                         const Response& response) = 0;

 protected:
  int64_t NumElements(const std::vector<TensorTableEntry>& entries) const;
  // Packs every entry's input into the fusion buffer; returns buffer + bytes.
  Status MemcpyInFusionBuffer(std::vector<TensorTableEntry>& entries,
                              void** buffer_data, std::size_t* buffer_len);
  // Unpacks the fusion buffer back into every entry's output.
  void MemcpyOutFusionBuffer(const void* buffer_data,
                             std::vector<TensorTableEntry>& entries);

  HorovodGlobalState* global_state_;
};

class AllreduceOp : public HorovodOp {
 public:
  using HorovodOp::HorovodOp;
};

class AllgatherOp : public HorovodOp {
 public:
  using HorovodOp::HorovodOp;
};

class BroadcastOp : public HorovodOp {
 public:
  using HorovodOp::HorovodOp;
};

// Reduce-scatter (docs/ZERO.md): the sum lands SHARDED — rank r's output
// buffer receives logical chunk r of the PartitionChunks partition over
// the flattened tensor (the same partition the Python binding's
// shard_partition computes).
class ReduceScatterOp : public HorovodOp {
 public:
  using HorovodOp::HorovodOp;
};

class ErrorOp : public HorovodOp {
 public:
  using HorovodOp::HorovodOp;
  bool Enabled(const std::vector<TensorTableEntry>& entries,
               const Response& response) const override {
    return true;
  }
  Status Execute(std::vector<TensorTableEntry>& entries,
                 const Response& response) override {
    return Status::PreconditionError(response.error_message());
  }
};

class OperationManager {
 public:
  OperationManager(std::vector<std::shared_ptr<AllreduceOp>> allreduce_ops,
                   std::vector<std::shared_ptr<AllgatherOp>> allgather_ops,
                   std::vector<std::shared_ptr<BroadcastOp>> broadcast_ops,
                   std::vector<std::shared_ptr<ReduceScatterOp>>
                       reducescatter_ops,
                   std::shared_ptr<ErrorOp> error_op)
      : allreduce_ops_(std::move(allreduce_ops)),
        allgather_ops_(std::move(allgather_ops)),
        broadcast_ops_(std::move(broadcast_ops)),
        reducescatter_ops_(std::move(reducescatter_ops)),
        error_op_(std::move(error_op)) {}

  Status ExecuteOperation(std::vector<TensorTableEntry>& entries,
                          const Response& response);

 private:
  template <typename Op>
  Status ExecuteFirstEnabled(
      const std::vector<std::shared_ptr<Op>>& ops,
      std::vector<TensorTableEntry>& entries, const Response& response);

  std::vector<std::shared_ptr<AllreduceOp>> allreduce_ops_;
  std::vector<std::shared_ptr<AllgatherOp>> allgather_ops_;
  std::vector<std::shared_ptr<BroadcastOp>> broadcast_ops_;
  std::vector<std::shared_ptr<ReduceScatterOp>> reducescatter_ops_;
  std::shared_ptr<ErrorOp> error_op_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_COLLECTIVE_OPERATIONS_H
