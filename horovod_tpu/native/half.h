// Bit-level float16 and bfloat16 <-> float32 converters used by the CPU
// reduction path (reductions accumulate in float32 for both 16-bit types).
//
// Capability parity with the reference fp16 support (/root/reference
// horovod/common/half.{h,cc}); bfloat16 is new here — it is the native TPU
// 16-bit format and gets first-class treatment.
#ifndef HVD_TPU_HALF_H
#define HVD_TPU_HALF_H

#include <cstdint>
#include <cstring>

namespace hvdtpu {

inline float HalfToFloat(uint16_t h) {
  uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1fu;
  uint32_t mant = h & 0x3ffu;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;  // +-0
    } else {
      // subnormal: normalize
      int shift = 0;
      while ((mant & 0x400u) == 0) {
        mant <<= 1;
        ++shift;
      }
      mant &= 0x3ffu;
      bits = sign | ((127 - 15 - shift + 1) << 23) | (mant << 13);
    }
  } else if (exp == 0x1fu) {
    bits = sign | 0x7f800000u | (mant << 13);  // inf / nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t FloatToHalf(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xffu) - 127 + 15;
  uint32_t mant = bits & 0x7fffffu;
  if (exp >= 0x1f) {
    // overflow -> inf; preserve nan payload bit
    uint32_t nan = ((bits & 0x7fffffffu) > 0x7f800000u) ? 0x200u : 0;
    return static_cast<uint16_t>(sign | 0x7c00u | nan);
  }
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);  // underflow -> 0
    // subnormal with round-to-nearest-even
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half_mant = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half_mant & 1))) ++half_mant;
    return static_cast<uint16_t>(sign | half_mant);
  }
  uint16_t h = static_cast<uint16_t>(sign | (exp << 10) | (mant >> 13));
  // round-to-nearest-even on dropped 13 bits
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (h & 1))) ++h;
  return h;
}

inline float BFloat16ToFloat(uint16_t b) {
  uint32_t bits = static_cast<uint32_t>(b) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t FloatToBFloat16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  if ((bits & 0x7fffffffu) > 0x7f800000u) {
    return static_cast<uint16_t>((bits >> 16) | 0x40u);  // quiet nan
  }
  // round-to-nearest-even on the dropped 16 bits
  uint32_t lsb = (bits >> 16) & 1u;
  bits += 0x7fffu + lsb;
  return static_cast<uint16_t>(bits >> 16);
}

}  // namespace hvdtpu

#endif  // HVD_TPU_HALF_H
