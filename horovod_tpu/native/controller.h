// Coordinator/worker negotiation protocol.
//
// The protocol (capability parity with /root/reference
// horovod/common/controller.{h,cc}, documented there at controller.h:62-97):
// every cycle, all ranks synchronously:
//   a) check queued requests against the response cache and agree on globally
//      cached-and-ready tensors with one bitwise-AND bit-vector allreduce;
//   b) if everything queued was cached everywhere, execute straight from the
//      cache (fast path — no coordinator round trip);
//   c) otherwise workers send their ready-tensor RequestLists to rank 0,
//      which counts readiness per tensor name; when a tensor has been
//      announced by all ranks it is ready;
//   d) rank 0 validates (shape/dtype/op/root-rank consistency), fuses small
//      responses up to the fusion threshold, and broadcasts the final
//      ResponseList; every rank executes the same responses in order.
//
// Subclasses provide the rank-discovery and the four cross-rank primitives
// (gather / broadcast / bitwise AND / bitwise OR). TcpController implements
// them over the host network; a single-process build short-circuits.
#ifndef HVD_TPU_CONTROLLER_H
#define HVD_TPU_CONTROLLER_H

#include <atomic>
#include <chrono>
#include <cstdint>
#include <deque>
#include <memory>
#include <stdexcept>
#include <string>
#include <unordered_map>
#include <vector>

#include "divergence.h"
#include "group_table.h"
#include "message.h"
#include "response_cache.h"
#include "stall_inspector.h"
#include "tensor_queue.h"
#include "timeline.h"

namespace hvdtpu {

class ParameterManager;

// Thrown by transport-backed controllers when a cross-rank primitive fails
// mid-protocol (peer EOF / reset). The background loop catches it and fails
// outstanding handles with a RECOVERABLE connection-lost status — the
// process survives and can re-initialize for a new generation (elastic
// membership change), instead of aborting the whole job.
class ConnectionLostError : public std::runtime_error {
 public:
  explicit ConnectionLostError(const std::string& what)
      : std::runtime_error(what) {}
};

class Controller {
 public:
  Controller(ResponseCache& response_cache, TensorQueue& tensor_queue,
             Timeline& timeline, ParameterManager& parameter_manager);
  virtual ~Controller() = default;

  // Rank discovery / communicator construction.
  virtual void Initialize() = 0;

  virtual int rank() const { return rank_; }
  virtual int local_rank() const { return local_rank_; }
  virtual int cross_rank() const { return cross_rank_; }
  virtual int size() const { return size_; }
  virtual int local_size() const { return local_size_; }
  virtual int cross_size() const { return cross_size_; }
  bool is_coordinator() const { return rank_ == 0; }
  bool is_homogeneous() const { return is_homogeneous_; }
  const std::vector<int>& local_sizes() const { return local_sizes_; }

  // The per-cycle negotiation. Returns the agreed list of operations to
  // perform this cycle (identical on every rank, in identical order).
  ResponseList ComputeResponseList(bool this_process_requested_shutdown);

  // Fusion threshold rounded so fused allreduce buffers divide evenly across
  // local ranks (needed by hierarchical ops).
  int64_t TensorFusionThresholdBytes() const;

  // Broadcasts autotuned parameters from rank 0 (wraps Bcast).
  void SynchronizeParameters();

  StallInspector& stall_inspector() { return stall_inspector_; }

  // Coordinator: the pending-negotiation table as JSON — every tensor
  // still waiting on announcements, which ranks reported it and which
  // are missing (group members for group tensors, world otherwise).
  // The flight recorder embeds it in post-mortem bundles (trace.h) so a
  // bundle names the missing rank and the in-flight tensors. "{}" off
  // the coordinator.
  std::string PendingNegotiationJson() const;

  // --- divergence cross-check (divergence.h) ---
  // The process-wide call tracker feeds each cycle's RequestList with this
  // rank's (seq, digest, recent calls); on the coordinator the detector
  // cross-checks them against the pending table and fails provably
  // diverged tensors with ERROR responses naming the offending call site.
  void SetCallTracker(CallTracker* tracker) { call_tracker_ = tracker; }

  // --- process groups (group_table.h / docs/GROUPS.md) ---
  // The registry the coordinator validates group requests against:
  // readiness counts are sized to the GROUP (a tensor is ready when all
  // MEMBERS announced, regardless of the other ranks), membership
  // digests are cross-checked, and non-member announcements are
  // rejected by name.
  void SetGroupTable(const GroupTable* table) { group_table_ = table; }
  // Call after Initialize() (needs size_). progress_calls==0 and
  // grace_seconds<=0 disable the respective rules.
  void ConfigureDivergence(int64_t progress_calls, double grace_seconds) {
    divergence_.Configure(size_, progress_calls, grace_seconds);
  }

  // --- metrics plane (metrics.h) ---
  // When enabled, workers attach their compact counter summary to the
  // RequestList at most once per `sync_seconds`, and the coordinator
  // forces a full negotiation cycle on the same cadence so summaries
  // keep flowing through all-cached steady state and total quiescence
  // (the exact windows where live metrics matter most).
  void ConfigureMetrics(bool enabled, double sync_seconds) {
    metrics_plane_enabled_ = enabled;
    metrics_sync_seconds_ = sync_seconds;
  }

  // --- negotiation-cycle accounting (fast path vs full round trip) ---
  // fast  = all-cached cycles that produced work from the bit-vector
  //         sync alone (no coordinator round trip);
  // full  = FinishCycle round trips (request gather + response bcast).
  uint64_t cycles_fast() const { return cycles_fast_.load(); }
  uint64_t cycles_full() const { return cycles_full_.load(); }
  void ResetCycleCounters() {
    cycles_fast_.store(0);
    cycles_full_.store(0);
  }

  // --- cross-rank primitives, implemented per transport ---
  // Gathers every rank's serialized blob at rank 0 (out: indexed by rank).
  virtual void GatherBlobs(const std::string& mine,
                           std::vector<std::string>* all) = 0;
  // Rank 0 sends `blob` to everyone; other ranks receive into `blob`.
  virtual void BroadcastBlob(std::string* blob) = 0;
  virtual void CrossRankBitwiseAnd(std::vector<uint64_t>& bits) = 0;
  virtual void CrossRankBitwiseOr(std::vector<uint64_t>& bits) = 0;
  virtual void Barrier() = 0;

 protected:
  // Coordinator: record that `rank` reported readiness of msg's tensor.
  // Returns true when all of the tensor's GROUP members have reported it
  // (all world ranks for group 0) — or immediately when the report is
  // provably bad (unknown group / non-member / membership-digest
  // mismatch), so ConstructResponse can reject it by name instead of
  // letting the count hang forever.
  bool IncrementTensorCount(const Request& msg, int rank);

  // Coordinator: build the validated Response for a fully-ready tensor,
  // checking cross-rank consistency of shape/dtype/op/root rank and
  // group membership. `key` is the pending-table key
  // (GroupQualifiedName); the response carries the bare tensor name.
  Response ConstructResponse(const std::string& key);

  // Coordinator: fuse eligible same-type/dtype responses under the threshold.
  void FuseResponses(std::deque<Response>& responses, ResponseList& out);

  // The negotiation round-trip (request gather -> validate/fuse -> response
  // broadcast). `responses` seeds the list with globally-cached responses.
  ResponseList FinishCycle(std::deque<Response> responses,
                           std::vector<Request>& non_cached_messages,
                           bool should_shut_down);

  int rank_ = 0;
  int local_rank_ = 0;
  int cross_rank_ = 0;
  int size_ = 1;
  int local_size_ = 1;
  int cross_size_ = 1;
  bool is_homogeneous_ = true;
  std::vector<int> local_sizes_;

  // Coordinator-side table: GroupQualifiedName(group, tensor name) ->
  // one Request per reported rank. The composite key keeps the same
  // tensor name active in two groups at once (the 2-D mesh's per-column
  // gradient reduce) as two independent negotiations.
  std::unordered_map<std::string, std::vector<Request>> message_table_;
  const GroupTable* group_table_ = nullptr;

  ResponseCache& response_cache_;
  TensorQueue& tensor_queue_;
  Timeline& timeline_;
  ParameterManager& parameter_manager_;
  StallInspector stall_inspector_;
  CallTracker* call_tracker_ = nullptr;
  DivergenceDetector divergence_;

  // Metrics plane: summary-attach / forced-sync pacing and the
  // coordinator's per-tensor first-announce clock (negotiation latency
  // histogram + per-rank announce lag — the straggler signal).
  bool metrics_plane_enabled_ = false;
  double metrics_sync_seconds_ = 1.0;
  std::chrono::steady_clock::time_point last_summary_attach_{};
  std::chrono::steady_clock::time_point last_metrics_force_{};
  std::unordered_map<std::string, std::chrono::steady_clock::time_point>
      negotiate_started_;
  // Highest tracker seq already shipped (worker) / self-observed
  // (coordinator); records above it ride the next RequestList.
  uint64_t reported_call_seq_ = 0;
  // Tracker snapshot taken at the TOP of ComputeResponseList, BEFORE the
  // message-queue pop. Ordering invariant for the progress rule: a call
  // enters the tracker only after its Request is queued, so every call
  // counted by this snapshot has its Request in this cycle's pop (or an
  // earlier one) — the reported seq can never run ahead of the shipped
  // requests, which is what made a mid-burst rank look "provably past"
  // a tensor it was still about to submit.
  uint64_t cycle_call_seq_ = 0;
  uint64_t cycle_call_digest_ = 0;

  std::atomic<uint64_t> cycles_fast_{0};
  std::atomic<uint64_t> cycles_full_{0};

  // Coordinator: ResponseList::kFlagDumpBundle et al, armed by a stall
  // escalation / divergence this cycle and shipped on the next
  // FinishCycle broadcast so every worker dumps a flight-recorder
  // bundle while the evidence is still in its ring (trace.h).
  uint8_t pending_trace_flags_ = 0;

  uint32_t cache_capacity_ = 1024;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_CONTROLLER_H
