#include "checksum.h"

#include <mutex>

namespace hvdtpu {

namespace {

// 8 slicing tables, generated once at first use (8 KiB total).
uint32_t g_tables[8][256];
std::once_flag g_tables_once;

void BuildTables() {
  constexpr uint32_t kPoly = 0x82F63B78u;  // CRC32C, reflected
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j) {
      crc = (crc >> 1) ^ ((crc & 1) ? kPoly : 0);
    }
    g_tables[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = g_tables[0][i];
    for (int t = 1; t < 8; ++t) {
      crc = g_tables[0][crc & 0xFF] ^ (crc >> 8);
      g_tables[t][i] = crc;
    }
  }
}

}  // namespace

uint32_t Crc32c(const void* data, std::size_t len, uint32_t crc) {
  std::call_once(g_tables_once, BuildTables);
  const unsigned char* p = static_cast<const unsigned char*>(data);
  crc = ~crc;
  // Byte-at-a-time until 8-byte alignment, then slicing-by-8.
  while (len > 0 && (reinterpret_cast<uintptr_t>(p) & 7) != 0) {
    crc = g_tables[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    --len;
  }
  while (len >= 8) {
    uint64_t word;
    __builtin_memcpy(&word, p, 8);
    // Little-endian lane split (the build targets are LE; a BE port
    // would byte-swap here).
    word ^= crc;
    crc = g_tables[7][word & 0xFF] ^
          g_tables[6][(word >> 8) & 0xFF] ^
          g_tables[5][(word >> 16) & 0xFF] ^
          g_tables[4][(word >> 24) & 0xFF] ^
          g_tables[3][(word >> 32) & 0xFF] ^
          g_tables[2][(word >> 40) & 0xFF] ^
          g_tables[1][(word >> 48) & 0xFF] ^
          g_tables[0][(word >> 56) & 0xFF];
    p += 8;
    len -= 8;
  }
  while (len > 0) {
    crc = g_tables[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    --len;
  }
  return ~crc;
}

}  // namespace hvdtpu
