#include "response_cache.h"

#include <cassert>

#include "controller.h"
#include "logging.h"
#include "tensor_queue.h"

namespace hvdtpu {

void ResponseCache::set_capacity(uint32_t capacity) {
  capacity_ = capacity;
  cache_.reserve(capacity);
  cache_iters_.reserve(capacity);
}

uint32_t ResponseCache::num_active_bits() const {
  return static_cast<uint32_t>(cache_.size());
}

void ResponseCache::clear() {
  cache_.clear();
  cache_iters_.clear();
  lru_.clear();
  key_to_bit_.clear();
  name_refs_.clear();
  non_member_entries_ = 0;
  bits_outdated_ = false;
}

ResponseCache::CacheState ResponseCache::cached(const Request& request) const {
  const std::string key =
      GroupQualifiedName(request.group_id(), request.tensor_name());
  auto it = key_to_bit_.find(key);
  if (it == key_to_bit_.end()) {
    // The NAME cached under a different group id is a membership change:
    // INVALID, so the stale entry is erased on every rank (via the
    // invalid-bit OR sync) and the tensor renegotiates under its new
    // group — same contract as a compression-mode change. The bare-name
    // index keeps the ordinary miss (every auto-named tensor, fresh
    // each call) a single hash lookup; the scan only runs when the name
    // genuinely lives under some other group.
    if (name_refs_.count(request.tensor_name())) {
      for (const auto& e : cache_) {
        if (e.response.tensor_names()[0] == request.tensor_name() &&
            e.group_id != request.group_id()) {
          return CacheState::INVALID;
        }
      }
    }
    return CacheState::MISS;
  }
  const CacheEntry& e = cache_[it->second];
  // Foreign entries are bit-position mirrors on non-members; they carry
  // no validation params. A local lookup on one means this rank now
  // enqueues a (group, name) it never executed — renegotiate.
  if (e.foreign) return CacheState::INVALID;
  bool same = e.dtype == request.tensor_type() &&
              e.shape == request.tensor_shape() &&
              e.root_rank == request.root_rank() &&
              e.prescale_factor == request.prescale_factor() &&
              e.postscale_factor == request.postscale_factor() &&
              e.compression == request.compression() &&
              e.group_digest == request.group_digest();
  // Response type must match the request type too. The two enums agree
  // numerically for allreduce/allgather/broadcast but diverge at
  // REDUCESCATTER (Response appends it AFTER ERROR for wire
  // compatibility, Request has no ERROR) — map before comparing, or a
  // cached reduce-scatter could never hit.
  int cached_as_request =
      e.response.response_type() == Response::REDUCESCATTER
          ? static_cast<int>(Request::REDUCESCATTER)
          : static_cast<int>(e.response.response_type());
  same = same &&
         cached_as_request == static_cast<int>(request.request_type());
  return same ? CacheState::HIT : CacheState::INVALID;
}

void ResponseCache::put_entry(CacheEntry entry) {
  // Copies, not references: `entry` is moved into the slot below, and a
  // reference into the moved-from object would index an empty key.
  const std::string key = entry.key;
  const std::string name = entry.response.tensor_names()[0];
  const bool new_non_member = !entry.is_member;
  auto it = key_to_bit_.find(key);
  if (it != key_to_bit_.end()) {
    uint32_t bit = it->second;
    if (!cache_[bit].is_member) --non_member_entries_;
    if (new_non_member) ++non_member_entries_;
    cache_[bit] = std::move(entry);
    lru_.erase(cache_iters_[bit]);
    lru_.push_front(bit);
    cache_iters_[bit] = lru_.begin();
    return;
  }
  uint32_t bit;
  if (cache_.size() < capacity_) {
    bit = static_cast<uint32_t>(cache_.size());
    cache_.push_back(std::move(entry));
    lru_.push_front(bit);
    cache_iters_.push_back(lru_.begin());
  } else {
    // Evict the LRU entry; its bit is recycled, so positions shift — all
    // ranks evict identically because they run identical put sequences.
    bit = lru_.back();
    lru_.pop_back();
    key_to_bit_.erase(cache_[bit].key);
    DropNameRef(cache_[bit].response.tensor_names()[0]);
    if (!cache_[bit].is_member) --non_member_entries_;
    cache_[bit] = std::move(entry);
    lru_.push_front(bit);
    cache_iters_[bit] = lru_.begin();
    bits_outdated_ = true;
  }
  key_to_bit_[key] = bit;
  name_refs_[name] += 1;
  if (new_non_member) ++non_member_entries_;
}

void ResponseCache::put(const Response& response, TensorQueue& tensor_queue,
                        const GroupTable* groups, int my_rank) {
  if (capacity_ == 0) return;
  if (response.response_type() == Response::ERROR) return;
  uint32_t gid = response.group_id();
  bool member = gid == 0 ||
                (groups != nullptr && groups->Contains(gid, my_rank));
  // Fused responses are cached per-tensor so each tensor can hit alone.
  for (std::size_t i = 0; i < response.tensor_names().size(); ++i) {
    const std::string& name = response.tensor_names()[i];
    Response single;
    single.set_response_type(response.response_type());
    single.set_tensor_type(response.tensor_type());
    single.set_devices(response.devices());
    single.set_compression(response.compression());
    single.set_group_id(gid);
    single.add_tensor_name(name);
    CacheEntry entry;
    entry.key = GroupQualifiedName(gid, name);
    entry.group_id = gid;
    entry.group_digest =
        gid != 0 && groups != nullptr ? groups->Digest(gid) : 0;
    entry.is_member = member;
    // Capture validation params from the table entry if it still exists;
    // member callers invoke put() before callbacks fire, so it does.
    if (member && tensor_queue.HasEntry(name) &&
        tensor_queue.GetTensorEntry(name).group_id == gid) {
      const TensorTableEntry& te = tensor_queue.GetTensorEntry(name);
      entry.dtype = te.dtype;
      entry.shape = te.shape.dims();
      entry.root_rank = te.root_rank;
      entry.prescale_factor = te.prescale_factor;
      entry.postscale_factor = te.postscale_factor;
      entry.compression = te.compression;
      if (response.response_type() == Response::ALLGATHER) {
        single.set_tensor_sizes(response.tensor_sizes());
      } else {
        // Allreduce/broadcast: carry the element count so the
        // cached-path FuseResponses sees real bytes — without it a
        // cached response weighs 0 and fusion merges past the
        // threshold.
        single.add_tensor_size(te.shape.num_elements());
      }
    } else {
      // Foreign mirror: this rank never executes (group, name), but the
      // bit POSITION must exist here too or the cross-rank bit vectors
      // desync. Sizes come from the response so fusion weighing stays
      // rank-identical on the cached fast path.
      entry.dtype = response.tensor_type();
      entry.foreign = true;
      if (response.response_type() == Response::ALLGATHER) {
        single.set_tensor_sizes(response.tensor_sizes());
      } else if (i < response.tensor_sizes().size()) {
        single.add_tensor_size(response.tensor_sizes()[i]);
      }
    }
    entry.response = single;
    put_entry(std::move(entry));
  }
}

const Response& ResponseCache::get_response(uint32_t cache_bit) {
  assert(cache_bit < cache_.size());
  lru_.erase(cache_iters_[cache_bit]);
  lru_.push_front(cache_bit);
  cache_iters_[cache_bit] = lru_.begin();
  return cache_[cache_bit].response;
}

const Response& ResponseCache::peek_response(uint32_t cache_bit) const {
  assert(cache_bit < cache_.size());
  return cache_[cache_bit].response;
}

uint32_t ResponseCache::peek_cache_bit(const Request& request) const {
  auto it = key_to_bit_.find(
      GroupQualifiedName(request.group_id(), request.tensor_name()));
  if (it != key_to_bit_.end()) return it->second;
  // Membership-change INVALID path: the name lives under another group's
  // key — return that stale bit so the invalid-bit sync erases it.
  for (uint32_t bit = 0; bit < cache_.size(); ++bit) {
    if (cache_[bit].response.tensor_names()[0] == request.tensor_name()) {
      return bit;
    }
  }
  assert(false && "peek_cache_bit on an uncached request");
  return 0;
}

uint32_t ResponseCache::peek_cache_bit(const std::string& cache_key) const {
  auto it = key_to_bit_.find(cache_key);
  assert(it != key_to_bit_.end());
  return it->second;
}

void ResponseCache::NonMemberBits(std::vector<uint32_t>* out) const {
  // O(1) in the common (pure data-parallel) case: no foreign entries,
  // no scan — this runs every negotiation cycle.
  if (non_member_entries_ == 0) return;
  for (uint32_t bit = 0; bit < cache_.size(); ++bit) {
    if (!cache_[bit].is_member) out->push_back(bit);
  }
}

void ResponseCache::DropNameRef(const std::string& name) {
  auto it = name_refs_.find(name);
  if (it == name_refs_.end()) return;
  if (--it->second == 0) name_refs_.erase(it);
}

void ResponseCache::erase_response(uint32_t cache_bit) {
  if (cache_bit >= cache_.size()) return;
  key_to_bit_.erase(cache_[cache_bit].key);
  DropNameRef(cache_[cache_bit].response.tensor_names()[0]);
  if (!cache_[cache_bit].is_member) --non_member_entries_;
  lru_.erase(cache_iters_[cache_bit]);
  // Compact: move last entry into the freed slot to keep bits dense.
  uint32_t last = static_cast<uint32_t>(cache_.size()) - 1;
  if (cache_bit != last) {
    cache_[cache_bit] = std::move(cache_[last]);
    cache_iters_[cache_bit] = cache_iters_[last];
    *cache_iters_[cache_bit] = cache_bit;
    key_to_bit_[cache_[cache_bit].key] = cache_bit;
  }
  cache_.pop_back();
  cache_iters_.pop_back();
  bits_outdated_ = true;
}

void ResponseCache::update_cache_bits() {
  if (!bits_outdated_) return;
  // Reassign bits by LRU order (most recent = 0) so bit positions are a pure
  // function of the (identical) access history on every rank.
  std::vector<CacheEntry> new_cache;
  new_cache.reserve(cache_.size());
  std::list<uint32_t> new_lru;
  std::vector<std::list<uint32_t>::iterator> new_iters(cache_.size());
  uint32_t new_bit = 0;
  for (uint32_t old_bit : lru_) {
    new_cache.push_back(std::move(cache_[old_bit]));
    new_lru.push_back(new_bit);
    ++new_bit;
  }
  uint32_t i = 0;
  for (auto it = new_lru.begin(); it != new_lru.end(); ++it, ++i) {
    new_iters[i] = it;
  }
  cache_ = std::move(new_cache);
  lru_ = std::move(new_lru);
  cache_iters_ = std::move(new_iters);
  key_to_bit_.clear();
  for (uint32_t bit = 0; bit < cache_.size(); ++bit) {
    key_to_bit_[cache_[bit].key] = bit;
  }
  bits_outdated_ = false;
}

CacheCoordinator::CacheCoordinator(std::size_t num_active_bits)
    : num_active_bits_(num_active_bits) {}

void CacheCoordinator::record_hit(uint32_t bit) {
  assert(!synced_);
  cache_hits_.insert(bit);
}

void CacheCoordinator::record_invalid_bit(uint32_t bit) {
  assert(!synced_);
  invalid_bits_.insert(bit);
  invalid_in_queue_ = true;
}

void CacheCoordinator::erase_hit(uint32_t bit) { cache_hits_.erase(bit); }

void CacheCoordinator::sync(Controller* controller, bool timeline_enabled) {
  assert(!synced_);
  // Layout: word 0 = status bits (inverted semantics for AND: a bit survives
  // the AND only if *every* rank set it; for "any rank wants X" flags we set
  // the bit when X is FALSE locally and invert after, i.e. surviving bit
  // means "no rank wants X").
  std::size_t num_words = (num_active_bits_ + 63) / 64 + 1;
  std::vector<uint64_t> bits(num_words, 0);
  if (!should_shut_down_) bits[0] |= 1ull << SHOULD_SHUT_DOWN;
  if (!uncached_in_queue_) bits[0] |= 1ull << UNCACHED_IN_QUEUE;
  if (!invalid_in_queue_) bits[0] |= 1ull << INVALID_IN_QUEUE;
  for (uint32_t bit : cache_hits_) {
    bits[1 + bit / 64] |= 1ull << (bit % 64);
  }
  controller->CrossRankBitwiseAnd(bits);

  should_shut_down_ = (bits[0] & (1ull << SHOULD_SHUT_DOWN)) == 0;
  uncached_in_queue_ = (bits[0] & (1ull << UNCACHED_IN_QUEUE)) == 0;
  invalid_in_queue_ = (bits[0] & (1ull << INVALID_IN_QUEUE)) == 0;

  std::set<uint32_t> global_hits;
  for (uint32_t bit = 0; bit < num_active_bits_; ++bit) {
    bool global = (bits[1 + bit / 64] & (1ull << (bit % 64))) != 0;
    if (global) {
      global_hits.insert(bit);
    } else if (timeline_enabled && cache_hits_.count(bit)) {
      timeline_bits_.insert(bit);
    }
  }
  cache_hits_ = std::move(global_hits);

  if (invalid_in_queue_) {
    // Second pass: union of invalid bits so every rank drops the same set.
    std::vector<uint64_t> inv(num_words, 0);
    for (uint32_t bit : invalid_bits_) {
      inv[1 + bit / 64] |= 1ull << (bit % 64);
    }
    controller->CrossRankBitwiseOr(inv);
    invalid_bits_.clear();
    for (uint32_t bit = 0; bit < num_active_bits_; ++bit) {
      if (inv[1 + bit / 64] & (1ull << (bit % 64))) {
        invalid_bits_.insert(bit);
        cache_hits_.erase(bit);
      }
    }
  }
  synced_ = true;
}

}  // namespace hvdtpu
