#include "fault.h"

#include <cstdlib>
#include <cstring>

#include "logging.h"
#include "metrics.h"

namespace hvdtpu {

const char* FaultActionName(FaultAction a) {
  switch (a) {
    case FaultAction::NONE: return "none";
    case FaultAction::DROP: return "drop";
    case FaultAction::DELAY: return "delay";
    case FaultAction::CORRUPT: return "corrupt";
    case FaultAction::CLOSE: return "close";
    case FaultAction::STALL: return "stall";
  }
  return "?";
}

namespace {

bool ParseChan(const std::string& v, int* out) {
  if (v == "any") { *out = -1; return true; }
  if (v == "control") { *out = static_cast<int>(Channel::CONTROL); return true; }
  if (v == "ring") { *out = static_cast<int>(Channel::RING); return true; }
  if (v == "local") { *out = static_cast<int>(Channel::LOCAL_RING); return true; }
  if (v == "cross") { *out = static_cast<int>(Channel::CROSS_RING); return true; }
  if (v == "shm") { *out = static_cast<int>(Channel::SHM); return true; }
  return false;
}

bool ParseAction(const std::string& v, FaultAction* out) {
  if (v == "drop") { *out = FaultAction::DROP; return true; }
  if (v == "delay") { *out = FaultAction::DELAY; return true; }
  if (v == "corrupt") { *out = FaultAction::CORRUPT; return true; }
  if (v == "close") { *out = FaultAction::CLOSE; return true; }
  if (v == "stall") { *out = FaultAction::STALL; return true; }
  return false;
}

}  // namespace

void FaultInjector::Configure(const char* spec, int rank) {
  std::lock_guard<std::mutex> lk(mutex_);
  rules_.clear();
  rank_ = rank;
  uint64_t seed = 0;
  bool ok = true;
  if (spec != nullptr && spec[0] != '\0') {
    for (const std::string& clause : SplitString(spec, ';')) {
      if (clause.empty()) continue;
      if (clause.compare(0, 5, "seed=") == 0) {
        seed = std::strtoull(clause.c_str() + 5, nullptr, 10);
        continue;
      }
      Rule rule;
      for (const std::string& field : SplitString(clause, ',')) {
        auto eq = field.find('=');
        if (eq == std::string::npos) { ok = false; break; }
        std::string key = field.substr(0, eq);
        std::string val = field.substr(eq + 1);
        if (key == "rank") {
          rule.rank = std::atoi(val.c_str());
        } else if (key == "chan") {
          ok = ParseChan(val, &rule.chan) && ok;
        } else if (key == "dir") {
          if (val == "any") rule.dir = -1;
          else if (val == "send") rule.dir = 0;
          else if (val == "recv") rule.dir = 1;
          else ok = false;
        } else if (key == "frame") {
          rule.frame = std::strtoll(val.c_str(), nullptr, 10);
        } else if (key == "prob") {
          rule.prob = std::strtod(val.c_str(), nullptr);
        } else if (key == "count") {
          rule.count = std::strtoll(val.c_str(), nullptr, 10);
        } else if (key == "delay_ms") {
          rule.delay_ms = std::atoi(val.c_str());
        } else if (key == "action") {
          ok = ParseAction(val, &rule.action) && ok;
        } else {
          ok = false;
        }
      }
      if (rule.action == FaultAction::NONE) ok = false;
      if (rule.count < 0 && rule.frame >= 0) rule.count = 1;
      if (rule.delay_ms == 0 && rule.action == FaultAction::STALL) {
        rule.delay_ms = 600000;  // effectively a hang; deadlines must fire
      }
      if (rule.delay_ms == 0 && rule.action == FaultAction::DELAY) {
        rule.delay_ms = 100;
      }
      if (ok) rules_.push_back(rule);
    }
    if (!ok) {
      LOG(ERROR) << "HVD_TPU_FAULT_SPEC parse error in \"" << spec
                 << "\" — fault injection disabled (see docs/CHAOS.md "
                 << "for the grammar)";
      rules_.clear();
    } else if (!rules_.empty()) {
      LOG(WARNING) << "fault injection ACTIVE (rank " << rank << ", seed "
                   << seed << ", " << rules_.size() << " rule(s)): \""
                   << spec << "\"";
    }
  }
  rng_.seed(seed ^ (0x9E3779B97F4A7C15ull * static_cast<uint64_t>(rank + 1)));
  fires_.store(0, std::memory_order_relaxed);
  active_.store(!rules_.empty(), std::memory_order_relaxed);
}

FaultDecision FaultInjector::OnFrame(Channel chan, bool send, bool shm) {
  FaultDecision d;
  if (!active()) return d;
  std::lock_guard<std::mutex> lk(mutex_);
  for (auto& rule : rules_) {
    if (rule.rank >= 0 && rule.rank != rank_) continue;
    // chan=shm filters by TRANSPORT (a data-plane leg riding a shared-
    // memory ring, whatever its logical channel); chan=ring/local/cross
    // keep matching by LOGICAL channel regardless of transport, so
    // pre-shm specs and their frame counters are unchanged when the
    // shm plane engages (docs/CHAOS.md).
    if (rule.chan == static_cast<int>(Channel::SHM)) {
      if (!shm) continue;
    } else if (rule.chan >= 0 && rule.chan != static_cast<int>(chan)) {
      continue;
    }
    if (rule.dir >= 0 && rule.dir != (send ? 0 : 1)) continue;
    int64_t idx = rule.seen++;
    if (rule.count == 0) continue;  // exhausted
    bool fire = false;
    if (rule.frame >= 0) {
      fire = idx == rule.frame;
    } else if (rule.prob > 0.0) {
      fire = std::uniform_real_distribution<double>(0.0, 1.0)(rng_) <
             rule.prob;
    }
    if (!fire) continue;
    if (rule.count > 0) --rule.count;
    d.action = rule.action;
    d.delay_ms = rule.delay_ms;
    fires_.fetch_add(1, std::memory_order_relaxed);
    Metrics& m = GlobalMetrics();
    m.faults_injected_total.fetch_add(1, std::memory_order_relaxed);
    switch (rule.action) {
      case FaultAction::DROP:
        m.fault_drop_total.fetch_add(1, std::memory_order_relaxed); break;
      case FaultAction::DELAY:
        m.fault_delay_total.fetch_add(1, std::memory_order_relaxed); break;
      case FaultAction::CORRUPT:
        m.fault_corrupt_total.fetch_add(1, std::memory_order_relaxed); break;
      case FaultAction::CLOSE:
        m.fault_close_total.fetch_add(1, std::memory_order_relaxed); break;
      case FaultAction::STALL:
        m.fault_stall_total.fetch_add(1, std::memory_order_relaxed); break;
      case FaultAction::NONE: break;
    }
    LOG(WARNING) << "fault injected: " << FaultActionName(rule.action)
                 << " on " << (send ? "send" : "recv") << " frame " << idx
                 << " chan " << static_cast<int>(chan) << " (rank " << rank_
                 << ")";
    return d;  // first matching rule that fires wins
  }
  return d;
}

FaultInjector& GlobalFaultInjector() {
  static FaultInjector* injector = new FaultInjector();  // leaked: outlives threads
  return *injector;
}

}  // namespace hvdtpu
