// Live metrics plane: a lock-light registry of counters, gauges, and
// fixed-bucket histograms populated from the coordination hot paths
// (SURVEY 5.5 names the gap: "No metrics-server/Prometheus-style
// subsystem" in the reference — its only observability is the post-hoc
// timeline file and log-only stall warnings).
//
// Concurrency model: hot-path writes are single atomic RMWs with relaxed
// ordering (the background coordination thread and enqueue threads never
// take a lock here); snapshot readers (the C API / the Python scraper
// thread) read the same atomics. The only mutex guards the COLD per-rank
// state on the coordinator: worker summaries ingested once per piggyback
// (~1/s) and the per-rank announce-lag accumulators (once per tensor
// completion). `make check-tsan` runs the negotiation fuzz with an active
// scraper thread to prove the discipline.
//
// Counters are MONOTONIC for the life of the process (Prometheus
// convention) — unlike the per-generation protocol counters
// (tcp_context.h), they deliberately survive elastic re-init so a scrape
// never sees a counter go backwards. Gauges and rank-scoped state reset
// with each generation (Configure()).
#ifndef HVD_TPU_METRICS_H
#define HVD_TPU_METRICS_H

#include <atomic>
#include <chrono>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace hvdtpu {

// Fixed upper-bound-bucket histogram (atomics only; +Inf bucket implicit
// as counts[bounds.size()]). `scale` converts the observed double into
// the integer units the sum accumulates in (1e6 for seconds -> the sum
// stays exact to the microsecond without atomic<double>).
class MetricHistogram {
 public:
  MetricHistogram(std::vector<double> bounds, double scale);

  void Observe(double v);

  struct Snapshot {
    std::vector<double> bounds;
    std::vector<uint64_t> counts;  // bounds.size() + 1 (last = overflow)
    double sum = 0.0;
    uint64_t count = 0;
  };
  Snapshot snapshot() const;
  double sum() const;
  uint64_t count() const { return count_.load(std::memory_order_relaxed); }

 private:
  std::vector<double> bounds_;
  double scale_;
  std::unique_ptr<std::atomic<uint64_t>[]> counts_;
  std::atomic<int64_t> sum_scaled_{0};
  std::atomic<uint64_t> count_{0};
};

// Compact per-rank summary piggybacked on the RequestList wire (the same
// channel PR 2 used for call digests). Wire order == enum order; the
// count prefix makes additions forward-compatible (an older decoder
// ignores the tail, a newer one zero-fills).
enum SummaryField : int {
  SUM_CYCLES_TOTAL = 0,
  SUM_CYCLES_FAST,
  SUM_CYCLES_FULL,
  SUM_CYCLE_SECONDS_SUM,
  SUM_TENSORS_ENQUEUED,
  SUM_TENSORS_PERFORMED,
  SUM_RESPONSES_PERFORMED,
  SUM_BYTES_PERFORMED,
  SUM_FUSED_TENSORS,
  SUM_FUSED_BYTES,
  SUM_CACHE_HIT,
  SUM_CACHE_MISS,
  SUM_QUEUE_DEPTH,
  SUM_STALL_WARNINGS,
  SUM_DIVERGENCE_ERRORS,
  SUM_NEGOTIATION_SECONDS_SUM,
  SUM_NEGOTIATION_COUNT,
  // Transport robustness (PR 4, docs/CHAOS.md). Appended AFTER the
  // original 17 fields — the count prefix keeps the wire
  // forward-compatible with pre-chaos decoders.
  SUM_NET_CRC_ERRORS,
  SUM_NET_TIMEOUTS,
  SUM_NET_RECONNECTS,
  SUM_FAULTS_INJECTED,
  // Durable checkpoints (docs/ELASTIC.md "Durability"). Appended after
  // the chaos fields, same forward-compatibility rule.
  SUM_CKPT_WRITES,
  SUM_CKPT_WRITE_FAILURES,
  SUM_LAST_DURABLE_STEP,
  // Wire compression (docs/COMPRESSION.md). Appended after the durable
  // fields; an older worker's summary simply lacks the tail and the job
  // view / hvd-top render "-" for it instead of misaligning.
  SUM_COMPRESSION_BYTES_IN,
  SUM_COMPRESSION_BYTES_OUT,
  SUM_NET_RING_BYTES_SENT,
  // Graceful drain (docs/FLEET.md). Appended after the compression
  // fields, same forward-compatibility rule: drain requests this worker
  // honored and whether it is currently draining (1) / surviving a
  // peer's drain (0) / has never seen one (-1).
  SUM_DRAINS_REQUESTED,
  SUM_DRAINING,
  // Sharded weight update (docs/ZERO.md). Appended after the drain
  // fields: executed reduce-scatter collectives and this rank's reported
  // optimizer-state bytes (-1 = never reported); older decoders ignore
  // the tail.
  SUM_REDUCE_SCATTER,
  SUM_OPT_STATE_BYTES,
  // Always-on closed-loop autotune (docs/AUTOTUNE.md). Appended after
  // the sharded fields: whether this rank's tuner is actively sampling
  // (1) or converged (0) and how many times it re-armed; the hvd-top
  // `tun` column renders them ('-' for a pre-autotune worker's summary).
  SUM_AUTOTUNE_ACTIVE,
  SUM_AUTOTUNE_REARMS,
  // Process groups (docs/GROUPS.md). Appended after the autotune
  // fields: registered groups on this rank and group-scoped tensors it
  // executed; the hvd-top `grp` column renders them ('-' for a
  // pre-groups worker's summary).
  SUM_GROUPS,
  SUM_GROUP_TENSORS,
  // Shared-memory data plane (docs/TRANSPORT.md). Appended last: live
  // attached segments on this rank and payload bytes its ring legs
  // moved through shared memory instead of loopback TCP; the hvd-top
  // `shm` column renders them ('-' for a pre-shm worker's summary).
  SUM_SHM_SEGMENTS,
  SUM_SHM_BYTES_SENT,
  // Distributed tracing + flight recorder (docs/TRACING.md). Appended
  // after the shm fields: spans recorded / spans lost to ring overrun
  // on this rank, and post-mortem bundles it wrote; the hvd-top `trc`
  // column renders them ('-' for a pre-trace worker's summary). The
  // values live in the Trace singleton (trace.h) — Summary() reads
  // them through GlobalTrace() like any other registry field.
  SUM_TRACE_SPANS,
  SUM_TRACE_SPANS_DROPPED,
  SUM_BUNDLES_WRITTEN,
  SUM_FIELD_COUNT
};
const char* SummaryFieldName(int field);

class Metrics {
 public:
  Metrics();

  // --- hot-path counters (background thread + enqueue threads) ---
  std::atomic<uint64_t> cycles_total{0};
  std::atomic<uint64_t> cycles_fast_total{0};
  std::atomic<uint64_t> cycles_full_total{0};
  std::atomic<uint64_t> tensors_enqueued_total{0};
  std::atomic<uint64_t> responses_performed_total{0};
  std::atomic<uint64_t> tensors_performed_total{0};
  std::atomic<uint64_t> bytes_performed_total{0};
  std::atomic<uint64_t> fused_tensors_total{0};
  std::atomic<uint64_t> fused_bytes_total{0};
  std::atomic<uint64_t> cache_hit_total{0};
  std::atomic<uint64_t> cache_miss_total{0};
  std::atomic<uint64_t> cache_invalid_total{0};
  std::atomic<uint64_t> stall_warnings_total{0};
  std::atomic<uint64_t> stall_missing_rank_micros_total{0};
  std::atomic<uint64_t> divergence_errors_total{0};
  std::atomic<uint64_t> error_responses_total{0};
  std::atomic<uint64_t> init_total{0};

  // --- transport robustness (net.cc / tcp_context.cc / fault.cc) ---
  std::atomic<uint64_t> net_crc_errors_total{0};       // checksum mismatches
  std::atomic<uint64_t> net_recv_timeouts_total{0};    // SO_RCVTIMEO expiry
  std::atomic<uint64_t> net_send_timeouts_total{0};    // SO_SNDTIMEO expiry
  std::atomic<uint64_t> net_oversize_frames_total{0};  // > MAX_FRAME_BYTES
  std::atomic<uint64_t> net_reconnect_attempts_total{0};
  std::atomic<uint64_t> net_reconnects_total{0};       // successful resumes
  std::atomic<uint64_t> faults_injected_total{0};      // all injected faults
  std::atomic<uint64_t> fault_drop_total{0};
  std::atomic<uint64_t> fault_delay_total{0};
  std::atomic<uint64_t> fault_corrupt_total{0};
  std::atomic<uint64_t> fault_close_total{0};
  std::atomic<uint64_t> fault_stall_total{0};

  // --- wire compression (compression.cc / cpu_operations.cc) ---
  // Codec throughput: f32 bytes entering the compressor vs bytes put on
  // the wire (the ratio is the live compression factor), plus encode-op
  // counts per mode and allreduce executions per negotiated mode.
  std::atomic<uint64_t> compression_bytes_in_total{0};
  std::atomic<uint64_t> compression_bytes_out_total{0};
  std::atomic<uint64_t> compression_bf16_total{0};   // encode calls
  std::atomic<uint64_t> compression_int8_total{0};   // encode calls
  std::atomic<uint64_t> allreduce_uncompressed_total{0};
  std::atomic<uint64_t> allreduce_bf16_total{0};
  std::atomic<uint64_t> allreduce_int8_total{0};
  // Data-ring wire accounting (frame headers included): the quantity
  // the compression stage shrinks, measured at the transport layer —
  // bench.py --compression reads the A/B from these. Counts data-plane
  // bytes WHATEVER the transport (loopback TCP or an intra-host shm
  // ring), so a compression ratio A/B is transport-independent; the
  // net_shm_* counters below split out the shm share.
  std::atomic<uint64_t> net_ring_bytes_sent_total{0};
  std::atomic<uint64_t> net_ring_bytes_recv_total{0};

  // --- shared-memory data plane (tcp_context.cc / docs/TRANSPORT.md) ---
  // Payload+header bytes ring legs moved through shared-memory segments
  // (also counted in net_ring_bytes_* above — these isolate the shm
  // share so bench.py --shm can prove the plane engaged).
  std::atomic<uint64_t> net_shm_bytes_sent_total{0};
  std::atomic<uint64_t> net_shm_bytes_recv_total{0};

  // --- durable checkpoints (elastic/durable.py via the C API) ---
  std::atomic<uint64_t> ckpt_writes_total{0};          // published snapshots
  std::atomic<uint64_t> ckpt_write_failures_total{0};  // degraded writes
  std::atomic<uint64_t> ckpt_bytes_total{0};           // shard bytes written
  std::atomic<uint64_t> ckpt_restores_total{0};        // successful restores
  std::atomic<uint64_t> ckpt_restore_failures_total{0};

  // --- graceful drain (elastic/run.py via the C API; docs/FLEET.md) ---
  std::atomic<uint64_t> drains_requested_total{0};  // agreed drain epochs

  // --- sharded weight update (cpu_operations.cc / docs/ZERO.md) ---
  std::atomic<uint64_t> reduce_scatter_total{0};  // executed reduce-scatters
  // Full-tensor payload bytes entering reduce-scatter executions (the
  // shard each rank keeps is 1/N of this).
  std::atomic<uint64_t> reduce_scatter_bytes_total{0};
  // Reduce-scatters that took the two-level (intra-host reduce ->
  // inter-host ring -> shard distribution) composite path.
  std::atomic<uint64_t> reduce_scatter_hierarchical_total{0};

  // --- pipelined ring transport (cpu_operations.cc / docs/AUTOTUNE.md) ---
  // Segment exchanges issued by the double-buffered pipelined hops (a
  // hop that ran unsliced contributes nothing here).
  std::atomic<uint64_t> pipeline_segments_total{0};

  // --- always-on closed-loop autotune (parameter_manager / operations.cc) ---
  std::atomic<uint64_t> autotune_rearms_total{0};

  // --- process groups (controller.cc / operations.cc; docs/GROUPS.md) ---
  // Group-scoped tensors this rank EXECUTED (non-members of a group
  // skip its responses and contribute nothing).
  std::atomic<uint64_t> group_tensors_total{0};
  // Coordinator-side per-group negotiation counters, rendered as
  // group-labeled Prometheus families. Fixed slots: group ids 1..16
  // are tracked individually; higher ids still count into
  // group_negotiated_overflow_total (no silent drop).
  static constexpr int kGroupStatSlots = 16;
  std::atomic<uint64_t> group_negotiated_total[kGroupStatSlots] = {};
  std::atomic<uint64_t> group_negotiated_overflow_total{0};
  void AddGroupNegotiated(uint32_t group_id, uint64_t tensors) {
    if (group_id >= 1 &&
        group_id <= static_cast<uint32_t>(kGroupStatSlots)) {
      group_negotiated_total[group_id - 1].fetch_add(
          tensors, std::memory_order_relaxed);
    } else {
      group_negotiated_overflow_total.fetch_add(tensors,
                                                std::memory_order_relaxed);
    }
  }

  // --- gauges (instantaneous; reset per generation) ---
  std::atomic<int64_t> queue_depth{0};
  std::atomic<int64_t> pending_negotiation{0};
  std::atomic<int64_t> elastic_generation{0};
  std::atomic<int64_t> world_size{0};
  std::atomic<int64_t> rank{-1};
  std::atomic<int64_t> fusion_threshold_bytes{0};
  // Newest step known durable on THIS rank's storage view (-1 = none).
  // Deliberately survives Configure(): an elastic re-init does not
  // un-write a checkpoint.
  std::atomic<int64_t> last_durable_step{-1};
  // Drain posture: -1 = never saw a drain, 1 = this worker is the
  // victim of the current drain epoch (about to durable-commit and
  // exit), 0 = it survived a peer's drain. Survives Configure() like
  // last_durable_step — a post-drain re-init does not erase history.
  std::atomic<int64_t> draining{-1};
  // Optimizer-state bytes held by THIS rank, reported by the sharded
  // optimizer wrappers (docs/ZERO.md; -1 = never reported). Reset per
  // generation: an elastic resize re-shards the state and re-reports.
  std::atomic<int64_t> opt_state_bytes{-1};
  // Live tuner posture: 1 while actively sampling, 0 once converged
  // (docs/AUTOTUNE.md). Updated from the background loop each cycle.
  std::atomic<int64_t> autotune_active{0};
  // Pipelined-ring segment size currently in force (0 = slicing off).
  std::atomic<int64_t> pipeline_chunk_bytes{0};
  // Registered process groups (group_table.h; reset per generation —
  // re-init clears the table and Python re-creates the mesh groups).
  std::atomic<int64_t> groups{0};
  // Live attached shared-memory segments (writer + reader side both
  // count; maintained by ShmSegmentTable, shm_context.cc). A fresh
  // value is stored on every attach/close, so it tracks re-inits
  // naturally.
  std::atomic<int64_t> shm_segments_active{0};

  // --- histograms ---
  MetricHistogram cycle_seconds;        // background work-cycle duration
  MetricHistogram negotiation_seconds;  // coordinator: first announce -> response
  MetricHistogram cycle_tensors;        // tensors executed per work cycle
  MetricHistogram cycle_bytes;          // payload bytes executed per work cycle
  MetricHistogram fusion_fill_ratio;    // fused payload / fusion threshold
  MetricHistogram ckpt_write_seconds;   // durable shard write+publish time
  MetricHistogram compression_seconds;  // one encode/decode call's CPU time

  // Whether the metrics PLANE (wire piggyback, forced sync cycles, HTTP
  // serving) is live — HVD_TPU_METRICS=1 or HVD_TPU_METRICS_PORT set.
  // The registry itself always counts (single relaxed atomics, the same
  // cost class as the pre-existing perf counters).
  void set_enabled(bool v) { enabled_.store(v, std::memory_order_relaxed); }
  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }

  // Generation (re)start: sizes the per-rank state, resets gauges and
  // rank-scoped accumulators. Counters deliberately persist.
  void Configure(int world_size, int rank);

  // Coordinator: rank announced a pending tensor `seconds` after its
  // first announcement (0 for the first announcer). The accumulated
  // per-rank lag is the straggler signal: the rank the job spends the
  // most time waiting on has the largest total. Takes the rank mutex —
  // callers gate on the metrics plane being enabled so metrics-off jobs
  // never touch it from the negotiation path.
  void AddRankLag(int rank, double seconds);

  // This rank's compact summary (SummaryField order).
  std::vector<double> Summary() const;
  // Coordinator: ingest a worker's piggybacked summary.
  void SetRankSummary(int rank, const std::vector<double>& values);

  // Full registry snapshot of THIS worker, as JSON (consumed by
  // hvd.metrics() and the Prometheus renderer in Python).
  std::string SnapshotJson() const;
  // Coordinator job view: per-rank summaries (+ own, fresh), summary
  // staleness, and the per-rank announce-lag table. "{}" off-coordinator.
  std::string JobJson() const;

 private:
  using Clock = std::chrono::steady_clock;

  std::atomic<bool> enabled_{false};

  mutable std::mutex rank_mutex_;
  // Announce-lag accumulators, indexed by rank (coordinator only).
  std::vector<double> rank_lag_seconds_;    // guarded_by(rank_mutex_)
  std::vector<uint64_t> rank_lag_count_;    // guarded_by(rank_mutex_)
  // Latest ingested summary per rank + receive time (coordinator only).
  std::vector<std::vector<double>> rank_summaries_;      // guarded_by(rank_mutex_)
  std::vector<Clock::time_point> rank_summary_time_;     // guarded_by(rank_mutex_)
  bool is_coordinator_ = false;
};

// Process-wide registry. A singleton (not a HorovodGlobalState member
// value) so leaf components without a state pointer — the stall
// inspector, the C snapshot API — reach it directly; global_state.h
// holds a reference for everything that does carry state.
Metrics& GlobalMetrics();

}  // namespace hvdtpu

#endif  // HVD_TPU_METRICS_H
