#include "shm_context.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <thread>

#ifdef __linux__
#include <linux/futex.h>
#include <sys/syscall.h>
#include <sys/time.h>
#endif

#include "logging.h"
#include "metrics.h"

namespace hvdtpu {

static constexpr uint32_t kShmMagic = 0x53484d52;  // "SHMR"
static constexpr uint32_t kShmVersion = 1;

bool ShmEnabled() {
  static bool v = [] {
    const char* e = std::getenv("HVD_TPU_SHM");
    return e == nullptr || e[0] != '0';
  }();
  return v;
}

bool ShmCrcEnabled() {
  static bool v = [] {
    const char* e = std::getenv("HVD_TPU_SHM_CRC");
    if (e != nullptr) return e[0] != '0';
    return NetCrcEnabled();
  }();
  return v;
}

std::size_t ShmSegmentBytes() {
  static std::size_t v = [] {
    const char* e = std::getenv("HVD_TPU_SHM_SEGMENT_BYTES");
    // Default 4 MiB: large enough that a typical ring chunk (a 2-rank
    // hop of a 4 MB fused buffer is 2 MB) fits the ring whole, so the
    // writer publishes without ping-ponging with the reader's drain —
    // on small hosts the context-switch cadence, not the copies, is
    // what that saves.
    long long b = e ? std::strtoll(e, nullptr, 10) : (4ll << 20);
    // Floor: one frame header plus a sane payload slice must fit, and
    // the double-buffered pipelining the ring exists for needs at least
    // two slices in flight.
    if (b < 4096) b = 4096;
    return static_cast<std::size_t>(b);
  }();
  return v;
}

std::string ShmSegmentName(int my_rank, int peer_rank, int channel,
                           uint32_t generation) {
  static std::atomic<uint64_t> counter{0};
  char buf[96];
  std::snprintf(buf, sizeof(buf), "/hvdtpu-%d-%u-%d-%d-%d-%llu",
                static_cast<int>(::getpid()), generation, channel, my_rank,
                peer_rank,
                static_cast<unsigned long long>(
                    counter.fetch_add(1, std::memory_order_relaxed)));
  return buf;
}

std::string ShmHostKey(const std::string& addr_host, int cross_rank,
                       int cross_size) {
  if (cross_size > 1) {
    return addr_host + "/c" + std::to_string(cross_rank);
  }
  return addr_host;
}

// ---------------- futex ----------------

static void FutexWait(std::atomic<uint32_t>* addr, uint32_t expected,
                      int timeout_ms) {
#ifdef __linux__
  struct timespec ts;
  ts.tv_sec = timeout_ms / 1000;
  ts.tv_nsec = (timeout_ms % 1000) * 1000000l;
  ::syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT,
            expected, &ts, nullptr, 0);
#else
  (void)addr;
  (void)expected;
  std::this_thread::sleep_for(std::chrono::milliseconds(
      timeout_ms < 1 ? 1 : std::min(timeout_ms, 2)));
#endif
}

static void FutexWake(std::atomic<uint32_t>* addr) {
#ifdef __linux__
  ::syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE,
            INT32_MAX, nullptr, nullptr, 0);
#else
  (void)addr;
#endif
}

// ---------------- segment table ----------------

void ShmSegmentTable::Register(ShmRing* ring) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    rings_.push_back(ring);
    if (ring->creator()) pending_.push_back(ring->name());
  }
  GlobalMetrics().shm_segments_active.store(active(),
                                            std::memory_order_relaxed);
}

void ShmSegmentTable::Unregister(ShmRing* ring) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    rings_.erase(std::remove(rings_.begin(), rings_.end(), ring),
                 rings_.end());
    pending_.erase(
        std::remove(pending_.begin(), pending_.end(), ring->name()),
        pending_.end());
  }
  GlobalMetrics().shm_segments_active.store(active(),
                                            std::memory_order_relaxed);
}

int ShmSegmentTable::active() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int>(rings_.size());
}

void ShmSegmentTable::SweepNames() {
  std::lock_guard<std::mutex> lk(mu_);
  for (const auto& name : pending_) ::shm_unlink(name.c_str());
  pending_.clear();
}

ShmSegmentTable& GlobalShmSegments() {
  static ShmSegmentTable* table = new ShmSegmentTable();  // outlives threads
  return *table;
}

// ---------------- ShmRing ----------------

std::unique_ptr<ShmRing> ShmRing::Create(const std::string& name,
                                         std::size_t capacity) {
  int fd = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    LOG(WARNING) << "shm_open(" << name << ") failed: " << strerror(errno)
                 << " — pair falls back to TCP";
    return nullptr;
  }
  std::size_t total = sizeof(ShmRingHeader) + capacity;
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name.c_str());
    return nullptr;
  }
  void* map = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    ::shm_unlink(name.c_str());
    return nullptr;
  }
  std::unique_ptr<ShmRing> ring(new ShmRing(name, /*creator=*/true));
  ring->fd_ = fd;
  ring->map_bytes_ = total;
  ring->hdr_ = new (map) ShmRingHeader();
  ring->hdr_->capacity = capacity;
  ring->hdr_->head.store(0, std::memory_order_relaxed);
  ring->hdr_->tail.store(0, std::memory_order_relaxed);
  ring->hdr_->data_seq.store(0, std::memory_order_relaxed);
  ring->hdr_->space_seq.store(0, std::memory_order_relaxed);
  ring->hdr_->read_waiters.store(0, std::memory_order_relaxed);
  ring->hdr_->write_waiters.store(0, std::memory_order_relaxed);
  ring->hdr_->closed.store(0, std::memory_order_relaxed);
  ring->hdr_->version = kShmVersion;
  // Magic last, release: an attacher that sees the magic sees a fully
  // initialized header.
  ring->data_ = static_cast<char*>(map) + sizeof(ShmRingHeader);
  std::atomic_thread_fence(std::memory_order_release);
  ring->hdr_->magic = kShmMagic;
  GlobalShmSegments().Register(ring.get());
  return ring;
}

std::unique_ptr<ShmRing> ShmRing::Attach(const std::string& name) {
  int fd = ::shm_open(name.c_str(), O_RDWR, 0600);
  if (fd < 0) {
    LOG(WARNING) << "shm attach(" << name << ") failed: " << strerror(errno)
                 << " — pair falls back to TCP";
    return nullptr;
  }
  struct stat st;
  if (::fstat(fd, &st) != 0 ||
      static_cast<std::size_t>(st.st_size) <= sizeof(ShmRingHeader)) {
    ::close(fd);
    return nullptr;
  }
  std::size_t total = static_cast<std::size_t>(st.st_size);
  void* map = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  ShmRingHeader* hdr = static_cast<ShmRingHeader*>(map);
  if (hdr->magic != kShmMagic || hdr->version != kShmVersion ||
      sizeof(ShmRingHeader) + hdr->capacity != total) {
    LOG(WARNING) << "shm attach(" << name
                 << "): header mismatch — pair falls back to TCP";
    ::munmap(map, total);
    ::close(fd);
    return nullptr;
  }
  std::unique_ptr<ShmRing> ring(new ShmRing(name, /*creator=*/false));
  ring->fd_ = fd;
  ring->map_bytes_ = total;
  ring->hdr_ = hdr;
  ring->data_ = static_cast<char*>(map) + sizeof(ShmRingHeader);
  GlobalShmSegments().Register(ring.get());
  return ring;
}

ShmRing::~ShmRing() { Close(); }

void ShmRing::MarkExchanged() {
  if (creator_ && !unlinked_) {
    ::shm_unlink(name_.c_str());
    unlinked_ = true;
    std::lock_guard<std::mutex> lk(GlobalShmSegments().mu_);
    auto& pending = GlobalShmSegments().pending_;
    pending.erase(std::remove(pending.begin(), pending.end(), name_),
                  pending.end());
  }
}

void ShmRing::Close() {
  if (hdr_ == nullptr) return;
  hdr_->closed.store(1, std::memory_order_release);
  // Wake a peer parked on either futex so it observes the hangup now,
  // not at its wait timeout (unconditional: hangup is rare and a missed
  // wake here would cost a full wait timeout).
  hdr_->data_seq.fetch_add(1, std::memory_order_release);
  hdr_->space_seq.fetch_add(1, std::memory_order_release);
  FutexWake(&hdr_->data_seq);
  FutexWake(&hdr_->space_seq);
  GlobalShmSegments().Unregister(this);
  MarkExchanged();
  ::munmap(static_cast<void*>(hdr_), map_bytes_);
  hdr_ = nullptr;
  data_ = nullptr;
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool ShmRing::closed() const {
  return hdr_ == nullptr || hdr_->closed.load(std::memory_order_acquire) != 0;
}

// Per-call move quantum: a counter (head/tail) only advances AFTER its
// memcpy, so one giant move would serialize the producer's copy-in
// against the consumer's copy-out. Bounded moves publish progress
// incrementally and the two sides' copies overlap — the shm analogue of
// TCP's segment-sized pipelining, with zero syscalls.
static constexpr std::size_t kShmMoveQuantum = 128 << 10;

int64_t ShmRing::WriteSome(const void* buf, std::size_t len) {
  if (closed()) return -1;
  uint64_t head = hdr_->head.load(std::memory_order_relaxed);
  uint64_t tail = hdr_->tail.load(std::memory_order_acquire);
  std::size_t cap = hdr_->capacity;
  std::size_t space = cap - static_cast<std::size_t>(head - tail);
  if (space == 0) return 0;
  std::size_t n = std::min(std::min(len, space), kShmMoveQuantum);
  std::size_t pos = static_cast<std::size_t>(head % cap);
  std::size_t first = std::min(n, cap - pos);
  std::memcpy(data_ + pos, buf, first);
  if (n > first) {
    std::memcpy(data_, static_cast<const char*>(buf) + first, n - first);
  }
  hdr_->head.store(head + n, std::memory_order_release);
  // seq bump is unconditional (the kernel's FUTEX_WAIT compare makes a
  // parked peer with a stale expected value return immediately); the
  // WAKE syscall only fires when the reader announced it is parked.
  // seq_cst on the bump and the waiters load pairs with the reader's
  // seq_cst store/load (WaitReadable): in the SC order either the
  // reader sees the new seq (no sleep) or the writer sees the waiter
  // flag (wake) — a missed wake is impossible.
  hdr_->data_seq.fetch_add(1, std::memory_order_seq_cst);
  if (hdr_->read_waiters.load(std::memory_order_seq_cst) != 0) {
    FutexWake(&hdr_->data_seq);
  }
  return static_cast<int64_t>(n);
}

int64_t ShmRing::ReadSome(void* buf, std::size_t len) {
  if (hdr_ == nullptr) return -1;
  uint64_t tail = hdr_->tail.load(std::memory_order_relaxed);
  uint64_t head = hdr_->head.load(std::memory_order_acquire);
  std::size_t avail = static_cast<std::size_t>(head - tail);
  if (avail == 0) {
    // Drained AND hung up = EOF; closed with bytes still in flight
    // drains first (orderly shutdown mirrors TCP semantics).
    return closed() ? -1 : 0;
  }
  std::size_t cap = hdr_->capacity;
  std::size_t n = std::min(std::min(len, avail), kShmMoveQuantum);
  std::size_t pos = static_cast<std::size_t>(tail % cap);
  std::size_t first = std::min(n, cap - pos);
  std::memcpy(buf, data_ + pos, first);
  if (n > first) {
    std::memcpy(static_cast<char*>(buf) + first, data_, n - first);
  }
  hdr_->tail.store(tail + n, std::memory_order_release);
  hdr_->space_seq.fetch_add(1, std::memory_order_seq_cst);
  if (hdr_->write_waiters.load(std::memory_order_seq_cst) != 0) {
    FutexWake(&hdr_->space_seq);
  }
  return static_cast<int64_t>(n);
}

// Spin budget before parking: covers the common case where the peer is
// actively pumping the other end of the same exchange (it publishes
// within the spin, saving the ~10-20us park+wake round trip) without
// burning a core while it encodes a big chunk. Sized in PAUSE terms:
// a modern PAUSE is ~140 cycles, so 64 of them is a few microseconds —
// a longer spin would cost more than the futex park it avoids
// (measured on this container; bench.py --shm).
static constexpr int kSpinIters = 64;

// Polite spin: the PAUSE hint keeps a spinning hyperthread/core from
// flooding the coherence fabric with speculative loads of the line the
// peer is actively writing (its memcpy shares the same LLC here).
static inline void CpuRelax() {
#if defined(__x86_64__) || defined(__i386__)
  __builtin_ia32_pause();
#elif defined(__aarch64__)
  asm volatile("yield");
#endif
}

void ShmRing::WaitReadable(int timeout_ms) {
  if (hdr_ == nullptr) return;
  for (int i = 0; i < kSpinIters; ++i) {
    if (hdr_->head.load(std::memory_order_acquire) !=
            hdr_->tail.load(std::memory_order_relaxed) ||
        closed()) {
      return;
    }
    CpuRelax();
  }
  // Announce the park BEFORE loading the expected seq: paired with the
  // writer's seq_cst bump-then-load-waiters, either the writer sees the
  // flag (wake) or this load sees the bumped seq and FUTEX_WAIT's
  // compare returns immediately — a missed wake is impossible.
  hdr_->read_waiters.store(1, std::memory_order_seq_cst);
  uint32_t seq = hdr_->data_seq.load(std::memory_order_seq_cst);
  if (hdr_->head.load(std::memory_order_acquire) ==
          hdr_->tail.load(std::memory_order_relaxed) &&
      !closed()) {
    FutexWait(&hdr_->data_seq, seq, timeout_ms);
  }
  hdr_->read_waiters.store(0, std::memory_order_release);
}

void ShmRing::WaitWritable(int timeout_ms) {
  if (hdr_ == nullptr) return;
  std::size_t cap = hdr_->capacity;
  for (int i = 0; i < kSpinIters; ++i) {
    if (hdr_->head.load(std::memory_order_relaxed) -
                hdr_->tail.load(std::memory_order_acquire) <
            cap ||
        closed()) {
      return;
    }
    CpuRelax();
  }
  hdr_->write_waiters.store(1, std::memory_order_seq_cst);
  uint32_t seq = hdr_->space_seq.load(std::memory_order_seq_cst);
  if (hdr_->head.load(std::memory_order_relaxed) -
              hdr_->tail.load(std::memory_order_acquire) >=
          cap &&
      !closed()) {
    FutexWait(&hdr_->space_seq, seq, timeout_ms);
  }
  hdr_->write_waiters.store(0, std::memory_order_release);
}

bool ShmRing::WriteAll(const void* buf, std::size_t len, int deadline_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(deadline_ms);
  const char* p = static_cast<const char*>(buf);
  std::size_t done = 0;
  while (done < len) {
    int64_t n = WriteSome(p + done, len - done);
    if (n < 0) return false;
    if (n == 0) {
      if (std::chrono::steady_clock::now() >= deadline) return false;
      WaitWritable(5);
      continue;
    }
    done += static_cast<std::size_t>(n);
  }
  return true;
}

bool ShmRing::ReadAll(void* buf, std::size_t len, int deadline_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(deadline_ms);
  char* p = static_cast<char*>(buf);
  std::size_t done = 0;
  while (done < len) {
    int64_t n = ReadSome(p + done, len - done);
    if (n < 0) return false;
    if (n == 0) {
      if (std::chrono::steady_clock::now() >= deadline) return false;
      WaitReadable(5);
      continue;
    }
    done += static_cast<std::size_t>(n);
  }
  return true;
}

}  // namespace hvdtpu
