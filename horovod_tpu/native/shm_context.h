// Shared-memory intra-host data plane (docs/TRANSPORT.md).
//
// Every data-plane connection in this runtime is unidirectional (a ring
// member SENDS on its successor conn and RECEIVES on its predecessor
// conn — tcp_context.h PairExchange/PairBroadcast), so the shm
// transport is one single-producer single-consumer byte ring per
// connection: the CONNECTOR (the ring sender) creates the segment, the
// ACCEPTOR attaches read-only-in-role. A ring hop's payload then moves
// as one user-space memcpy per side instead of two kernel socket copies
// plus syscalls — the loopback-TCP overhead the original Horovod paper
// (arXiv 1802.05799) and the CUDA-aware-MPI characterization (arXiv
// 1810.11112) both identify as the dominant intra-node cost once the
// algorithm is ring-optimal.
//
// Segments are POSIX shm objects (shm_open) whose NAME is exchanged
// over the already-handshaked TCP connection (tcp_context.cc
// NegotiateShm): SCM_RIGHTS fd-passing needs a Unix-domain socket, so a
// memfd cannot cross the existing TCP rendezvous — named segments
// negotiated in-band fill that role, and the creator unlinks the name
// as soon as the peer has mapped it (the mappings keep it alive; no
// /dev/shm litter survives a crash of BOTH sides for longer than the
// next init's sweep of its own names).
//
// Signaling is spin-then-sleep: a reader/writer first spins briefly on
// the head/tail words (the common case — the peer is actively pumping),
// then parks on a futex word with a bounded timeout so a dead peer
// surfaces as a transport timeout, never a hang. The closed word makes
// an orderly hangup prompt in both directions.
//
// The frame protocol over the ring is IDENTICAL to the socket framing
// ([u32 tag][u64 len][u32 crc] + payload, net.h): CRC verification
// stays on by default (HVD_TPU_SHM_CRC=0 switches it off job-wide;
// memory is not a network, but a cheap end-to-end check catches DMA or
// logic corruption for ~free), so wire compression, pipelined
// segmenting, and the chaos invariant apply to shm legs unchanged.
#ifndef HVD_TPU_SHM_CONTEXT_H
#define HVD_TPU_SHM_CONTEXT_H

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "net.h"

namespace hvdtpu {

// Effective knob values (env, cached after first read).
bool ShmEnabled();              // HVD_TPU_SHM != 0 (default on; "0" = off)
bool ShmCrcEnabled();           // HVD_TPU_SHM_CRC (default: HVD_TPU_NET_CRC)
std::size_t ShmSegmentBytes();  // HVD_TPU_SHM_SEGMENT_BYTES, default 4 MiB

// Mapped-segment layout: one cache-line-padded header then `capacity`
// payload bytes. head/tail are free-running byte counters (head - tail
// = bytes in flight); data_seq/space_seq are the futex words the
// producer/consumer bump after publishing/consuming so the parked peer
// wakes; closed makes hangup prompt in both directions.
// Fields are grouped by WRITING side onto separate cache lines: the
// producer line (head, data_seq, write_waiters) is only ever stored by
// the writer, the consumer line only by the reader — so each side's
// hot-loop stores never invalidate a line the peer is also storing to
// (the ping-pong would tax every move on a shared-LLC host).
struct ShmRingHeader {
  uint32_t magic;
  uint32_t version;
  uint64_t capacity;
  // --- producer-written line ---
  alignas(64) std::atomic<uint64_t> head;       // bytes produced
  std::atomic<uint32_t> data_seq;               // bumped after publish
  std::atomic<uint32_t> write_waiters;          // writer announces a park
  // --- consumer-written line ---
  alignas(64) std::atomic<uint64_t> tail;       // bytes consumed
  std::atomic<uint32_t> space_seq;              // bumped after consume
  std::atomic<uint32_t> read_waiters;           // reader announces a park
  // --- rare events ---
  alignas(64) std::atomic<uint32_t> closed;     // either side hung up
};

// One direction of an intra-host pair: an SPSC byte ring in a POSIX shm
// segment. Exactly one of (writer, reader) per process per ring; all
// I/O happens on the background coordination thread (same discipline as
// the sockets it replaces).
class ShmRing {
 public:
  ~ShmRing();
  ShmRing(const ShmRing&) = delete;
  ShmRing& operator=(const ShmRing&) = delete;

  // Creator (writer) side: shm_open(O_CREAT|O_EXCL) + ftruncate + mmap.
  // Returns nullptr on failure (no /dev/shm, EEXIST, ...), which the
  // caller treats as "negotiate TCP instead".
  static std::unique_ptr<ShmRing> Create(const std::string& name,
                                         std::size_t capacity);
  // Attacher (reader) side: open + validate magic/version/capacity +
  // mmap. nullptr on any mismatch (the fallback path).
  static std::unique_ptr<ShmRing> Attach(const std::string& name);

  // Marks the ring closed, wakes any parked peer, and unmaps. Safe to
  // call twice. The creator additionally shm_unlinks (normally already
  // done at negotiation time — see MarkExchanged).
  void Close();
  bool closed() const;
  bool valid() const { return hdr_ != nullptr; }

  // Creator: the peer has mapped the segment — unlink the name now so
  // the kernel reclaims it when the last mapping drops, even on crash.
  void MarkExchanged();

  // Nonblocking progress: moves up to `len` bytes and returns how many
  // (0 = ring full/empty, would block), or -1 when the ring is closed.
  // Writer-side / reader-side respectively; never partial-syscall —
  // pure memcpy into/out of the mapped ring.
  int64_t WriteSome(const void* buf, std::size_t len);
  int64_t ReadSome(void* buf, std::size_t len);

  // Spin-then-sleep wait for readable bytes / writable space: spins a
  // short budget on the counter words, then parks on the futex word for
  // at most timeout_ms. Returns immediately when the condition already
  // holds or the ring is closed.
  void WaitReadable(int timeout_ms);
  void WaitWritable(int timeout_ms);

  // Blocking helpers for the tiny fixed-size header exchanges: false on
  // closed or when deadline_ms passes without completion.
  bool WriteAll(const void* buf, std::size_t len, int deadline_ms);
  bool ReadAll(void* buf, std::size_t len, int deadline_ms);

  std::size_t capacity() const { return hdr_ ? hdr_->capacity : 0; }
  const std::string& name() const { return name_; }
  bool creator() const { return creator_; }

 private:
  ShmRing(std::string name, bool creator) noexcept
      : name_(std::move(name)), creator_(creator) {}

  std::string name_;
  bool creator_ = false;
  bool unlinked_ = false;
  int fd_ = -1;
  ShmRingHeader* hdr_ = nullptr;
  char* data_ = nullptr;
  std::size_t map_bytes_ = 0;
};

// Process-wide registry of live segments: keeps the
// shm_segments_active gauge honest and lets Finalize/atexit sweep any
// creator-side name that never reached MarkExchanged (a peer that died
// mid-negotiation must not leave /dev/shm litter). Reached from the
// background thread (negotiation, Finalize) and the C selftest API, so
// the table is mutex-guarded.
class ShmSegmentTable {
 public:
  void Register(ShmRing* ring);
  void Unregister(ShmRing* ring);
  int active() const;
  // Unlinks every still-linked creator-side name (crash hygiene).
  void SweepNames();

 private:
  mutable std::mutex mu_;
  std::vector<ShmRing*> rings_;        // guarded_by(mu_)
  std::vector<std::string> pending_;   // guarded_by(mu_) names not yet unlinked

  friend class ShmRing;
};

ShmSegmentTable& GlobalShmSegments();

// Distinct, collision-free segment name for (rank pair, channel,
// generation): "/hvdtpu-<pid>-<gen>-<chan>-<me>-<peer>-<n>".
std::string ShmSegmentName(int my_rank, int peer_rank, int channel,
                           uint32_t generation);

// The PURE same-host key formula (one definition; TcpContext's
// DefaultHostKey/MyHostKey delegate here, the latter adding the
// per-rank HVD_TPU_HOST_KEY test override): the rank's HVD_TPU_ADDRS
// host, suffixed with its cross_rank when the topology is a forced
// multi-host grid (HVD_TPU_CROSS_SIZE > 1 on one physical box — the
// cross suffix keeps emulated "hosts" distinct; on real fleets ranks
// on one host share both the address and the cross index).
std::string ShmHostKey(const std::string& addr_host, int cross_rank,
                       int cross_size);

}  // namespace hvdtpu

#endif  // HVD_TPU_SHM_CONTEXT_H
