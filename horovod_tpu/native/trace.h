// Cross-rank distributed tracing + always-on flight recorder
// (docs/TRACING.md). Three pieces share one fixed-size ring of binary
// span records:
//
//  1. A per-rank, always-on span recorder instrumenting the full tensor
//     lifecycle (enqueue -> negotiation wait -> fuse -> exec -> per-hop
//     wire/encode/decode -> callback) plus serve-plane requests. The
//     hot path is lock-light in the metrics.h sense: one relaxed
//     fetch_add to claim a slot plus relaxed stores to publish it — a
//     seqlock variant where EVERY slot field is an atomic, so a reader
//     racing a wraparound sees a torn *sequence check*, never a torn
//     read (TSAN-clean by construction). Overruns drop spans and count
//     them (spans_dropped); recording never blocks.
//
//  2. NTP-style clock alignment: the worker stamps T1/T4 around its
//     FinishCycle gather/broadcast pair and the coordinator piggybacks
//     its own T2/T3 stamps on the ResponseList tail (message.cc), giving
//     offset = ((T2-T1)+(T3-T4))/2 with uncertainty = ((T4-T1)-(T3-T2))/2
//     — the classic symmetric-delay bound. Rank 0 is the reference
//     (offset 0 by definition); a new sample is adopted when its
//     uncertainty beats the current one or the current one is stale.
//     bin/hvd-trace uses the per-shard offsets to merge all ranks onto
//     rank 0's timebase.
//
//  3. A flight recorder: on stall escalation, divergence, connection
//     loss, drain, or a fatal signal, DumpBundle() writes ring contents
//     + metrics snapshot + pending-negotiation table + the last
//     kControlFrameLog control-frame headers + the clock offset to
//     HVD_TPU_BUNDLE_DIR as one JSON file. The launcher lists bundle
//     paths in its failure summary.
//
// Env: HVD_TPU_TRACE=0 disables recording (default on),
// HVD_TPU_TRACE_RING=N ring capacity (power of two, default 32768),
// HVD_TPU_TRACE_DIR=<dir> stream spans to <dir>/trace_rank<r>.jsonl,
// HVD_TPU_BUNDLE_DIR=<dir> where post-mortem bundles land (the launcher
// injects a default under its log dir).
#ifndef HVD_TPU_TRACE_H
#define HVD_TPU_TRACE_H

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace hvdtpu {

// Span phases, ordered by lifecycle position. Values are wire/shard
// format (bin/hvd-trace decodes them) — append only.
enum TracePhase : int {
  TRACE_ENQUEUE = 0,   // instant: tensor handed to the background queue
  TRACE_NEGOTIATE = 1, // enqueue -> response performed (the wait)
  TRACE_FUSE = 2,      // memcpy into the fusion buffer
  TRACE_EXEC = 3,      // ExecuteOperation (the collective itself)
  TRACE_WIRE_HOP = 4,  // one PairExchange leg (tcp or shm)
  TRACE_ENCODE = 5,    // compression encode call
  TRACE_DECODE = 6,    // compression decode call
  TRACE_CALLBACK = 7,  // user completion callback
  TRACE_REQUEST = 8,   // serve plane: one batched forward
};
const char* TracePhaseName(int p);

// One ring slot. Every field is an atomic so the drainer/bundle reader
// can race a wraparound without a data race; `seq` is the seqlock word:
// kSlotBusy while a writer is mid-publish, claim_index+1 once published.
// The name is stored as 6 relaxed 64-bit words (47 chars + NUL).
struct TraceSlot {
  static constexpr int kNameWords = 6;
  static constexpr uint64_t kBusy = ~0ull;
  std::atomic<uint64_t> seq{0};
  std::atomic<int64_t> t_start{0};
  std::atomic<int64_t> t_end{0};
  std::atomic<uint64_t> cycle{0};
  // phase(8) | flags(8) | group(16) | peer-as-u32(32).
  std::atomic<uint64_t> meta{0};
  std::atomic<int64_t> bytes{0};
  std::atomic<uint64_t> name[kNameWords] = {};
};

// A decoded (untorn) slot, for the drainer / bundle writer / tests.
struct TraceSpan {
  char name[TraceSlot::kNameWords * 8];
  int phase = 0;
  uint8_t flags = 0;
  uint32_t group = 0;
  int peer = -1;
  uint64_t cycle = 0;
  int64_t bytes = 0;
  int64_t t_start = 0;
  int64_t t_end = 0;
};

// Span flag bits.
constexpr uint8_t TRACE_FLAG_SHM = 1;  // wire hop rode a shm segment

class Trace {
 public:
  static constexpr int kControlFrameLog = 64;
  static constexpr int kMaxBundles = 8;

  Trace();

  // Generation (re)start: reads env, sizes the ring on first call
  // (capacity is fixed for the process lifetime — monotonic counters
  // and the shard file survive elastic re-init like metrics.h).
  void Configure(int rank, int world_size, int64_t generation);
  // Final shard drain + drainer join. Safe to call repeatedly.
  void Shutdown();

  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }
  int rank() const { return rank_.load(std::memory_order_relaxed); }

  // Monotonic ns since this process's trace epoch (steady_clock; the
  // clock offset below maps it onto rank 0's epoch).
  int64_t NowNs() const;

  // Hot path: claim a slot, publish a span. Never blocks; when the ring
  // has wrapped past the drainer the overwritten spans count as drops.
  void Record(const char* name, int phase, int64_t start_ns, int64_t end_ns,
              int64_t bytes = 0, uint32_t group = 0, int peer = -1,
              uint64_t cycle = 0, uint8_t flags = 0);

  // Open-span table for the negotiation wait (enqueue -> perform spans
  // cross threads, so they can't live on the recording thread's stack).
  // Key convention: "<group>/<tensor name>".
  void OpenSpan(const std::string& key, int64_t start_ns);
  int64_t CloseSpan(const std::string& key);  // -1 = never opened

  // Last-N control-frame header log for bundles (tag is the frame's
  // 4-byte type tag; called from the control send/recv wrappers).
  void NoteControlFrame(uint32_t tag, bool send, uint64_t bytes);

  // Clock alignment (worker side; rank 0 never calls it — its offset is
  // 0 by definition). All stamps are NowNs() values from the respective
  // rank. Adopts the sample if its uncertainty beats the current
  // estimate or the estimate is older than kClockStaleNs.
  void UpdateClockSample(int64_t t1, int64_t t2, int64_t t3, int64_t t4);
  int64_t clock_offset_ns() const {
    return clock_offset_ns_.load(std::memory_order_relaxed);
  }
  // -1 until the first sample lands.
  int64_t clock_uncertainty_ns() const {
    return clock_uncertainty_ns_.load(std::memory_order_relaxed);
  }

  // Flight recorder: write one post-mortem bundle (ring + metrics
  // snapshot + `pending_json` + control frames + clock) to
  // HVD_TPU_BUNDLE_DIR. Returns the path, or "" when no dir is
  // configured / the per-process cap is hit / the write failed.
  // Callable from any thread; best-effort from fatal-signal context.
  std::string DumpBundle(const char* reason, const std::string& pending_json);

  // Push ring contents to the shard file now (shutdown/bundle points).
  void FlushShard();

  // Decode the currently-readable ring contents (oldest first) without
  // advancing the drain cursor. Bundle writer + tests.
  std::vector<TraceSpan> SnapshotSpans() const;

  // --- monotonic counters (summary wire: trace_spans_total etc.) ---
  std::atomic<uint64_t> spans_total{0};
  std::atomic<uint64_t> spans_dropped{0};
  std::atomic<uint64_t> bundles_written{0};

 private:
  static constexpr int64_t kClockStaleNs = 30ll * 1000 * 1000 * 1000;

  void DrainerLoop();
  // Drain published slots [cursor_, head) to the shard file; counts
  // overrun drops. Caller holds shard_mutex_.
  void DrainLocked();
  void WriteShardHeaderLocked();
  // Read slot at claim index `idx`; false on unpublished/torn.
  bool ReadSlot(uint64_t idx, TraceSpan* out) const;

  std::atomic<bool> enabled_{false};
  std::atomic<int> rank_{-1};
  std::atomic<int> world_size_{0};
  std::atomic<int64_t> generation_{-1};
  std::chrono::steady_clock::time_point epoch_;

  // Ring storage; allocated once, capacity fixed thereafter.
  std::unique_ptr<TraceSlot[]> ring_;
  uint64_t ring_mask_ = 0;       // capacity - 1 (set once at first Configure)
  std::atomic<uint64_t> head_{0};  // next claim index (monotonic)

  // Clock estimate (worker). Offset maps local NowNs onto rank 0's:
  // t_rank0 = t_local + offset.
  std::atomic<int64_t> clock_offset_ns_{0};
  std::atomic<int64_t> clock_uncertainty_ns_{-1};
  std::atomic<int64_t> clock_sampled_at_ns_{0};

  mutable std::mutex open_mutex_;
  std::unordered_map<std::string, int64_t> open_spans_;  // guarded_by(open_mutex_)

  mutable std::mutex frame_mutex_;
  struct FrameNote {
    int64_t t_ns;
    uint32_t tag;
    bool send;
    uint64_t bytes;
  };
  std::deque<FrameNote> control_frames_;  // guarded_by(frame_mutex_)

  mutable std::mutex shard_mutex_;
  std::FILE* shard_file_ = nullptr;        // guarded_by(shard_mutex_)
  uint64_t drain_cursor_ = 0;              // guarded_by(shard_mutex_)
  int64_t last_clock_emitted_ = -2;        // guarded_by(shard_mutex_)
  std::string trace_dir_;                  // guarded_by(shard_mutex_)
  std::thread drainer_thread_;             // guarded_by(shard_mutex_)
  bool drainer_running_ = false;           // guarded_by(shard_mutex_)
  std::atomic<bool> drainer_stop_{false};

  std::mutex bundle_mutex_;
  std::string bundle_dir_;  // guarded_by(bundle_mutex_)
};

// Process-wide recorder. A leaked singleton like GlobalMetrics() so leaf
// components without a state pointer (the transport pair-exchange, the
// fatal-signal handler) reach it directly; global_state.h holds a
// reference for everything that does carry state.
Trace& GlobalTrace();

}  // namespace hvdtpu

#endif  // HVD_TPU_TRACE_H
