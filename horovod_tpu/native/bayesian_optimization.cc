#include "bayesian_optimization.h"

#include <cmath>
#include <limits>

namespace hvdtpu {

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0.0;
  for (std::size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return signal_var_ * std::exp(-d2 / (2.0 * length_scale_ * length_scale_));
}

void GaussianProcess::Fit(const std::vector<std::vector<double>>& x,
                          const std::vector<double>& y) {
  x_ = x;
  std::size_t n = x.size();
  // Center and scale targets for numerical stability.
  y_mean_ = 0.0;
  for (double v : y) y_mean_ += v;
  y_mean_ /= static_cast<double>(n);
  y_scale_ = 1e-12;
  for (double v : y) y_scale_ = std::max(y_scale_, std::fabs(v - y_mean_));
  std::vector<double> yc(n);
  for (std::size_t i = 0; i < n; ++i) yc[i] = (y[i] - y_mean_) / y_scale_;

  // K + noise I, then Cholesky L L^T.
  std::vector<std::vector<double>> k(n, std::vector<double>(n));
  for (std::size_t i = 0; i < n; ++i) {
    for (std::size_t j = 0; j <= i; ++j) {
      k[i][j] = k[j][i] = Kernel(x[i], x[j]);
    }
    k[i][i] += noise_var_;
  }
  chol_.assign(n, std::vector<double>(n, 0.0));
  for (std::size_t i = 0; i < n; ++i) {
    for (std::size_t j = 0; j <= i; ++j) {
      double sum = k[i][j];
      for (std::size_t m = 0; m < j; ++m) sum -= chol_[i][m] * chol_[j][m];
      if (i == j) {
        chol_[i][i] = std::sqrt(std::max(sum, 1e-12));
      } else {
        chol_[i][j] = sum / chol_[j][j];
      }
    }
  }
  // alpha = (K + nI)^-1 yc via two triangular solves.
  std::vector<double> z(n);
  for (std::size_t i = 0; i < n; ++i) {
    double sum = yc[i];
    for (std::size_t m = 0; m < i; ++m) sum -= chol_[i][m] * z[m];
    z[i] = sum / chol_[i][i];
  }
  alpha_.assign(n, 0.0);
  for (std::size_t ii = n; ii-- > 0;) {
    double sum = z[ii];
    for (std::size_t m = ii + 1; m < n; ++m) sum -= chol_[m][ii] * alpha_[m];
    alpha_[ii] = sum / chol_[ii][ii];
  }
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mu,
                              double* sigma) const {
  std::size_t n = x_.size();
  if (n == 0) {
    *mu = 0.0;
    *sigma = std::sqrt(signal_var_);
    return;
  }
  std::vector<double> ks(n);
  for (std::size_t i = 0; i < n; ++i) ks[i] = Kernel(x, x_[i]);
  double m = 0.0;
  for (std::size_t i = 0; i < n; ++i) m += ks[i] * alpha_[i];
  *mu = m * y_scale_ + y_mean_;
  // v = L^-1 ks; var = k(x,x) - v.v
  std::vector<double> v(n);
  for (std::size_t i = 0; i < n; ++i) {
    double sum = ks[i];
    for (std::size_t mi = 0; mi < i; ++mi) sum -= chol_[i][mi] * v[mi];
    v[i] = sum / chol_[i][i];
  }
  double var = Kernel(x, x);
  for (std::size_t i = 0; i < n; ++i) var -= v[i] * v[i];
  *sigma = std::sqrt(std::max(var, 1e-12)) * y_scale_;
}

BayesianOptimizer::BayesianOptimizer(
    std::vector<std::pair<double, double>> bounds, uint64_t seed)
    : bounds_(std::move(bounds)),
      best_y_(-std::numeric_limits<double>::infinity()),
      rng_state_(seed ? seed : 1) {}

double BayesianOptimizer::NextRand() {
  // xorshift64* — deterministic, dependency-free.
  rng_state_ ^= rng_state_ >> 12;
  rng_state_ ^= rng_state_ << 25;
  rng_state_ ^= rng_state_ >> 27;
  uint64_t r = rng_state_ * 2685821657736338717ull;
  return static_cast<double>(r >> 11) / 9007199254740992.0;
}

std::vector<double> BayesianOptimizer::Normalize(
    const std::vector<double>& x) const {
  std::vector<double> z(x.size());
  for (std::size_t i = 0; i < x.size(); ++i) {
    double lo = bounds_[i].first, hi = bounds_[i].second;
    z[i] = (x[i] - lo) / (hi - lo);
  }
  return z;
}

std::vector<double> BayesianOptimizer::Denormalize(
    const std::vector<double>& z) const {
  std::vector<double> x(z.size());
  for (std::size_t i = 0; i < z.size(); ++i) {
    double lo = bounds_[i].first, hi = bounds_[i].second;
    x[i] = lo + z[i] * (hi - lo);
  }
  return x;
}

std::vector<double> BayesianOptimizer::NextSample() {
  std::size_t d = bounds_.size();
  if (x_.size() < 3) {
    // Bootstrap with quasi-random exploration.
    std::vector<double> z(d);
    for (std::size_t i = 0; i < d; ++i) z[i] = NextRand();
    return Denormalize(z);
  }
  gp_.Fit(x_, y_);
  // Expected improvement over random candidates.
  double best_ei = -1.0;
  std::vector<double> best_z(d, 0.5);
  const double xi = 0.01;
  for (int c = 0; c < 512; ++c) {
    std::vector<double> z(d);
    for (std::size_t i = 0; i < d; ++i) z[i] = NextRand();
    double mu, sigma;
    gp_.Predict(z, &mu, &sigma);
    double improve = mu - best_y_ - xi;
    double ei;
    if (sigma < 1e-12) {
      ei = improve > 0 ? improve : 0.0;
    } else {
      double u = improve / sigma;
      double cdf = 0.5 * std::erfc(-u / std::sqrt(2.0));
      double pdf = std::exp(-0.5 * u * u) / std::sqrt(2.0 * M_PI);
      ei = improve * cdf + sigma * pdf;
    }
    if (ei > best_ei) {
      best_ei = ei;
      best_z = z;
    }
  }
  return Denormalize(best_z);
}

void BayesianOptimizer::AddSample(const std::vector<double>& x, double y) {
  x_.push_back(Normalize(x));
  y_.push_back(y);
  if (y > best_y_) {
    best_y_ = y;
    best_x_ = x;
  }
}

std::vector<double> BayesianOptimizer::BestSample() const {
  if (!best_x_.empty()) return best_x_;
  std::vector<double> mid(bounds_.size());
  for (std::size_t i = 0; i < bounds_.size(); ++i) {
    mid[i] = 0.5 * (bounds_[i].first + bounds_[i].second);
  }
  return mid;
}

}  // namespace hvdtpu
