// In-memory control-plane message model: Request / RequestList / Response /
// ResponseList plus the DataType enum, with a compact length-prefixed binary
// wire format (no flatbuffers dependency).
//
// Capability parity with the reference message model (/root/reference
// horovod/common/message.{h,cc} and wire/message.fbs); the wire format here is
// a fresh TPU-build design: little-endian, varint-free, length-prefixed.
#ifndef HVD_TPU_MESSAGE_H
#define HVD_TPU_MESSAGE_H

#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

enum class DataType : uint8_t {
  HVD_UINT8 = 0,
  HVD_INT8 = 1,
  HVD_UINT16 = 2,
  HVD_INT16 = 3,
  HVD_INT32 = 4,
  HVD_INT64 = 5,
  HVD_FLOAT16 = 6,
  HVD_FLOAT32 = 7,
  HVD_FLOAT64 = 8,
  HVD_BOOL = 9,
  HVD_BFLOAT16 = 10,
};

const char* DataTypeName(DataType dt);
std::size_t DataTypeSize(DataType dt);

// A Request is one rank announcing "tensor <name> is ready on my side".
class Request {
 public:
  enum RequestType : uint8_t {
    ALLREDUCE = 0,
    ALLGATHER = 1,
    BROADCAST = 2,
    // Sharded weight update (docs/ZERO.md): the reduce-scatter leg of the
    // ring as a first-class negotiated op — each rank receives its own
    // 1/N shard of the summed tensor instead of the full result.
    REDUCESCATTER = 3,
  };

  static const char* RequestTypeName(RequestType t);

  int32_t request_rank() const { return request_rank_; }
  void set_request_rank(int32_t rank) { request_rank_ = rank; }

  RequestType request_type() const { return request_type_; }
  void set_request_type(RequestType t) { request_type_ = t; }

  DataType tensor_type() const { return tensor_type_; }
  void set_tensor_type(DataType dt) { tensor_type_ = dt; }

  const std::string& tensor_name() const { return tensor_name_; }
  void set_tensor_name(const std::string& name) { tensor_name_ = name; }

  int32_t root_rank() const { return root_rank_; }
  void set_root_rank(int32_t r) { root_rank_ = r; }

  int32_t device() const { return device_; }
  void set_device(int32_t d) { device_ = d; }

  const std::vector<int64_t>& tensor_shape() const { return tensor_shape_; }
  void set_tensor_shape(const std::vector<int64_t>& s) { tensor_shape_ = s; }
  void add_tensor_shape(int64_t dim) { tensor_shape_.push_back(dim); }

  // Prescale/postscale factors fold averaging into the collective.
  double prescale_factor() const { return prescale_factor_; }
  void set_prescale_factor(double f) { prescale_factor_ = f; }
  double postscale_factor() const { return postscale_factor_; }
  void set_postscale_factor(double f) { postscale_factor_ = f; }

  // Wire-compression mode (compression.h CompressionMode as u8). Part of
  // the negotiated contract: the coordinator rejects mixed-mode ranks by
  // name, and the response cache treats a mode change as a miss.
  uint8_t compression() const { return compression_; }
  void set_compression(uint8_t c) { compression_ = c; }

  // Process group this collective is scoped to (group_table.h; 0 = the
  // world). The coordinator counts readiness against the GROUP's member
  // set, the response cache keys on it, and the executing op rides the
  // group's ring. group_digest is the sender's membership digest for the
  // id — the coordinator rejects mixed-membership groups by name.
  uint32_t group_id() const { return group_id_; }
  void set_group_id(uint32_t g) { group_id_ = g; }
  uint64_t group_digest() const { return group_digest_; }
  void set_group_digest(uint64_t d) { group_digest_ = d; }

  void SerializeTo(std::string* out) const;
  // Returns bytes consumed, 0 on error.
  std::size_t ParseFrom(const char* data, std::size_t len);

 private:
  int32_t request_rank_ = 0;
  RequestType request_type_ = ALLREDUCE;
  DataType tensor_type_ = DataType::HVD_FLOAT32;
  int32_t root_rank_ = 0;
  int32_t device_ = -1;  // -1 == host
  std::string tensor_name_;
  std::vector<int64_t> tensor_shape_;
  double prescale_factor_ = 1.0;
  double postscale_factor_ = 1.0;
  uint8_t compression_ = 0;  // CompressionMode::NONE
  uint32_t group_id_ = 0;    // 0 = world
  uint64_t group_digest_ = 0;
};

// One entry of a rank's collective call history (divergence.h): enough to
// name the call site in a cross-rank divergence report without shipping
// the full Request.
struct CallRecord {
  uint64_t seq = 0;   // 1-based position in the rank's call sequence
  uint8_t op = 0;     // Request::RequestType
  uint8_t dtype = 0;  // DataType
  uint8_t ndim = 0;   // shape rank
  std::string name;
};

class RequestList {
 public:
  const std::vector<Request>& requests() const { return requests_; }
  void add_request(const Request& r) { requests_.push_back(r); }

  bool shutdown() const { return shutdown_; }
  void set_shutdown(bool v) { shutdown_ = v; }

  // Divergence-tracker piggyback (divergence.h): the sending rank's call
  // sequence position, rolling digest, and records since its last report.
  uint64_t call_seq() const { return call_seq_; }
  void set_call_seq(uint64_t v) { call_seq_ = v; }
  uint64_t call_digest() const { return call_digest_; }
  void set_call_digest(uint64_t v) { call_digest_ = v; }
  const std::vector<CallRecord>& recent_calls() const {
    return recent_calls_;
  }
  void set_recent_calls(std::vector<CallRecord> v) {
    recent_calls_ = std::move(v);
  }

  // Metrics-plane piggyback (metrics.h, SummaryField order): the sending
  // rank's compact counter summary. Empty when the metrics plane is off
  // or the attach interval hasn't elapsed — the wire carries one extra
  // u32 (count 0) then, nothing more.
  const std::vector<double>& metrics_summary() const {
    return metrics_summary_;
  }
  void set_metrics_summary(std::vector<double> v) {
    metrics_summary_ = std::move(v);
  }

  void SerializeTo(std::string* out) const;
  bool ParseFrom(const char* data, std::size_t len);

 private:
  std::vector<Request> requests_;
  bool shutdown_ = false;
  uint64_t call_seq_ = 0;
  uint64_t call_digest_ = 0;
  std::vector<CallRecord> recent_calls_;
  std::vector<double> metrics_summary_;
};

// A Response is the coordinator's verdict: do this (possibly fused) op now,
// or report an error for these tensors.
class Response {
 public:
  enum ResponseType : uint8_t {
    ALLREDUCE = 0,
    ALLGATHER = 1,
    BROADCAST = 2,
    ERROR = 3,
    // Appended after ERROR so pre-sharded decoders keep their numbering.
    REDUCESCATTER = 4,
  };

  static const char* ResponseTypeName(ResponseType t);

  ResponseType response_type() const { return response_type_; }
  void set_response_type(ResponseType t) { response_type_ = t; }

  const std::vector<std::string>& tensor_names() const { return tensor_names_; }
  std::vector<std::string>& mutable_tensor_names() { return tensor_names_; }
  void add_tensor_name(const std::string& n) { tensor_names_.push_back(n); }
  std::string tensor_names_string() const;

  const std::string& error_message() const { return error_message_; }
  void set_error_message(const std::string& m) { error_message_ = m; }

  DataType tensor_type() const { return tensor_type_; }
  void set_tensor_type(DataType dt) { tensor_type_ = dt; }

  // For allgather: first-dimension size contributed by every rank.
  const std::vector<int64_t>& tensor_sizes() const { return tensor_sizes_; }
  void set_tensor_sizes(const std::vector<int64_t>& s) { tensor_sizes_ = s; }
  void add_tensor_size(int64_t s) { tensor_sizes_.push_back(s); }

  int32_t devices() const { return devices_; }
  void set_devices(int32_t d) { devices_ = d; }

  // Negotiated wire-compression mode the executing ops apply per hop
  // (compression.h). Fusion only merges same-mode responses.
  uint8_t compression() const { return compression_; }
  void set_compression(uint8_t c) { compression_ = c; }

  // Process group scope (0 = world). Executing ranks ride the group's
  // ring; ranks outside the group skip the response (no table entry)
  // but still mirror it into their response cache so cache bits stay
  // rank-identical (response_cache.h). Fusion only merges same-group
  // responses.
  uint32_t group_id() const { return group_id_; }
  void set_group_id(uint32_t g) { group_id_ = g; }

  void SerializeTo(std::string* out) const;
  std::size_t ParseFrom(const char* data, std::size_t len);

 private:
  ResponseType response_type_ = ALLREDUCE;
  std::vector<std::string> tensor_names_;
  std::string error_message_;
  std::vector<int64_t> tensor_sizes_;
  DataType tensor_type_ = DataType::HVD_FLOAT32;
  int32_t devices_ = -1;
  uint8_t compression_ = 0;  // CompressionMode::NONE
  uint32_t group_id_ = 0;    // 0 = world
};

class ResponseList {
 public:
  // Autotune bootstrap word: (rearm_epoch << 8) | profile bits, attached
  // by the coordinator to every full-cycle broadcast so workers re-enter
  // tuning at the same cycle the coordinator re-arms
  // (parameter_manager.h). kAutotuneAbsent marks a list that never
  // crossed the wire (fast-path local lists) or an older serializer.
  static constexpr uint64_t kAutotuneAbsent = ~0ull;

  const std::vector<Response>& responses() const { return responses_; }
  std::vector<Response>& mutable_responses() { return responses_; }
  void add_response(const Response& r) { responses_.push_back(r); }

  bool shutdown() const { return shutdown_; }
  void set_shutdown(bool v) { shutdown_ = v; }

  uint64_t autotune_wire() const { return autotune_wire_; }
  void set_autotune_wire(uint64_t v) { autotune_wire_ = v; }

  // Clock-alignment piggyback (trace.h, docs/TRACING.md): the
  // coordinator's trace-clock stamps taken right after its gather
  // returned (T2) and right before its broadcast (T3), appended AFTER
  // the autotune word — pre-trace decoders stop at the shorter blob and
  // see -1 ("no sample"). The worker combines them with its own
  // T1(pre-gather)/T4(post-broadcast) stamps into an NTP offset sample.
  int64_t clock_t2() const { return clock_t2_; }
  int64_t clock_t3() const { return clock_t3_; }
  void set_clock(int64_t t2, int64_t t3) {
    clock_t2_ = t2;
    clock_t3_ = t3;
  }
  // Coordinator->worker flag bits on the same tail. Bit 0: every rank
  // dumps a flight-recorder bundle this cycle (stall escalation /
  // divergence — the coordinator saw it, the workers hold the evidence).
  static constexpr uint8_t kFlagDumpBundle = 1;
  uint8_t trace_flags() const { return trace_flags_; }
  void set_trace_flags(uint8_t f) { trace_flags_ = f; }

  void SerializeTo(std::string* out) const;
  bool ParseFrom(const char* data, std::size_t len);

 private:
  std::vector<Response> responses_;
  bool shutdown_ = false;
  uint64_t autotune_wire_ = kAutotuneAbsent;
  int64_t clock_t2_ = -1;
  int64_t clock_t3_ = -1;
  uint8_t trace_flags_ = 0;
};

// --- low-level wire helpers (shared with net.cc) ---
namespace wire {
void PutU8(std::string* out, uint8_t v);
void PutU32(std::string* out, uint32_t v);
void PutI32(std::string* out, int32_t v);
void PutI64(std::string* out, int64_t v);
void PutF64(std::string* out, double v);
void PutStr(std::string* out, const std::string& s);

class Reader {
 public:
  Reader(const char* data, std::size_t len) : p_(data), end_(data + len) {}
  bool GetU8(uint8_t* v);
  bool GetU32(uint32_t* v);
  bool GetI32(int32_t* v);
  bool GetI64(int64_t* v);
  bool GetF64(double* v);
  bool GetStr(std::string* s);
  std::size_t consumed(const char* start) const { return p_ - start; }
  bool ok() const { return p_ <= end_; }

 private:
  const char* p_;
  const char* end_;
};
}  // namespace wire

}  // namespace hvdtpu

#endif  // HVD_TPU_MESSAGE_H
