// Owns the process's host-network communicator state: the listener, the
// control star (worker <-> rank 0), the global data ring (rank i <-> i+1
// mod N), and — when the topology is homogeneous — a local ring (within
// one host's ranks) and a cross ring (across hosts at one local_rank),
// plus rank/local/cross topology read from launcher-injected env.
//
// Role parity with /root/reference horovod/common/mpi/mpi_context.{h,cc}
// (global/local/cross communicator splits, mpi_context.cc:133-165) and
// gloo/gloo_context.{h,cc} (communicator ownership + rendezvous); transport
// here is plain TCP with launcher-assigned ports:
//   HVD_TPU_RANK / HVD_TPU_SIZE / HVD_TPU_LOCAL_RANK / HVD_TPU_LOCAL_SIZE /
//   HVD_TPU_CROSS_RANK / HVD_TPU_CROSS_SIZE
//   HVD_TPU_ADDRS = host:port per rank, comma-separated, index == rank.
//
// Failure discipline (docs/CHAOS.md): every frame is CRC32C-checked, all
// sockets carry I/O deadlines and keepalive, and the worker side of the
// control star survives a dropped connection by reconnecting to the
// coordinator with capped exponential backoff — the handshake echoes the
// elastic generation and this side's completed control-frame count, so a
// stale worker can never splice into a newer ring and a desynced resume
// is rejected into the ordinary elastic recovery path.
#ifndef HVD_TPU_TCP_CONTEXT_H
#define HVD_TPU_TCP_CONTEXT_H

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "net.h"
#include "shm_context.h"

namespace hvdtpu {

// Which ring a neighbor exchange rides.
enum class Ring { GLOBAL, LOCAL, CROSS };

class TcpContext {
 public:
  // Reads env, opens the listener, and builds the star + ring connections.
  // Blocking; returns false on rendezvous failure.
  bool Initialize();
  void Finalize();
  bool initialized() const { return initialized_; }

  int rank() const { return rank_; }
  int size() const { return size_; }
  int local_rank() const { return local_rank_; }
  int local_size() const { return local_size_; }
  int cross_rank() const { return cross_rank_; }
  int cross_size() const { return cross_size_; }
  // Elastic generation this communicator was built for (HVD_TPU_GENERATION).
  uint32_t generation() const { return generation_; }

  // Human-readable cause of the most recent transport failure on this
  // context ("frame checksum mismatch on control channel", "recv
  // deadline expired on ring channel", ...). Read by the controller to
  // build recoverable-error messages that NAME the failure; background
  // thread only.
  const std::string& last_error() const { return last_error_; }

  // True when every rank reported the same local/cross sizes and the
  // (local_rank, cross_rank) grid is complete — the precondition for the
  // two-level collectives (reference gates hierarchical ops on
  // is_homogeneous the same way, mpi_controller.cc:25-81).
  bool is_homogeneous() const { return is_homogeneous_; }
  // Local + cross rings exist and two-level ops can run.
  bool hierarchical_possible() const {
    return is_homogeneous_ && local_size_ > 1 && cross_size_ > 1;
  }
  // Global rank of the peer at (local_rank, cross_rank); -1 if unknown.
  int RankAt(int local_rank, int cross_rank) const;

  // --- control star (coordinator protocol) ---
  // Worker sends its blob to rank 0; rank 0 fills all[r] for r=1..N-1.
  // Rank 0 services every worker socket concurrently (poll-multiplexed).
  bool GatherBlobs(const std::string& mine, std::vector<std::string>* all);
  bool BroadcastBlob(std::string* blob);
  // Elementwise bitwise AND / OR across ranks (fixed-size u64 vectors).
  bool BitwiseSync(std::vector<uint64_t>& bits, bool is_or);
  bool Barrier();

  // --- data rings (collective ops) ---
  // Full-duplex neighbor exchange on the chosen ring: sends send_len bytes
  // to the ring successor while receiving recv_len bytes from the ring
  // predecessor, pumping both directions so large transfers can't deadlock
  // on full socket buffers.
  bool RingExchange(const void* send_buf, std::size_t send_len, void* recv_buf,
                    std::size_t recv_len) {
    return RingExchangeOn(Ring::GLOBAL, send_buf, send_len, recv_buf,
                          recv_len);
  }
  bool RingExchangeOn(Ring ring, const void* send_buf, std::size_t send_len,
                      void* recv_buf, std::size_t recv_len);
  // This rank's index / participant count on the given ring.
  int RingRank(Ring ring) const;
  int RingSize(Ring ring) const;

  // Chunked pipelined broadcast over the global ring: the root streams
  // `len` bytes; every other rank receives into `buf` and forwards.
  // Root passes its source in `buf` too.
  bool RingBroadcast(void* buf, std::size_t len, int root);

  // --- process-group rings (docs/GROUPS.md) ---
  // A group collective rides a dedicated ring over the GROUP's member
  // subset — hops shrink from world-1 to group-1 and disjoint groups'
  // rings run concurrently. Connections are built lazily by the
  // background thread at a group op's first execution: every member
  // connects to its ring successor (the TCP backlog completes the
  // connect even before the peer accepts), then accepts from its
  // predecessor — connect-before-accept on every member, so the pairing
  // cannot deadlock. Accepted connects for OTHER groups (a member of a
  // later response's group racing ahead) are stashed and consumed by
  // that group's own EnsureGroupRing. Background thread only.
  bool EnsureGroupRing(uint32_t group_id, const std::vector<int>& members);
  // This rank's ring position / member count for a BUILT group ring
  // (-1 / 0 when EnsureGroupRing has not run for the id).
  int GroupRank(uint32_t group_id) const;
  int GroupSize(uint32_t group_id) const;
  // Neighbor exchange / rooted broadcast on the group's ring (root_pos
  // is the GROUP-ring position, not the world rank). CRC framing, the
  // fault injector, deadlines, and the bandwidth throttle apply exactly
  // as on the global ring (Channel::RING).
  bool GroupExchange(uint32_t group_id, const void* send_buf,
                     std::size_t send_len, void* recv_buf,
                     std::size_t recv_len);
  bool GroupBroadcast(uint32_t group_id, void* buf, std::size_t len,
                      int root_pos);
  // --- group sub-rings (hierarchical composites for subgroups) ---
  // When a group's member set forms a uniform (local, cross) grid of
  // the world topology — every participating host contributes the same
  // number of members — its hierarchical composites ride dedicated
  // per-group local/cross rings (the local legs over shm when
  // negotiated) instead of the flat group ring. docs/TRANSPORT.md.
  struct GroupGrid {
    bool uniform = false;
    int local_size = 0;          // members per host
    int cross_size = 0;          // hosts the group spans
    int local_pos = -1;          // my index among my host's members
    int cross_pos = -1;          // my host's index among the group's hosts
    // pos_grid[c * local_size + j] = GROUP position (index into the
    // member list) of the j-th member (by world local_rank) on the
    // group's c-th host (hosts ordered by world cross_rank).
    std::vector<int> pos_grid;
  };
  // Pure function of (members, world grid): identical on every rank, so
  // op Enabled() decisions made from it can never diverge.
  GroupGrid GroupGridOf(const std::vector<int>& members) const;
  // Uniform grid with >1 member per host and >1 host: the precondition
  // for a subgroup's two-level composites.
  bool GroupHierarchicalPossible(const std::vector<int>& members) const;
  // Lazily builds the group's local+cross rings (background thread
  // only; connect-before-accept exactly like the flat group ring) and
  // negotiates shm on the new legs.
  bool EnsureGroupSubRings(uint32_t group_id, const std::vector<int>& members);

  // Group-aware ring coordinates: group == 0 -> the enum rings;
  // group != 0 with GLOBAL -> the group's flat ring; group != 0 with
  // LOCAL/CROSS -> the group's sub-rings (EnsureGroupSubRings first).
  int RingRankOn(Ring ring, uint32_t group) const;
  int RingSizeOn(Ring ring, uint32_t group) const;
  // Dispatch helper for the ring ops, same coordinate rule.
  bool ExchangeOn(Ring ring, uint32_t group, const void* send_buf,
                  std::size_t send_len, void* recv_buf,
                  std::size_t recv_len) {
    if (group == 0) {
      return RingExchangeOn(ring, send_buf, send_len, recv_buf, recv_len);
    }
    if (ring == Ring::GLOBAL) {
      return GroupExchange(group, send_buf, send_len, recv_buf, recv_len);
    }
    return GroupSubExchange(group, ring, send_buf, send_len, recv_buf,
                            recv_len);
  }
  bool GroupSubExchange(uint32_t group_id, Ring ring, const void* send_buf,
                        std::size_t send_len, void* recv_buf,
                        std::size_t recv_len);

  // --- shared-memory data plane (docs/TRANSPORT.md) ---
  // Whether the launcher-visible topology has at least one intra-host
  // pair AND HVD_TPU_SHM is enabled — computed from the full address
  // list, so it is identical on every rank (the autotuner's capability
  // seed). Actual per-pair use additionally requires a successfully
  // negotiated segment on both ends.
  bool shm_topology_possible() const { return shm_topology_possible_; }
  // The autotuned shm_transport knob's cycle-synchronized application
  // point (operations.cc RunLoopOnce): when off, negotiated segments
  // stay attached but every leg rides TCP. Background thread only.
  void SetShmUse(bool use) { shm_use_ = use; }
  bool shm_use() const { return shm_use_; }

  // --- control-plane protocol accounting ---
  // Bytes/messages THIS rank moved on the control star (16-byte frame
  // headers included; data-ring traffic is not counted — these isolate
  // the NEGOTIATION cost, the quantity the response-cache fast path
  // exists to shrink; reference design goal: response_cache.cc:308-409).
  // Idle heartbeat cycles also send control frames, so bytes accrue
  // with wall time when cycle pacing is zeroed.
  // Written by the background thread, read from the C API.
  uint64_t ctrl_bytes_sent() const { return ctrl_bytes_sent_.load(); }
  uint64_t ctrl_bytes_recv() const { return ctrl_bytes_recv_.load(); }
  uint64_t ctrl_msgs() const { return ctrl_msgs_.load(); }
  void ResetProtocolCounters() {
    ctrl_bytes_sent_.store(0);
    ctrl_bytes_recv_.store(0);
    ctrl_msgs_.store(0);
  }

 private:
  bool ExchangeTopology();
  bool ConnectSubRings(int timeout_ms);

  // --- shm negotiation (tcp_context.cc; docs/TRANSPORT.md) ---
  // A connector that advertised kHandshakeShmCap sends exactly ONE
  // setup frame per data conn (host key + segment name, or an empty
  // name = "TCP please"); the acceptor answers with a one-byte ack.
  // The three phases run in send-all / serve-all / collect-acks order
  // so no pair can deadlock (setup and ack frames are tiny and fit any
  // socket buffer).
  struct ShmPending {
    Conn* conn;
    std::unique_ptr<ShmRing> ring;  // null when the connector chose TCP
  };
  // Runs the full three-phase negotiation over the init-time data conns
  // (global + local + cross rings). Soft failures (attach refused, no
  // /dev/shm) land pairs on TCP; false only on a frame-protocol error.
  bool NegotiateShmInit();
  bool ShmSetupSend(Conn* conn, int peer_rank, Channel chan,
                    std::vector<ShmPending>* pending);
  bool ShmSetupRecv(Conn* conn, uint8_t peer_flags);
  bool ShmAckRecv(ShmPending* p);
  // Negotiation for one freshly built group leg pair (flat or sub).
  bool NegotiateShmPair(Conn* next, int next_rank, Conn* prev,
                        uint8_t prev_flags, Channel chan);
  // Host key WITHOUT the per-rank HVD_TPU_HOST_KEY override — the
  // connector's symmetric same-host guess for any rank (the override
  // only affects the authoritative key THIS rank puts in its setup
  // frame / compares on accept).
  std::string DefaultHostKey(int rank) const;
  std::string MyHostKey() const;

  // Shared connect-then-accept body for a group leg pair (flat ring or
  // a sub-ring): connects to next_rank on `chan`, then accepts from
  // prev_rank, stashing unrelated group connects for their own builds.
  bool GroupPairConnect(uint32_t group_id, Channel chan, int next_rank,
                        int prev_rank, Conn* next, Conn* prev,
                        uint8_t* prev_flags);
  // World local_rank of an arbitrary rank (grid scan; -1 when unknown).
  int LocalRankOfWorld(int rank) const;
  // Shared duplex-pump body for all neighbor exchanges (enum rings and
  // group rings): header swap, CRC-verified full-duplex payload pump,
  // fault hooks, TX pacing, socket-layer byte accounting.
  bool PairExchange(Conn* next, Conn* prev, Channel chan, int ring_size,
                    const void* send_buf, std::size_t send_len,
                    void* recv_buf, std::size_t recv_len);
  // Duplex payload pump for exchanges where at least one leg rides a
  // shared-memory ring (tcp_context.cc; docs/TRANSPORT.md).
  bool PumpShmAware(Conn* next, Conn* prev, Channel chan, ShmRing* sshm,
                    ShmRing* rshm, const char* sp, std::size_t send_len,
                    char* rp, std::size_t recv_len, bool recv_crc_on,
                    uint32_t* crc_acc);
  // Shared cut-through broadcast body (global ring and group rings):
  // `pos`/`n`/`root_pos` are ring positions on the given conn pair.
  bool PairBroadcast(Conn* next, Conn* prev, int pos, int n, void* buf,
                     std::size_t len, int root_pos);
  // Root-side shm streaming body for PairBroadcast.
  bool StreamIntoShm(ShmRing* ring, Conn* conn, const char* p,
                     std::size_t len);
  // Rank 0: receive one frame from every worker concurrently.
  bool MultiRecvFrames(uint32_t expect_tag, std::vector<std::string>* blobs);
  // Rank 0: send per-worker payloads concurrently (all pairs may alias).
  bool MultiSendFrames(uint32_t tag,
                       const std::vector<std::pair<const void*, std::size_t>>&
                           payloads);

  // --- worker-side control star with reconnect ---
  // Frame-granular control I/O: on a CLOSED connection these reconnect
  // to the coordinator with capped exponential backoff (up to
  // HVD_TPU_RECONNECT_SECONDS) and retry the frame; checksum/deadline/
  // oversize failures are NOT retried (the frame stream is unrecoverable
  // — that is the elastic layer's job). Each completed frame bumps
  // my_ctrl_opseq_, the resume cursor the reconnect handshake carries.
  bool ControlSendFrame(uint32_t tag, const void* payload, std::size_t len);
  bool ControlRecvFrame(uint32_t expect_tag, std::string* payload);
  bool ControlRecvFrameInto(uint32_t expect_tag, void* buf, std::size_t len);
  bool ReconnectControl();

  // --- coordinator-side reconnect acceptance ---
  // Accepts a pending control reconnect on the listener, validates its
  // (rank, generation, opseq) against `expect_opseq_for` (per-rank
  // expected resume cursor) and the dead-peer mask, sends the verdict
  // byte, and swaps the new Conn in. Returns the reconnected worker
  // index (1..size-1), 0 when nothing usable was accepted, or -1 on a
  // fatal desync (the job must fail over).
  int TryAcceptControlReconnect(const std::vector<bool>& dead);

  void SetLastError(Channel chan, NetError err);

  // --- emulated data-ring bandwidth (HVD_TPU_RING_BANDWIDTH_MBPS) ---
  // A TX token bucket paces ring-exchange sends to the configured rate
  // so a laptop/CI host can reproduce the wait states of a real
  // inter-host link (capacity planning + the pipelined-ring bench,
  // docs/AUTOTUNE.md). 0 = off. Only the send side is paced, and only
  // by withholding POLLOUT — receives keep draining, so the emulation
  // never deadlocks the duplex pump. Background thread only.
  double ring_tx_bytes_per_us_ = 0.0;
  double ring_tx_ready_us_ = 0.0;

  // Per-logical-channel wire-hop sequence for trace spans (trace.h):
  // ring exchanges run in lockstep, so hop N on the sender is hop N on
  // the receiver — the merge tool pairs spans across ranks by
  // (channel, hop). Indexed by Channel value. Background thread only.
  uint64_t trace_hop_seq_[4] = {0, 0, 0, 0};

  int rank_ = 0;
  int size_ = 1;
  int local_rank_ = 0;
  int local_size_ = 1;
  int cross_rank_ = 0;
  int cross_size_ = 1;
  bool is_homogeneous_ = false;
  bool initialized_ = false;
  uint32_t generation_ = 0;

  std::atomic<uint64_t> ctrl_bytes_sent_{0};
  std::atomic<uint64_t> ctrl_bytes_recv_{0};
  std::atomic<uint64_t> ctrl_msgs_{0};

  // rank_grid_[cross_rank * local_size + local_rank] = global rank.
  std::vector<int> rank_grid_;
  // Reverse lookup: rank_cross_[rank] = that rank's cross index (host)
  // when homogeneous; empty otherwise.
  std::vector<int> rank_cross_;
  // Host part of each rank's HVD_TPU_ADDRS entry (index == rank).
  std::vector<std::string> addr_hosts_;
  bool shm_topology_possible_ = false;
  bool shm_use_ = true;

  Listener listener_;
  // Rank 0: control_conns_[r] for r=1..N-1; workers: control_conns_[0].
  std::vector<Conn> control_conns_;
  // Completed control-frame counts: the coordinator tracks one cursor
  // per worker; a worker tracks its own in my_ctrl_opseq_. A reconnect
  // resumes only when the two cursors agree (both sides then retry the
  // same in-flight frame from its first byte).
  std::vector<uint64_t> ctrl_opseq_;
  uint64_t my_ctrl_opseq_ = 0;
  std::string coord_host_;  // rank 0's address, kept for reconnects
  int coord_port_ = 0;
  std::string last_error_;

  Conn ring_next_;        // connected to (rank+1) % size
  Conn ring_prev_;        // accepted from (rank-1+size) % size
  Conn local_next_;       // successor within my host's local ring
  Conn local_prev_;
  Conn cross_next_;       // successor within my local_rank's cross ring
  Conn cross_prev_;
  // Handshake flags of the accepted (prev) side of each init-time data
  // conn: NegotiateShmInit needs to know whether the connector
  // advertised kHandshakeShmCap (a setup frame is then in flight).
  uint8_t ring_prev_flags_ = 0;
  uint8_t local_prev_flags_ = 0;
  uint8_t cross_prev_flags_ = 0;

  // Lazily-built per-group rings (background thread only; see
  // EnsureGroupRing). pending_group_fds_ stashes accepted group-ring
  // connects that belong to a (group, channel) pair this rank has not
  // built yet, keyed (channel << 60) | (group_id << 24) | peer_rank,
  // carrying the handshake flags for the later shm negotiation.
  struct GroupRing {
    Conn next;
    Conn prev;
    int pos = 0;
    int size = 1;
  };
  // Per-group local/cross sub-rings for uniform-grid subgroups
  // (EnsureGroupSubRings).
  struct GroupSubRings {
    GroupGrid grid;
    Conn lnext, lprev;  // intra-host ring among my host's group members
    Conn cnext, cprev;  // cross-host ring at my local position
  };
  struct PendingGroupFd {
    int fd = -1;
    uint8_t flags = 0;
  };
  std::unordered_map<uint32_t, GroupRing> group_rings_;
  std::unordered_map<uint32_t, GroupSubRings> group_subrings_;
  std::unordered_map<uint64_t, PendingGroupFd> pending_group_fds_;
};

}  // namespace hvdtpu

#endif  // HVD_TPU_TCP_CONTEXT_H
