// Owns the process's host-network communicator state: the listener, the
// control star (worker <-> rank 0) and the data ring (rank i <-> i+1 mod N),
// plus rank/local/cross topology read from launcher-injected env.
//
// Role parity with /root/reference horovod/common/mpi/mpi_context.{h,cc} and
// gloo/gloo_context.{h,cc} (communicator ownership + rendezvous); transport
// here is plain TCP with launcher-assigned ports:
//   HVD_TPU_RANK / HVD_TPU_SIZE / HVD_TPU_LOCAL_RANK / HVD_TPU_LOCAL_SIZE /
//   HVD_TPU_CROSS_RANK / HVD_TPU_CROSS_SIZE
//   HVD_TPU_ADDRS = host:port per rank, comma-separated, index == rank.
#ifndef HVD_TPU_TCP_CONTEXT_H
#define HVD_TPU_TCP_CONTEXT_H

#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include "net.h"

namespace hvdtpu {

class TcpContext {
 public:
  // Reads env, opens the listener, and builds the star + ring connections.
  // Blocking; returns false on rendezvous failure.
  bool Initialize();
  void Finalize();
  bool initialized() const { return initialized_; }

  int rank() const { return rank_; }
  int size() const { return size_; }
  int local_rank() const { return local_rank_; }
  int local_size() const { return local_size_; }
  int cross_rank() const { return cross_rank_; }
  int cross_size() const { return cross_size_; }

  // --- control star (coordinator protocol) ---
  // Worker sends its blob to rank 0; rank 0 fills all[r] for r=1..N-1.
  bool GatherBlobs(const std::string& mine, std::vector<std::string>* all);
  bool BroadcastBlob(std::string* blob);
  // Elementwise bitwise AND / OR across ranks (fixed-size u64 vectors).
  bool BitwiseSync(std::vector<uint64_t>& bits, bool is_or);
  bool Barrier();

  // Bulk point-to-point on the control star (workers may only address rank
  // 0; rank 0 may address anyone). Used by broadcast; safe because ops run
  // lockstep on the single coordination thread.
  bool StarSend(int peer, const void* data, std::size_t len);
  bool StarRecv(int peer, void* buf, std::size_t len);

  // --- data ring (collective ops) ---
  // Full-duplex neighbor exchange: sends send_len bytes to rank+1 while
  // receiving recv_len bytes from rank-1, pumping both directions so large
  // transfers can't deadlock on full socket buffers.
  bool RingExchange(const void* send_buf, std::size_t send_len, void* recv_buf,
                    std::size_t recv_len);

 private:
  int rank_ = 0;
  int size_ = 1;
  int local_rank_ = 0;
  int local_size_ = 1;
  int cross_rank_ = 0;
  int cross_size_ = 1;
  bool initialized_ = false;

  Listener listener_;
  // Rank 0: control_conns_[r] for r=1..N-1; workers: control_conns_[0].
  std::vector<Conn> control_conns_;
  Conn ring_next_;  // connected to (rank+1) % size
  Conn ring_prev_;  // accepted from (rank-1+size) % size
};

}  // namespace hvdtpu

#endif  // HVD_TPU_TCP_CONTEXT_H
