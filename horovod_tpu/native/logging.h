// Leveled logging with optional rank prefix, configured from env.
// TPU-native equivalent of the reference logger (see /root/reference
// horovod/common/logging.{h,cc}) — same capability, fresh implementation.
//
// Env: HVD_TPU_LOG_LEVEL = trace|debug|info|warning|error|fatal (default warning)
//      HVD_TPU_LOG_HIDE_TIME = 1 to suppress timestamps.
#ifndef HVD_TPU_LOGGING_H
#define HVD_TPU_LOGGING_H

#include <sstream>
#include <string>

namespace hvdtpu {

enum class LogLevel : int {
  TRACE = 0,
  DEBUG = 1,
  INFO = 2,
  WARNING = 3,
  ERROR = 4,
  FATAL = 5,
};

LogLevel MinLogLevelFromEnv();
void SetLogRank(int rank);

class LogMessage : public std::basic_ostringstream<char> {
 public:
  LogMessage(const char* file, int line, LogLevel level);
  ~LogMessage();

 private:
  const char* file_;
  int line_;
  LogLevel level_;
};

class LogMessageFatal : public LogMessage {
 public:
  LogMessageFatal(const char* file, int line);
  ~LogMessageFatal();
};

#define HVD_LOG_TRACE \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::TRACE)
#define HVD_LOG_DEBUG \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::DEBUG)
#define HVD_LOG_INFO \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::INFO)
#define HVD_LOG_WARNING \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::WARNING)
#define HVD_LOG_ERROR \
  ::hvdtpu::LogMessage(__FILE__, __LINE__, ::hvdtpu::LogLevel::ERROR)
#define HVD_LOG_FATAL ::hvdtpu::LogMessageFatal(__FILE__, __LINE__)

#define LOG(level) HVD_LOG_##level

#define SHOULD_LOG(level) \
  (::hvdtpu::LogLevel::level >= ::hvdtpu::MinLogLevelFromEnv())

}  // namespace hvdtpu

#endif  // HVD_TPU_LOGGING_H
