// Core shared types for the TPU-native runtime: Status, TensorShape,
// TensorTableEntry, and well-known constants.
//
// Capability parity with the reference core types (/root/reference
// horovod/common/common.h:95-244), redesigned for a host-buffer data path:
// the C API hands the core raw host pointers (NumPy / dlpack-exported
// buffers); completion is handle-based (HandleManager) rather than
// callback-based so no foreign thread ever has to re-enter Python.
#ifndef HVD_TPU_COMMON_H
#define HVD_TPU_COMMON_H

#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "message.h"

namespace hvdtpu {

// Well-known env vars (runtime knobs; see SURVEY.md §5.6 for the reference's
// canonical list in horovod/common/common.h:60-84).
#define HVD_TPU_FUSION_THRESHOLD "HVD_TPU_FUSION_THRESHOLD"
#define HVD_TPU_CYCLE_TIME "HVD_TPU_CYCLE_TIME"
#define HVD_TPU_CACHE_CAPACITY "HVD_TPU_CACHE_CAPACITY"
#define HVD_TPU_TIMELINE "HVD_TPU_TIMELINE"
#define HVD_TPU_TIMELINE_MARK_CYCLES "HVD_TPU_TIMELINE_MARK_CYCLES"
#define HVD_TPU_AUTOTUNE "HVD_TPU_AUTOTUNE"
#define HVD_TPU_AUTOTUNE_LOG "HVD_TPU_AUTOTUNE_LOG"
#define HVD_TPU_STALL_CHECK_TIME "HVD_TPU_STALL_CHECK_TIME_SECONDS"
#define HVD_TPU_STALL_SHUTDOWN_TIME "HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS"
#define HVD_TPU_DIVERGENCE_CALLS "HVD_TPU_DIVERGENCE_CALLS"
#define HVD_TPU_DIVERGENCE_GRACE "HVD_TPU_DIVERGENCE_GRACE_SECONDS"
#define HVD_TPU_HIERARCHICAL_ALLREDUCE "HVD_TPU_HIERARCHICAL_ALLREDUCE"
#define HVD_TPU_HIERARCHICAL_ALLGATHER "HVD_TPU_HIERARCHICAL_ALLGATHER"
#define HVD_TPU_HIERARCHICAL_REDUCESCATTER "HVD_TPU_HIERARCHICAL_REDUCESCATTER"
// Pipelined ring transport (docs/AUTOTUNE.md): slice every ring hop's
// payload into segments of this many bytes with double-buffered
// send/recv so encode, transport, and ReduceSum overlap within the hop.
// 0 disables slicing; unset leaves the knob to the autotuner.
#define HVD_TPU_PIPELINE_CHUNK_BYTES "HVD_TPU_PIPELINE_CHUNK_BYTES"
// Metrics plane (metrics.h / docs/METRICS.md): HVD_TPU_METRICS=1 turns on
// the wire piggyback + coordinator job view without HTTP serving;
// HVD_TPU_METRICS_PORT additionally makes Python serve Prometheus text at
// port+rank. SYNC bounds how often per-rank summaries ride the wire.
#define HVD_TPU_METRICS "HVD_TPU_METRICS"
#define HVD_TPU_METRICS_PORT "HVD_TPU_METRICS_PORT"
#define HVD_TPU_METRICS_SYNC "HVD_TPU_METRICS_SYNC_SECONDS"
#define HVD_TPU_GENERATION_ENV "HVD_TPU_GENERATION"
// Chaos-hardened transport knobs (net.cc / tcp_context.cc / fault.cc;
// docs/CHAOS.md): frame checksums are on by default (NET_CRC=0 disables,
// job-wide); NET_TIMEOUT bounds every blocking send/recv (default: the
// control poll window, 60 s); KEEPALIVE detects powered-off hosts in
// ~2*idle seconds (0 disables); MAX_FRAME_BYTES bounds a single frame
// allocation (default 1 GiB); RECONNECT_SECONDS is the window a broken
// worker->coordinator control connection may take to resume with capped
// exponential backoff (0 disables reconnect); FAULT_SPEC arms the
// deterministic fault injector (never set it on a production job).
#define HVD_TPU_NET_CRC_ENV "HVD_TPU_NET_CRC"
#define HVD_TPU_NET_TIMEOUT_ENV "HVD_TPU_NET_TIMEOUT_SECONDS"
#define HVD_TPU_NET_KEEPALIVE_ENV "HVD_TPU_NET_KEEPALIVE_SECONDS"
#define HVD_TPU_MAX_FRAME_BYTES_ENV "HVD_TPU_MAX_FRAME_BYTES"
#define HVD_TPU_RECONNECT_ENV "HVD_TPU_RECONNECT_SECONDS"
#define HVD_TPU_FAULT_SPEC_ENV "HVD_TPU_FAULT_SPEC"
// Wire-compression default for host-plane allreduces (compression.h /
// docs/COMPRESSION.md): "none" (default), "bf16", or "int8". Per-call
// compression= arguments override it; Python resolves the env once per
// call so the mode rides the Request and is validated cross-rank.
#define HVD_TPU_COMPRESSION_ENV "HVD_TPU_COMPRESSION"
// Job-wide sharded-weight-update default (docs/ZERO.md): "1" makes
// DistributedOptimizer wrappers that were not given an explicit
// sharded_update= argument reduce-scatter gradients and shard optimizer
// state 1/N per rank. Per-call arguments override it; negotiation
// validates the mode cross-rank (mixed sharded/replicated ranks are
// rejected by name, like mixed compression).
#define HVD_TPU_SHARDED_UPDATE_ENV "HVD_TPU_SHARDED_UPDATE"

enum class StatusType : int32_t {
  OK = 0,
  UNKNOWN_ERROR = 1,
  PRECONDITION_ERROR = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
  IN_PROGRESS = 5,
};

// Device id for host-memory tensors (the only device the core data path
// touches; TPU tensors ride the in-XLA path and never enter the core).
constexpr int32_t HOST_DEVICE_ID = -1;

extern const std::string SHUT_DOWN_ERROR;
extern const std::string DUPLICATE_NAME_ERROR;
extern const std::string CONNECTION_LOST_ERROR;

// Shared env parsing (single definition so every consumer agrees on
// strtoll/strtod semantics). `present`, when non-null, reports whether
// the variable was set at all — the autotuner treats an env-present
// knob as FIXED (excluded from the search).
int64_t EnvInt64(const char* name, int64_t dflt, bool* present = nullptr);
double EnvDouble(const char* name, double dflt, bool* present = nullptr);
bool EnvBool(const char* name, bool dflt, bool* present = nullptr);

class Status {
 public:
  Status() = default;
  static Status OK() { return Status(); }
  static Status UnknownError(const std::string& msg) {
    return Status(StatusType::UNKNOWN_ERROR, msg);
  }
  static Status PreconditionError(const std::string& msg) {
    return Status(StatusType::PRECONDITION_ERROR, msg);
  }
  static Status Aborted(const std::string& msg) {
    return Status(StatusType::ABORTED, msg);
  }
  static Status InvalidArgument(const std::string& msg) {
    return Status(StatusType::INVALID_ARGUMENT, msg);
  }
  static Status InProgress() { return Status(StatusType::IN_PROGRESS, ""); }

  bool ok() const { return type_ == StatusType::OK; }
  bool in_progress() const { return type_ == StatusType::IN_PROGRESS; }
  StatusType type() const { return type_; }
  const std::string& reason() const { return reason_; }

 private:
  Status(StatusType type, std::string reason)
      : type_(type), reason_(std::move(reason)) {}
  StatusType type_ = StatusType::OK;
  std::string reason_;
};

class TensorShape {
 public:
  TensorShape() = default;
  explicit TensorShape(std::vector<int64_t> dims) : dims_(std::move(dims)) {}
  void AddDim(int64_t d) { dims_.push_back(d); }
  int ndims() const { return static_cast<int>(dims_.size()); }
  int64_t dim_size(int i) const { return dims_[i]; }
  const std::vector<int64_t>& dims() const { return dims_; }
  int64_t num_elements() const {
    int64_t n = 1;
    for (auto d : dims_) n *= d;
    return n;
  }
  bool operator==(const TensorShape& o) const { return dims_ == o.dims_; }
  bool operator!=(const TensorShape& o) const { return dims_ != o.dims_; }
  std::string DebugString() const;

 private:
  std::vector<int64_t> dims_;
};

struct TensorTableEntry;
// Completion callback: receives the final status and the executed entry
// (whose `gathered` buffers carry allgather results).
using StatusCallback =
    std::function<void(const Status&, const TensorTableEntry&)>;

// One queued collective on one rank. `data` is the caller-owned input
// buffer, `output` the caller-owned output buffer (may alias `data` for
// in-place ops). For allgather the output buffer is allocated lazily by the
// caller after negotiation reports the gathered first-dim sizes — the core
// writes the result into `gathered` storage it owns, which the C API then
// exposes for copy-out (see operations.cc).
struct TensorTableEntry {
  std::string tensor_name;
  const void* data = nullptr;
  void* output = nullptr;
  DataType dtype = DataType::HVD_FLOAT32;
  TensorShape shape;
  int32_t device = HOST_DEVICE_ID;
  int32_t root_rank = 0;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  // Effective wire-compression mode (compression.h CompressionMode as
  // u8; already dtype-filtered at enqueue).
  uint8_t compression = 0;
  // Process group the collective is scoped to (group_table.h; 0 =
  // world). Responses only claim entries of their own group, so the
  // same tensor name active in two groups at once never cross-executes.
  uint32_t group_id = 0;
  // Allgather result storage (core-owned) — set after execution.
  std::shared_ptr<std::vector<char>> gathered;
  std::shared_ptr<std::vector<int64_t>> gathered_sizes;
  StatusCallback callback;

  int64_t NumElements() const { return shape.num_elements(); }
  std::size_t SizeBytes() const {
    return static_cast<std::size_t>(NumElements()) * DataTypeSize(dtype);
  }
};

}  // namespace hvdtpu

#endif  // HVD_TPU_COMMON_H
