// Runtime divergence detection — the dynamic complement of hvd-lint.
//
// The stall inspector is time-based and reactive: it can say "tensor X is
// waiting on rank 1" only after a long timeout, and never *which call site*
// rank 1 took instead. This module makes divergence a first-class protocol
// signal:
//
// * CallTracker (every rank): folds the process's collective call sequence
//   (op, dtype, shape-rank, name) into a monotonically increasing seq, a
//   rolling FNV-1a digest, and a bounded ring of recent call descriptors.
//   The seq/digest ride each worker RequestList (and are exposed to Python
//   via horovod_tpu_call_digest for hvd.jax.assert_synchronized).
//
// * DivergenceDetector (coordinator): cross-checks the per-rank streams
//   against the pending negotiation table and proves divergence two ways —
//     progress rule: a rank missing from a pending tensor has submitted
//       >= `progress_calls` other collectives since the tensor was first
//       announced (it is demonstrably past that call site);
//     cross-stall rule: a pending tensor has aged past `grace_seconds`
//       and every missing rank is itself waiting on a *different* aged
//       tensor (mutual wait on diverged call sites).
//   A proven divergence fails the tensor with an ERROR response naming the
//   diverging call sites, instead of hanging until the stall timeout.
#ifndef HVD_TPU_DIVERGENCE_H
#define HVD_TPU_DIVERGENCE_H

#include <chrono>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "group_table.h"
#include "message.h"

namespace hvdtpu {

class CallTracker {
 public:
  // Called from user threads on every enqueue (allreduce/allgather/
  // broadcast, any binding — everything funnels through EnqueueTensor).
  void Record(uint8_t op, uint8_t dtype, int ndim, const std::string& name);

  // Current (seq, digest) — the value Python's assert_synchronized
  // compares across ranks.
  void Snapshot(uint64_t* seq, uint64_t* digest) const;

  // Records with after_seq < seq <= up_to_seq, oldest first, capped at
  // `limit` most-recent entries (the ring itself holds kRingCapacity).
  // `up_to_seq` lets the controller ship exactly the calls covered by a
  // cycle-start snapshot, never ones recorded mid-cycle.
  std::vector<CallRecord> RecordsSince(uint64_t after_seq,
                                       std::size_t limit,
                                       uint64_t up_to_seq) const;

  // Generation reset (elastic re-init): every member restarts the
  // sequence so survivors and fresh workers agree again.
  void Reset();

  static constexpr std::size_t kRingCapacity = 256;

 private:
  mutable std::mutex mutex_;
  uint64_t seq_ = 0;     // guarded_by(mutex_)
  // FNV-1a offset basis
  uint64_t digest_ = 14695981039346656037ULL;  // guarded_by(mutex_)
  std::deque<CallRecord> ring_;                // guarded_by(mutex_)
};

class DivergenceDetector {
 public:
  struct Diagnosis {
    std::string key;          // pending-table key (GroupQualifiedName)
    std::string tensor_name;  // bare tensor name (entry lookup on ranks)
    uint32_t group_id = 0;
    std::string message;
  };

  // progress_calls == 0 disables the progress rule; grace_seconds <= 0
  // disables the cross-stall rule.
  void Configure(int world_size, int64_t progress_calls,
                 double grace_seconds);

  // Ingests one rank's (seq, digest, recent records) from its RequestList
  // (the coordinator feeds its own tracker state through here too).
  void Observe(int rank, uint64_t seq, uint64_t digest,
               const std::vector<CallRecord>& recent);

  // True when some pending tensor has aged enough that the coordinator
  // should force a full negotiation cycle (so quiescent, all-blocked
  // ranks still ship their seq/digest for cross-checking). Rate-limited
  // internally.
  bool ShouldForceFullCycle(
      const std::unordered_map<std::string, std::vector<Request>>& pending);

  // Cross-checks the pending table; returns proven divergences. The
  // caller (controller) erases the tensors and emits ERROR responses.
  // `groups` scopes the missing-rank set: a tensor pending in a process
  // group is only waited on by that group's MEMBERS, and its diagnosis
  // names the group — a rank-divergent collective inside one group must
  // never read as the whole world hanging.
  std::vector<Diagnosis> Check(
      const std::unordered_map<std::string, std::vector<Request>>& pending,
      const GroupTable* groups = nullptr);

  uint64_t last_seq(int rank) const {
    return rank < static_cast<int>(ranks_.size()) ? ranks_[rank].seq : 0;
  }

 private:
  using Clock = std::chrono::steady_clock;

  struct RankState {
    uint64_t seq = 0;
    uint64_t digest = 0;
    std::deque<CallRecord> log;  // merged recent records, bounded
  };

  struct PendingState {
    Clock::time_point first_seen;
    std::vector<uint64_t> seq_at_announce;  // per rank, at first sight
  };

  std::string DescribeRecentCalls(int rank, uint64_t after_seq,
                                  std::size_t max_shown) const;

  int world_size_ = 1;
  int64_t progress_calls_ = 0;
  double grace_seconds_ = 0.0;
  std::vector<RankState> ranks_;
  std::unordered_map<std::string, PendingState> pending_;
  Clock::time_point last_forced_{};
};

}  // namespace hvdtpu

#endif  // HVD_TPU_DIVERGENCE_H
