#include "common.h"

#include <cstdlib>
#include <sstream>

namespace hvdtpu {

int64_t EnvInt64(const char* name, int64_t dflt, bool* present) {
  const char* v = std::getenv(name);
  if (present != nullptr) *present = v != nullptr;
  return v == nullptr ? dflt : std::strtoll(v, nullptr, 10);
}

double EnvDouble(const char* name, double dflt, bool* present) {
  const char* v = std::getenv(name);
  if (present != nullptr) *present = v != nullptr;
  return v == nullptr ? dflt : std::strtod(v, nullptr);
}

bool EnvBool(const char* name, bool dflt, bool* present) {
  const char* v = std::getenv(name);
  if (present != nullptr) *present = v != nullptr;
  if (v == nullptr) return dflt;
  return std::strtol(v, nullptr, 10) != 0;
}

const std::string SHUT_DOWN_ERROR =
    "Horovod-TPU has been shut down. This was caused by an exception on one "
    "of the ranks or an attempt to enqueue a collective after one of the "
    "ranks finished execution.";

const std::string DUPLICATE_NAME_ERROR =
    "Requested to collect a tensor with the same name as another tensor that "
    "is currently being processed. If you want to request another tensor, "
    "use a different tensor name.";

const std::string CONNECTION_LOST_ERROR =
    "Horovod-TPU connection to a peer was lost (a worker likely failed or "
    "was preempted). The job can recover elastically: roll back to the "
    "last committed state and re-initialize (hvd.elastic.run does this "
    "automatically).";

std::string TensorShape::DebugString() const {
  std::ostringstream os;
  os << "[";
  for (std::size_t i = 0; i < dims_.size(); ++i) {
    if (i > 0) os << ", ";
    os << dims_[i];
  }
  os << "]";
  return os.str();
}

}  // namespace hvdtpu
