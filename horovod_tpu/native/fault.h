// Deterministic fault injection for the transport (docs/CHAOS.md).
//
// The chaos harness's premise: a failure path that has never fired is
// a failure path that does not work. This injector lets a test provoke
// an exact fault at an exact point in the frame stream — and lets a
// soak run sprinkle seeded random faults — without touching production
// code paths (one relaxed atomic-bool check per frame when inactive).
//
// Configured from HVD_TPU_FAULT_SPEC, parsed once per (re)init:
//
//   spec   := clause (';' clause)*
//   clause := 'seed=N' | rule
//   rule   := field (',' field)*
//   field  := 'rank=N'          fire only on this process rank
//           | 'chan=control|ring|local|cross|shm|any'
//                               (shm filters by TRANSPORT: data-plane
//                               frames riding a shared-memory ring,
//                               whatever their logical channel; the
//                               ring/local/cross filters keep matching
//                               by logical channel regardless of the
//                               transport underneath)
//           | 'dir=send|recv|any'
//           | 'frame=N'         fire at the Nth matching frame (0-based,
//                               counted per rule over matching frames)
//           | 'prob=P'          fire with probability P per matching
//                               frame (seeded PRNG; exclusive w/ frame=)
//           | 'count=K'         max fires for this rule (default: 1 for
//                               frame=, unlimited for prob=)
//           | 'action=drop|delay|corrupt|close|stall'
//           | 'delay_ms=D'      delay duration (actions delay/stall;
//                               stall defaults to 600000 = a hang)
//
// Example — kill rank 1's control connection at its 25th control frame
// and corrupt 1% of its ring frames:
//   HVD_TPU_FAULT_SPEC='seed=7;rank=1,chan=control,frame=25,action=close;
//                       rank=1,chan=ring,prob=0.01,action=corrupt'
//
// Action semantics at the frame layer (net.cc / tcp_context.cc):
//   drop     send side: silently skip the frame (peer starves -> its
//            recv deadline fires). Ignored on recv.
//   delay    sleep delay_ms before the frame I/O, then proceed.
//   corrupt  send: flip one payload byte after the CRC is computed (the
//            receiver's checksum catches it); recv: flip one received
//            payload byte before verification. Either way the frame
//            surfaces as a detected checksum mismatch, never bad data.
//   close    close the connection's fd (peer sees EOF; local I/O fails
//            promptly) — the control-star reconnect path's trigger.
//   stall    sleep delay_ms (default 600 s) holding the frame: the
//            hung-peer scenario the I/O deadlines exist for.
//
// Determinism: a worker's frame stream is produced by the single
// background thread, so per-rule frame counters and the seeded PRNG
// replay exactly for a given (spec, rank, program). On the coordinator
// the control star is poll-multiplexed; frames count in service order,
// which can vary across runs — filter coordinator rules by frame
// ranges, not exact peers, when exactness matters.
#ifndef HVD_TPU_FAULT_H
#define HVD_TPU_FAULT_H

#include <atomic>
#include <cstdint>
#include <mutex>
#include <random>
#include <string>
#include <vector>

#include "net.h"

namespace hvdtpu {

enum class FaultAction : int {
  NONE = 0,
  DROP,
  DELAY,
  CORRUPT,
  CLOSE,
  STALL,
};

const char* FaultActionName(FaultAction a);

struct FaultDecision {
  FaultAction action = FaultAction::NONE;
  int delay_ms = 0;
};

class FaultInjector {
 public:
  // (Re)parses `spec` (nullptr/empty disables). Resets all frame
  // counters and reseeds the PRNG — an elastic re-init replays the
  // spec from frame 0 of the new generation.
  void Configure(const char* spec, int rank);

  bool active() const { return active_.load(std::memory_order_relaxed); }

  // Consulted once per frame by the transport. Returns the action to
  // apply (delay/stall sleeps are applied by the CALLER so it can pick
  // the right moment relative to its I/O). NONE when inactive or no
  // rule matches. `shm` marks a frame riding the shared-memory plane
  // (the chan=shm filter's match key; logical-channel filters ignore
  // it).
  FaultDecision OnFrame(Channel chan, bool send, bool shm = false);

  // Test hook: number of times any rule has fired since Configure.
  uint64_t fires() const { return fires_.load(std::memory_order_relaxed); }

 private:
  struct Rule {
    int rank = -1;       // -1 = any
    int chan = -1;       // -1 = any, else (int)Channel
    int dir = -1;        // -1 = any, 0 = send, 1 = recv
    int64_t frame = -1;  // fire at Nth matching frame (exclusive w/ prob)
    double prob = 0.0;
    int64_t count = -1;  // remaining fires; -1 = unlimited
    int delay_ms = 0;
    FaultAction action = FaultAction::NONE;
    int64_t seen = 0;  // matching frames observed so far
  };

  std::atomic<bool> active_{false};
  std::atomic<uint64_t> fires_{0};
  std::mutex mutex_;
  std::vector<Rule> rules_;  // guarded_by(mutex_)
  std::mt19937_64 rng_;      // guarded_by(mutex_)
  int rank_ = -1;            // guarded_by(mutex_)
};

// Process-wide injector (configured by TcpContext::Initialize; reached
// from the Conn frame layer which carries no context pointer).
FaultInjector& GlobalFaultInjector();

}  // namespace hvdtpu

#endif  // HVD_TPU_FAULT_H
