#include "tcp_controller.h"

#include "logging.h"

namespace hvdtpu {

void TcpController::Initialize() {
  rank_ = tcp_context_.rank();
  size_ = tcp_context_.size();
  local_rank_ = tcp_context_.local_rank();
  local_size_ = tcp_context_.local_size();
  cross_rank_ = tcp_context_.cross_rank();
  cross_size_ = tcp_context_.cross_size();

  // Gather every rank's local_size to detect heterogeneous placements
  // (affects hierarchical op eligibility, mirroring the reference's
  // homogeneity check in mpi_controller.cc:25-81).
  std::string mine = std::to_string(local_size_);
  std::vector<std::string> all;
  if (is_coordinator()) {
    tcp_context_.GatherBlobs(mine, &all);
    all[0] = mine;
    std::string packed;
    for (auto& s : all) {
      packed += s;
      packed.push_back(',');
    }
    tcp_context_.BroadcastBlob(&packed);
    local_sizes_.clear();
    for (auto& s : SplitString(packed, ',')) {
      if (!s.empty()) local_sizes_.push_back(std::atoi(s.c_str()));
    }
  } else {
    tcp_context_.GatherBlobs(mine, nullptr);
    std::string packed;
    tcp_context_.BroadcastBlob(&packed);
    local_sizes_.clear();
    for (auto& s : SplitString(packed, ',')) {
      if (!s.empty()) local_sizes_.push_back(std::atoi(s.c_str()));
    }
  }
  is_homogeneous_ = true;
  for (int ls : local_sizes_) {
    if (ls != local_size_) is_homogeneous_ = false;
  }
  LOG(DEBUG) << "TcpController initialized: rank " << rank_ << " size "
             << size_ << " local " << local_rank_ << "/" << local_size_
             << " cross " << cross_rank_ << "/" << cross_size_;
}

// Control-plane failures mean a peer went away mid-protocol (EOF/reset on
// the star) — or, post-chaos-hardening, that a frame failed its checksum
// or an I/O deadline expired. Throwing ConnectionLostError (instead of
// the previous LOG(FATAL) abort) lets the background loop fail
// outstanding work with a recoverable status so Python can roll back and
// re-initialize for a new generation — the core of elastic fault
// tolerance. The message NAMES the transport-level cause
// (tcp_context.last_error) so a chaos run's failure is attributable.

namespace {
std::string WithCause(const char* what, const TcpContext& ctx) {
  std::string msg(what);
  if (!ctx.last_error().empty()) {
    msg += ": ";
    msg += ctx.last_error();
  }
  return msg;
}
}  // namespace

void TcpController::GatherBlobs(const std::string& mine,
                                std::vector<std::string>* all) {
  if (!tcp_context_.GatherBlobs(mine, all)) {
    throw ConnectionLostError(
        WithCause("control-plane gather failed", tcp_context_));
  }
}

void TcpController::BroadcastBlob(std::string* blob) {
  if (!tcp_context_.BroadcastBlob(blob)) {
    throw ConnectionLostError(
        WithCause("control-plane broadcast failed", tcp_context_));
  }
}

void TcpController::CrossRankBitwiseAnd(std::vector<uint64_t>& bits) {
  if (!tcp_context_.BitwiseSync(bits, /*is_or=*/false)) {
    throw ConnectionLostError(
        WithCause("bitwise AND sync failed", tcp_context_));
  }
}

void TcpController::CrossRankBitwiseOr(std::vector<uint64_t>& bits) {
  if (!tcp_context_.BitwiseSync(bits, /*is_or=*/true)) {
    throw ConnectionLostError(
        WithCause("bitwise OR sync failed", tcp_context_));
  }
}

void TcpController::Barrier() {
  if (!tcp_context_.Barrier()) {
    throw ConnectionLostError(WithCause("barrier failed", tcp_context_));
  }
}

}  // namespace hvdtpu
