"""ctypes binding to the native core runtime (libhorovod_tpu.so).

Capability parity with the reference ``horovod/common/basics.py:22-197``
(HorovodBasics): process-wide init/shutdown/rank/size queries and build
probes, plus the handle-based enqueue/wait surface the collective wrappers
use (reference analogue: the torch binding's handle manager,
``horovod/torch/mpi_ops.py:58-90``).
"""

import ctypes
import fcntl
import os
import subprocess
import threading

import numpy as np

_MOD_DIR = os.path.dirname(os.path.abspath(__file__))
# HVD_TPU_NATIVE_DIR points at an alternate build of the core (e.g. a
# `make SANITIZE=thread` TSAN build, or a system-installed location).
_NATIVE_DIR = os.environ.get(
    "HVD_TPU_NATIVE_DIR", os.path.join(_MOD_DIR, "..", "native"))
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhorovod_tpu.so")
_build_lock = threading.Lock()


def _ensure_built():
    """Builds the native core on first use (the .so is not checked in).

    Launcher-spawned worker processes hit this concurrently on a fresh
    checkout, so an inter-process flock serializes the build (the
    threading.Lock only covers threads within one process)."""
    with _build_lock:
        if os.path.exists(_LIB_PATH):
            return
        lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
        with open(lock_path, "w") as lock_file:
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            try:
                if os.path.exists(_LIB_PATH):
                    return
                subprocess.run(["make", "-j", str(os.cpu_count() or 4)],
                               cwd=_NATIVE_DIR, check=True,
                               stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT)
            except subprocess.CalledProcessError as e:
                raise RuntimeError(
                    "failed to build libhorovod_tpu.so:\n" +
                    e.stdout.decode("utf-8", "replace")) from e
            finally:
                fcntl.flock(lock_file, fcntl.LOCK_UN)

# DataType enum values must match native/message.h.
_NUMPY_TO_DTYPE = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.uint16): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int64): 5,
    np.dtype(np.float16): 6,
    np.dtype(np.float32): 7,
    np.dtype(np.float64): 8,
    np.dtype(np.bool_): 9,
}

_DTYPE_TO_NUMPY = {v: k for k, v in _NUMPY_TO_DTYPE.items()}

HVD_BFLOAT16 = 10

try:  # ml_dtypes ships with jax; bfloat16 is the native TPU 16-bit format.
    import ml_dtypes

    _NUMPY_TO_DTYPE[np.dtype(ml_dtypes.bfloat16)] = HVD_BFLOAT16
    _DTYPE_TO_NUMPY[HVD_BFLOAT16] = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    pass


def numpy_to_hvd_dtype(dtype):
    dt = np.dtype(dtype)
    if dt not in _NUMPY_TO_DTYPE:
        raise ValueError("Unsupported dtype for horovod_tpu collective: %s"
                         % dt)
    return _NUMPY_TO_DTYPE[dt]


class HorovodBasics:
    """Wraps the extern "C" API exported by the native core."""

    def __init__(self, lib_path=_LIB_PATH):
        _ensure_built()
        self.lib = ctypes.CDLL(os.path.abspath(lib_path),
                               mode=ctypes.RTLD_GLOBAL)
        lib = self.lib
        lib.horovod_tpu_init.restype = ctypes.c_int
        for fn in ("horovod_tpu_rank", "horovod_tpu_local_rank",
                   "horovod_tpu_cross_rank", "horovod_tpu_size",
                   "horovod_tpu_local_size", "horovod_tpu_cross_size",
                   "horovod_tpu_initialized", "horovod_tpu_is_homogeneous",
                   "horovod_tpu_connection_lost",
                   "horovod_tpu_tcp_built", "horovod_tpu_cpu_ops_built"):
            getattr(lib, fn).restype = ctypes.c_int
        lib.horovod_tpu_enqueue_allreduce.restype = ctypes.c_int
        lib.horovod_tpu_enqueue_allreduce.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_double,
            ctypes.c_double, ctypes.c_int,
        ]
        lib.horovod_tpu_enqueue_reduce_scatter.restype = ctypes.c_int
        lib.horovod_tpu_enqueue_reduce_scatter.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_double,
            ctypes.c_double, ctypes.c_int,
        ]
        # Process groups (docs/GROUPS.md): registry + group-scoped
        # enqueue variants (the plain entry points stay group-0 so older
        # bindings keep their signatures).
        lib.horovod_tpu_new_group.restype = ctypes.c_int
        lib.horovod_tpu_new_group.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        lib.horovod_tpu_group_size.restype = ctypes.c_int
        lib.horovod_tpu_group_size.argtypes = [ctypes.c_int]
        lib.horovod_tpu_group_rank.restype = ctypes.c_int
        lib.horovod_tpu_group_rank.argtypes = [ctypes.c_int]
        lib.horovod_tpu_group_count.restype = ctypes.c_int
        lib.horovod_tpu_group_count.argtypes = []
        lib.horovod_tpu_enqueue_allreduce_grp.restype = ctypes.c_int
        lib.horovod_tpu_enqueue_allreduce_grp.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_double,
            ctypes.c_double, ctypes.c_int, ctypes.c_int,
        ]
        lib.horovod_tpu_enqueue_reduce_scatter_grp.restype = ctypes.c_int
        lib.horovod_tpu_enqueue_reduce_scatter_grp.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_double,
            ctypes.c_double, ctypes.c_int, ctypes.c_int,
        ]
        lib.horovod_tpu_enqueue_allgather_grp.restype = ctypes.c_int
        lib.horovod_tpu_enqueue_allgather_grp.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
        ]
        lib.horovod_tpu_enqueue_broadcast_grp.restype = ctypes.c_int
        lib.horovod_tpu_enqueue_broadcast_grp.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.horovod_tpu_sharded_update_default.restype = ctypes.c_int
        lib.horovod_tpu_sharded_update_default.argtypes = []
        lib.horovod_tpu_opt_state_metrics.restype = None
        lib.horovod_tpu_opt_state_metrics.argtypes = [ctypes.c_int64]
        lib.horovod_tpu_parse_compression.restype = ctypes.c_int
        lib.horovod_tpu_parse_compression.argtypes = [ctypes.c_char_p]
        lib.horovod_tpu_effective_compression.restype = ctypes.c_int
        lib.horovod_tpu_effective_compression.argtypes = [ctypes.c_int,
                                                          ctypes.c_int]
        lib.horovod_tpu_compressed_size.restype = ctypes.c_int64
        lib.horovod_tpu_compressed_size.argtypes = [ctypes.c_int64,
                                                    ctypes.c_int]
        lib.horovod_tpu_enqueue_allgather.restype = ctypes.c_int
        lib.horovod_tpu_enqueue_allgather.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        lib.horovod_tpu_enqueue_broadcast.restype = ctypes.c_int
        lib.horovod_tpu_enqueue_broadcast.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
        ]
        lib.horovod_tpu_poll.restype = ctypes.c_int
        lib.horovod_tpu_poll.argtypes = [ctypes.c_int]
        lib.horovod_tpu_wait.restype = ctypes.c_int
        lib.horovod_tpu_wait.argtypes = [ctypes.c_int]
        lib.horovod_tpu_error_string.restype = ctypes.c_char_p
        lib.horovod_tpu_error_string.argtypes = [ctypes.c_int]
        lib.horovod_tpu_allgather_bytes.restype = ctypes.c_int64
        lib.horovod_tpu_allgather_bytes.argtypes = [ctypes.c_int]
        lib.horovod_tpu_allgather_rank_dim.restype = ctypes.c_int64
        lib.horovod_tpu_allgather_rank_dim.argtypes = [ctypes.c_int,
                                                       ctypes.c_int]
        lib.horovod_tpu_allgather_copy.restype = ctypes.c_int
        lib.horovod_tpu_allgather_copy.argtypes = [ctypes.c_int,
                                                   ctypes.c_void_p]
        lib.horovod_tpu_allgather_data.restype = ctypes.c_void_p
        lib.horovod_tpu_allgather_data.argtypes = [ctypes.c_int]
        lib.horovod_tpu_release.argtypes = [ctypes.c_int]
        lib.horovod_tpu_perf_counters.restype = None
        lib.horovod_tpu_perf_counters.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.horovod_tpu_effective_fusion_threshold.restype = ctypes.c_int64
        lib.horovod_tpu_protocol_counters.restype = None
        lib.horovod_tpu_protocol_counters.argtypes = [
            ctypes.POINTER(ctypes.c_uint64)]
        lib.horovod_tpu_protocol_counters_reset.restype = None
        lib.horovod_tpu_protocol_counters_reset.argtypes = []
        lib.horovod_tpu_call_digest.restype = None
        lib.horovod_tpu_call_digest.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        lib.horovod_tpu_metrics_json.restype = ctypes.c_char_p
        lib.horovod_tpu_metrics_json.argtypes = []
        lib.horovod_tpu_crc32c.restype = ctypes.c_uint32
        lib.horovod_tpu_crc32c.argtypes = [ctypes.c_char_p,
                                           ctypes.c_uint64]
        lib.horovod_tpu_crc32c_extend.restype = ctypes.c_uint32
        lib.horovod_tpu_crc32c_extend.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint64]
        lib.horovod_tpu_ckpt_metrics.restype = None
        lib.horovod_tpu_ckpt_metrics.argtypes = [
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double]
        lib.horovod_tpu_drain_metrics.restype = None
        lib.horovod_tpu_drain_metrics.argtypes = [
            ctypes.c_int64, ctypes.c_int64]
        lib.horovod_tpu_job_metrics_json.restype = ctypes.c_char_p
        lib.horovod_tpu_job_metrics_json.argtypes = []
        lib.horovod_tpu_autotune_params.restype = None
        lib.horovod_tpu_autotune_params.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        # Optional in older cores (a stale HVD_TPU_NATIVE_DIR build):
        # the binding degrades to autotune_params-only introspection
        # instead of failing every import.
        try:
            lib.horovod_tpu_autotune_json.restype = ctypes.c_char_p
            lib.horovod_tpu_autotune_json.argtypes = []
            self._has_autotune_json = True
        except AttributeError:
            self._has_autotune_json = False
        # Distributed tracing (native/trace.h, docs/TRACING.md) — also
        # optional, same stale-build tolerance.
        try:
            lib.horovod_tpu_trace_now_ns.restype = ctypes.c_int64
            lib.horovod_tpu_trace_now_ns.argtypes = []
            lib.horovod_tpu_trace_record.restype = None
            lib.horovod_tpu_trace_record.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
            lib.horovod_tpu_trace_dump_bundle.restype = ctypes.c_char_p
            lib.horovod_tpu_trace_dump_bundle.argtypes = [ctypes.c_char_p]
            lib.horovod_tpu_trace_counters.restype = None
            lib.horovod_tpu_trace_counters.argtypes = [
                ctypes.POINTER(ctypes.c_uint64)]
            self._has_trace = True
        except AttributeError:
            self._has_trace = False

    # -- lifecycle ---------------------------------------------------------
    def init(self):
        if not self.lib.horovod_tpu_init():
            raise RuntimeError(
                "horovod_tpu initialization failed (rendezvous error?). "
                "Check HVD_TPU_ADDRS / HVD_TPU_RANK / HVD_TPU_SIZE.")

    def shutdown(self):
        self.lib.horovod_tpu_shutdown()

    def initialized(self):
        return bool(self.lib.horovod_tpu_initialized())

    def connection_lost(self):
        """True when the background loop died because a peer connection
        was lost (elastic-recoverable), not a requested shutdown."""
        return bool(self.lib.horovod_tpu_connection_lost())

    def perf_counters(self):
        """(responses_performed, tensors_performed) — fusion
        diagnostics: equal counts mean no tensor shared a response."""
        responses = ctypes.c_int64()
        tensors = ctypes.c_int64()
        self.lib.horovod_tpu_perf_counters(ctypes.byref(responses),
                                           ctypes.byref(tensors))
        return responses.value, tensors.value

    def effective_fusion_threshold(self):
        """The controller's working fusion threshold in bytes, after
        hierarchical divisibility rounding; -1 before init."""
        return self.lib.horovod_tpu_effective_fusion_threshold()

    def protocol_counters(self):
        """Control-plane negotiation accounting for THIS rank: dict of
        ctrl_bytes_sent / ctrl_bytes_recv (12-byte frame headers
        included, data-plane ring traffic excluded), ctrl_msgs, and
        cycles_fast / cycles_full — both counting WORK cycles only
        (idle heartbeat cycles are excluded from cycle counts, but
        their control bytes DO accrue with wall time; keep cycle
        pacing at its default when byte-per-op numbers matter)."""
        out = (ctypes.c_uint64 * 5)()
        self.lib.horovod_tpu_protocol_counters(out)
        return {
            "ctrl_bytes_sent": out[0],
            "ctrl_bytes_recv": out[1],
            "ctrl_msgs": out[2],
            "cycles_fast": out[3],
            "cycles_full": out[4],
        }

    def protocol_counters_reset(self):
        self.lib.horovod_tpu_protocol_counters_reset()

    def call_digest(self):
        """(seq, digest) of this rank's collective call sequence since
        init: seq counts enqueued collectives, digest is a rolling
        FNV-1a over each call's (op, dtype, shape-rank, name). Ranks
        that executed identical call sequences report identical values
        (the runtime divergence assertion compares them)."""
        seq = ctypes.c_uint64()
        digest = ctypes.c_uint64()
        self.lib.horovod_tpu_call_digest(ctypes.byref(seq),
                                         ctypes.byref(digest))
        return seq.value, digest.value

    def metrics_json(self):
        """This worker's live metrics registry snapshot (counters /
        gauges / histograms / rank-lag tables) as a JSON string —
        native/metrics.h, rendered by horovod_tpu._metrics. Callable
        any time from any thread (the registry is process-global
        atomics)."""
        return self.lib.horovod_tpu_metrics_json().decode("utf-8")

    def job_metrics_json(self):
        """Rank 0's job-wide view as JSON: every rank's piggybacked
        summary, summary staleness, and the per-rank announce-lag
        table (straggler signal). "{}" on non-coordinator ranks."""
        return self.lib.horovod_tpu_job_metrics_json().decode("utf-8")

    def crc32c(self, data, crc=0):
        """CRC32C (Castagnoli) over `data` via the native slicing-by-8
        implementation (the transport frame checksum, native/checksum) —
        chained from `crc` for incremental use. The durable checkpoint
        writer checksums every shard and manifest through this."""
        buf = bytes(data)
        return int(self.lib.horovod_tpu_crc32c_extend(
            ctypes.c_uint32(crc), buf, len(buf))) if crc else \
            int(self.lib.horovod_tpu_crc32c(buf, len(buf)))

    def ckpt_metrics(self, writes=0, failures=0, nbytes=0, restores=0,
                     restore_failures=0, last_step=-1,
                     write_seconds=-1.0):
        """Reports durable-checkpoint accounting into the native
        registry (deltas; last_step absolute with <0 = skip;
        write_seconds one histogram observation with <0 = skip)."""
        self.lib.horovod_tpu_ckpt_metrics(
            int(writes), int(failures), int(nbytes), int(restores),
            int(restore_failures), int(last_step), float(write_seconds))

    def sharded_update_default(self):
        """The HVD_TPU_SHARDED_UPDATE job default (docs/ZERO.md)."""
        return bool(self.lib.horovod_tpu_sharded_update_default())

    def opt_state_metrics(self, nbytes):
        """Reports this rank's optimizer-state byte count into the
        native opt_state_bytes gauge (docs/ZERO.md; < 0 = skip)."""
        self.lib.horovod_tpu_opt_state_metrics(int(nbytes))

    def drain_metrics(self, requested=0, draining=-2):
        """Reports graceful-drain accounting into the native registry
        (docs/FLEET.md): `requested` is a counter delta; `draining` the
        absolute posture gauge (1 = victim of the current drain epoch,
        0 = survivor, -1 = reset; < -1 = leave unchanged)."""
        self.lib.horovod_tpu_drain_metrics(int(requested), int(draining))

    def compressed_size(self, count, mode):
        """Wire bytes `count` f32 elements occupy under compression
        mode `mode` (native/compression.cc layout)."""
        return int(self.lib.horovod_tpu_compressed_size(
            int(count), int(mode)))

    def effective_compression(self, mode, dtype):
        """The mode a payload of native dtype id `dtype` actually rides
        the wire with (non-f32 degrades to 0 = none)."""
        return int(self.lib.horovod_tpu_effective_compression(
            int(mode), int(dtype)))

    def autotune_json(self):
        """The full live closed-loop tuner state (docs/AUTOTUNE.md) as a
        JSON string: knobs (incl. pipeline_chunk_kb and
        hierarchical_reduce_scatter), fixed flags, workload profile,
        re-arm epoch/counters, and the convergence baseline the drift
        watch compares against. Callable any time from any thread."""
        if not self._has_autotune_json:
            # Keep the documented hvd.autotune() schema stable: knobs
            # under "params", closed-loop state zeroed (the old core
            # has no re-arm machinery to report).
            import json
            p = self.autotune_params()
            return json.dumps({
                "active": p.pop("active"),
                "rearm_epoch": 0, "rearms_total": 0, "samples": 0,
                "best_score_bytes_per_us": 0.0, "last_rearm_reason": "",
                "params": p, "fixed": {}, "profile": {}, "baseline": {},
            })
        return self.lib.horovod_tpu_autotune_json().decode("utf-8")

    def autotune_params(self):
        """Current synchronized knob values (autotune introspection):
        dict with fusion_mb, cycle_time_ms, cache_enabled,
        hierarchical_allreduce, hierarchical_allgather, active."""
        fusion = ctypes.c_double()
        cycle = ctypes.c_double()
        cache = ctypes.c_int()
        har = ctypes.c_int()
        hag = ctypes.c_int()
        active = ctypes.c_int()
        self.lib.horovod_tpu_autotune_params(
            ctypes.byref(fusion), ctypes.byref(cycle), ctypes.byref(cache),
            ctypes.byref(har), ctypes.byref(hag), ctypes.byref(active))
        return {"fusion_mb": fusion.value, "cycle_time_ms": cycle.value,
                "cache_enabled": bool(cache.value),
                "hierarchical_allreduce": bool(har.value),
                "hierarchical_allgather": bool(hag.value),
                "active": bool(active.value)}

    # -- distributed tracing (docs/TRACING.md) -----------------------------
    def trace_now_ns(self):
        """Monotonic trace-clock ns on the native recorder's per-process
        epoch; 0 on a pre-trace core build."""
        if not self._has_trace:
            return 0
        return int(self.lib.horovod_tpu_trace_now_ns())

    def trace_record(self, name, phase, start_ns, end_ns, nbytes=0,
                     group=0):
        """Records one span into the native trace ring (no-op before
        init, with HVD_TPU_TRACE=0, or on a pre-trace core). `phase`
        takes the wire values from native/trace.h (8 = request)."""
        if not self._has_trace:
            return
        self.lib.horovod_tpu_trace_record(
            name.encode("utf-8"), int(phase), int(start_ns), int(end_ns),
            int(nbytes), int(group))

    def trace_dump_bundle(self, reason="manual"):
        """Forces a flight-recorder bundle dump; returns the bundle path
        or "" when HVD_TPU_BUNDLE_DIR is unset, the per-process cap is
        hit, or the core predates tracing."""
        if not self._has_trace:
            return ""
        out = self.lib.horovod_tpu_trace_dump_bundle(
            reason.encode("utf-8"))
        return out.decode("utf-8") if out else ""

    def trace_counters(self):
        """Dict of trace_spans_total / trace_spans_dropped_total /
        bundles_written_total (all zero on a pre-trace core)."""
        if not self._has_trace:
            return {"trace_spans_total": 0, "trace_spans_dropped_total": 0,
                    "bundles_written_total": 0}
        out = (ctypes.c_uint64 * 3)()
        self.lib.horovod_tpu_trace_counters(out)
        return {"trace_spans_total": int(out[0]),
                "trace_spans_dropped_total": int(out[1]),
                "bundles_written_total": int(out[2])}

    # -- topology ----------------------------------------------------------
    def rank(self):
        return self._query("horovod_tpu_rank")

    def local_rank(self):
        return self._query("horovod_tpu_local_rank")

    def cross_rank(self):
        return self._query("horovod_tpu_cross_rank")

    def size(self):
        return self._query("horovod_tpu_size")

    def local_size(self):
        return self._query("horovod_tpu_local_size")

    def cross_size(self):
        return self._query("horovod_tpu_cross_size")

    def is_homogeneous(self):
        return bool(self.lib.horovod_tpu_is_homogeneous())

    def _query(self, fn):
        value = getattr(self.lib, fn)()
        if value == -1:
            raise ValueError(
                "Horovod-TPU has not been initialized; call hvd.init() first.")
        return value

    # -- build probes ------------------------------------------------------
    def tcp_built(self):
        return bool(self.lib.horovod_tpu_tcp_built())

    def cpu_ops_built(self):
        return bool(self.lib.horovod_tpu_cpu_ops_built())


_basics = None


def get_basics():
    global _basics
    if _basics is None:
        _basics = HorovodBasics()
    return _basics
