from .basics import HorovodBasics, get_basics  # noqa: F401
from .ops import (  # noqa: F401
    HorovodInternalError,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    broadcast,
    broadcast_async,
    poll,
    reduce_scatter,
    reduce_scatter_async,
    shard_partition,
    synchronize,
)
