"""Framework-agnostic host collectives over the native core.

Async handle semantics mirror the reference torch binding
(``horovod/torch/mpi_ops.py:58-90,413-445``): ``*_async`` returns an int
handle, ``poll``/``synchronize`` complete it, and a module-level handle map
keeps the numpy buffers alive until the background thread is done with them.
"""

import ctypes
import os

import numpy as np

from horovod_tpu import compression as _compression
from horovod_tpu import groups as _groups
from .basics import get_basics, numpy_to_hvd_dtype, _DTYPE_TO_NUMPY

# handle -> (input array, output array or None, participant count) —
# keeps buffers alive while the background thread works on them; the
# participant count (group size; world size for group 0) shapes
# allgather results at synchronize time.
_handle_map = {}

# Status codes must match native/common.h StatusType.
_STATUS_OK = 0
_STATUS_IN_PROGRESS = 5


class HorovodInternalError(RuntimeError):
    pass


def _shape_array(arr):
    return (ctypes.c_int64 * arr.ndim)(*arr.shape)


def allreduce_async(tensor, name, prescale_factor=1.0, postscale_factor=1.0,
                    out=None, compression=None, group=None):
    """Starts an allreduce (sum) on a numpy array; returns a handle.

    `out`, when given, is a C-contiguous same-dtype/size array the core
    writes the result into directly — it MAY alias the input (the native
    ops guard self-copy: cpu_operations.cc `e.output != e.data`). This
    is the zero-copy path for framework tensors whose memory numpy can
    view (torch CPU tensors).

    `compression` selects the wire codec ('none'/'bf16'/'int8' or a
    `horovod_tpu.compression.Compression` mode; None defers to
    HVD_TPU_COMPRESSION). The array stays this dtype end to end — only
    ring-hop payloads are encoded — and the mode rides the negotiation,
    so every rank must pass the same value (docs/COMPRESSION.md).

    `group` scopes the collective to a `horovod_tpu.ProcessGroup`
    (docs/GROUPS.md): the sum spans only the group's members and rides
    the group's ring; only members may call it."""
    basics = get_basics()
    mode = _compression.resolve(compression)
    gid = _groups.resolve_group(group)
    arr = np.ascontiguousarray(tensor)
    # ascontiguousarray promotes 0-d to (1,); the result must round-trip
    # the caller's shape (a reshape view shares the output buffer).
    if out is None:
        out = np.empty_like(arr).reshape(np.shape(tensor))
    handle = basics.lib.horovod_tpu_enqueue_allreduce_grp(
        name.encode("utf-8"), arr.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p), arr.ndim, _shape_array(arr),
        numpy_to_hvd_dtype(arr.dtype), float(prescale_factor),
        float(postscale_factor), int(mode.mode), gid)
    _handle_map[handle] = (arr, out, None)
    return handle


def shard_partition(count, n):
    """(counts, offsets) of the reduce-scatter shard partition: `count`
    elements into `n` near-equal chunks, chunk i owned by rank i. MUST
    match native/cpu_operations.cc PartitionChunks — both ends size the
    shard buffers from this."""
    base, rem = divmod(int(count), int(n))
    counts = [base + (1 if i < rem else 0) for i in range(n)]
    offsets = [0] * n
    for i in range(1, n):
        offsets[i] = offsets[i - 1] + counts[i - 1]
    return counts, offsets


def sharded_update_default():
    """The job-wide ``HVD_TPU_SHARDED_UPDATE`` default, parsed by the
    native helper so every consumer (framework wrappers, tooling,
    tests) agrees on the same semantics (strtol: any nonzero value
    enables, docs/ZERO.md)."""
    return get_basics().sharded_update_default()


def reduce_scatter_async(tensor, name, prescale_factor=1.0,
                         postscale_factor=1.0, compression=None, out=None,
                         group=None):
    """Starts a reduce-scatter (sum) on a numpy array; returns a handle.

    The tensor is treated as FLAT: its elements are partitioned into
    ``size()`` near-equal chunks (:func:`shard_partition`) and this
    rank's result is chunk ``rank()`` of the cross-rank sum — a 1-D
    array of ``counts[rank]`` elements (the sharded-update gradient leg,
    docs/ZERO.md). `out`, when given, must be a C-contiguous same-dtype
    array of exactly that many elements. `compression` rides the
    negotiation per hop exactly as in :func:`allreduce_async`.

    With `group=` the partition spans the GROUP: chunk i goes to the
    group's i-th member and the sum covers members only."""
    basics = get_basics()
    mode = _compression.resolve(compression)
    gid = _groups.resolve_group(group)
    arr = np.ascontiguousarray(tensor)
    counts, _ = shard_partition(arr.size, _groups.group_size(group))
    my_count = counts[_groups.group_rank(group)]
    if out is None:
        out = np.empty(my_count, dtype=arr.dtype)
    elif out.size != my_count:
        raise ValueError("reduce_scatter out has %d elements; this rank's "
                         "shard needs %d" % (out.size, my_count))
    elif out.dtype != arr.dtype or not out.flags["C_CONTIGUOUS"]:
        # The native core memcpys counts[rank]*itemsize bytes straight
        # into out's base pointer: a narrower dtype or a strided view
        # would be silent heap corruption, not a wrong answer.
        raise ValueError("reduce_scatter out must be a C-contiguous %s "
                         "array (got %s%s)"
                         % (arr.dtype, out.dtype,
                            "" if out.flags["C_CONTIGUOUS"]
                            else ", non-contiguous"))
    handle = basics.lib.horovod_tpu_enqueue_reduce_scatter_grp(
        name.encode("utf-8"), arr.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p), arr.ndim, _shape_array(arr),
        numpy_to_hvd_dtype(arr.dtype), float(prescale_factor),
        float(postscale_factor), int(mode.mode), gid)
    _handle_map[handle] = (arr, out, None)
    return handle


def reduce_scatter(tensor, name, average=False, prescale_factor=1.0,
                   postscale_factor=1.0, compression=None, group=None):
    """Synchronous reduce-scatter; returns this rank's 1-D shard of the
    sum (or the average with ``average=True``)."""
    if average:
        postscale_factor = postscale_factor / _groups.group_size(group)
    return synchronize(reduce_scatter_async(
        tensor, name, prescale_factor, postscale_factor,
        compression=compression, group=group))


def allgather_async(tensor, name, group=None):
    """Starts an allgather along dim 0; returns a handle. With `group=`
    the concatenation spans the group's members in group order."""
    basics = get_basics()
    gid = _groups.resolve_group(group)
    arr = np.ascontiguousarray(tensor)
    if arr.ndim == 0:
        arr = arr.reshape(1)
    handle = basics.lib.horovod_tpu_enqueue_allgather_grp(
        name.encode("utf-8"), arr.ctypes.data_as(ctypes.c_void_p), arr.ndim,
        _shape_array(arr), numpy_to_hvd_dtype(arr.dtype), gid)
    _handle_map[handle] = (arr, None, _groups.group_size(group))
    return handle


def broadcast_async(tensor, root_rank, name, out=None, group=None):
    """Starts a broadcast from root_rank; returns a handle. `out` as in
    :func:`allreduce_async` (may alias the input). `root_rank` is the
    WORLD rank and must be a member of `group` when one is given."""
    basics = get_basics()
    gid = _groups.resolve_group(group)
    arr = np.ascontiguousarray(tensor)
    if out is None:
        out = np.empty_like(arr).reshape(np.shape(tensor))
    handle = basics.lib.horovod_tpu_enqueue_broadcast_grp(
        name.encode("utf-8"), arr.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p), arr.ndim, _shape_array(arr),
        numpy_to_hvd_dtype(arr.dtype), int(root_rank), gid)
    _handle_map[handle] = (arr, out, None)
    return handle


def poll(handle):
    """True when the collective behind `handle` completed."""
    return bool(get_basics().lib.horovod_tpu_poll(handle))


def synchronize(handle):
    """Blocks until completion; returns the result array.

    Allgather results are zero-copy views over the core-owned gather
    buffer; the handle (and with it the buffer) is released when the
    returned array is garbage-collected. Callers that retain a result
    long-term (or cache it where reference cycles may delay GC) should
    ``np.copy`` it — or set ``HVD_TPU_ALLGATHER_COPY=1`` to make every
    allgather return an owned copy and release the core buffer
    immediately (trades one memcpy for deterministic lifetime)."""
    basics = get_basics()
    if handle not in _handle_map:
        raise ValueError("unknown handle %d" % handle)
    released = False
    try:
        status = basics.lib.horovod_tpu_wait(handle)
        if status != _STATUS_OK:
            msg = basics.lib.horovod_tpu_error_string(handle)
            raise HorovodInternalError(
                msg.decode("utf-8") if msg else "collective failed")
        arr, out, gsize = _handle_map[handle]
        if out is not None:
            return out
        # Allgather: view the core-owned result in place. The first-dim
        # table spans the PARTICIPANTS (group members, or the world).
        nbytes = basics.lib.horovod_tpu_allgather_bytes(handle)
        if nbytes < 0:
            raise HorovodInternalError("allgather produced no result")
        size = gsize if gsize is not None else get_basics().size()
        first_dim = 0
        for r in range(size):
            d = basics.lib.horovod_tpu_allgather_rank_dim(handle, r)
            if d < 0:
                raise HorovodInternalError("allgather sizes missing")
            first_dim += d
        shape = (first_dim,) + tuple(arr.shape[1:])
        expected = int(np.prod(shape, dtype=np.int64)) * arr.dtype.itemsize
        if nbytes != expected:
            raise HorovodInternalError(
                "allgather size mismatch: %d != %d" % (nbytes, expected))
        if nbytes == 0:  # empty gather: a vector's data() may be null
            return np.empty(shape, dtype=arr.dtype)
        ptr = basics.lib.horovod_tpu_allgather_data(handle)
        if not ptr:
            raise HorovodInternalError("allgather buffer missing")
        if os.environ.get("HVD_TPU_ALLGATHER_COPY", "0") == "1":
            buf = (ctypes.c_char * nbytes).from_address(ptr)
            return np.frombuffer(buf, dtype=arr.dtype).reshape(
                shape).copy()
        result = _view_core_buffer(basics, handle, ptr, nbytes, arr.dtype,
                                   shape)
        released = True  # ownership moved to the view's finalizer
        return result
    finally:
        if not released:
            basics.lib.horovod_tpu_release(handle)
        del _handle_map[handle]


def _view_core_buffer(basics, handle, ptr, nbytes, dtype, shape):
    """Wraps the core-owned gather buffer as a numpy array without
    copying; `horovod_tpu_release` fires when the array (and any views
    of it) is garbage-collected."""
    import weakref

    buf = (ctypes.c_char * nbytes).from_address(ptr)
    result = np.frombuffer(buf, dtype=dtype).reshape(shape)
    weakref.finalize(buf, basics.lib.horovod_tpu_release, handle)
    return result


def allreduce(tensor, name, average=False, prescale_factor=1.0,
              postscale_factor=1.0, compression=None, group=None):
    """Synchronous allreduce; returns the reduced array. ``average``
    divides by the participant count (the group's size under
    ``group=``)."""
    if average:
        postscale_factor = postscale_factor / _groups.group_size(group)
    return synchronize(allreduce_async(tensor, name, prescale_factor,
                                       postscale_factor,
                                       compression=compression,
                                       group=group))


def allgather(tensor, name, group=None):
    return synchronize(allgather_async(tensor, name, group=group))


def broadcast(tensor, root_rank, name, group=None):
    return synchronize(broadcast_async(tensor, root_rank, name, group=group))
