"""Gradient compression for the torch binding (reference:
horovod/torch/compression.py — fp16 cast before allreduce, cast back
after; bf16 added as the TPU-native 16-bit format)."""

import torch


class NoneCompressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor:
    @staticmethod
    def compress(tensor):
        if tensor.dtype in (torch.float32, torch.float64):
            return tensor.half(), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class BF16Compressor:
    @staticmethod
    def compress(tensor):
        if tensor.dtype in (torch.float32, torch.float64):
            return tensor.bfloat16(), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
