"""PyTorch binding.

Capability parity with the reference torch API
(``horovod/torch/__init__.py`` + ``horovod/torch/mpi_ops.py``):
``allreduce[_async][_]``, ``allgather[_async]``, ``broadcast[_async][_]``,
``poll``/``synchronize`` handle semantics, ``DistributedOptimizer`` with
per-parameter grad hooks and ``backward_passes_per_step`` accumulation,
``broadcast_parameters`` / ``broadcast_optimizer_state``, ``Compression``.

Torch here is the CPU-tensor framework (the environment ships CPU torch);
tensors ride the native host core — the same path as the reference's
``DoAllreduceCudaOnCPU`` staging variant (`torch/mpi_ops_v2.cc:84-117`),
minus the GPU staging copy. Contiguous CPU tensors ride ZERO-COPY
through compiled C glue (`torch_cext.c`, built lazily): the tensor's
own storage pointer enters the core enqueue API from C for both input
and output, so ``allreduce_async_`` / ``broadcast_async_`` reduce in
place with no host copies and no per-call interpreter marshalling —
the reference's binding architecture (`torch/mpi_ops_v2.cc:52-76`)
with the CPython C API instead of pybind11. The ctypes + buffer-
protocol path remains as the portable fallback (and carries
allgather / non-contiguous / unsupported-dtype cases). TPU training
from torch graphs is out of scope; use the jax binding for the
XLA/ICI plane.
"""

import torch

import horovod_tpu as _hvd
from horovod_tpu import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, mpi_built, gloo_enabled,
    gloo_built, nccl_built, ddl_built, mlsl_built,
)
from horovod_tpu.common import ops as _ops
from horovod_tpu.common.ops import HorovodInternalError  # noqa: F401

from .compression import Compression  # noqa: F401

# handle -> (input torch tensor, result torch tensor or None, bound).
# `bound=True` means the core writes the result DIRECTLY into the result
# tensor's storage (zero-copy path) — synchronize just returns it.
_torch_handles = {}
# Handles started through the C-extension glue (they bypass the ctypes
# handle map; poll/synchronize must use the extension's calls).
_cext_handles = set()

_name_counter = [0]

# torch dtype -> native DataType enum (native/message.h; same table as
# common.basics._NUMPY_TO_DTYPE).
_TORCH_TO_HVD_DTYPE = {
    torch.uint8: 0, torch.int8: 1, torch.int16: 3, torch.int32: 4,
    torch.int64: 5, torch.float16: 6, torch.float32: 7,
    torch.float64: 8, torch.bool: 9, torch.bfloat16: 10,
}


def _cext_mod():
    from . import _cext
    return _cext.load()


def _auto_name(prefix):
    _name_counter[0] += 1
    return "%s.t%d" % (prefix, _name_counter[0])


def _numpy_view(tensor):
    """Zero-copy numpy view over a contiguous CPU torch tensor, or None
    when the memory can't be viewed (non-CPU, non-contiguous). This is
    the reference's in-place-on-tensor-storage design
    (`torch/mpi_ops_v2.cc:52-76`) done with the buffer protocol instead
    of C++ glue: the view's .ctypes pointer IS the tensor's storage."""
    if tensor.device.type != "cpu" or not tensor.is_contiguous():
        return None
    t = tensor.detach()
    if tensor.dtype == torch.bfloat16:
        # Bit-pattern reinterpret (no value conversion): torch bf16 ->
        # int16 view -> numpy -> ml_dtypes.bfloat16 view.
        import ml_dtypes
        return t.view(torch.int16).numpy().view(ml_dtypes.bfloat16)
    try:
        return t.numpy()
    except (TypeError, RuntimeError):
        return None


def _to_numpy(tensor):
    """Copying fallback for tensors `_numpy_view` can't handle."""
    if tensor.dtype == torch.bfloat16:
        import ml_dtypes
        return tensor.detach().float().cpu().numpy().astype(
            ml_dtypes.bfloat16)
    return tensor.detach().cpu().numpy()


# -- async collectives ----------------------------------------------------

def _cext_eligible(tensor):
    return (tensor.device.type == "cpu" and tensor.is_contiguous() and
            tensor.dtype in _TORCH_TO_HVD_DTYPE and
            tensor.dim() <= 16)  # torch_cext.c MAX_DIMS


def _start_cext(tensor, dest, enqueue):
    """Shared C-extension bookkeeping: allocate/alias the result tensor,
    enqueue via `enqueue(data_ptr, out_ptr, shape, dtype)`, register the
    handle in both maps. The tensor's own storage pointer enters the
    core from C (reference mpi_ops_v2.cc architecture)."""
    result = tensor if dest is tensor else torch.empty_like(tensor)
    shape = tuple(tensor.shape) or (1,)
    handle = enqueue(tensor.data_ptr(), result.data_ptr(), shape,
                     _TORCH_TO_HVD_DTYPE[tensor.dtype])
    _torch_handles[handle] = (tensor, result, True)
    _cext_handles.add(handle)
    return handle


def _start_allreduce(tensor, dest, name, prescale, post, group=None):
    """dest=None: allocate a result tensor; dest=tensor: in place."""
    ext = _cext_mod()
    # The C extension predates process groups; group-scoped calls ride
    # the Python ops layer (same core, one extra numpy view).
    if ext is not None and _cext_eligible(tensor) and group is None:
        return _start_cext(
            tensor, dest,
            lambda dp, op, sh, dt: ext.enqueue_allreduce(
                name, dp, op, sh, dt, prescale, post))
    view = _numpy_view(tensor)
    if view is not None:
        result = tensor if dest is tensor else torch.empty_like(tensor)
        out_view = view if result is tensor else _numpy_view(result)
        handle = _ops.allreduce_async(view, name,
                                      prescale_factor=prescale,
                                      postscale_factor=post, out=out_view,
                                      group=group)
        _torch_handles[handle] = (tensor, result, True)
        return handle
    handle = _ops.allreduce_async(_to_numpy(tensor), name,
                                  prescale_factor=prescale,
                                  postscale_factor=post, group=group)
    _torch_handles[handle] = (tensor, dest, False)
    return handle


def allreduce_async(tensor, average=True, name=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    group=None):
    from horovod_tpu import groups as _grp
    post = (postscale_factor / _grp.group_size(group) if average
            else postscale_factor)
    return _start_allreduce(tensor, None, name or _auto_name("allreduce"),
                            prescale_factor, post, group)


def allreduce_async_(tensor, average=True, name=None,
                     prescale_factor=1.0, postscale_factor=1.0,
                     group=None):
    """In-place variant: the result lands back in `tensor` — zero-copy
    (the core reduces straight into the tensor's storage) when the
    tensor is contiguous CPU.

    Failure semantics match the reference's in-place design: if the
    collective fails (peer crash, shutdown), the tensor's contents are
    UNDEFINED — fault-tolerant callers must re-broadcast state after
    catching HorovodInternalError, exactly as with the reference's
    in-place ops."""
    from horovod_tpu import groups as _grp
    post = (postscale_factor / _grp.group_size(group) if average
            else postscale_factor)
    return _start_allreduce(tensor, tensor,
                            name or _auto_name("allreduce"),
                            prescale_factor, post, group)


def allgather_async(tensor, name=None, group=None):
    """The gathered result returned by :func:`synchronize` is a
    zero-copy view over the core-owned gather buffer (released when the
    result tensor is garbage-collected). Callers retaining many results
    long-term should ``.clone()`` them — or set
    ``HVD_TPU_ALLGATHER_COPY=1`` to make every allgather return an
    owned copy with deterministic buffer release."""
    view = _numpy_view(tensor)
    handle = _ops.allgather_async(
        view if view is not None else _to_numpy(tensor),
        name or _auto_name("allgather"), group=group)
    _torch_handles[handle] = (tensor, None, False)
    return handle


def _start_broadcast(tensor, dest, root_rank, name, group=None):
    ext = _cext_mod()
    # Group-scoped calls ride the Python ops layer (the C extension
    # predates groups), like _start_allreduce.
    if ext is not None and _cext_eligible(tensor) and group is None:
        return _start_cext(
            tensor, dest,
            lambda dp, op, sh, dt: ext.enqueue_broadcast(
                name, dp, op, sh, dt, int(root_rank)))
    view = _numpy_view(tensor)
    if view is not None:
        result = tensor if dest is tensor else torch.empty_like(tensor)
        out_view = view if result is tensor else _numpy_view(result)
        handle = _ops.broadcast_async(view, root_rank, name, out=out_view,
                                      group=group)
        _torch_handles[handle] = (tensor, result, True)
        return handle
    handle = _ops.broadcast_async(_to_numpy(tensor), root_rank, name,
                                  group=group)
    _torch_handles[handle] = (tensor, dest, False)
    return handle


def broadcast_async(tensor, root_rank, name=None, group=None):
    return _start_broadcast(tensor, None, root_rank,
                            name or _auto_name("broadcast"), group)


def broadcast_async_(tensor, root_rank, name=None, group=None):
    """In-place variant — zero-copy for contiguous CPU tensors."""
    return _start_broadcast(tensor, tensor, root_rank,
                            name or _auto_name("broadcast"), group)


def poll(handle):
    if handle in _cext_handles:
        return bool(_cext_mod().poll(handle))
    return _ops.poll(handle)


def synchronize(handle):
    """Completes `handle`; returns the result as a torch tensor (writing
    in place when the `_`-variant started it)."""
    if handle not in _torch_handles:
        raise ValueError("unknown handle %d" % handle)
    tensor, dest, bound = _torch_handles.pop(handle)
    if handle in _cext_handles:
        _cext_handles.discard(handle)
        try:
            _cext_mod().wait(handle)
        except RuntimeError as e:
            raise HorovodInternalError(str(e)) from e
        return dest
    out = _ops.synchronize(handle)
    if bound:
        # The core already wrote the result into dest's storage.
        return dest
    try:
        # No .copy(): allgather results stay views over the core-owned
        # gather buffer (torch.from_numpy holds the numpy base, whose
        # finalizer releases the core handle).
        result = torch.from_numpy(out)
    except TypeError:  # bfloat16 numpy extension dtype: bit reinterpret
        import numpy as np
        result = torch.from_numpy(
            np.ascontiguousarray(out).view(np.int16)).view(torch.bfloat16)
    if result.dtype != tensor.dtype:
        result = result.to(tensor.dtype)
    if dest is not None:
        dest.copy_(result.reshape(dest.shape))
        return dest
    return result


# -- differentiable collectives (reference: the autograd Functions in
# horovod/torch/mpi_ops.py:117-128,243-261,325-339) ------------------------

class _AllreduceFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, average, name, prescale, postscale, group):
        ctx.average, ctx.name = average, name
        ctx.prescale, ctx.postscale = prescale, postscale
        ctx.group = group
        return synchronize(
            allreduce_async(tensor, average, name, prescale, postscale,
                            group=group))

    @staticmethod
    def backward(ctx, grad):
        # The gradient of an allreduce is the allreduce of the gradient
        # with the same scaling (over the same group).
        reduced = _AllreduceFunction.apply(
            grad, ctx.average, ctx.name and ctx.name + ".grad",
            ctx.prescale, ctx.postscale, ctx.group)
        return reduced, None, None, None, None, None


class _AllgatherFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name, group):
        ctx.dim0 = tensor.shape[0]
        ctx.name = name or _auto_name("allgather")
        ctx.group = group
        return synchronize(allgather_async(tensor, ctx.name, group=group))

    @staticmethod
    def backward(ctx, grad):
        # Sum (not average) the upstream grads — the reference's exact
        # convention (torch/mpi_ops.py:254 `allreduce(grad_output,
        # average=False)`): the objective is implicitly the sum of every
        # rank's loss. Then slice out this rank's segment; the segment
        # table comes from an allgather of first dims so unequal gathers
        # differentiate correctly.
        from horovod_tpu import groups as _grp
        grad_sum = synchronize(allreduce_async(
            grad.contiguous(), average=False, name=ctx.name + ".grad",
            group=ctx.group))
        sizes = synchronize(allgather_async(
            torch.tensor([ctx.dim0], dtype=torch.int64),
            name=ctx.name + ".grad_sizes", group=ctx.group))
        offset = int(sizes[:_grp.group_rank(ctx.group)].sum())
        return grad_sum[offset:offset + ctx.dim0], None, None


class _BroadcastFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name, group):
        ctx.root_rank = root_rank
        ctx.name = name or _auto_name("broadcast")
        ctx.group = group
        return synchronize(broadcast_async(tensor, root_rank, ctx.name,
                                           group=group))

    @staticmethod
    def backward(ctx, grad):
        # Every rank's output grad sums onto the root's input (reference
        # torch/mpi_ops.py:336 uses average=False the same way);
        # non-root inputs are unused.
        reduced = synchronize(allreduce_async(
            grad.contiguous(), average=False, name=ctx.name + ".grad",
            group=ctx.group))
        if rank() != ctx.root_rank:
            reduced = torch.zeros_like(reduced)
        return reduced, None, None, None


# -- sync wrappers ---------------------------------------------------------

def allreduce(tensor, average=True, name=None, compression=Compression.none,
              prescale_factor=1.0, postscale_factor=1.0, group=None):
    compressed, ctx = compression.compress(tensor)
    reduced = _AllreduceFunction.apply(compressed, average, name,
                                       prescale_factor, postscale_factor,
                                       group)
    return compression.decompress(reduced, ctx)


def allreduce_(tensor, average=True, name=None,
               prescale_factor=1.0, postscale_factor=1.0, group=None):
    return synchronize(allreduce_async_(tensor, average, name,
                                        prescale_factor, postscale_factor,
                                        group=group))


def allgather(tensor, name=None, group=None):
    return _AllgatherFunction.apply(tensor, name, group)


def broadcast(tensor, root_rank, name=None, group=None):
    return _BroadcastFunction.apply(tensor, root_rank, name, group)


def broadcast_(tensor, root_rank, name=None, group=None):
    return synchronize(broadcast_async_(tensor, root_rank, name,
                                        group=group))


# -- parameter / optimizer state broadcast --------------------------------

def broadcast_parameters(params, root_rank=0):
    """Broadcasts a model's `state_dict()` or `named_parameters()` from
    root (reference: torch/__init__.py:255-284)."""
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        items = sorted(params)
    handles = []
    for name, p in items:
        if not torch.is_tensor(p):
            continue
        handles.append((p, broadcast_async(p, root_rank, "bc_param.%s" %
                                           name)))
    for p, h in handles:
        with torch.no_grad():
            p.copy_(synchronize(h).reshape(p.shape))


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcasts optimizer state from root, tensor-izing scalar state the
    way the reference does (torch/__init__.py:287-403)."""
    state_dict = optimizer.state_dict()
    casts = []
    handles = []

    def _walk(prefix, obj):
        if torch.is_tensor(obj):
            handles.append((obj, broadcast_async(obj, root_rank,
                                                 "bc_opt.%s" % prefix)))
        elif isinstance(obj, (int, float)):
            t = torch.tensor(float(obj), dtype=torch.float64)
            handles.append((t, broadcast_async(t, root_rank,
                                               "bc_opt.%s" % prefix)))
            casts.append((prefix, type(obj), t))
        elif isinstance(obj, dict):
            for k in sorted(obj, key=str):
                _walk("%s.%s" % (prefix, k), obj[k])
        elif isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                _walk("%s.%d" % (prefix, i), v)

    _walk("state", state_dict.get("state", {}))
    for i, group in enumerate(state_dict.get("param_groups", [])):
        for k in sorted(group, key=str):
            if k != "params":
                _walk("group.%d.%s" % (i, k), group[k])

    for t, h in handles:
        with torch.no_grad():
            t.copy_(synchronize(h).reshape(t.shape))
    # Write back tensor-ized scalars.
    scalar_map = {prefix: typ(t.item()) for prefix, typ, t in casts}

    def _apply(prefix, obj):
        if isinstance(obj, dict):
            for k in list(obj):
                p = "%s.%s" % (prefix, k)
                if p in scalar_map:
                    obj[k] = scalar_map[p]
                else:
                    _apply(p, obj[k])
        elif isinstance(obj, list):
            for i in range(len(obj)):
                p = "%s.%d" % (prefix, i)
                if p in scalar_map:
                    obj[i] = scalar_map[p]
                else:
                    _apply(p, obj[i])

    _apply("state", state_dict.get("state", {}))
    for i, group in enumerate(state_dict.get("param_groups", [])):
        for k in list(group):
            if k != "params":
                p = "group.%d.%s" % (i, k)
                if p in scalar_map:
                    group[k] = scalar_map[p]
    optimizer.load_state_dict(state_dict)


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcasts an arbitrary picklable object from root."""
    import io
    import pickle

    import numpy as np
    if rank() == root_rank:
        data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    else:
        data = np.zeros(0, dtype=np.uint8)
    length = torch.tensor([len(data)], dtype=torch.int64)
    broadcast_(length, root_rank, (name or "bc_obj") + ".len")
    payload = torch.zeros(int(length.item()), dtype=torch.uint8)
    if rank() == root_rank:
        payload.copy_(torch.from_numpy(data.copy()))
    broadcast_(payload, root_rank, (name or "bc_obj") + ".data")
    return pickle.loads(io.BytesIO(payload.numpy().tobytes()).getvalue())


# -- DistributedOptimizer --------------------------------------------------

class _DistributedOptimizer(torch.optim.Optimizer):
    """Wraps a torch optimizer: registers per-parameter grad-accumulator
    hooks that fire async allreduce as gradients become ready (reference:
    torch/__init__.py:108-143); `step()` drains the handles first."""

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1, group=None, agc=None):
        # params is the wrapped optimizer's param_groups: each group dict
        # already carries its hyperparameters, so the parent optimizer's
        # defaults never overwrite them (same trick as the reference,
        # torch/__init__.py:50).
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._backward_passes_per_step = backward_passes_per_step
        # Adaptive gradient clipping factor (ops/agc.py, arxiv
        # 2102.06171): unit-wise clip of each reduced gradient against
        # its parameter's norm, applied in step() AFTER synchronize()
        # so the threshold sees the true global gradient and every rank
        # clips identically. The norm-free models' trainability knob.
        self._agc = agc
        # Gradient-reduction scope (docs/GROUPS.md): None = resolve this
        # rank's CURRENT batch group at each reduce — resolving at
        # construction would capture a group id that goes stale across
        # elastic re-inits (the mesh re-forms with fresh ids) and would
        # miss a mesh formed after the optimizer was built.
        self._group = group
        self._allreduce_delay = {}
        self._handles = {}
        self._grad_accs = []
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [("allreduce.noname.%s" % i, v)
                     for param_group in self.param_groups
                     for i, v in enumerate(param_group["params"])]
        all_params = {id(v) for pg in self.param_groups
                      for v in pg["params"]}
        self._parameter_names = {id(v): k for k, v in named
                                 if id(v) in all_params}
        if _hvd.size() > 1:
            self._register_hooks()

    def _register_hooks(self):
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self._backward_passes_per_step
                    p_tmp = p.expand_as(p)
                    grad_acc = p_tmp.grad_fn.next_functions[0][0]
                    grad_acc.register_hook(self._make_hook(p))
                    self._grad_accs.append(grad_acc)

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(id(p), "grad.%d" % id(p))
        compressed, ctx = self._compression.compress(p.grad)
        group = self._group if self._group is not None \
            else _hvd.batch_group()
        handle = allreduce_async(compressed, average=True,
                                 name="allreduce.%s" % name,
                                 group=group)
        return handle, ctx

    def _make_hook(self, p):
        def hook(*ignore):
            if p in self._handles and self._handles[p][0] is not None:
                if self._allreduce_delay[p] <= 0:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step.")
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                handle, ctx = self._allreduce_grad_async(p)
                self._handles[p] = (handle, ctx)
        return hook

    def synchronize(self):
        """Drains every outstanding gradient allreduce into p.grad."""
        missing = [p for p in self._requires_update
                   if p not in self._handles]
        for p in missing:
            self._handles[p] = self._allreduce_grad_async(p)
        for p, (handle, ctx) in sorted(
                self._handles.items(),
                key=lambda kv: self._parameter_names.get(id(kv[0]), "")):
            output = synchronize(handle)
            self._allreduce_delay[p] = self._backward_passes_per_step
            with torch.no_grad():
                p.grad.copy_(self._compression.decompress(output, ctx)
                             .reshape(p.grad.shape))
        self._handles.clear()
        self._synchronized = True

    class _SkipSync:
        def __init__(self, opt):
            self._opt = opt

        def __enter__(self):
            self._opt._should_synchronize = False

        def __exit__(self, *args):
            self._opt._should_synchronize = True

    def skip_synchronize(self):
        """Context manager to call step() without draining handles
        (reference: torch/__init__.py:164-182)."""
        return self._SkipSync(self)

    def _agc_clip_grads(self):
        """Unit-wise adaptive gradient clipping (AGC) in place on every
        p.grad: g *= min(1, agc * max(||w_unit||, eps) / ||g_unit||),
        units = output rows (dim 0 of torch's (out, in, ...) layout;
        whole tensor for <=1-D). Mirrors ops/agc.py for the jax plane."""
        eps = 1e-3
        with torch.no_grad():
            for pg in self.param_groups:
                for p in pg["params"]:
                    if p.grad is None:
                        continue
                    if p.dim() <= 1:
                        dims, keep = None, False
                    else:
                        dims, keep = tuple(range(1, p.dim())), True
                    if dims is None:
                        p_norm = p.norm()
                        g_norm = p.grad.norm()
                    else:
                        p_norm = p.norm(dim=dims, keepdim=keep)
                        g_norm = p.grad.norm(dim=dims, keepdim=keep)
                    max_norm = self._agc * p_norm.clamp(min=eps)
                    scale = (max_norm / g_norm.clamp(min=1e-16)).clamp(
                        max=1.0)
                    p.grad.mul_(scale)

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings
                warnings.warn(
                    "optimizer.step() called without a preceding backward "
                    "pass (synchronize() already ran)")
            self.synchronize()
        self._synchronized = False
        if self._agc:
            self._agc_clip_grads()
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "zero_grad called while allreduce handles are outstanding; "
                "call step() or synchronize() first")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


class _ShardedOptimizer(torch.optim.Optimizer):
    """ZeRO-style sharded weight update (docs/ZERO.md): per parameter
    group, gradients are flattened into one fused buffer and
    reduce-scattered (the ring's reduce-scatter leg — same wire bytes
    as the allreduce it replaces), an INNER optimizer of the wrapped
    class applies the update to this rank's 1/N flat shard (so
    momentum/Adam state is held for 1/N of the elements), and updated
    parameter shards are allgathered back into the real parameters.

    Numerically identical to the replicated wrapper for ELEMENTWISE
    optimizers (SGD/momentum/Adam/AdamW...); optimizers that couple
    elements across a parameter (e.g. per-tensor LARS trust ratios) see
    flat shards instead of whole tensors. The inner state is RANK-LOCAL
    — ``self.state`` on this wrapper stays empty by design; reading
    shard moments as if they were global is exactly what hvd-lint's
    ``sharded-update-rank-local-param-read`` flags.

    Parameters become OPTIMIZER-OWNED after the first ``step()``: the
    f32 flat shard captured then is the master copy, and every step's
    allgather overwrites the parameters from it — external parameter
    mutation between steps (weight clamping, ``load_state_dict`` on the
    model, re-tying) is silently reverted by the next allgather. To
    adopt externally-set values, rebuild the wrapper (or restore
    through ITS ``state_dict()`` contract, docs/ZERO.md).

    A parameter whose gradient is ``None`` this step rides the dense
    flat buffer as ZEROS (the shard partition is static), so stateful
    optimizers still decay its moments — unlike plain torch's skip.
    Freeze parameters BEFORE constructing the wrapper to exclude them
    (docs/ZERO.md)."""

    def __init__(self, params, named_parameters, compression=None,
                 backward_passes_per_step=1, group=None):
        super(self.__class__, self).__init__(params)
        from horovod_tpu import compression as _wire
        if backward_passes_per_step != 1:
            raise ValueError("sharded_update does not support "
                             "backward_passes_per_step > 1")
        from horovod_tpu.groups import assert_sharded_update_world_scope
        assert_sharded_update_world_scope(group)
        self._hvd_mode = _wire.resolve_wire_arg(compression,
                                                Compression.none)
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [("allreduce.noname.%s" % i, v)
                     for param_group in self.param_groups
                     for i, v in enumerate(param_group["params"])]
        self._hvd_param_names = {id(v): k for k, v in named}
        self._hvd_built = False

    def _hvd_build(self):
        """Builds the flat shard parameters and the inner optimizer
        lazily (so the wrapper sees the params' CURRENT values, e.g.
        after broadcast_parameters)."""
        from horovod_tpu.common.ops import shard_partition
        n, r = _hvd.size(), _hvd.rank()
        base_cls = type(self).__mro__[1]
        self._hvd_meta = []
        self._hvd_names = []
        shard_groups = []
        for group in self.param_groups:
            ps = [p for p in group["params"] if p.requires_grad]
            total = sum(p.numel() for p in ps)
            counts, offsets = shard_partition(max(total, 1), n)
            if ps:
                flat = torch.cat(
                    [p.detach().reshape(-1).float() for p in ps])
                sp = flat[offsets[r]:offsets[r] + counts[r]].clone()
            else:
                sp = torch.zeros(0)
            self._hvd_meta.append((ps, total, counts, offsets, sp))
            # Grad tensor name = the replicated wrapper's name for the
            # group's FIRST parameter: a sharded rank meeting a
            # replicated peer then collides at negotiation and the
            # coordinator rejects the op naming both ranks and modes
            # (docs/ZERO.md) instead of hanging.
            first = ps[0] if ps else None
            self._hvd_names.append(
                "allreduce.%s" % self._hvd_param_names.get(
                    id(first), "grad.%d" % id(first)))
            g = {k: v for k, v in group.items() if k != "params"}
            g["params"] = [sp]
            shard_groups.append(g)
        self._hvd_inner = base_cls(shard_groups)
        self._hvd_built = True

    def _hvd_report_state_bytes(self):
        total = 0
        for st in self._hvd_inner.state.values():
            for v in st.values():
                if torch.is_tensor(v):
                    total += v.numel() * v.element_size()
        _hvd.get_basics().opt_state_metrics(total)

    def state_dict(self):
        """The wrapper's own state is empty by design; the REAL moments
        live on the inner flat-shard optimizer. Fold them (plus the
        shard parameter values and the (rank, world) they were built
        for) into the dict so a save/load round-trip preserves them
        instead of silently resetting every moment to zero."""
        import copy
        if not self._hvd_built:
            self._hvd_build()
        d = super(self.__class__, self).state_dict()
        # deepcopy: torch's Optimizer.state_dict() references LIVE state
        # tensors and load_state_dict() only shallow-copies (its float
        # cast `.to(same dtype)` returns the same tensor), so without a
        # snapshot here the restored optimizer's moments would alias the
        # saver's and every subsequent step would mutate both.
        d["hvd_sharded"] = {
            "world": _hvd.size(), "rank": _hvd.rank(),
            "inner": copy.deepcopy(self._hvd_inner.state_dict()),
            "shards": [sp.detach().clone()
                       for (_, _, _, _, sp) in self._hvd_meta],
        }
        return d

    def load_state_dict(self, state_dict):
        state_dict = dict(state_dict)
        sharded = state_dict.pop("hvd_sharded", None)
        super(self.__class__, self).load_state_dict(state_dict)
        if sharded is None:
            raise ValueError(
                "this state_dict has no sharded-optimizer state (saved "
                "by a replicated optimizer?); sharded_update cannot "
                "restore it (docs/ZERO.md)")
        if sharded["world"] != _hvd.size() or \
                sharded["rank"] != _hvd.rank():
            raise RuntimeError(
                "sharded optimizer state_dict was saved by rank %d of "
                "%d but this process is rank %d of %d; torch shard "
                "state is rank-local — restore at the same membership "
                "(for cross-world restores ride the jax "
                "sharded_state_full/sharded_state_shard contract, "
                "docs/ZERO.md)"
                % (sharded["rank"], sharded["world"], _hvd.rank(),
                   _hvd.size()))
        if not self._hvd_built:
            self._hvd_build()
        import copy
        self._hvd_inner.load_state_dict(copy.deepcopy(sharded["inner"]))
        with torch.no_grad():
            for (_, _, _, _, sp), saved in zip(self._hvd_meta,
                                               sharded["shards"]):
                sp.copy_(saved)

    def step(self, closure=None):
        import numpy as np

        # Re-checked per step: a mesh formed AFTER construction must
        # fail here, not reduce-scatter across model shards.
        from horovod_tpu.groups import assert_sharded_update_world_scope
        assert_sharded_update_world_scope()
        loss = None
        if closure is not None:
            loss = closure()
        if not self._hvd_built:
            self._hvd_build()
        # LR schedulers (and manual tuning) mutate the WRAPPER's
        # param_groups; mirror every hyperparameter onto the inner
        # shard groups (1:1 by construction) or the shard update would
        # run at the construction-time values forever.
        for group, inner_group in zip(self.param_groups,
                                      self._hvd_inner.param_groups):
            for k, v in group.items():
                if k != "params":
                    inner_group[k] = v
        # Reduce-scatter every group's fused flat gradient into the
        # shard gradients (async: all groups negotiate/execute
        # concurrently), update the shards, allgather them back.
        scale = 1.0 / _hvd.size()
        handles = []
        for (ps, total, counts, offsets, sp), name in zip(
                self._hvd_meta, self._hvd_names):
            if not ps:
                handles.append(None)
                continue
            flat_g = torch.cat([
                (p.grad if p.grad is not None
                 else torch.zeros_like(p)).detach().reshape(-1).float()
                for p in ps])
            handles.append(_ops.reduce_scatter_async(
                flat_g.numpy(), name, postscale_factor=scale,
                compression=self._hvd_mode))
        for (_, _, _, _, sp), handle in zip(self._hvd_meta, handles):
            if handle is None:
                continue
            shard = _ops.synchronize(handle)
            sp.grad = torch.from_numpy(
                np.ascontiguousarray(shard)).to(sp.dtype)
        self._hvd_inner.step()
        handles = []
        for (ps, _, _, _, sp), name in zip(self._hvd_meta,
                                           self._hvd_names):
            handles.append(_ops.allgather_async(
                sp.detach().numpy(), name + ".param_ag")
                if ps else None)
        for (ps, total, counts, offsets, sp), handle in zip(
                self._hvd_meta, handles):
            if handle is None:
                continue
            full = _ops.synchronize(handle)
            full_t = torch.from_numpy(np.ascontiguousarray(full))
            off = 0
            with torch.no_grad():
                for p in ps:
                    p.copy_(full_t[off:off + p.numel()]
                            .reshape(p.shape).to(p.dtype))
                    off += p.numel()
        self._hvd_report_state_bytes()
        return loss

    def zero_grad(self, *args, **kwargs):
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         sharded_update=None, group=None, agc=None):
    """Wraps `optimizer` into a gradient-averaging distributed optimizer
    (reference: torch/__init__.py DistributedOptimizer factory — dynamic
    subclass so isinstance(opt, type(optimizer)) keeps working).

    ``sharded_update=True`` (job-wide: ``HVD_TPU_SHARDED_UPDATE=1``)
    switches to the ZeRO-style sharded weight update — reduce-scatter
    gradients, apply the optimizer to this rank's 1/N shard (optimizer
    state shrinks N-fold), allgather updated params (docs/ZERO.md).
    ``compression`` is then a wire mode ('none'/'bf16'/'int8'), and
    mixed sharded/replicated ranks are rejected at negotiation.

    ``group`` scopes the gradient averaging to a process group
    (docs/GROUPS.md); it defaults to this rank's batch group under
    ``hvd.init(model_parallel=k)``.

    ``agc`` enables adaptive gradient clipping at the given factor
    (e.g. 0.01 — unit-wise clip against each parameter's own norm,
    ops/agc.py, arxiv 2102.06171), applied in ``step()`` after the
    gradient synchronize — the knob that makes norm-free models
    trainable. Rejected with ``sharded_update`` (1/N flat shards
    destroy the unit structure)."""
    if sharded_update is None:
        sharded_update = _ops.sharded_update_default()
    if sharded_update:
        if agc is not None:
            raise ValueError(
                "agc= does not compose with sharded_update: the "
                "sharded path updates 1/N flat shards, destroying the "
                "per-unit norm structure AGC clips against")
        cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
                   dict(_ShardedOptimizer.__dict__))
        return cls(optimizer.param_groups, named_parameters, compression,
                   backward_passes_per_step, group)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, group, agc)
