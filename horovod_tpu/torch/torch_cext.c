/* CPython C-extension glue for the torch binding — the native analogue
 * of the reference's torch/mpi_ops_v2.cc: tensors enter the core
 * enqueue API from C with their own storage pointers (zero-copy, in
 * place), no ctypes marshalling on the hot path.
 *
 * Built lazily (see _cext.py) against libhorovod_tpu.so, pybind11-free
 * (plain Python C API, per the environment's constraints). The Python
 * side resolves tensors to (data_ptr, out_ptr, shape, dtype) — this
 * module performs the foreign calls and handle management.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>

/* Core C API (linked against libhorovod_tpu.so). */
extern int horovod_tpu_enqueue_allreduce(const char* name, const void* data,
                                         void* output, int ndim,
                                         const int64_t* shape, int dtype,
                                         double prescale, double postscale,
                                         int compression);
extern int horovod_tpu_default_compression(void);
extern int horovod_tpu_enqueue_broadcast(const char* name, const void* data,
                                         void* output, int ndim,
                                         const int64_t* shape, int dtype,
                                         int root_rank);
extern int horovod_tpu_poll(int handle);
extern int horovod_tpu_wait(int handle);
extern const char* horovod_tpu_error_string(int handle);
extern void horovod_tpu_release(int handle);

#define MAX_DIMS 16

static int parse_shape(PyObject* shape_obj, int64_t* shape, int* ndim) {
  Py_ssize_t n = PySequence_Length(shape_obj);
  if (n < 0 || n > MAX_DIMS) {
    PyErr_SetString(PyExc_ValueError, "bad tensor rank");
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PySequence_GetItem(shape_obj, i);
    if (item == NULL) return -1;
    shape[i] = PyLong_AsLongLong(item);
    Py_DECREF(item);
    if (PyErr_Occurred()) return -1;
  }
  *ndim = (int)n;
  return 0;
}

/* enqueue_allreduce(name, data_ptr, out_ptr, shape, dtype, pre, post) */
static PyObject* cext_enqueue_allreduce(PyObject* self, PyObject* args) {
  const char* name;
  unsigned long long data_ptr, out_ptr;
  PyObject* shape_obj;
  int dtype;
  double pre, post;
  if (!PyArg_ParseTuple(args, "sKKOidd", &name, &data_ptr, &out_ptr,
                        &shape_obj, &dtype, &pre, &post)) {
    return NULL;
  }
  int64_t shape[MAX_DIMS];
  int ndim;
  if (parse_shape(shape_obj, shape, &ndim) != 0) return NULL;
  int handle;
  Py_BEGIN_ALLOW_THREADS
  /* Wire compression follows the HVD_TPU_COMPRESSION job default (the
     torch binding's Compression codecs stay tensor-level). */
  handle = horovod_tpu_enqueue_allreduce(
      name, (const void*)(uintptr_t)data_ptr, (void*)(uintptr_t)out_ptr,
      ndim, shape, dtype, pre, post, horovod_tpu_default_compression());
  Py_END_ALLOW_THREADS
  return PyLong_FromLong(handle);
}

/* enqueue_broadcast(name, data_ptr, out_ptr, shape, dtype, root) */
static PyObject* cext_enqueue_broadcast(PyObject* self, PyObject* args) {
  const char* name;
  unsigned long long data_ptr, out_ptr;
  PyObject* shape_obj;
  int dtype, root;
  if (!PyArg_ParseTuple(args, "sKKOii", &name, &data_ptr, &out_ptr,
                        &shape_obj, &dtype, &root)) {
    return NULL;
  }
  int64_t shape[MAX_DIMS];
  int ndim;
  if (parse_shape(shape_obj, shape, &ndim) != 0) return NULL;
  int handle;
  Py_BEGIN_ALLOW_THREADS
  handle = horovod_tpu_enqueue_broadcast(
      name, (const void*)(uintptr_t)data_ptr, (void*)(uintptr_t)out_ptr,
      ndim, shape, dtype, root);
  Py_END_ALLOW_THREADS
  return PyLong_FromLong(handle);
}

static PyObject* cext_poll(PyObject* self, PyObject* args) {
  int handle;
  if (!PyArg_ParseTuple(args, "i", &handle)) return NULL;
  return PyBool_FromLong(horovod_tpu_poll(handle));
}

/* wait(handle) -> None on success; raises RuntimeError on failure.
 * Releases the handle either way (the caller owns the output buffer). */
static PyObject* cext_wait(PyObject* self, PyObject* args) {
  int handle;
  if (!PyArg_ParseTuple(args, "i", &handle)) return NULL;
  int status;
  Py_BEGIN_ALLOW_THREADS
  status = horovod_tpu_wait(handle);
  Py_END_ALLOW_THREADS
  if (status != 0) {  /* StatusType::OK == 0 */
    const char* msg = horovod_tpu_error_string(handle);
    PyErr_SetString(PyExc_RuntimeError,
                    msg ? msg : "collective failed");
    horovod_tpu_release(handle);
    return NULL;
  }
  horovod_tpu_release(handle);
  Py_RETURN_NONE;
}

static PyMethodDef cext_methods[] = {
    {"enqueue_allreduce", cext_enqueue_allreduce, METH_VARARGS,
     "enqueue_allreduce(name, data_ptr, out_ptr, shape, dtype, pre, post)"},
    {"enqueue_broadcast", cext_enqueue_broadcast, METH_VARARGS,
     "enqueue_broadcast(name, data_ptr, out_ptr, shape, dtype, root)"},
    {"poll", cext_poll, METH_VARARGS, "poll(handle) -> bool"},
    {"wait", cext_wait, METH_VARARGS,
     "wait(handle); raises RuntimeError on collective failure"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef cext_module = {
    PyModuleDef_HEAD_INIT, "_hvd_torch_cext",
    "Native torch-binding glue over the horovod_tpu core C API.", -1,
    cext_methods};

PyMODINIT_FUNC PyInit__hvd_torch_cext(void) {
  return PyModule_Create(&cext_module);
}
