"""Lazy builder/loader for the torch binding's C-extension glue
(`torch_cext.c`) — the native analogue of the reference's
torch/mpi_ops_v2.cc binding layer, built with the plain CPython C API
(pybind11 is not available in this environment).

Build happens once per interpreter ABI into the package directory,
linked against the already-built libhorovod_tpu.so (whose build the
ctypes loader owns). Failure to build degrades silently to the ctypes
path — set HVD_TPU_REQUIRE_CEXT=1 to make a missing extension fatal.

Symbol-resolution contract: the ctypes loader maps the core with
RTLD_GLOBAL *before* this extension imports, so the extension's
horovod_tpu_* references bind to that already-loaded (initialized)
instance via interposition — even if HVD_TPU_NATIVE_DIR pointed the
ctypes load at a different build than this extension's rpath (the
tf_ops.cc kernels rely on the same contract).
"""

import os
import subprocess
import sys
import sysconfig

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE = os.path.abspath(os.path.join(_HERE, "..", "native"))
_SO = os.path.join(
    _HERE, "_hvd_torch_cext%s" % sysconfig.get_config_var("EXT_SUFFIX"))

_mod = None
_tried = False


def _build():
    import fcntl

    from horovod_tpu.common.basics import get_basics
    get_basics()  # ensures libhorovod_tpu.so exists (ctypes loader builds)

    src = os.path.join(_HERE, "torch_cext.c")
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(src):
        return
    include = sysconfig.get_path("include")
    lock_path = os.path.join(_HERE, ".cext_build_lock")
    with open(lock_path, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if os.path.exists(_SO) and \
                    os.path.getmtime(_SO) >= os.path.getmtime(src):
                return
            # Link to a temp name and rename into place: the lock-free
            # fast path above (and any process with the old .so mapped)
            # must never observe a partially written file.
            tmp = _SO + ".tmp.%d" % os.getpid()
            cmd = ["g++", "-O2", "-shared", "-fPIC",
                   "-I%s" % include, "-x", "c", src,
                   "-L%s" % _NATIVE, "-lhorovod_tpu",
                   "-Wl,-rpath,%s" % _NATIVE,
                   "-o", tmp]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    "g++ failed building the torch C extension:\n%s" %
                    (proc.stderr or proc.stdout))
            os.replace(tmp, _SO)
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)


_load_error = None


def load():
    """The extension module, or None when unavailable. With
    HVD_TPU_REQUIRE_CEXT=1 a build/load failure is fatal on EVERY call
    (not just the first), so collectives can never silently fall back."""
    global _mod, _tried, _load_error
    if _mod is not None:
        return _mod
    if _tried:
        if _load_error is not None and \
                os.environ.get("HVD_TPU_REQUIRE_CEXT") == "1":
            raise RuntimeError(
                "torch C-extension glue unavailable: %s" % _load_error)
        return None
    _tried = True
    if os.environ.get("HVD_TPU_DISABLE_CEXT") == "1":
        return None
    try:
        _build()
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_hvd_torch_cext", _SO)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _mod = mod
    except Exception as e:
        _load_error = e
        if os.environ.get("HVD_TPU_REQUIRE_CEXT") == "1":
            raise RuntimeError(
                "torch C-extension glue unavailable: %s" % e) from e
        print("horovod_tpu: torch C extension unavailable (%s); "
              "using the ctypes path" % e, file=sys.stderr)
    return _mod
