"""JAX version compatibility shims.

The codebase targets the current stable API (``jax.shard_map`` with the
``check_vma`` flag). Older environments (jax <= 0.4.x) only ship
``jax.experimental.shard_map.shard_map`` with the flag spelled
``check_rep`` — without a shim every shard_map'd path (parallel train
steps, the multichip dryrun, most distributed tests) dies at import
time on such containers. ``ensure_jax_compat()`` installs a forwarding
wrapper once; on current jax it is a no-op.
"""


def ensure_jax_compat():
    import jax

    try:
        from jax.experimental.pallas import tpu as pltpu
        if not hasattr(pltpu, "CompilerParams") and \
                hasattr(pltpu, "TPUCompilerParams"):
            # Renamed TPUCompilerParams -> CompilerParams in newer jax;
            # the kernels use the current spelling.
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:
        pass

    if not hasattr(jax.distributed, "is_initialized"):
        # Added in newer jax; the old spelling is the global_state
        # client check (what is_initialized wraps upstream).
        def _dist_is_initialized():
            try:
                from jax._src.distributed import global_state
                return global_state.client is not None
            except Exception:
                return False
        jax.distributed.is_initialized = _dist_is_initialized

    if not hasattr(jax.lax, "axis_size"):
        # psum of the literal 1 over a named axis resolves statically to
        # the axis size on every jax version — the old-API spelling of
        # lax.axis_size.
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = bool(check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)

    jax.shard_map = shard_map
