"""Spark integration — ``horovod_tpu.spark.run(fn, ...)``.

Capability parity with the reference (`horovod/spark/__init__.py:35-233`):
run `fn` as a data-parallel horovod job on `num_proc` Spark tasks and
return the per-rank results. The reference tunnels `mpirun`'s remote shell
through Spark task RPC (mpirun_rsh); the TPU-native build needs no MPI —
Spark's **barrier execution mode** gives every task a rendezvous
(`BarrierTaskContext.allGather`), so each task exchanges its
host:port, computes the same rank/local/cross topology the launcher
would inject (`horovod_tpu/run/util.py:allocate_slots`), sets the
``HVD_TPU_*`` env, and calls ``hvd.init()`` directly.

The barrier-task body is factored framework-free (``_task_topology_env``)
so it is unit-testable without a Spark cluster (the reference mocks its
shell layer the same way, test/test_spark.py:51-91).
"""

import os
import socket


def _importable(mod):
    import importlib.util
    return importlib.util.find_spec(mod) is not None


def _task_topology_env(rank, host_ports):
    """Shared topology computation; see `horovod_tpu.run.util.topology_env`."""
    from horovod_tpu.run.util import topology_env
    return topology_env(rank, host_ports)


def _free_port():
    from horovod_tpu.run.rendezvous import reserve_port
    return reserve_port()


def _barrier_task(fn, args, kwargs, extra_env, context=None):
    """Runs inside one barrier task; `context` injectable for tests."""
    if context is None:
        from pyspark import BarrierTaskContext
        context = BarrierTaskContext.get()
    rank = context.partitionId()
    addr = "%s:%d" % (socket.gethostname(), _free_port())
    host_ports = [m.strip() for m in context.allGather(addr)]
    env = _task_topology_env(rank, host_ports)
    if extra_env:
        env.update(extra_env)
    # The task does not own this process (Spark reuses python workers,
    # and tests run the barrier body in-process): restore every mutated
    # key afterwards so stale topology can't leak into a later init().
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)

    import horovod_tpu as hvd
    try:
        hvd.init()
        try:
            result = fn(*args, **kwargs)
        finally:
            hvd.shutdown()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return rank, result


def run(fn, args=(), kwargs=None, num_proc=None, extra_env=None,
        verbose=1):
    """Runs `fn` on `num_proc` Spark barrier tasks with horovod_tpu
    initialized; returns results ordered by rank (reference semantics:
    spark/__init__.py:98-233)."""
    if not _importable("pyspark"):
        raise ImportError(
            "horovod_tpu.spark.run requires pyspark, which is not "
            "installed in this environment.")
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)
    if verbose:
        print("Running %d processes (Spark barrier mode)..." % num_proc)
    kwargs = kwargs or {}

    def _mapper(_):
        yield _barrier_task(fn, args, kwargs, extra_env)

    results = (sc.parallelize(range(num_proc), num_proc)
               .barrier()
               .mapPartitions(_mapper)
               .collect())
    return [r for _, r in sorted(results)]
