"""Spark integration — ``horovod_tpu.spark.run(fn, ...)``.

Capability parity with the reference (`horovod/spark/__init__.py:35-233`):
run `fn` as a data-parallel horovod job on `num_proc` Spark tasks and
return the per-rank results. The reference tunnels `mpirun`'s remote shell
through Spark task RPC (mpirun_rsh); the TPU-native build needs no MPI —
Spark's **barrier execution mode** gives every task a rendezvous
(`BarrierTaskContext.allGather`), so each task exchanges its
host:port, computes the same rank/local/cross topology the launcher
would inject (`horovod_tpu/run/util.py:allocate_slots`), sets the
``HVD_TPU_*`` env, and calls ``hvd.init()`` directly.

The barrier-task body is factored framework-free (``_task_topology_env``)
so it is unit-testable without a Spark cluster (the reference mocks its
shell layer the same way, test/test_spark.py:51-91).
"""

import collections
import os
import socket


def _importable(mod):
    import importlib.util
    return importlib.util.find_spec(mod) is not None


def _task_topology_env(rank, host_ports):
    """Computes the HVD_TPU_* env for `rank` given every task's
    "host:port" (index = rank). Same topology semantics as the launcher:
    local = same host, cross = same local_rank across hosts."""
    size = len(host_ports)
    hosts = [hp.rsplit(":", 1)[0] for hp in host_ports]
    # local_rank: position among ranks on the same host.
    by_host = collections.defaultdict(list)
    for r, h in enumerate(hosts):
        by_host[h].append(r)
    my_host = hosts[rank]
    local_ranks = by_host[my_host]
    local_rank = local_ranks.index(rank)
    # cross: hosts that have a rank at this local_rank, ordered by first
    # appearance.
    host_order = list(dict.fromkeys(hosts))
    cross_hosts = [h for h in host_order
                   if len(by_host[h]) > local_rank]
    return {
        "HVD_TPU_RANK": str(rank),
        "HVD_TPU_SIZE": str(size),
        "HVD_TPU_LOCAL_RANK": str(local_rank),
        "HVD_TPU_LOCAL_SIZE": str(len(local_ranks)),
        "HVD_TPU_CROSS_RANK": str(cross_hosts.index(my_host)),
        "HVD_TPU_CROSS_SIZE": str(len(cross_hosts)),
        "HVD_TPU_ADDRS": ",".join(host_ports),
    }


def _free_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind(("0.0.0.0", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _barrier_task(fn, args, kwargs, extra_env, context=None):
    """Runs inside one barrier task; `context` injectable for tests."""
    if context is None:
        from pyspark import BarrierTaskContext
        context = BarrierTaskContext.get()
    rank = context.partitionId()
    addr = "%s:%d" % (socket.gethostname(), _free_port())
    host_ports = [m.strip() for m in context.allGather(addr)]
    env = _task_topology_env(rank, host_ports)
    if extra_env:
        env.update(extra_env)
    os.environ.update(env)

    import horovod_tpu as hvd
    hvd.init()
    try:
        result = fn(*args, **kwargs)
    finally:
        hvd.shutdown()
    return rank, result


def run(fn, args=(), kwargs=None, num_proc=None, extra_env=None,
        verbose=1):
    """Runs `fn` on `num_proc` Spark barrier tasks with horovod_tpu
    initialized; returns results ordered by rank (reference semantics:
    spark/__init__.py:98-233)."""
    if not _importable("pyspark"):
        raise ImportError(
            "horovod_tpu.spark.run requires pyspark, which is not "
            "installed in this environment.")
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)
    if verbose:
        print("Running %d processes (Spark barrier mode)..." % num_proc)
    kwargs = kwargs or {}

    def _mapper(_):
        yield _barrier_task(fn, args, kwargs, extra_env)

    results = (sc.parallelize(range(num_proc), num_proc)
               .barrier()
               .mapPartitions(_mapper)
               .collect())
    return [r for _, r in sorted(results)]
