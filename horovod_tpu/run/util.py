"""Launcher utilities: host parsing, slot allocation, free ports.

Capability parity with the reference launcher internals
(``horovod/run/run.py:384-398`` host parsing and
``horovod/run/gloo_run.py:51-109`` slot allocation); fresh implementation.
"""

import collections
import socket

HostInfo = collections.namedtuple("HostInfo", ["hostname", "slots"])

SlotInfo = collections.namedtuple(
    "SlotInfo",
    ["hostname", "rank", "local_rank", "cross_rank", "size", "local_size",
     "cross_size"])


def parse_hosts(hosts_string):
    """Parses "host1:2,host2:2" into HostInfo list ("host" implies 1 slot)."""
    hosts = []
    for part in hosts_string.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            hosts.append(HostInfo(name, int(slots)))
        else:
            hosts.append(HostInfo(part, 1))
    return hosts


def parse_hostfile(path):
    """Hostfile lines: "hostname slots=N" (or just "hostname")."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            fields = line.split()
            slots = 1
            for field in fields[1:]:
                if field.startswith("slots="):
                    slots = int(field[len("slots="):])
            hosts.append(HostInfo(fields[0], slots))
    return hosts


def allocate_slots(hosts, np):
    """Assigns np ranks to host slots in order; computes local/cross ranks.

    Mirrors the reference allocation semantics (gloo_run.py:51-109): ranks
    fill hosts in order, local_rank counts within a host, cross_rank indexes
    the host among hosts that have a slot at that local_rank.
    """
    total_slots = sum(h.slots for h in hosts)
    if np > total_slots:
        raise ValueError(
            "requested %d processes but only %d slots available" %
            (np, total_slots))
    slots = []
    rank = 0
    host_idx_assigned = []  # (host_index, local_rank) per rank
    local_sizes = collections.defaultdict(int)
    for hi, host in enumerate(hosts):
        for local_rank in range(host.slots):
            if rank >= np:
                break
            host_idx_assigned.append((hi, local_rank, host.hostname))
            local_sizes[hi] += 1
            rank += 1
    # cross structures: for a given local_rank, ranks across hosts.
    cross_groups = collections.defaultdict(list)  # local_rank -> [host_index]
    for hi, local_rank, _ in host_idx_assigned:
        if hi not in cross_groups[local_rank]:
            cross_groups[local_rank].append(hi)
    for rank, (hi, local_rank, hostname) in enumerate(host_idx_assigned):
        cross_ranks = cross_groups[local_rank]
        slots.append(SlotInfo(
            hostname=hostname,
            rank=rank,
            local_rank=local_rank,
            cross_rank=cross_ranks.index(hi),
            size=np,
            local_size=local_sizes[hi],
            cross_size=len(cross_ranks),
        ))
    return slots


def topology_env(rank, host_ports):
    """Computes the HVD_TPU_* env for `rank` given every rank's "host:port"
    (index == rank). Topology semantics shared by the launcher, the Spark
    barrier tasks and rank-subset init: local = same host, cross = same
    local_rank across hosts."""
    size = len(host_ports)
    hosts = [hp.rsplit(":", 1)[0] for hp in host_ports]
    by_host = collections.defaultdict(list)
    for r, h in enumerate(hosts):
        by_host[h].append(r)
    my_host = hosts[rank]
    local_ranks = by_host[my_host]
    local_rank = local_ranks.index(rank)
    # cross: hosts that have a rank at this local_rank, ordered by first
    # appearance.
    host_order = list(dict.fromkeys(hosts))
    cross_hosts = [h for h in host_order if len(by_host[h]) > local_rank]
    return {
        "HVD_TPU_RANK": str(rank),
        "HVD_TPU_SIZE": str(size),
        "HVD_TPU_LOCAL_RANK": str(local_rank),
        "HVD_TPU_LOCAL_SIZE": str(len(local_ranks)),
        "HVD_TPU_CROSS_RANK": str(cross_hosts.index(my_host)),
        "HVD_TPU_CROSS_SIZE": str(len(cross_hosts)),
        "HVD_TPU_ADDRS": ",".join(host_ports),
    }


def is_local_host(hostname):
    return hostname in ("localhost", "127.0.0.1", socket.gethostname())


def cpu_worker_env(base_env=None, extra_env=None, repo_root=None):
    """Env for spawning CPU-only worker subprocesses: TPU plugin
    disengaged, CPU backend pinned, shared jit compile cache. The
    SINGLE source of truth for this scrub (tests/bench previously
    carried drifting inline copies):

    * pop ``PALLAS_AXON_POOL_IPS`` — the tunnel TPU plugin registers at
      interpreter boot whenever it is set and dials its relay in an
      unbounded retry loop; a dead relay hangs the worker before main()
      runs (JAX_PLATFORMS=cpu alone does NOT prevent the boot dial);
    * pin ``JAX_PLATFORMS=cpu`` (and ``JAX_PLATFORM_NAME=cpu`` for
      older jax) — these are CPU workers by definition, and a soft
      NAME-only demotion still lets jax CREATE the accelerator client:
      with libtpu installed and its backing service dead, that client
      init blocks on cloud-metadata queries and the worker hangs
      mid-test (observed: workers wedged in ESTABLISHED connections to
      169.254.169.254:80 while the fixture timed out);
    * pop ``PYTHONUNBUFFERED`` — with it set, every text write is its
      own raw write, so ``print(line)`` becomes TWO pipe writes
      (payload, then newline) and N workers sharing the launcher's
      stdout pipe interleave mid-line, corrupting line-oriented test
      protocols (observed: two COUNTERS JSON lines merged into one).
      Buffered stdout flushes a whole line atomically; workers that
      need promptness use ``print(..., flush=True)``;
    * default a persistent compile cache so identical worker jit
      programs compile once across the fleet.
    """
    import os as _os
    env = dict(base_env if base_env is not None else _os.environ)
    if repo_root:
        env["PYTHONPATH"] = repo_root + _os.pathsep + \
            env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PYTHONUNBUFFERED", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_PLATFORM_NAME"] = "cpu"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/hvd_tpu_jax_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    if extra_env:
        env.update(extra_env)
    return env
