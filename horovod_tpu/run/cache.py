"""On-disk result cache for launcher host checks.

Reference analogue: `horovod/run/util/cache.py` — a 60-minute
staleness window over an on-disk store keyed by the run parameters,
used so repeated `horovodrun` invocations skip re-probing every host
(`horovod/run/run.py:421-424`). TPU-native differences: JSON instead
of cloudpickle (stdlib-only, human-inspectable, no code execution on
load), atomic replace writes that merge with the on-disk state and
prune expired entries, best-effort I/O (an unwritable cache never
breaks a launch), and corrupt/stale-format files self-heal to empty
instead of raising.
"""

import json
import os
import threading
import time


class Cache:
    """String-keyed (timestamp, value) store under ``folder``.

    Entries older than ``staleness_minutes`` read as misses; a
    ``parameters_hash`` mismatch (different launcher arguments than the
    run that wrote the file) invalidates the whole store, like the
    reference's parameters_hash gate."""

    def __init__(self, folder, staleness_minutes, parameters_hash):
        self._file = os.path.join(folder, "cache.json")
        self._ttl = staleness_minutes * 60.0
        self._lock = threading.Lock()
        os.makedirs(folder, exist_ok=True)
        content = {}
        try:
            with open(self._file) as f:
                content = json.load(f)
        except (OSError, ValueError):
            content = {}
        if not isinstance(content, dict) or \
                content.get("parameters_hash") != parameters_hash:
            content = {"parameters_hash": parameters_hash}
        content.setdefault("entries", {})
        self._content = content

    def get(self, key):
        with self._lock:
            ent = self._content["entries"].get(key)
        if not ent:
            return None
        ts, val = ent
        if time.time() - ts <= self._ttl:
            return val
        return None

    def put(self, key, val):
        """Best-effort write-through: merges with whatever is on disk
        (another launcher may have written since we loaded), prunes
        expired entries, and never raises on I/O failure — a read-only
        or vanished cache directory must not break a launch (the cache
        only saves re-probing)."""
        now = time.time()
        with self._lock:
            self._content["entries"][key] = (now, val)
            # Merge: keep the newer timestamp per key so concurrent
            # launchers don't clobber each other's fresh probes.
            try:
                with open(self._file) as f:
                    disk = json.load(f)
                if isinstance(disk, dict) and \
                        disk.get("parameters_hash") == \
                        self._content["parameters_hash"]:
                    ours = self._content["entries"]
                    for k, ent in disk.get("entries", {}).items():
                        try:
                            ts = float(ent[0])
                        except (TypeError, ValueError, IndexError):
                            continue
                        if k not in ours or ts > ours[k][0]:
                            ours[k] = (ts, ent[1])
            except (OSError, ValueError):
                pass
            # Prune: expired entries only grow the file; they already
            # read as misses.
            self._content["entries"] = {
                k: ent for k, ent in self._content["entries"].items()
                if now - ent[0] <= self._ttl}
            tmp = self._file + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump(self._content, f)
                os.replace(tmp, self._file)
            except OSError:
                pass
