"""On-disk result cache for launcher host checks.

Reference analogue: `horovod/run/util/cache.py` — a 60-minute
staleness window over an on-disk store keyed by the run parameters,
used so repeated `horovodrun` invocations skip re-probing every host
(`horovod/run/run.py:421-424`). TPU-native differences: JSON instead
of cloudpickle (stdlib-only, human-inspectable, no code execution on
load), atomic replace writes, and corrupt/stale-format files self-heal
to empty instead of raising.
"""

import json
import os
import threading
import time


class Cache:
    """String-keyed (timestamp, value) store under ``folder``.

    Entries older than ``staleness_minutes`` read as misses; a
    ``parameters_hash`` mismatch (different launcher arguments than the
    run that wrote the file) invalidates the whole store, like the
    reference's parameters_hash gate."""

    def __init__(self, folder, staleness_minutes, parameters_hash):
        self._file = os.path.join(folder, "cache.json")
        self._ttl = staleness_minutes * 60.0
        self._lock = threading.Lock()
        os.makedirs(folder, exist_ok=True)
        content = {}
        try:
            with open(self._file) as f:
                content = json.load(f)
        except (OSError, ValueError):
            content = {}
        if not isinstance(content, dict) or \
                content.get("parameters_hash") != parameters_hash:
            content = {"parameters_hash": parameters_hash}
        content.setdefault("entries", {})
        self._content = content

    def get(self, key):
        with self._lock:
            ent = self._content["entries"].get(key)
        if not ent:
            return None
        ts, val = ent
        if time.time() - ts <= self._ttl:
            return val
        return None

    def put(self, key, val):
        with self._lock:
            self._content["entries"][key] = (time.time(), val)
            tmp = self._file + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._content, f)
            os.replace(tmp, self._file)
