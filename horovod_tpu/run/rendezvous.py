"""Dynamic rendezvous — launcher-hosted HTTP KV store + worker client.

The launcher starts :class:`RendezvousServer` and hands every worker just
``HVD_TPU_RENDEZVOUS_ADDR`` (+ rank/size). Each worker binds a free port
on its own host, publishes ``rank -> ip:port``, then polls until the full
peer table is present and derives its local/cross topology from it. This
replaces pre-assigned port tables (the fixed ``29500+i`` scheme) with
worker-chosen ports, the way the reference's Gloo path does it
(capability parity with /root/reference horovod/run/rendezvous/
http_server.py:33-205 and horovod/common/gloo/http_store.cc:1-134;
fresh implementation over the Python stdlib http server).

Protocol (scoped KV, values are opaque bytes):
  PUT  /set/<scope>/<key>   body = value         -> 200
  GET  /get/<scope>/<key>                        -> 200 value | 404
  GET  /list/<scope>                             -> 200 JSON {key: utf8 value}

Requests are HMAC-authenticated: the launcher generates a per-job secret
(injected as ``HVD_TPU_RENDEZVOUS_KEY``) and every request carries
``X-Hvd-Auth: hmac_sha256(secret, method + path + body)`` — an
unauthenticated peer on the network cannot poison the peer table
(reference analogue: the HMAC-signed launcher service messages,
``horovod/run/common/util/secret.py:26-36`` + ``network.py``).
"""

import hashlib
import hmac
import json
import os
import secrets as _secrets
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

MAX_VALUE_BYTES = 1 << 20

SCOPE_ADDRS = "addrs"
# Rank 0 publishes the probed, globally-consistent address table here;
# every rank consumes it verbatim (a per-rank interface choice could
# diverge and split the local/cross topology).
SCOPE_RESOLVED = "resolved"


def gen_scope(base, generation):
    """Scope name for one elastic generation. Generation 0 keeps the bare
    name (static jobs never re-register); later generations get their own
    scope so a re-rendezvous never reads stale entries from the previous
    membership (e.g. an old size-3 table during a size-2 restart)."""
    return base if not generation else "%s@g%d" % (base, generation)

PROBE_CONNECT_TIMEOUT = 2.0

AUTH_HEADER = "X-Hvd-Auth"
KEY_ENV = "HVD_TPU_RENDEZVOUS_KEY"


def make_secret():
    return _secrets.token_hex(16)


def _sign(key, method, path, body):
    mac = hmac.new(key.encode(), digestmod=hashlib.sha256)
    mac.update(method.encode())
    mac.update(path.encode())
    mac.update(body or b"")
    return mac.hexdigest()


class RendezvousServer:
    """Threaded HTTP KV server; one per launcher process.

    `key=None` disables authentication (unit tests); the launcher always
    passes a per-job secret."""

    def __init__(self, host="0.0.0.0", port=0, key=None):
        self._store = {}  # (scope, key) -> bytes
        self._lock = threading.Lock()
        store, lock = self._store, self._lock
        auth_key = key

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, code, body=b"",
                       ctype="application/octet-stream"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self, body=b""):
                if auth_key is None:
                    return True
                got = self.headers.get(AUTH_HEADER, "")
                want = _sign(auth_key, self.command, self.path, body)
                return hmac.compare_digest(got, want)

            def do_PUT(self):
                parts = self.path.strip("/").split("/")
                if len(parts) != 3 or parts[0] != "set":
                    return self._reply(400, b"bad path")
                # Before buffering the body: a peer without the key
                # can't produce even a well-formed signature header, so
                # reject it here rather than reading (and holding) up
                # to MAX_VALUE_BYTES per unauthenticated connection.
                if auth_key is not None:
                    header = self.headers.get(AUTH_HEADER, "")
                    if len(header) != 64 or any(
                            c not in "0123456789abcdef" for c in header):
                        return self._reply(403, b"bad signature")
                length = int(self.headers.get("Content-Length", 0))
                if length > MAX_VALUE_BYTES:
                    return self._reply(413, b"value too large")
                value = self.rfile.read(length)
                if not self._authorized(value):
                    return self._reply(403, b"bad signature")
                with lock:
                    store[(parts[1], parts[2])] = value
                self._reply(200)

            do_POST = do_PUT

            def do_GET(self):
                if not self._authorized():
                    return self._reply(403, b"bad signature")
                parts = self.path.strip("/").split("/")
                if len(parts) == 3 and parts[0] == "get":
                    with lock:
                        value = store.get((parts[1], parts[2]))
                    if value is None:
                        return self._reply(404, b"not found")
                    return self._reply(200, value)
                if len(parts) == 2 and parts[0] == "list":
                    with lock:
                        scoped = {k: v.decode("utf-8", "replace")
                                  for (s, k), v in store.items()
                                  if s == parts[1]}
                    return self._reply(200, json.dumps(scoped).encode(),
                                       "application/json")
                self._reply(400, b"bad path")

        class Server(ThreadingHTTPServer):
            # socketserver's default listen backlog of 5 drops
            # connections when every worker of a large job publishes at
            # once (observed at 32 local ranks: ECONNRESET on PUT).
            request_queue_size = 512
            daemon_threads = True

        self._httpd = Server((host, port), Handler)
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    # Same-process access for the elastic driver (which owns the server):
    # no HTTP round trip, no signing.
    def put_local(self, scope, key, value):
        if isinstance(value, str):
            value = value.encode()
        with self._lock:
            self._store[(scope, str(key))] = value

    def scope_items(self, scope):
        """{key: bytes} snapshot of one scope."""
        with self._lock:
            return {k: v for (s, k), v in self._store.items() if s == scope}

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="hvd-tpu-rendezvous")
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# Client side (workers)

def _auth_key():
    return os.environ.get(KEY_ENV)


def _request(method, addr, path, body=None):
    req = urllib.request.Request("http://%s%s" % (addr, path), data=body,
                                 method=method)
    key = _auth_key()
    if key is not None:
        req.add_header(AUTH_HEADER, _sign(key, method, path, body))
    return urllib.request.urlopen(req, timeout=10)


def put(addr, scope, key, value, timeout=30):
    """Publishes key=value, retrying transient connection failures:
    under a large simultaneous fan-out the server may reset or refuse
    individual connections even with a deep listen backlog."""
    if isinstance(value, str):
        value = value.encode()
    deadline = time.monotonic() + timeout
    delay = 0.05
    while True:
        try:
            with _request("PUT", addr, "/set/%s/%s" % (scope, key),
                          value):
                return
        except urllib.error.HTTPError:
            raise  # auth/size errors are not transient
        except OSError as e:
            # DNS failure means a misconfigured address, not a burst —
            # surface it immediately instead of retrying for 30s.
            reason = getattr(e, "reason", e)
            if isinstance(reason, socket.gaierror):
                raise
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "rendezvous PUT to %s kept failing: %s" % (addr, e))
            time.sleep(delay)
            delay = min(delay * 2, 1.0)


def get(addr, scope, key):
    try:
        with _request("GET", addr, "/get/%s/%s" % (scope, key)) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def list_scope(addr, scope):
    with _request("GET", addr, "/list/%s" % scope) as resp:
        return json.loads(resp.read().decode())


def wait_all(addr, scope, keys, timeout, poll_interval=0.1):
    """Polls until every key in `keys` is present; returns {key: str}."""
    deadline = time.monotonic() + timeout
    keys = [str(k) for k in keys]
    while True:
        try:
            table = list_scope(addr, scope)
        except urllib.error.HTTPError as e:
            if e.code == 403:
                raise RuntimeError(
                    "rendezvous auth failed (HTTP 403): %s mismatch "
                    "between launcher and worker" % KEY_ENV) from e
            raise
        except (urllib.error.URLError, ConnectionError, socket.timeout) as e:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "rendezvous server at %s unreachable: %s" % (addr, e))
            table = {}
        missing = [k for k in keys if k not in table]
        if not missing:
            return table
        if time.monotonic() > deadline:
            raise TimeoutError(
                "rendezvous timed out after %.0fs waiting for %d/%d "
                "workers (missing ranks: %s...). A worker likely failed "
                "to start — check its log." %
                (timeout, len(missing), len(keys),
                 ",".join(missing[:8])))
        time.sleep(poll_interval)


def routable_ip(peer_host, peer_port=80):
    """The local IP the kernel routes toward `peer_host` (UDP connect
    trick — no packet is sent). Falls back through getfqdn to hostname."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((peer_host, peer_port or 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        for name in (socket.getfqdn(), socket.gethostname()):
            try:
                return socket.gethostbyname(name)
            except OSError:
                continue
        return "127.0.0.1"


def candidate_ips(peer_host=None, peer_port=80):
    """All plausible local IPv4 addresses, the kernel-routed guess
    toward `peer_host` first. On a multi-NIC host the interface the
    kernel routes toward the launcher may not be the one peers can
    reach — publishing every candidate lets the coordinator probe and
    pick a working one (reference analogue: the driver/task services'
    interface discovery, /root/reference/horovod/run/run.py:189-259).
    """
    cands = []
    if peer_host:
        primary = routable_ip(peer_host, peer_port)
        if primary:
            cands.append(primary)
    try:
        import fcntl
        import struct
        for _, name in socket.if_nameindex():
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                try:
                    packed = fcntl.ioctl(
                        s.fileno(), 0x8915,  # SIOCGIFADDR
                        struct.pack("256s", name.encode()[:15]))
                except OSError:  # interface without an IPv4 address
                    continue
            ip = socket.inet_ntoa(packed[20:24])
            if ip not in cands and not ip.startswith("127."):
                cands.append(ip)
    except (OSError, ImportError):  # ImportError: no fcntl off-Linux
        pass
    return cands or ["127.0.0.1"]


class ProbeListener:
    """Accept-and-close TCP listener: lets the coordinator verify this
    worker's advertised interfaces actually accept connections, before
    the native listener exists. Runs until release_held_ports()."""

    def __init__(self):
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(128)
        self._sock.settimeout(0.25)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-tpu-probe")
        self._thread.start()

    def _loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
                conn.close()
            except socket.timeout:
                continue
            except OSError:
                return

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


_probe_listeners = []


def probe_connect(ip, port, timeout=None):
    """True when a TCP connect to ip:port succeeds within timeout."""
    try:
        socket.create_connection(
            (ip, port),
            timeout=PROBE_CONNECT_TIMEOUT if timeout is None else timeout
        ).close()
        return True
    except OSError:
        return False


# Reservation sockets held open (bound, not listening) until the native
# listener re-binds their port — see reserve_port(hold=True).
_held_sockets = []


def reserve_port(hold=False):
    """Binds an ephemeral port; with ``hold=False`` releases it
    immediately (callers that only need a number and tolerate the tiny
    reuse window, e.g. picking a coordinator port to broadcast).

    ``hold=True`` keeps the socket open with SO_REUSEPORT so no other
    process can be handed the port in the release-to-rebind window; the
    native listener (which also sets SO_REUSEPORT when told the port is
    a held reservation) binds alongside it, and `release_held_ports()`
    closes the reservation after init. The reservation socket never
    listens, so every incoming connection reaches the native listener.
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if hold and hasattr(socket, "SO_REUSEPORT"):
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind(("0.0.0.0", 0))
        _held_sockets.append(s)
        return s.getsockname()[1]
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def release_held_ports():
    """Closes reservation sockets held by reserve_port(hold=True) and
    stops probe listeners; called once the native listener has bound.
    Also clears the REUSEPORT hint so any later (re-)init binds with
    strict EADDRINUSE semantics again."""
    while _held_sockets:
        _held_sockets.pop().close()
    while _probe_listeners:
        _probe_listeners.pop().stop()
    os.environ.pop("HVD_TPU_LISTEN_REUSEPORT", None)


def _parse_entry(value):
    """A published worker entry: JSON {"cands": [...], "port": p,
    "probe": pp}, or the legacy plain "ip:port" form."""
    try:
        d = json.loads(value)
        return list(d["cands"]), int(d["port"]), int(d.get("probe", 0))
    except (ValueError, KeyError, TypeError):
        ip, _, port = value.rpartition(":")
        return [ip], int(port), 0


def _resolve_table(table, size, my_rank):
    """Coordinator-side interface selection: for each worker, the first
    published candidate that accepts a TCP connect to the worker's
    probe listener. Raises (fast, actionably) when none does — the
    failure that previously surfaced as a silent native-init hang.

    Known blind spot: candidates of workers colocated with the
    coordinator's host are probed over local routing, which succeeds
    even for interfaces other hosts can't reach (the reference's
    interface-set intersection has the same single-vantage limitation,
    run/run.py:189-259). Cross-host misadvertises from the
    coordinator's own host still fall through to the bounded native
    HVD_TPU_START_TIMEOUT; HVD_TPU_RENDEZVOUS_HOST overrides the
    launcher side."""
    import concurrent.futures

    entries = {r: _parse_entry(table[str(r)]) for r in range(size)}

    def pick(r):
        cands, port, probe_port = entries[r]
        if not probe_port:  # legacy entry without a probe listener
            return "%s:%d" % (cands[0], port)
        for ip in cands:
            if probe_connect(ip, probe_port):
                return "%s:%d" % (ip, port)
        raise RuntimeError(
            "rank %d advertised interface(s) %s but none accepts "
            "connections from rank %d (probe port %d). Check firewalls "
            "and that the hosts share a network; on multi-NIC hosts "
            "verify the advertised interfaces are the routable ones."
            % (r, ",".join(cands), my_rank, probe_port))

    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(32, size)) as pool:
        return list(pool.map(pick, range(size)))


def resolve_topology(rank, size, rendezvous_addr, timeout=60, generation=0):
    """Worker-side rendezvous: publish my candidate addresses + chosen
    port, let rank 0 probe reachability and publish ONE resolved table
    (globally consistent — per-rank interface choices could split the
    derived local/cross topology), derive the HVD_TPU_* env from it.
    `generation` scopes the exchange to one elastic membership epoch."""
    from .util import topology_env

    scope_addrs = gen_scope(SCOPE_ADDRS, generation)
    scope_resolved = gen_scope(SCOPE_RESOLVED, generation)
    host = rendezvous_addr.rsplit(":", 1)[0]
    port = int(rendezvous_addr.rsplit(":", 1)[1])
    cands = candidate_ips(host, port)
    my_port = reserve_port(hold=True)
    probe = ProbeListener()
    _probe_listeners.append(probe)
    env = {}
    if _held_sockets:
        # Tell the native listener its port is a held reservation (it
        # must set SO_REUSEPORT to bind alongside the reservation
        # socket). Only ever set on kernel-allocated ephemeral ports, so
        # the static fixed-port path keeps strict EADDRINUSE semantics.
        env["HVD_TPU_LISTEN_REUSEPORT"] = "1"
    put(rendezvous_addr, scope_addrs, str(rank),
        json.dumps({"cands": cands, "port": my_port, "probe": probe.port}))
    deadline = time.monotonic() + timeout
    if rank == 0:
        table = wait_all(rendezvous_addr, scope_addrs, range(size),
                         timeout)
        try:
            addrs = _resolve_table(table, size, my_rank=0)
        except RuntimeError as e:
            # Publish the failure so waiting ranks fail fast with the
            # actionable message instead of a generic timeout.
            put(rendezvous_addr, scope_resolved, "table",
                json.dumps({"error": str(e)}))
            raise
        put(rendezvous_addr, scope_resolved, "table", json.dumps(addrs))
    else:
        # Wait out the shared publish deadline PLUS a probing allowance
        # (rank 0 starts probing only after the last publish, and each
        # unreachable candidate burns PROBE_CONNECT_TIMEOUT).
        resolved = wait_all(
            rendezvous_addr, scope_resolved, ["table"],
            max(30.0, deadline - time.monotonic() + 30.0))
        addrs = json.loads(resolved["table"])
        if isinstance(addrs, dict):
            raise RuntimeError(
                "rendezvous coordinator failed: %s"
                % addrs.get("error", "unknown error"))
        if len(addrs) != size:
            raise RuntimeError(
                "resolved rendezvous table has %d entries for world "
                "size %d" % (len(addrs), size))
    env.update(topology_env(rank, addrs))
    return env
