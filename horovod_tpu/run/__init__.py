from .run import main, run_command  # noqa: F401
from .util import allocate_slots, parse_hostfile, parse_hosts  # noqa: F401
