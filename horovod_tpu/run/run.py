"""``horovodrun_tpu`` — the launcher.

Starts N copies of a training script, the way the reference ``horovodrun``
does for its Gloo path (/root/reference horovod/run/run.py:379-508 +
gloo_run.py:156-233): local slots via subprocess, remote slots via ssh
(after a reachability preflight, ref run/run.py:53-106), TPU pod slices
via metadata auto-discovery. SIGINT/SIGTERM fan out to every launched
process.

Rendezvous is dynamic by default: the launcher hosts a KV server and
injects only HVD_TPU_RANK / HVD_TPU_SIZE / HVD_TPU_RENDEZVOUS_ADDR;
every worker binds its own free port, publishes it, and derives the
local/cross topology from the published peer table (see rendezvous.py).
``--start-port`` switches to a static pre-assigned port table.

``--min-np`` / ``--max-np`` / ``--host-discovery-script`` switch to the
ELASTIC supervisor (horovod_tpu/elastic/driver.py): a failing worker
shrinks the job instead of tearing it down, recovered hosts grow it
back, and failing hosts are blacklisted with exponential backoff.
"""

import argparse
import json
import os
import shlex
import signal
import subprocess
import sys
import tempfile
import time

from . import rendezvous, util

# Workers that honor a graceful drain exit with this code
# (docs/FLEET.md) — the launcher must read it as a planned hand-back,
# not a failure.
from horovod_tpu.elastic.state import EXIT_DRAINED  # noqa: E402


def check_build(out=sys.stdout):
    """Prints the capability matrix (reference: run.py:262-298)."""
    import horovod_tpu as hvd

    def flag(v):
        return "X" if v else " "

    def binding(framework, binding_mod):
        # A framework counts only when BOTH it and our binding for it are
        # importable (the matrix diagnoses what this build supports).
        return flag(_importable(framework) and _importable(binding_mod))

    out.write("""\
Horovod-TPU v%s:

Available frameworks:
    [%s] JAX
    [%s] PyTorch
    [%s] TensorFlow
    [%s] Keras
    [%s] MXNet

Available controllers:
    [X] TCP (dynamic rendezvous)

Available data planes:
    [X] CPU (TCP ring + hierarchical)
    [%s] XLA/ICI (in-jit)
    [%s] TF graph kernels
    [%s] Torch C-extension glue (zero-copy)

Available kernels (Pallas):
    [%s] flash attention / ring attention
    [%s] fused BatchNorm statistics
""" % (hvd.__version__,
       binding("jax", "horovod_tpu.jax"),
       binding("torch", "horovod_tpu.torch"),
       binding("tensorflow", "horovod_tpu.tensorflow"),
       flag((_importable("tensorflow") or _importable("keras"))
            and _importable("horovod_tpu.keras")),
       binding("mxnet", "horovod_tpu.mxnet"),
       flag(_importable("jax")),
       flag(_tf_native_kernels()),
       flag(_torch_cext()),
       flag(_importable("jax")),
       flag(_importable("jax"))))


def _torch_cext():
    if not _importable("torch"):
        return False
    try:
        from horovod_tpu.torch import _cext
        return _cext.load() is not None
    except Exception:
        return False


def _tf_native_kernels():
    """True when the compiled TF custom-op library is present on disk.
    Deliberately does NOT import TF or trigger the on-demand build — the
    capability printout must stay instant (the library builds lazily on
    first `horovod_tpu.tensorflow` collective use)."""
    import os

    if not _importable("tensorflow"):
        return False
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.exists(os.path.join(
        here, "..", "native", "libhorovod_tpu_tf.so"))


def _importable(mod):
    import importlib.util
    return importlib.util.find_spec(mod) is not None


def discover_tpu_pod():
    """TPU pod-slice auto-discovery from TPU VM metadata env.

    On TPU VMs, `TPU_WORKER_HOSTNAMES` lists every host in the slice and
    `TPU_WORKER_ID` identifies this one; one worker process per host drives
    all local chips through JAX. Returns a hosts string or None.
    """
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES")
    if not hostnames:
        return None
    return ",".join("%s:1" % h for h in hostnames.split(","))


def make_parser():
    parser = argparse.ArgumentParser(
        prog="horovodrun_tpu",
        description="Launch a horovod_tpu distributed job.")
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="number of processes to launch")
    parser.add_argument("-H", "--hosts", default=None,
                        help='host slots, e.g. "localhost:4,host2:4"')
    parser.add_argument("--hostfile", default=None,
                        help='hostfile; lines "hostname slots=N"')
    parser.add_argument("--tpu-pod", action="store_true",
                        help="auto-discover hosts from TPU pod metadata")
    parser.add_argument("--start-port", type=int, default=0,
                        help="base port for rendezvous (0 = auto for local)")
    parser.add_argument("--min-np", type=int, default=None,
                        help="elastic mode: minimum world size the job "
                             "may shrink to before the driver gives up")
    parser.add_argument("--max-np", type=int, default=None,
                        help="elastic mode: maximum world size to grow "
                             "to (default: -np)")
    parser.add_argument("--host-discovery-script", default=None,
                        help="elastic mode: executable printing one "
                             "'host' or 'host:slots' line per available "
                             "host; polled to grow/shrink the job")
    parser.add_argument("--ckpt-dir", default=None,
                        help="durable checkpoint directory: elastic "
                             "commits are asynchronously written here "
                             "as CRC-checksummed shards + manifest, and "
                             "a fresh job auto-resumes from the newest "
                             "valid one (docs/ELASTIC.md 'Durability')")
    parser.add_argument("--restart-from-ckpt", action="store_true",
                        help="elastic mode with --ckpt-dir: when the "
                             "world would fall below --min-np, perform "
                             "a full-job restart that resumes from the "
                             "newest durable checkpoint instead of "
                             "tearing the job down (bounded by "
                             "HVD_TPU_CKPT_MAX_RESTARTS, default 3)")
    parser.add_argument("--drain-grace", type=float, default=None,
                        metavar="SECONDS",
                        help="graceful drain window (docs/FLEET.md): on "
                             "SIGTERM the launcher publishes a drain "
                             "request instead of killing — workers "
                             "finish the in-flight step, force a "
                             "durable commit, and exit cleanly (code "
                             "83) — and only escalates to a hard tree "
                             "kill after SECONDS. Needs the dynamic "
                             "rendezvous KV (np > 1 without "
                             "--start-port), or elastic mode")
    parser.add_argument("--ssh-port", type=int, default=None)
    parser.add_argument("--start-timeout", type=int, default=60,
                        help="seconds to wait for all ranks to connect")
    parser.add_argument("--check-build", action="store_true")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="serve live Prometheus metrics from every "
                             "worker at this base port + rank (rank 0 "
                             "additionally serves the aggregated job "
                             "view at /job — poll it with bin/hvd-top); "
                             "see docs/METRICS.md")
    parser.add_argument("--lint", nargs="?", const="warn",
                        choices=("warn", "strict", "verify"), default=None,
                        help="hvd-lint preflight: statically check the "
                             "training script for cross-rank divergence "
                             "hazards before spawning workers; 'warn' "
                             "(default when the flag is bare) reports and "
                             "launches anyway, '--lint=strict' refuses to "
                             "launch on any finding, '--lint=verify' "
                             "additionally runs the hvd-verify symbolic "
                             "collective-schedule verifier (interproc, "
                             "N symbolic ranks) and refuses to launch on "
                             "any finding (see docs/LINT.md)")
    parser.add_argument("--disable-cache", action="store_true",
                        help="re-run host checks even if cached "
                             "(reference: horovodrun --disable-cache; "
                             "successful ssh probes are otherwise "
                             "remembered for 60 minutes in "
                             "~/.horovod_tpu/cache.json)")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run, e.g. python train.py")
    return parser


def make_log_dir():
    """Per-job worker log directory (HVD_TPU_LOG_DIR overrides the
    tmp default). Every rank's middleman tees its output into
    ``rank<k>.log`` here, so the failure summary can name the exact log
    of the first-failing rank. Returns None when unwritable."""
    log_dir = os.environ.get("HVD_TPU_LOG_DIR")
    try:
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            return log_dir
        return tempfile.mkdtemp(prefix="hvd_tpu_logs_")
    except OSError:
        return None


def describe_exit(rc):
    """Human-readable exit status: middlemen report signal deaths as
    128+signum (shell convention)."""
    if rc > 128 and rc <= 128 + 64:
        try:
            name = signal.Signals(rc - 128).name
        except ValueError:
            name = "signal %d" % (rc - 128)
        return "killed by %s" % name
    return "exit code %d" % rc


def build_env(slot, addrs, base_env=None):
    env = dict(base_env if base_env is not None else os.environ)
    env.update({
        "HVD_TPU_RANK": str(slot.rank),
        "HVD_TPU_SIZE": str(slot.size),
        "HVD_TPU_LOCAL_RANK": str(slot.local_rank),
        "HVD_TPU_LOCAL_SIZE": str(slot.local_size),
        "HVD_TPU_CROSS_RANK": str(slot.cross_rank),
        "HVD_TPU_CROSS_SIZE": str(slot.cross_size),
        "HVD_TPU_ADDRS": ",".join(addrs),
    })
    return env


def _ssh_base_cmd(extra_opts=(), ssh_port=None):
    """The remote-shell argv prefix. HVD_TPU_SSH_CMD overrides the
    program (bastion wrappers, agents — and it lets tests drive the
    remote branch with a fake ssh that execs locally); the standard
    non-interactive options are only added for real ssh."""
    override = os.environ.get("HVD_TPU_SSH_CMD")
    if override:
        cmd = shlex.split(override)
    else:
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no"] + list(extra_opts)
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    return cmd


def _preflight_cache(ssh_port):
    """60-minute on-disk cache of successful host checks (reference:
    run/run.py:421-424 + run/util/cache.py), keyed by the remote-shell
    configuration so an ssh-command/port change invalidates it.
    Disabled by --disable-cache / HVD_TPU_DISABLE_CACHE=1."""
    if os.environ.get("HVD_TPU_DISABLE_CACHE") == "1":
        return None
    from horovod_tpu.run.cache import Cache
    params = "%r:%r" % (_ssh_base_cmd(), ssh_port)
    folder = os.path.join(os.path.expanduser("~"), ".horovod_tpu")
    try:
        return Cache(folder, staleness_minutes=60,
                     parameters_hash=params)
    except OSError:
        return None  # unwritable home: probe uncached


def ssh_preflight(hostnames, ssh_port=None, timeout=5, fn_cache=None):
    """Verifies every remote host is reachable over non-interactive ssh
    before launching anything (reference: run/run.py:53-106). Raises with
    an actionable message listing the unreachable hosts. Successful
    checks are remembered in `fn_cache` (only successes — a host that
    failed is re-probed next run, like the reference's None-result
    rule)."""
    import concurrent.futures

    CACHED = "cached"

    def probe(host):
        if fn_cache is not None and fn_cache.get("ssh://" + host):
            return host, 0, CACHED
        cmd = _ssh_base_cmd(
            ["-o", "BatchMode=yes", "-o", "ConnectTimeout=%d" % timeout],
            ssh_port=ssh_port)
        cmd += [host, "true"]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=timeout + 10)
            return host, r.returncode, r.stderr.strip()
        except (subprocess.TimeoutExpired, OSError) as e:
            return host, 255, str(e)

    failures = []
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(32, len(hostnames))) as pool:
        for host, rc, err in pool.map(probe, hostnames):
            if rc != 0:
                failures.append((host, err))
            elif fn_cache is not None and err is not CACHED:
                # Record REAL probes only: re-putting a cache hit would
                # slide the entry's timestamp forever and the 60-minute
                # staleness window would never re-probe a frequently
                # used host.
                fn_cache.put("ssh://" + host, True)
    if failures:
        detail = "\n".join("  %s: %s" % (h, e or "ssh exited nonzero")
                           for h, e in failures)
        raise RuntimeError(
            "ssh preflight failed for %d host(s):\n%s\n"
            "Ensure passwordless (key-based) ssh to every host in -H/"
            "--hostfile works from this machine, e.g. "
            "`ssh -o BatchMode=yes %s true`." %
            (len(failures), detail, failures[0][0]))


def rendezvous_preflight(remote_host, addr, port, ssh_port=None,
                         timeout=8):
    """Connect-back check: `remote_host` must be able to open a TCP
    connection to the launcher's advertised rendezvous address. Raises
    with an actionable message naming the override knob when it can't
    (reference analogue: the driver/task service reachability probes,
    run/run.py:189-259)."""
    cmd = _ssh_base_cmd(
        ["-o", "BatchMode=yes", "-o", "ConnectTimeout=%d" % timeout],
        ssh_port=ssh_port)
    probe = "timeout %d bash -c 'exec 3<>/dev/tcp/%s/%d' 2>&1" % (
        timeout, addr, port)
    cmd += [remote_host, probe]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout + 15)
    except (subprocess.TimeoutExpired, OSError) as e:
        raise RuntimeError(
            "rendezvous connect-back preflight could not run on %s: %s"
            % (remote_host, e))
    if r.returncode != 0:
        raise RuntimeError(
            "remote host %s cannot reach the launcher's rendezvous "
            "address %s:%d (%s). The launcher guessed this interface "
            "from its route toward %s; on multi-NIC machines set "
            "HVD_TPU_RENDEZVOUS_HOST=<ip reachable from the workers> "
            "or fix the firewall/route." %
            (remote_host, addr, port,
             (r.stdout + r.stderr).strip() or "connection refused/timed "
             "out", remote_host))


def launch(slots, rank_envs, command, ssh_port=None, verbose=False):
    """Launches one process per slot; returns the list of Popens."""
    procs = []
    for slot, rank_env in zip(slots, rank_envs):
        if util.is_local_host(slot.hostname):
            if verbose:
                sys.stderr.write("[launcher] rank %d local: %s\n" %
                                 (slot.rank, " ".join(command)))
            # Via the middleman so teardown reaps the worker's WHOLE
            # descendant tree — killpg alone misses grandchildren that
            # re-sessioned with setsid (see exec_middleman.py).
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.run.exec_middleman",
                 "--"] + list(command),
                env=rank_env, start_new_session=True))
        else:
            # Remote launch over ssh with explicit env exports. The
            # rendezvous secret must NOT ride the command line (argv is
            # world-readable via ps on both hosts); it is piped over the
            # ssh channel's stdin instead.
            secret = rank_env.get(rendezvous.KEY_ENV)
            exports = " ".join(
                "%s=%s" % (k, shlex.quote(v))
                for k, v in rank_env.items()
                if (k.startswith("HVD_TPU_") or k in ("PYTHONPATH", "PATH"))
                and k != rendezvous.KEY_ENV)
            ssh_cmd = _ssh_base_cmd(ssh_port=ssh_port)
            # Same middleman wrapping as local slots: the remote
            # worker's descendant tree (incl. setsid'd helpers) dies
            # with the ssh channel, not just its process group.
            # Requires a python + horovod_tpu importable remotely —
            # both already required to run the worker itself.
            # HVD_TPU_REMOTE_PYTHON names the remote interpreter (venv
            # workers where bare `python3` is the wrong env).
            remote_py = (rank_env.get("HVD_TPU_REMOTE_PYTHON") or
                         os.environ.get("HVD_TPU_REMOTE_PYTHON") or
                         "python3")
            remote = "cd %s && env %s %s -m " \
                "horovod_tpu.run.exec_middleman -- %s" % (
                    shlex.quote(os.getcwd()), exports,
                    shlex.quote(remote_py),
                    " ".join(shlex.quote(c) for c in command))
            if secret is not None:
                remote = ("IFS= read -r %s && export %s && " %
                          (rendezvous.KEY_ENV, rendezvous.KEY_ENV)) + remote
            if verbose:
                sys.stderr.write("[launcher] rank %d ssh %s\n" %
                                 (slot.rank, slot.hostname))
            proc = subprocess.Popen(
                ssh_cmd + [slot.hostname, remote],
                start_new_session=True,
                stdin=subprocess.PIPE if secret is not None else None)
            if secret is not None:
                proc.stdin.write((secret + "\n").encode())
                proc.stdin.close()
            procs.append(proc)
    return procs


def run_command(np, hosts, command, start_port=0, ssh_port=None,
                start_timeout=60, verbose=False, env=None,
                drain_grace=None):
    """Programmatic entry: launch and wait; returns max exit code
    (EXIT_DRAINED after a SIGTERM-driven graceful drain when
    `drain_grace` is set)."""
    host_list = util.parse_hosts(hosts) if isinstance(hosts, str) else hosts
    slots = util.allocate_slots(host_list, np)

    all_local = all(util.is_local_host(s.hostname) for s in slots)
    remote_hosts = sorted({s.hostname for s in slots
                           if not util.is_local_host(s.hostname)})
    if remote_hosts:
        ssh_preflight(remote_hosts, ssh_port=ssh_port,
                      fn_cache=_preflight_cache(ssh_port))

    base_env = dict(env if env is not None else os.environ)
    base_env.setdefault("HVD_TPU_START_TIMEOUT", str(start_timeout))
    if drain_grace:
        # Rank-uniform drain-polling gate (elastic/run.py): set at spawn
        # time for EVERY worker, so the per-commit agreement allreduce
        # is enabled identically across the job.
        base_env["HVD_TPU_DRAIN_ENABLE"] = "1"

    # Local slots must be advertised with an address the *other hosts*
    # can reach; 127.0.0.1 is only valid when every slot is local.
    # HVD_TPU_RENDEZVOUS_HOST overrides the kernel-route guess on
    # multi-NIC launchers.
    local_addr = base_env.get("HVD_TPU_RENDEZVOUS_HOST") or (
        "127.0.0.1" if all_local
        else rendezvous.routable_ip(remote_hosts[0]))

    server = None
    if start_port:
        # Static pre-assigned port table (compat path).
        ports = [start_port + i for i in range(np)]
        addrs = ["%s:%d" % (slot.hostname
                            if not util.is_local_host(slot.hostname)
                            else local_addr, port)
                 for slot, port in zip(slots, ports)]
        rank_envs = [build_env(slot, addrs, base_env) for slot in slots]
    elif np == 1:
        rank_envs = [build_env(slots[0], ["127.0.0.1:0"], base_env)]
    else:
        # Dynamic rendezvous: workers pick their own ports and publish
        # them to the launcher-hosted KV server. Requests are signed
        # with a per-job secret so a network peer can't poison the
        # peer table.
        rdv_key = rendezvous.make_secret()
        server = rendezvous.RendezvousServer(key=rdv_key)
        rdv_addr = "%s:%d" % (local_addr, server.start())
        if remote_hosts:
            # Connect-back preflight: before launching all ranks,
            # verify one remote host can actually reach the advertised
            # rendezvous address (a wrong interface guess otherwise
            # surfaces as every worker hanging until timeout).
            rendezvous_preflight(remote_hosts[0], local_addr,
                                 server.port, ssh_port=ssh_port)
        rank_envs = []
        for slot in slots:
            rank_env = dict(base_env)
            # A stale address table in the caller's env must not bypass
            # the rendezvous the workers are about to perform.
            for key in ("HVD_TPU_ADDRS", "HVD_TPU_LOCAL_RANK",
                        "HVD_TPU_LOCAL_SIZE", "HVD_TPU_CROSS_RANK",
                        "HVD_TPU_CROSS_SIZE"):
                rank_env.pop(key, None)
            rank_env.update({
                "HVD_TPU_RANK": str(slot.rank),
                "HVD_TPU_SIZE": str(slot.size),
                "HVD_TPU_RENDEZVOUS_ADDR": rdv_addr,
                rendezvous.KEY_ENV: rdv_key,
            })
            rank_envs.append(rank_env)

    # Per-rank tee'd logs: the middleman duplicates each worker's output
    # into rank<k>.log so a torn-down job's failure summary can point at
    # the first-failing rank's exact log. Local slots only — a
    # launcher-local tmp path does not exist on a remote host (set
    # HVD_TPU_LOG_DIR to a path valid everywhere to tee remote ranks
    # too; remote output still streams through the ssh channel either
    # way). The tmp dir is created lazily and removed again when the
    # job succeeds, so a long-lived launcher host doesn't accumulate
    # one directory per run.
    tee_slots = [i for i, slot in enumerate(slots)
                 if util.is_local_host(slot.hostname)
                 or os.environ.get("HVD_TPU_LOG_DIR")]
    log_dir = make_log_dir() if tee_slots else None
    log_paths = [None] * len(slots)
    if log_dir is not None:
        for i in tee_slots:
            log_paths[i] = os.path.join(log_dir,
                                        "rank%d.log" % slots[i].rank)
            rank_envs[i]["HVD_TPU_LOG_FILE"] = log_paths[i]

    # Flight-recorder bundles (docs/TRACING.md): unless the caller
    # already routes them, local ranks dump post-mortem bundles next to
    # the tee'd logs so the failure summary below can name them. Same
    # local-only caveat as the logs: a launcher-local path means nothing
    # on a remote host, so remote ranks only get the env when the user
    # set it to a path valid everywhere.
    bundle_dir = os.environ.get("HVD_TPU_BUNDLE_DIR")
    if not bundle_dir and log_dir is not None:
        bundle_dir = os.path.join(log_dir, "bundles")
        for i in tee_slots:
            rank_envs[i].setdefault("HVD_TPU_BUNDLE_DIR", bundle_dir)

    def sweep_bundles():
        """Post-mortem bundles the ranks left behind, oldest first."""
        if not bundle_dir or not os.path.isdir(bundle_dir):
            return []
        found = [os.path.join(bundle_dir, n)
                 for n in os.listdir(bundle_dir)
                 if n.startswith("hvd_bundle_") and n.endswith(".json")]
        return sorted(found, key=lambda p: os.path.getmtime(p))

    procs = launch(slots, rank_envs, command, ssh_port=ssh_port,
                   verbose=verbose)

    # Graceful drain (docs/FLEET.md): a SIGTERM with --drain-grace set
    # publishes a drain request on the rendezvous KV instead of killing
    # — workers finish the in-flight step, force a durable commit, and
    # exit EXIT_DRAINED; the launcher escalates to the middleman's
    # kill_tree only after the grace window. Needs the KV server, so
    # the static port table and np==1 fall back to the immediate kill.
    drain = {"requested": False, "published_at": None,
             "escalated": False}

    def kill_all(signum, frame):
        if (signum == signal.SIGTERM and drain_grace
                and server is not None and not drain["requested"]):
            drain["requested"] = True
            return  # the poll loop publishes and supervises the drain
        for p in procs:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        sys.exit(1)

    old_int = signal.signal(signal.SIGINT, kill_all)
    old_term = signal.signal(signal.SIGTERM, kill_all)
    try:
        # Poll (rather than wait in rank order) so the FIRST failure —
        # the root cause, not the teardown collateral — is the one the
        # summary names.
        exit_code = 0
        first_fail = None  # (slot, rc, log_path)
        drained_ranks = []
        pending = set(range(len(procs)))
        while pending:
            if drain["requested"] and drain["published_at"] is None:
                from horovod_tpu.elastic.state import (KEY_DRAIN,
                                                       SCOPE_ELASTIC)
                server.put_local(SCOPE_ELASTIC, KEY_DRAIN, json.dumps({
                    "epoch": 1, "workers": "all",
                    "grace": drain_grace}))
                drain["published_at"] = time.monotonic()
                sys.stderr.write(
                    "[launcher] SIGTERM: drain requested (grace %.0fs); "
                    "workers will durable-commit and exit\n"
                    % drain_grace)
            if (drain["published_at"] is not None
                    and not drain["escalated"]
                    and time.monotonic() - drain["published_at"]
                    > drain_grace):
                drain["escalated"] = True
                sys.stderr.write(
                    "[launcher] drain grace expired; escalating to "
                    "kill_tree for %d remaining worker(s)\n"
                    % sum(1 for p in procs if p.poll() is None))
                for q in procs:
                    if q.poll() is None:
                        try:
                            os.killpg(os.getpgid(q.pid), signal.SIGTERM)
                        except (ProcessLookupError, PermissionError):
                            pass
            progressed = False
            for i in sorted(pending):
                rc = procs[i].poll()
                if rc is None:
                    continue
                pending.discard(i)
                progressed = True
                if rc == 0:
                    continue
                if drain["requested"] and (
                        rc == EXIT_DRAINED or drain["escalated"]):
                    # Voluntary exit under an active drain (or the
                    # launcher's own escalation kill): planned, not a
                    # failure.
                    drained_ranks.append(slots[i].rank)
                    continue
                exit_code = max(exit_code, rc if rc > 0 else 1)
                if first_fail is None:
                    first_fail = (slots[i], rc, log_paths[i])
                    if not drain["requested"]:
                        # One failed rank: tear down the rest (they
                        # would hang in negotiation otherwise). Under a
                        # drain the peers are already on their way out.
                        for q in procs:
                            if q.poll() is None:
                                try:
                                    os.killpg(os.getpgid(q.pid),
                                              signal.SIGTERM)
                                except (ProcessLookupError,
                                        PermissionError):
                                    pass
            if pending and not progressed:
                time.sleep(0.05)
        if drain["requested"] and exit_code == 0:
            sys.stderr.write(
                "[launcher] drain complete: %d worker(s) exited "
                "cleanly under the drain%s\n"
                % (len(drained_ranks),
                   " (after escalation)" if drain["escalated"] else ""))
            ckpt_dir = os.environ.get("HVD_TPU_CKPT_DIR")
            if ckpt_dir:
                from horovod_tpu.elastic.durable import \
                    describe_last_durable
                sys.stderr.write(
                    "[launcher] %s\n" % describe_last_durable(ckpt_dir))
            for bpath in sweep_bundles():
                sys.stderr.write(
                    "[launcher] post-mortem bundle: %s\n" % bpath)
            if drained_ranks:
                # EXIT_DRAINED (not 0) so a supervisor can tell a
                # preempted job from a completed one; ranks that
                # finished before the drain landed still count as a
                # completed job.
                return EXIT_DRAINED
        if first_fail is not None:
            slot, rc, log_path = first_fail
            where = ("" if util.is_local_host(slot.hostname)
                     else " on %s" % slot.hostname)
            sys.stderr.write(
                "[launcher] job failed: first failing rank was rank %d%s "
                "(%s); worker log: %s\n"
                % (slot.rank, where, describe_exit(rc),
                   log_path or "<unavailable>"))
            ckpt_dir = os.environ.get("HVD_TPU_CKPT_DIR")
            if ckpt_dir:
                # Durable checkpoints were on: tell the operator what a
                # relaunch of this same command recovers.
                from horovod_tpu.elastic.durable import \
                    describe_last_durable
                sys.stderr.write(
                    "[launcher] %s\n" % describe_last_durable(ckpt_dir))
            for bpath in sweep_bundles():
                sys.stderr.write(
                    "[launcher] post-mortem bundle: %s\n" % bpath)
        elif (exit_code == 0 and log_dir is not None
              and not os.environ.get("HVD_TPU_LOG_DIR")):
            # Clean run: reclaim the tmp log dir (an explicit
            # HVD_TPU_LOG_DIR is the user's to keep).
            import shutil
            shutil.rmtree(log_dir, ignore_errors=True)
        return exit_code
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)
        if server is not None:
            server.stop()


def lint_preflight(command, mode, out=sys.stderr, num_proc=None):
    """Statically checks the training script(s) in `command` for
    cross-rank divergence hazards before any worker spawns (the silent
    hangs the stall inspector and digest cross-check can only catch
    after launch — docs/LINT.md). Returns True when the launch may
    proceed: always in 'warn' mode, only on a clean report in 'strict'."""
    from horovod_tpu.lint import lint_paths
    from horovod_tpu.lint.report import format_human

    targets = [arg for arg in command
               if arg.endswith(".py") and os.path.isfile(arg)]
    if not targets:
        out.write("[hvd-lint] no .py file found in the command to lint; "
                  "skipping preflight\n")
        return True
    findings, _ = lint_paths(targets)
    if mode == "verify":
        # Whole-program pass: symbolic N-rank schedules over the script
        # and its local imports, diffed (docs/LINT.md "hvd-verify") —
        # the static twin of the runtime divergence cross-check. The
        # symbolic world matches the job's -np (a group of [0, 1] is
        # world-covering at -np 2 but not at 4), capped at 8 symbolic
        # ranks to bound the preflight's cost on wide jobs.
        from horovod_tpu.lint.schedule import DEFAULT_WORLD, verify_paths
        world = DEFAULT_WORLD if not num_proc \
            else max(2, min(int(num_proc), 8))
        vfindings, _ = verify_paths(targets, world=world)
        findings = sorted(findings + vfindings,
                          key=lambda f: (f.path, f.line, f.col, f.rule))
    if not findings:
        out.write("[hvd-lint] %s: clean%s\n" %
                  (", ".join(targets),
                   " (schedules verified)" if mode == "verify" else ""))
        return True
    format_human(findings, out)
    if mode in ("strict", "verify"):
        out.write("[hvd-lint] %d finding(s); refusing to launch "
                  "(--lint=%s). Fix them or suppress intentional "
                  "patterns with `# hvd-lint: disable=<rule>`.\n"
                  % (len(findings), mode))
        return False
    out.write("[hvd-lint] %d finding(s); launching anyway (use "
              "--lint=strict to fail instead)\n" % len(findings))
    return True


def main(argv=None):
    parser = make_parser()
    args = parser.parse_args(argv)
    if args.check_build:
        check_build()
        return 0
    if args.disable_cache:
        os.environ["HVD_TPU_DISABLE_CACHE"] = "1"
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given")
    if args.lint and not lint_preflight(command, args.lint,
                                        num_proc=args.num_proc):
        return 1
    if args.ckpt_dir:
        # Both launch paths (static run_command and the elastic driver)
        # inherit this process's env into every worker; workers
        # auto-enable durable commits from it (elastic/durable.py).
        os.environ["HVD_TPU_CKPT_DIR"] = os.path.abspath(args.ckpt_dir)
    if args.restart_from_ckpt and not (
            args.ckpt_dir or os.environ.get("HVD_TPU_CKPT_DIR")):
        # The env var is the documented equivalent of --ckpt-dir
        # everywhere else (worker auto-enable, driver, summaries).
        parser.error("--restart-from-ckpt requires --ckpt-dir (or "
                     "HVD_TPU_CKPT_DIR in the environment)")
    if args.metrics_port:
        # Workers read the base port from env and offset by their rank
        # (elastic re-ranks included); run_command/run_elastic inherit
        # this process's env into every worker.
        os.environ["HVD_TPU_METRICS_PORT"] = str(args.metrics_port)
        sys.stderr.write(
            "[launcher] metrics: per-rank Prometheus at "
            "http://<worker-host>:%d+rank/metrics; job view at "
            "http://<rank0-host>:%d/job (try: bin/hvd-top "
            "localhost:%d)\n"
            % (args.metrics_port, args.metrics_port, args.metrics_port))
    if args.tpu_pod:
        hosts = discover_tpu_pod()
        if hosts is None:
            parser.error("--tpu-pod given but no TPU pod metadata found")
        if args.num_proc is None:
            args.num_proc = len(util.parse_hosts(hosts))
    elif args.hostfile:
        hosts = util.parse_hostfile(args.hostfile)
        if args.num_proc is None:
            args.num_proc = sum(h.slots for h in hosts)
    else:
        hosts = args.hosts or "localhost:%d" % (args.num_proc or 1)
    if args.min_np or args.max_np or args.host_discovery_script:
        # Elastic mode: a supervisor loop (shrink on failure, grow on
        # recovery, host blacklisting) replaces the static
        # kill-all-on-first-exit behavior. See docs/ELASTIC.md.
        from horovod_tpu.elastic.discovery import (FixedHosts,
                                                   HostDiscoveryScript)
        from horovod_tpu.elastic.driver import run_elastic
        if args.start_port:
            parser.error("--start-port (static port table) is "
                         "incompatible with elastic mode")
        if args.host_discovery_script:
            discovery = HostDiscoveryScript(args.host_discovery_script)
        else:
            if isinstance(hosts, str):
                discovery = FixedHosts(hosts)
            else:
                discovery = FixedHosts({h.hostname: h.slots
                                        for h in hosts})
        capacity = sum(
            discovery.find_available_hosts_and_slots().values())
        np_ = args.num_proc or capacity
        if not np_:
            parser.error("elastic launch found no hosts (discovery "
                         "script returned nothing and no -np given)")
        return run_elastic(np_, discovery, command,
                           min_np=args.min_np or 1,
                           max_np=args.max_np or np_,
                           ssh_port=args.ssh_port,
                           start_timeout=args.start_timeout,
                           verbose=args.verbose,
                           ckpt_dir=os.environ.get("HVD_TPU_CKPT_DIR"),
                           restart_from_ckpt=args.restart_from_ckpt,
                           drain_grace=args.drain_grace)
    if args.restart_from_ckpt:
        parser.error("--restart-from-ckpt needs elastic mode (give "
                     "--min-np / --max-np / --host-discovery-script); "
                     "the static launcher has no supervisor to relaunch "
                     "the job")
    if args.num_proc is None:
        parser.error("-np is required")
    if args.drain_grace and args.start_port:
        parser.error("--drain-grace needs the dynamic rendezvous KV to "
                     "publish the drain request; it is incompatible "
                     "with --start-port's static port table")
    return run_command(args.num_proc, hosts, command,
                       start_port=args.start_port, ssh_port=args.ssh_port,
                       start_timeout=args.start_timeout, verbose=args.verbose,
                       drain_grace=args.drain_grace)


if __name__ == "__main__":
    sys.exit(main())
