"""``hvd-top`` — a live terminal view of a running horovod_tpu job.

Polls the coordinator's ``/job`` metrics endpoint (rank 0 of a job
launched with ``--metrics-port`` / ``HVD_TPU_METRICS_PORT``; see
docs/METRICS.md) and renders per-rank cycle / negotiation / fusion
stats, so a hanging or straggling job is diagnosable in seconds without
waiting for a timeline capture: the rank whose announce lag grows is
the one everybody else is waiting on.

Stdlib-only on purpose — it runs anywhere, against any reachable job.
"""

import argparse
import json
import sys
import time
import urllib.request


def fetch_job(endpoint, timeout=5):
    url = endpoint
    if not url.startswith("http"):
        url = "http://" + url
    if not url.rstrip("/").endswith("/job"):
        url = url.rstrip("/") + "/job"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _rate(cur, prev, field, dt):
    if prev is None or dt <= 0:
        return None
    return (cur.get(field, 0.0) - prev.get(field, 0.0)) / dt


def _fmt_rate(v, scale=1.0, suffix=""):
    if v is None:
        return "-"
    v *= scale
    if v >= 1e6:
        return "%.1fM%s" % (v / 1e6, suffix)
    if v >= 1e3:
        return "%.1fk%s" % (v / 1e3, suffix)
    return "%.1f%s" % (v, suffix)


def _int_field(field):
    """Column renderer for a plain integer counter; '-' when an older
    worker's summary predates the field (mixed-version elastic jobs)."""
    def fmt(cur, prev, dt, ctx):
        if field not in cur:
            return "-"
        return "%d" % int(cur[field])
    return fmt


def _tun_state(cur, prev, dt, ctx):
    """Closed-loop autotune posture (docs/AUTOTUNE.md): 'tun' while the
    tuner is actively sampling, 'cvg' once converged, suffixed with the
    re-arm count when it has re-armed (e.g. 'tun/2' = third tuning pass
    live). '-' when the worker's summary predates the autotune fields
    (mixed-version elastic job)."""
    if "autotune_active" not in cur:
        return "-"
    state = "tun" if int(cur.get("autotune_active", 0)) else "cvg"
    rearms = int(cur.get("autotune_rearms_total", 0))
    return "%s/%d" % (state, rearms) if rearms else state


def _grp_state(cur, prev, dt, ctx):
    """Process groups (docs/GROUPS.md): registered groups on the worker,
    suffixed with the group-scoped tensor throughput when any flows
    (e.g. '3/12.0' = 3 groups, 12 group tensors/s). '0' = no groups
    (pure data-parallel); '-' = the worker's summary predates the group
    fields (mixed-version elastic job)."""
    if "groups" not in cur:
        return "-"
    g = int(cur.get("groups", 0))
    rate = _rate(cur, prev, "group_tensors_total", dt)
    if g <= 0:
        return "0"
    if rate is None or rate <= 0:
        return "%d" % g
    return "%d/%s" % (g, _fmt_rate(rate))


def _shm_state(cur, prev, dt, ctx):
    """Shared-memory data plane (docs/TRANSPORT.md): live attached
    segments on the worker, suffixed with the shm byte rate when the
    plane is moving traffic (e.g. '3/1.2M' = 3 segments, 1.2 MB/s
    through shared memory). '0' = no segments (shm off, single-rank
    host, or every pair nacked); '-' = the worker's summary predates
    the shm fields (mixed-version elastic job)."""
    if "shm_segments_active" not in cur:
        return "-"
    segs = int(cur.get("shm_segments_active", 0))
    if segs <= 0:
        return "0"
    rate = _rate(cur, prev, "net_shm_bytes_sent_total", dt)
    if rate is None or rate <= 0:
        return "%d" % segs
    return "%d/%s" % (segs, _fmt_rate(rate))


def _trc_state(cur, prev, dt, ctx):
    """Trace recorder health (docs/TRACING.md): span rate through the
    ring, suffixed with the cumulative ring-drop count when any span was
    ever dropped (e.g. '1.2k/d37' = 1200 spans/s, 37 dropped — grow
    HVD_TPU_TRACE_RING). 'off' = tracing disabled on the worker; '-' =
    the worker's summary predates the trace fields (mixed-version
    elastic job)."""
    if "trace_spans_total" not in cur:
        return "-"
    dropped = int(cur.get("trace_spans_dropped_total", 0))
    rate = _rate(cur, prev, "trace_spans_total", dt)
    if rate is None and float(cur.get("trace_spans_total", 0.0)) <= 0:
        return "off"
    base = _fmt_rate(rate)
    return "%s/d%d" % (base, dropped) if dropped else base


def _cmp_ratio(cur, prev, dt, ctx):
    """Live wire-compression factor (docs/COMPRESSION.md): f32 bytes
    into the codec / bytes put on the wire. '-' when the worker
    predates the compression fields OR compression never engaged."""
    if "compression_bytes_out_total" not in cur:
        return "-"
    out_b = float(cur.get("compression_bytes_out_total", 0.0))
    in_b = float(cur.get("compression_bytes_in_total", 0.0))
    if out_b <= 0 or in_b <= 0:
        return "-"
    return "%.1fx" % (in_b / out_b)


# Column schema: (header, width, renderer(cur, prev, dt, ctx) -> str).
# Every cell renders through this table, so a worker whose summary lacks
# a NEWER field (elastic job mid-rolling-upgrade) shows '-' in that one
# column instead of shifting every column after it.
_COLUMNS = [
    ("cyc/s", 9,
     lambda cur, prev, dt, ctx: _fmt_rate(_rate(cur, prev, "cycles_total",
                                                dt))),
    ("cyc_ms", 9, lambda cur, prev, dt, ctx: "%.2f" % ctx["cyc_ms"]),
    ("ops/s", 8,
     lambda cur, prev, dt, ctx: _fmt_rate(
         _rate(cur, prev, "tensors_performed_total", dt))),
    ("B/s", 9,
     lambda cur, prev, dt, ctx: _fmt_rate(
         _rate(cur, prev, "bytes_performed_total", dt))),
    ("fused_B", 9,
     lambda cur, prev, dt, ctx: _fmt_rate(cur.get("fused_bytes_total",
                                                  0.0))),
    ("cache%", 7, lambda cur, prev, dt, ctx: "%.1f%%" % ctx["cache_pct"]),
    ("queue", 6, _int_field("queue_depth")),
    ("stall", 6, _int_field("stall_warnings_total")),
    ("diverr", 6, _int_field("divergence_errors_total")),
    # Transport health (docs/CHAOS.md): detected corrupt frames, I/O
    # deadline expiries, and control-star reconnects survived.
    ("crc", 5, _int_field("net_crc_errors_total")),
    ("nto", 5, _int_field("net_timeouts_total")),
    ("rcn", 5, _int_field("net_reconnects_total")),
    # Durable checkpoints: the newest step this rank knows is safely on
    # disk (-1 = durability off / nothing written yet; '-' = the worker
    # predates the field) — docs/ELASTIC.md.
    ("dur", 7, _int_field("last_durable_step")),
    # Graceful drain (docs/FLEET.md): drain requests this worker agreed
    # to honor (victims force a durable commit then exit EXIT_DRAINED).
    ("drn", 5, _int_field("drains_requested_total")),
    # Wire compression factor (codec bytes in / wire bytes out).
    ("cmp", 6, _cmp_ratio),
    # Sharded weight update (docs/ZERO.md): reduce-scatter collectives
    # this worker executed (0 = replicated mode; '-' = the worker
    # predates the field).
    ("shd", 6, _int_field("reduce_scatter_total")),
    # Closed-loop autotune posture: tun(actively sampling) / cvg
    # (converged), '/N' = re-armed N times (docs/AUTOTUNE.md).
    ("tun", 6, _tun_state),
    # Process groups: registered groups (+ group-tensor rate when the
    # mesh is actually moving traffic) — docs/GROUPS.md.
    ("grp", 8, _grp_state),
    # Shared-memory data plane: attached segments (+ shm byte rate) —
    # docs/TRANSPORT.md.
    ("shm", 8, _shm_state),
    # Trace recorder: span rate (+ '/dN' once the ring ever dropped) —
    # docs/TRACING.md.
    ("trc", 8, _trc_state),
    ("lag_s", 9, lambda cur, prev, dt, ctx: "%.2f" % ctx["lag_total"]),
]


def render(job, prev_job, dt, endpoint):
    """One frame: header + per-rank table + straggler verdict."""
    per_rank = job.get("per_rank") or {}
    lag = job.get("rank_lag_seconds") or []
    prev_rank = (prev_job or {}).get("per_rank") or {}
    prev_lag = (prev_job or {}).get("rank_lag_seconds") or []
    lines = []
    lines.append("hvd-top — %s — size %d, generation %d — %s" % (
        endpoint, int(job.get("size", 0)), int(job.get("generation", 0)),
        time.strftime("%H:%M:%S")))
    header = "%4s " % "rank" + " ".join(
        "%*s" % (width, name) for name, width, _ in _COLUMNS)
    lines.append(header)
    lines.append("-" * len(header))

    max_lag_delta, straggler = 0.0, None
    faults_total = 0
    for r in sorted(per_rank, key=int):
        cur = per_rank[r]
        prev = prev_rank.get(r)
        cyc_rate = _rate(cur, prev, "cycles_total", dt)
        # Mean work-cycle duration over the window (cumulative mean as
        # the first-frame fallback).
        dsec = _rate(cur, prev, "cycle_seconds_sum", dt)
        cyc_ms = (dsec / cyc_rate * 1e3) if cyc_rate else (
            cur.get("cycle_seconds_sum", 0.0) / cur["cycles_total"] * 1e3
            if cur.get("cycles_total") else 0.0)
        hits = cur.get("cache_hit_total", 0.0)
        misses = cur.get("cache_miss_total", 0.0)
        cache_pct = 100.0 * hits / (hits + misses) if hits + misses else 0.0
        ri = int(r)
        lag_total = lag[ri] if ri < len(lag) else 0.0
        lag_prev = prev_lag[ri] if ri < len(prev_lag) else 0.0
        lag_delta = lag_total - lag_prev
        if prev_job is not None and lag_delta > max_lag_delta:
            max_lag_delta, straggler = lag_delta, ri
        faults_total += int(cur.get("faults_injected_total", 0))
        ctx = {"cyc_ms": cyc_ms, "cache_pct": cache_pct,
               "lag_total": lag_total}
        lines.append("%4s " % r + " ".join(
            "%*s" % (width, fn(cur, prev, dt, ctx))
            for _, width, fn in _COLUMNS))
    if faults_total:
        lines.append("! fault injection active: %d fault(s) injected "
                     "across the job (HVD_TPU_FAULT_SPEC set)"
                     % faults_total)
    ages = job.get("age_seconds") or {}
    stale = [r for r, age in ages.items() if float(age) > 10.0]
    if stale:
        lines.append("! no summary from rank(s) %s for >10s (hung or dead?)"
                     % ", ".join(sorted(stale, key=int)))
    if lag and max(lag) > 0:
        worst = lag.index(max(lag))
        note = " (growing)" if straggler == worst else ""
        lines.append("straggler: rank %d holds the most waited-on-announce "
                     "time (%.2fs total)%s" % (worst, max(lag), note))
    return "\n".join(lines)


def fetch_fleet(endpoint, timeout=5):
    url = endpoint
    if not url.startswith("http"):
        url = "http://" + url
    if not url.rstrip("/").endswith("/fleet"):
        url = url.rstrip("/") + "/fleet"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


# Fleet column schema, same tolerance rule as _COLUMNS: a missing field
# renders '-' instead of shifting the row (docs/FLEET.md).
_FLEET_COLUMNS = [
    ("state", 10, lambda j: str(j.get("state", "-"))),
    # Job kind (docs/SERVE.md): train | serve; '-' = the controller
    # predates the serving plane (mixed-version fleets).
    ("kind", 6, lambda j: str(j.get("kind", "-"))),
    # Placement shape (docs/FLEET.md "Placement"): pack | spread.
    ("place", 7, lambda j: str(j.get("placement", "-"))),
    ("prio", 5, lambda j: "%d" % j.get("priority", 0)),
    ("live", 5, lambda j: "%d" % j.get("live", 0)),
    ("want", 5, lambda j: "%d" % j.get("np", 0)),
    ("min", 4, lambda j: "%d" % j.get("min_np", 0)),
    ("leased", 7, lambda j: "%d" % j.get("leased", 0)),
    ("drains", 7, lambda j: "%d" % j.get("drains", 0)),
    ("preempt", 8, lambda j: "%d" % j.get("preemptions", 0)),
    ("restore", 8, lambda j: "%d" % j.get("restores", 0)),
    ("restart", 8, lambda j: "%d" % j.get("restarts", 0)),
    ("dur", 6, lambda j: "-" if j.get("last_durable_step") is None
     else "%d" % j["last_durable_step"]),
    ("age_s", 8, lambda j: "-" if j.get("age_seconds") is None
     else "%.1f" % j["age_seconds"]),
]


def render_fleet(fleet, endpoint):
    """One frame of the cross-job view: per-job table + host states +
    the drain/preemption counters (docs/FLEET.md)."""
    jobs = fleet.get("jobs") or {}
    hosts = fleet.get("hosts") or {}
    counters = fleet.get("counters") or {}
    lines = ["hvd-fleet — %s — t=%.0fs — %d job(s), %d free slot(s) — %s"
             % (endpoint, float(fleet.get("t", 0.0)), len(jobs),
                int(fleet.get("free_slots", 0)),
                time.strftime("%H:%M:%S"))]
    width = max([len(n) for n in jobs] + [4])
    header = "%-*s " % (width, "job") + " ".join(
        "%*s" % (w, name) for name, w, _ in _FLEET_COLUMNS)
    lines.append(header)
    lines.append("-" * len(header))
    for name in sorted(jobs):
        j = jobs[name]
        lines.append("%-*s " % (width, name) + " ".join(
            "%*s" % (w, fn(j)) for _, w, fn in _FLEET_COLUMNS))
    by_state = {}
    for h in hosts.values():
        by_state[h.get("state", "?")] = by_state.get(
            h.get("state", "?"), 0) + 1
    lines.append("hosts: " + (", ".join(
        "%d %s" % (n, s) for s, n in sorted(by_state.items()))
        or "none discovered"))
    lines.append(
        "fleet: %d admitted, %d preempted, %d restored, %d drain(s), "
        "%d kill(s) injected, %d oversubscription refusal(s), "
        "%d occupancy violation(s)"
        % (counters.get("fleet_admissions_total", 0),
           counters.get("fleet_preemptions_total", 0),
           counters.get("fleet_restores_total", 0),
           counters.get("fleet_drains_requested_total", 0),
           counters.get("fleet_kills_injected_total", 0),
           counters.get("fleet_oversubscription_refusals_total", 0),
           counters.get("fleet_occupancy_violations_total", 0)))
    draining = sorted(n for n, j in jobs.items()
                      if j.get("state") == "draining")
    if draining:
        lines.append("! draining: %s (durable-committing, then hosts "
                     "return to the pool)" % ", ".join(draining))
    return "\n".join(lines)


def fetch_serve(endpoint, timeout=5):
    url = endpoint
    if not url.startswith("http"):
        url = "http://" + url
    if not url.rstrip("/").endswith("/serve"):
        url = url.rstrip("/") + "/serve"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _serve_num(field, fmt="%d"):
    """Serve-column renderer under the same mixed-version tolerance
    rule as _COLUMNS/_FLEET_COLUMNS: a replica (or pool) document that
    predates the field renders '-' in that one cell."""
    def render(v):
        if v.get(field) is None:
            return "-"
        return fmt % v[field]
    return render


# Per-replica serve columns (docs/SERVE.md; the pool's /serve document
# carries one row per replica under "per_replica").
_SERVE_COLUMNS = [
    ("state", 9, lambda v: str(v.get("state", "-"))),
    ("step", 7, _serve_num("model_step")),
    ("weights", 9, lambda v: str(v.get("weights_crc") or "-")),
    ("queue", 6, _serve_num("queue_depth")),
    ("infl", 5, _serve_num("inflight")),
    ("req", 8, _serve_num("requests_total")),
    ("resp", 8, _serve_num("responses_total")),
    ("batch", 7, _serve_num("batches_total")),
    ("rej", 5, _serve_num("rejects_total")),
    ("err", 5, _serve_num("errors_total")),
    # Deadline-expired tickets dropped before spending a forward row.
    ("cxl", 5, _serve_num("cancelled_total")),
    # Frame-integrity failures caught by the per-row CRC gate.
    ("corr", 5, _serve_num("frame_corrupt_total")),
    # Rolling weight swaps: landed / rejected (torn or CRC-invalid
    # lineage) / abandoned-to-drain.
    ("swp", 4, _serve_num("swaps_total")),
    ("swrej", 6, _serve_num("swap_rejects_total")),
    ("swabt", 6, _serve_num("swap_aborts_total")),
    ("p50ms", 8, _serve_num("p50_ms", "%.1f")),
    ("p99ms", 8, _serve_num("p99_ms", "%.1f")),
]


def render_serve(doc, endpoint):
    """One frame of the serving view: pool header + per-replica table
    (docs/SERVE.md). Works against a supervisor's aggregated /serve
    (per_replica rows) or a single replica's /serve (one row)."""
    replicas = doc.get("per_replica")
    if replicas is None:
        replicas = [doc] if doc.get("replica") is not None else []
    lines = ["hvd-serve — %s — %s replica(s) (%s reporting, %s "
             "draining), %s scale event(s) — %s"
             % (endpoint,
                doc.get("replicas", len(replicas)),
                doc.get("replicas_reporting", len(replicas)),
                doc.get("draining", "-"),
                doc.get("scale_events", "-"),
                time.strftime("%H:%M:%S"))]
    header = "%4s " % "rep" + " ".join(
        "%*s" % (w, name) for name, w, _ in _SERVE_COLUMNS)
    lines.append(header)
    lines.append("-" * len(header))
    for v in sorted(replicas, key=lambda v: v.get("replica", 0)):
        rep = v.get("replica")
        lines.append("%4s " % ("-" if rep is None else rep) + " ".join(
            "%*s" % (w, fn(v)) for _, w, fn in _SERVE_COLUMNS))
    totals = []
    for label, field in (("req", "requests_total"),
                         ("resp", "responses_total"),
                         ("rej", "rejects_total"),
                         ("err", "errors_total"),
                         ("swaps", "swaps_total")):
        if doc.get(field) is not None:
            totals.append("%s %s" % (doc[field], label))
    if doc.get("p99_ms") is not None:
        totals.append("p99 %.1fms" % doc["p99_ms"])
    if totals:
        lines.append("pool: " + ", ".join(totals))
    steps = doc.get("model_steps") or []
    if len(steps) > 1:
        lines.append("! mixed weights: replicas serve steps %s (a "
                     "rolling swap is in flight)"
                     % ", ".join(str(s) for s in steps))
    if doc.get("frame_corrupt_total"):
        lines.append("! %d corrupt batch frame(s) caught by the row-CRC "
                     "gate (requests failed with cause "
                     "'frame-corrupt', never silently wrong)"
                     % doc["frame_corrupt_total"])
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="hvd-top",
        description="Live per-rank view of a horovod_tpu job's metrics "
                    "plane (poll rank 0's /job endpoint), or — with "
                    "--fleet — the cross-job view of a fleet "
                    "controller's /fleet endpoint.")
    ap.add_argument("endpoint", nargs="?", default="localhost:9400",
                    help="coordinator metrics endpoint: host:port, URL, "
                         "or the --metrics-port base (rank 0 serves the "
                         "job view there). With --fleet: the hvd-fleet "
                         "--port endpoint. Default: localhost:9400")
    ap.add_argument("--fleet", action="store_true",
                    help="cross-job fleet view: poll a fleet "
                         "controller's /fleet endpoint instead of a "
                         "job's /job endpoint (docs/FLEET.md)")
    ap.add_argument("--serve", action="store_true",
                    help="serving-pool view: poll an hvd-serve "
                         "supervisor's (or single replica's) /serve "
                         "endpoint (docs/SERVE.md)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (no screen "
                         "clearing; for scripts/tests)")
    args = ap.parse_args(argv)

    if args.fleet:
        return _fleet_loop(args)
    if args.serve:
        return _serve_loop(args)

    prev_job, prev_t = None, None
    try:
        while True:
            try:
                job = fetch_job(args.endpoint)
            except Exception as e:
                msg = "hvd-top: cannot reach %s: %s" % (args.endpoint, e)
                if args.once:
                    print(msg, file=sys.stderr)
                    return 1
                print(msg, file=sys.stderr)
                time.sleep(args.interval)
                continue
            if not job or not job.get("per_rank"):
                msg = ("hvd-top: %s answered but has no job view — point "
                       "me at RANK 0's port (the --metrics-port base)"
                       % args.endpoint)
                if args.once:
                    print(msg, file=sys.stderr)
                    return 1
                print(msg, file=sys.stderr)
                time.sleep(args.interval)
                continue
            now = time.monotonic()
            dt = (now - prev_t) if prev_t is not None else 0.0
            frame = render(job, prev_job, dt, args.endpoint)
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            prev_job, prev_t = job, now
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _serve_loop(args):
    try:
        while True:
            try:
                doc = fetch_serve(args.endpoint)
            except Exception as e:
                msg = "hvd-top: cannot reach serve pool at %s: %s" % (
                    args.endpoint, e)
                print(msg, file=sys.stderr)
                if args.once:
                    return 1
                time.sleep(args.interval)
                continue
            frame = render_serve(doc, args.endpoint)
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _fleet_loop(args):
    try:
        while True:
            try:
                fleet = fetch_fleet(args.endpoint)
            except Exception as e:
                msg = "hvd-top: cannot reach fleet at %s: %s" % (
                    args.endpoint, e)
                print(msg, file=sys.stderr)
                if args.once:
                    return 1
                time.sleep(args.interval)
                continue
            frame = render_fleet(fleet, args.endpoint)
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
