"""Process-tree middleman for launched workers.

``python -m horovod_tpu.run.exec_middleman -- cmd args...`` runs the
command and guarantees that when the middleman is told to stop (or the
command exits), the command's WHOLE descendant tree dies — including
grandchildren that called ``setsid`` and thereby escaped the launcher's
process-group kill. Reference analogue: ``safe_shell_exec``'s middleman
that reaps the executor tree
(`/root/reference/horovod/run/common/util/safe_shell_exec.py`).

Descendants are discovered by walking /proc ppid links (Linux), and the
middleman registers itself as a child subreaper
(``PR_SET_CHILD_SUBREAPER``) so descendants orphaned by their parent's
exit — including setsid'd and double-forked ones — reparent to the
middleman instead of init and can still be swept after the command
exits.

When ``HVD_TPU_LOG_FILE`` is set, the middleman additionally TEES the
command's stdout/stderr into that file (line-wise, so concurrent ranks
sharing the launcher's pipes never interleave mid-line) while still
passing everything through — the launcher's failure summary can then
point at the exact log of the first-failing rank.

A command killed by a signal is reported as exit code 128+signum (the
shell convention) instead of a raw negative status, so supervisors and
failure summaries can name the signal.
"""

import os
import signal
import sys
import time


def _ppid_map():
    """pid -> ppid for every live (non-zombie) process, via
    /proc/*/stat; empty on systems without /proc."""
    ppids = {}
    try:
        entries = os.listdir("/proc")
    except OSError:
        return ppids
    for entry in entries:
        if not entry.isdigit():
            continue
        try:
            with open("/proc/%s/stat" % entry, "rb") as f:
                stat = f.read().decode("ascii", "replace")
        except OSError:
            continue
        # comm may contain spaces/parens: state is field 1 and ppid
        # field 2 after the LAST ')'.
        try:
            fields = stat[stat.rindex(")") + 2:].split()
            if fields[0] == "Z":
                continue  # zombie: nothing left to kill
            ppids[int(entry)] = int(fields[1])
        except (ValueError, IndexError):
            continue
    return ppids


def descendants(root_pid):
    """All transitive children of root_pid, leaves first."""
    ppids = _ppid_map()
    children = {}
    for pid, ppid in ppids.items():
        children.setdefault(ppid, []).append(pid)
    found, stack = [], [root_pid]
    while stack:
        for child in children.get(stack.pop(), []):
            found.append(child)
            stack.append(child)
    return list(reversed(found))  # leaves first


def kill_tree(root_pid, sig=signal.SIGTERM, grace=3.0):
    """Signals root+descendants; escalates to SIGKILL after `grace`."""
    targets = descendants(root_pid) + [root_pid]
    for pid in targets:
        try:
            os.kill(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not any(_alive(p) for p in targets):
            return
        time.sleep(0.1)
    for pid in descendants(root_pid) + [root_pid]:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def _alive(pid):
    """True for live processes; zombies count as dead (kill(pid, 0)
    succeeds on them, which would make the grace loop spin its full
    length on already-exited children)."""
    try:
        with open("/proc/%d/stat" % pid, "rb") as f:
            stat = f.read().decode("ascii", "replace")
        return stat[stat.rindex(")") + 2:].split()[0] != "Z"
    except (OSError, ValueError, IndexError):
        pass
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _become_subreaper():
    """PR_SET_CHILD_SUBREAPER: orphaned descendants reparent to us, not
    init, so they stay sweepable after their parent exits."""
    try:
        import ctypes
        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(36, 1, 0, 0, 0)  # PR_SET_CHILD_SUBREAPER = 36
    except (OSError, AttributeError):
        pass  # non-Linux: tree walk still covers live-parent chains


def _sweep_orphans(exclude):
    """Kills every process currently parented to us except `exclude`
    (reparented stragglers), then reaps zombies."""
    me = os.getpid()
    for pid, ppid in _ppid_map().items():
        if ppid == me and pid != exclude:
            kill_tree(pid)
    try:
        while True:
            pid, _ = os.waitpid(-1, os.WNOHANG)
            if pid == 0:
                break
    except ChildProcessError:
        pass


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        sys.stderr.write("usage: exec_middleman -- cmd args...\n")
        return 2

    _become_subreaper()
    import subprocess
    child = None

    def _terminate(signum, frame):
        # Installed BEFORE the spawn: a teardown signal racing the
        # launch must still sweep (child may be None in that window).
        if child is not None:
            kill_tree(child.pid)
        _sweep_orphans(exclude=child.pid if child else -1)
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    # SIGHUP: the remote (ssh) path tears down by dropping the channel.
    try:
        signal.signal(signal.SIGHUP, _terminate)
    except (ValueError, AttributeError):
        pass

    log_f = None
    log_path = os.environ.get("HVD_TPU_LOG_FILE")
    if log_path:
        try:
            log_f = open(log_path, "ab", buffering=0)
        except OSError:
            log_f = None  # unwritable log dir: plain pass-through

    if log_f is None:
        child = subprocess.Popen(argv)
        pumps = []
    else:
        import threading
        child = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE)
        log_lock = threading.Lock()

        def pump(src, dst):
            # Line-wise tee: each complete line is written atomically to
            # the inherited stream, so other ranks' middlemen sharing
            # the launcher's pipe never interleave mid-line.
            for line in iter(src.readline, b""):
                with log_lock:
                    try:
                        log_f.write(line)
                    except (OSError, ValueError):
                        pass  # ValueError: log closed during teardown
                try:
                    dst.write(line)
                    dst.flush()
                except (OSError, ValueError):
                    pass
            src.close()

        pumps = [
            threading.Thread(target=pump,
                             args=(child.stdout, sys.stdout.buffer),
                             daemon=True),
            threading.Thread(target=pump,
                             args=(child.stderr, sys.stderr.buffer),
                             daemon=True),
        ]
        for t in pumps:
            t.start()

    rc = child.wait()
    # The command exited on its own: descendants it left behind (even
    # setsid'd/double-forked ones) have reparented to us — sweep them
    # BEFORE joining the pumps: a straggler holding the pipes would
    # otherwise keep readline blocked and stall teardown; killing it
    # closes the pipes and EOFs the pumps promptly.
    _sweep_orphans(exclude=child.pid)
    for t in pumps:
        t.join(timeout=5)
    if log_f is not None:
        try:
            log_f.close()
        except OSError:
            pass
    # Signal deaths surface as 128+signum (shell convention) so the
    # launcher's failure summary can name the signal.
    return 128 - rc if rc < 0 else rc


if __name__ == "__main__":
    sys.exit(main())
