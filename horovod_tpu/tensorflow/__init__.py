"""TensorFlow binding.

Capability parity with the reference TF API
(``horovod/tensorflow/__init__.py``): ``allreduce`` (dense +
IndexedSlices sparse path + ``sparse_as_dense``), ``allgather``,
``broadcast``, ``broadcast_variables``, ``DistributedGradientTape``,
``DistributedOptimizer`` (Keras-3 optimizers), ``Compression``.

Tensors ride the native host core (negotiation/fusion/cache). The default
path is a compiled TF custom-op kernel (``native/tf_ops.cc``, built on
first use — the reference's `horovod/tensorflow/mpi_ops.cc` shape):
collectives are real graph nodes with registered gradients
(``mpi_ops.py``), so they compose with ``tf.function``, ``tf.gradients``
and SavedModel export. If the kernel library can't build/load, collectives
fall back to ``tf.py_function`` (eager-compatible, not differentiable
through the collective). For TPU-resident XLA training use the jax
binding; this binding is the TF-on-host-CPU compatibility surface.
"""

import tensorflow as tf

import horovod_tpu as _hvd
from horovod_tpu import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, cross_rank, size,
    local_size, cross_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, mpi_built, gloo_enabled,
    gloo_built, nccl_built, ddl_built, mlsl_built,
)
from horovod_tpu.common import ops as _ops
from horovod_tpu.common.ops import HorovodInternalError  # noqa: F401

from . import mpi_ops as _mpi_ops
from .compression import Compression  # noqa: F401

_name_counter = [0]


def _auto_name(prefix):
    _name_counter[0] += 1
    return "%s.tf%d" % (prefix, _name_counter[0])


def native_ops_available():
    """True when collectives run as compiled TF graph kernels."""
    return _mpi_ops.native_ops_available()


def _py_collective(fn, tensor, name, out_shape=None):
    """py_function fallback: runs `fn(numpy) -> numpy` on a tf tensor,
    eagerly or via tf.py_function inside tf.function graphs and TF1
    graph construction. `out_shape` overrides the static output shape
    when it differs from the input's (allgather grows axis 0)."""
    if tf.inside_function() or not tf.executing_eagerly():
        out = tf.py_function(lambda t: fn(t.numpy()), [tensor],
                             Tout=tensor.dtype, name=name)
        out.set_shape(tensor.shape if out_shape is None else out_shape)
        return out
    import numpy as np
    return tf.convert_to_tensor(fn(np.asarray(tensor)))


def allreduce(tensor, average=True, name=None, compression=Compression.none,
              sparse_as_dense=False, prescale_factor=1.0,
              postscale_factor=1.0, group=None):
    """Allreduce; IndexedSlices take the sparse allgather path (reference:
    tensorflow/__init__.py:65-76). ``group`` scopes a DENSE allreduce to
    a process group (docs/GROUPS.md); it rides the Python ops layer (the
    compiled kernel path predates groups)."""
    if isinstance(tensor, tf.IndexedSlices):
        if group is not None and sparse_as_dense:
            tensor = tf.convert_to_tensor(tensor)
        elif group is not None:
            raise ValueError(
                "group-scoped allreduce needs a dense tensor; pass "
                "sparse_as_dense=True for IndexedSlices")
        elif sparse_as_dense:
            tensor = tf.convert_to_tensor(tensor)
        else:
            op_name = name or _auto_name("ar_sparse")
            values = allgather(tensor.values, name=op_name + ".v")
            indices = allgather(tf.cast(tensor.indices, tf.int64),
                                name=op_name + ".i")
            if average:
                values = values / size()
            return tf.IndexedSlices(values, indices,
                                    dense_shape=tensor.dense_shape)
    op_name = name or _auto_name("allreduce")
    compressed, ctx = compression.compress(tensor)
    if _mpi_ops.native_ops_available() and group is None:
        out = _mpi_ops.allreduce(
            tf.convert_to_tensor(compressed), op_name, average=average,
            prescale=prescale_factor, postscale=postscale_factor)
        return compression.decompress(out, ctx)
    from horovod_tpu import groups as _grp
    post = (postscale_factor / _grp.group_size(group) if average
            else postscale_factor)

    def _do(arr):
        return _ops.allreduce(arr, op_name, prescale_factor=prescale_factor,
                              postscale_factor=post, group=group)

    out = _py_collective(_do, compressed, op_name.replace(".", "_"))
    return compression.decompress(out, ctx)


def allgather(tensor, name=None):
    op_name = name or _auto_name("allgather")
    if _mpi_ops.native_ops_available():
        return _mpi_ops.allgather(tf.convert_to_tensor(tensor), op_name)
    return _py_collective(
        lambda arr: _ops.allgather(arr, op_name), tensor,
        op_name.replace(".", "_"),
        out_shape=[None] + list(tensor.shape[1:]))


def broadcast(tensor, root_rank=0, name=None):
    op_name = name or _auto_name("broadcast")
    if _mpi_ops.native_ops_available():
        return _mpi_ops.broadcast(tf.convert_to_tensor(tensor), root_rank,
                                  op_name)
    return _py_collective(
        lambda arr: _ops.broadcast(arr, root_rank, op_name), tensor,
        op_name.replace(".", "_"))


def broadcast_variables(variables, root_rank=0):
    """Assigns every variable its root-rank value (reference:
    broadcast_global_variables / tensorflow/__init__.py:87-141)."""
    for i, var in enumerate(variables):
        name = "bc_var.%d.%s" % (i, getattr(var, "name", i))
        # tf.Variable has .value() (method); Keras-3 variables have
        # .value (property).
        value = getattr(var, "value", var)
        if callable(value):
            value = value()
        var.assign(broadcast(tf.convert_to_tensor(value), root_rank,
                             name=name))


def broadcast_global_variables(root_rank=0):
    """TF1 graph mode: one op assigning every global variable its
    root-rank value (reference: ``broadcast_global_variables``,
    ``tensorflow/__init__.py:160-193``). Build after the variables,
    run once in the session after initialization; in eager mode use
    :func:`broadcast_variables` instead."""
    v1 = tf.compat.v1
    if tf.executing_eagerly():
        raise RuntimeError(
            "broadcast_global_variables is graph-mode only; in eager "
            "TF2 use broadcast_variables(model.variables)")
    assigns = []
    for i, var in enumerate(v1.global_variables()):
        name = "bc_gvar.%d" % i
        assigns.append(v1.assign(var, broadcast(var, root_rank,
                                                name=name)))
    return tf.group(*assigns)


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """TF1 ``SessionRunHook`` that broadcasts rank 0's global variables
    once the session is created — drop-in for estimator /
    MonitoredTrainingSession training (reference:
    ``tensorflow/__init__.py:87-141``)."""

    def __init__(self, root_rank=0, device=""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        self.device = device  # accepted for API parity; host-core path

    def begin(self):
        self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


class DistributedGradientTape(tf.GradientTape):
    """GradientTape whose `gradient()` allreduces the results (reference:
    _DistributedGradientTape, tensorflow/__init__.py:322-377)."""

    def __init__(self, *args, average=True, compression=Compression.none,
                 sparse_as_dense=False, **kwargs):
        super().__init__(*args, **kwargs)
        self._hvd_average = average
        self._hvd_compression = compression
        self._hvd_sparse_as_dense = sparse_as_dense
        self._hvd_name_counter = [0]

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        grads = super().gradient(target, sources, output_gradients,
                                 **kwargs)
        flat = tf.nest.flatten(grads)
        reduced = []
        for i, g in enumerate(flat):
            if g is None:
                reduced.append(None)
                continue
            reduced.append(allreduce(
                g, average=self._hvd_average,
                name="tape_grad.%d" % i,
                compression=self._hvd_compression,
                sparse_as_dense=self._hvd_sparse_as_dense))
        return tf.nest.pack_sequence_as(grads, reduced)


def _make_sharded_keras(optimizer, average, compression):
    """ZeRO-style sharded weight update for Keras-3 optimizers
    (docs/ZERO.md), eager-only: gradients flatten into one fused f32
    buffer, reduce-scatter delivers this rank's 1/N shard, an INNER
    optimizer of the same class (rebuilt ``from_config``) updates ONE
    flat shard variable — so its slots (momentum/Adam moments) cover
    1/N of the elements — and the updated shard allgathers back into
    the real variables.

    Variables become OPTIMIZER-OWNED after the first
    ``apply_gradients()``: the flat shard variable seeded then is the
    master copy, and every step's allgather ``assign()``s the real
    variables from it — an external ``v.assign(...)`` between steps is
    silently reverted by the next allgather. To adopt externally-set
    values, rebuild the wrapper (docs/ZERO.md). ``None`` gradients ride
    the dense flat buffer as zeros (stateful optimizers still decay
    their moments); every call must pass the SAME variable list that
    built the shard layout — do not filter out None-grad pairs."""
    import numpy as np

    from horovod_tpu import compression as _wire
    from horovod_tpu.common.ops import shard_partition

    mode = _wire.resolve_wire_arg(compression, Compression.none)
    base = optimizer.__class__

    class _Sharded(base):
        _HVD_WRAPPED = True
        _HVD_SHARDED = True

        def _hvd_build_shard(self, variables):
            n, r = size(), rank()
            total = sum(int(np.prod(v.shape)) for v in variables)
            counts, offsets = shard_partition(total, n)
            flat = np.concatenate(
                [np.asarray(v).ravel().astype(np.float32)
                 for v in variables])
            self._hvd_vars = list(variables)
            self._hvd_total = total
            self._hvd_shard_var = tf.Variable(
                flat[offsets[r]:offsets[r] + counts[r]],
                trainable=False, name="hvd_shard")
            self._hvd_inner = base.from_config(self.get_config())

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            if not tf.executing_eagerly():
                raise RuntimeError(
                    "sharded_update runs the host data plane eagerly; "
                    "call apply_gradients outside tf.function (or use "
                    "the jax binding for in-XLA sharded updates)")
            # Re-checked per apply: a mesh formed AFTER construction
            # must fail here, not reduce-scatter across model shards.
            from horovod_tpu.groups import \
                assert_sharded_update_world_scope
            assert_sharded_update_world_scope()
            gvs = list(grads_and_vars)
            variables = [v for _, v in gvs]
            if not hasattr(self, "_hvd_shard_var"):
                self._hvd_build_shard(variables)
            else:
                # Mirror a dynamically-assigned learning rate onto the
                # inner shard optimizer (schedule objects already ride
                # from_config and advance in lockstep; .assign raises
                # on a schedule and is skipped).
                try:
                    self._hvd_inner.learning_rate.assign(
                        self.learning_rate)
                except (AttributeError, TypeError, ValueError):
                    pass
            if hasattr(self, "_hvd_vars") and \
                    [id(v) for v in variables] != \
                    [id(v) for v in self._hvd_vars]:
                # The shard layout (offsets, shard variable, inner
                # slots) was built from the FIRST call's variable list;
                # a filtered/reordered list would flatten a different
                # buffer and allgather segments back to the wrong
                # variables. Keep None grads in the list (they ride as
                # zeros) instead of filtering them out.
                raise RuntimeError(
                    "sharded_update apply_gradients got a different "
                    "variable list than the first call that built the "
                    "shard layout (%d vars vs %d, or reordered); pass "
                    "the same variables in the same order every step "
                    "(docs/ZERO.md)"
                    % (len(variables), len(self._hvd_vars)))
            flat_g = np.concatenate([
                (np.zeros(int(np.prod(v.shape)), np.float32)
                 if g is None else
                 np.asarray(tf.convert_to_tensor(g))
                 .ravel().astype(np.float32))
                for g, v in gvs])
            # Name matches the replicated wrapper's first per-variable
            # allreduce ("opt_grad.0") so mixed sharded/replicated
            # ranks collide at negotiation and are rejected naming both
            # ranks and modes (docs/ZERO.md).
            shard = _ops.reduce_scatter(flat_g, "opt_grad.0",
                                        average=average,
                                        compression=mode)
            self._hvd_inner.apply_gradients(
                [(tf.convert_to_tensor(shard), self._hvd_shard_var)])
            full = np.asarray(_ops.allgather(
                np.asarray(self._hvd_shard_var), "opt_grad.param_ag"))
            off = 0
            for v in variables:
                cnt = int(np.prod(v.shape))
                v.assign(tf.cast(tf.reshape(full[off:off + cnt],
                                            v.shape), v.dtype))
                off += cnt
            # Keras-3 variables report dtype as a string; tf.as_dtype
            # accepts both forms.
            nbytes = sum(
                int(np.prod(w.shape)) * tf.as_dtype(w.dtype).size
                for w in self._hvd_inner.variables)
            _hvd.get_basics().opt_state_metrics(nbytes)
            return self.iterations.assign_add(1)

    cls = type("ShardedDistributed%s" % base.__name__, (_Sharded,), {})
    return cls.from_config(optimizer.get_config())


def DistributedOptimizer(optimizer, average=True,
                         compression=Compression.none,
                         sparse_as_dense=False, sharded_update=None,
                         group=None):
    """Wraps an optimizer so gradients are averaged across ranks before
    being applied (reference: tensorflow/__init__.py:231-319).

    Keras-3 optimizers get a subclass whose ``apply_gradients``
    allreduces first. TF1 ``tf.compat.v1.train.Optimizer`` instances
    (the estimator-era API, reference tensorflow/__init__.py:186-240)
    get a wrapping v1 optimizer whose ``compute_gradients`` allreduces
    — so ``minimize()`` inside a session graph trains data-parallel.

    ``sharded_update=True`` (job-wide: ``HVD_TPU_SHARDED_UPDATE=1``)
    switches Keras-3 optimizers to the ZeRO-style sharded weight update
    (docs/ZERO.md): reduce-scatter gradients, shard-local update (slot
    memory drops N-fold), allgather updated params. Eager-only; not
    supported for v1 optimizers.

    ``group`` scopes the gradient averaging (docs/GROUPS.md); defaults
    to this rank's batch group under ``hvd.init(model_parallel=k)``."""
    if sharded_update is None:
        sharded_update = _ops.sharded_update_default()
    if isinstance(optimizer, tf.compat.v1.train.Optimizer):
        if sharded_update:
            raise ValueError("sharded_update is not supported for "
                             "tf.compat.v1 optimizers")
        return _DistributedV1Optimizer(optimizer, average, compression,
                                       sparse_as_dense, group=group)
    if sharded_update:
        from horovod_tpu.groups import assert_sharded_update_world_scope
        assert_sharded_update_world_scope(group)
        return _make_sharded_keras(optimizer, average, compression)

    base = optimizer.__class__

    class _Distributed(base):
        _HVD_WRAPPED = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            # group=None resolves to the CURRENT batch group per apply
            # (construction-time capture goes stale across elastic
            # re-inits — the mesh re-forms with fresh group ids).
            grp = group if group is not None else _hvd.batch_group()
            grads_and_vars = list(grads_and_vars)
            reduced = []
            for i, (g, v) in enumerate(grads_and_vars):
                if g is not None:
                    g = allreduce(g, average=average,
                                  name="opt_grad.%d" % i,
                                  compression=compression,
                                  sparse_as_dense=sparse_as_dense,
                                  group=grp)
                reduced.append((g, v))
            return super().apply_gradients(reduced, *args, **kwargs)

    cls = type("Distributed%s" % base.__name__, (_Distributed,), {})
    new_opt = cls.from_config(optimizer.get_config())
    return new_opt


class _DistributedV1Optimizer(tf.compat.v1.train.Optimizer):
    """Composition wrapper around a v1 optimizer: `compute_gradients`
    allreduces each gradient (graph ops), everything else delegates —
    the reference's v1 DistributedOptimizer shape."""

    def __init__(self, optimizer, average, compression, sparse_as_dense,
                 group=None):
        self._opt = optimizer
        self._hvd_average = average
        self._hvd_compression = compression
        self._hvd_sparse_as_dense = sparse_as_dense
        self._hvd_group = group
        # Collective names are the cross-rank rendezvous keys: scope
        # them per wrapper instance (two wrapped optimizers in one
        # graph must not collide) and per VARIABLE, not per position
        # (var_list ordering must not silently mis-pair gradients).
        self._hvd_scope = _auto_name("v1opt")
        super().__init__(use_locking=False,
                         name="Distributed%s" % type(optimizer).__name__)

    def compute_gradients(self, *args, **kwargs):
        gvs = self._opt.compute_gradients(*args, **kwargs)
        out = []
        for g, v in gvs:
            if g is not None:
                g = allreduce(g, average=self._hvd_average,
                              name="%s.grad.%s" % (self._hvd_scope,
                                                   v.name.replace(":", "_")),
                              compression=self._hvd_compression,
                              sparse_as_dense=self._hvd_sparse_as_dense,
                              group=self._hvd_group
                              if self._hvd_group is not None
                              else _hvd.batch_group())
            out.append((g, v))
        return out

    def apply_gradients(self, *args, **kwargs):
        return self._opt.apply_gradients(*args, **kwargs)

    def get_slot(self, *args, **kwargs):
        return self._opt.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._opt.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._opt.variables(*args, **kwargs)
