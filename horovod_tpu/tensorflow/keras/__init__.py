"""``horovod_tpu.tensorflow.keras`` — source-compatible alias of the
Keras binding (reference parity: ``horovod/tensorflow/keras/__init__.py``
is the same thin shell over ``horovod/_keras`` as ``horovod/keras``; a
user switching from ``import horovod.tensorflow.keras as hvd`` keeps the
identical import path here)."""

from horovod_tpu.keras import *  # noqa: F401,F403
from horovod_tpu.keras import (  # noqa: F401
    DistributedOptimizer, broadcast_model_weights, load_model, callbacks,
)
