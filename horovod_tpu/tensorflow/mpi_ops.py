"""Native TF graph ops for horovod_tpu collectives + gradient registration.

Loads (building on first use) the custom-op kernel library
``native/libhorovod_tpu_tf.so`` so allreduce/allgather/broadcast are real
graph nodes — differentiable, tf.function-composable, SavedModel-
exportable. Capability parity with the reference op loader + gradient
registrations (/root/reference horovod/tensorflow/mpi_ops.py:50-180);
fresh implementation over our handle-based C API.

Gradients (matching the reference's semantics):
  * allreduce: the gradient is itself allreduced (same scaling attrs) —
    each rank holds a different upstream grad, the true Jacobian-vector
    product sums them.
  * allgather: upstream grad covers the full gathered dim; allreduce it,
    then every rank slices out its own segment (segment boundaries come
    from an allgather of first-dim sizes, so unequal slices work).
  * broadcast: the root receives the summed grads of every rank's output;
    non-roots contribute zero to their (unused) input.
"""

import fcntl
import os
import subprocess
import threading

import tensorflow as tf
from tensorflow.python.framework import ops as tf_framework_ops

from horovod_tpu.common.basics import get_basics

_MOD_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.abspath(os.path.join(_MOD_DIR, "..", "native"))
_TF_LIB_PATH = os.path.join(_NATIVE_DIR, "libhorovod_tpu_tf.so")

_load_lock = threading.Lock()
_lib = None
_load_error = None


def _build_tf_ops():
    env = dict(os.environ)
    env["TF_CFLAGS"] = " ".join(tf.sysconfig.get_compile_flags())
    env["TF_LDFLAGS"] = " ".join(tf.sysconfig.get_link_flags())
    lock_path = os.path.join(_NATIVE_DIR, ".build_tf.lock")
    with open(lock_path, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if os.path.exists(_TF_LIB_PATH):
                return
            subprocess.run(["make", "tf"], cwd=_NATIVE_DIR, env=env,
                           check=True, stdout=subprocess.PIPE,
                           stderr=subprocess.STDOUT)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                "failed to build libhorovod_tpu_tf.so:\n" +
                e.stdout.decode("utf-8", "replace")) from e
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)


def _load():
    """Builds + loads the kernel library once; returns the op module or
    None (with the failure remembered) when native ops are unavailable."""
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    with _load_lock:
        if _lib is not None or _load_error is not None:
            return _lib
        if os.environ.get("HVD_TPU_TF_NATIVE", "1") == "0":
            _load_error = "disabled via HVD_TPU_TF_NATIVE=0"
            return None
        try:
            # The kernels resolve core symbols from libhorovod_tpu.so,
            # which basics loads RTLD_GLOBAL — load it first.
            get_basics()
            if not os.path.exists(_TF_LIB_PATH):
                _build_tf_ops()
            _lib = tf.load_op_library(_TF_LIB_PATH)
        except Exception as e:  # noqa: BLE001 — remember and fall back
            _load_error = str(e)
            return None
    return _lib


def native_ops_available():
    return _load() is not None


def load_error():
    _load()
    return _load_error


def allreduce(tensor, op_name, average=False, prescale=1.0, postscale=1.0):
    lib = _load()
    return lib.horovod_tpu_allreduce(tensor=tensor, op_name=op_name,
                                     average=average, prescale=prescale,
                                     postscale=postscale)


def allgather(tensor, op_name):
    lib = _load()
    squeeze = tensor.shape.rank == 0
    if squeeze:
        tensor = tf.reshape(tensor, [1])
    return lib.horovod_tpu_allgather(tensor=tensor, op_name=op_name)


def broadcast(tensor, root_rank, op_name):
    lib = _load()
    return lib.horovod_tpu_broadcast(tensor=tensor, op_name=op_name,
                                     root_rank=root_rank)


@tf_framework_ops.RegisterGradient("HorovodTpuAllreduce")
def _allreduce_grad(op, grad):
    # Reference semantics (horovod/tensorflow/mpi_ops.py:89-105): the
    # gradient of an allreduce is the allreduce of the gradient with the
    # same scaling.
    return allreduce(grad, op.get_attr("op_name").decode() + ".grad",
                     average=op.get_attr("average"),
                     prescale=op.get_attr("prescale"),
                     postscale=op.get_attr("postscale"))


@tf_framework_ops.RegisterGradient("HorovodTpuAllgather")
def _allgather_grad(op, grad):
    # Reference semantics (mpi_ops.py:107-141): sum the upstream grads,
    # then slice out this rank's segment (segment table via an allgather
    # of first-dim sizes, so unequal gathers differentiate correctly).
    import horovod_tpu as hvd

    op_name = op.get_attr("op_name").decode()
    grad = allreduce(grad, op_name + ".grad")
    my_dim = tf.shape(op.inputs[0], out_type=tf.int64)[:1]
    sizes = allgather(my_dim, op_name + ".grad_sizes")
    offset = tf.reduce_sum(sizes[:hvd.rank()])
    return tf.slice(grad, tf.concat(
        [[offset], tf.zeros([tf.rank(grad) - 1], tf.int64)], axis=0),
        tf.concat([sizes[hvd.rank():hvd.rank() + 1],
                   tf.fill([tf.rank(grad) - 1], tf.constant(-1, tf.int64))],
                  axis=0))


@tf_framework_ops.RegisterGradient("HorovodTpuBroadcast")
def _broadcast_grad(op, grad):
    # Reference semantics (mpi_ops.py:166-180): every rank's output grad
    # flows back to the root's input; non-root inputs are unused -> zero.
    import horovod_tpu as hvd

    op_name = op.get_attr("op_name").decode()
    reduced = allreduce(grad, op_name + ".grad")
    if hvd.rank() == op.get_attr("root_rank"):
        return reduced
    return tf.zeros_like(reduced)
