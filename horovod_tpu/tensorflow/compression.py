"""Gradient compression for the TF binding (reference:
horovod/tensorflow/compression.py:20-75; bf16 added as the TPU-native
16-bit format)."""

import tensorflow as tf


class NoneCompressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor:
    @staticmethod
    def compress(tensor):
        if tensor.dtype in (tf.float32, tf.float64):
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class BF16Compressor:
    @staticmethod
    def compress(tensor):
        if tensor.dtype in (tf.float32, tf.float64):
            return tf.cast(tensor, tf.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
